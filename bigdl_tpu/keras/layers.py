"""Keras-1.2-named layer wrappers with deferred build + shape inference.

Reference: ``DL/nn/keras/`` wraps every core layer in a ``KerasLayer`` that
adds Keras names and an ``InferShape`` implementation per layer
(``DL/nn/keras/KerasLayer.scala``, ``Dense.scala``, ``Convolution2D.scala``).

TPU redesign: a ``KerasLayer`` here is a *deferred* core module — it holds
Keras-style hyper-parameters and builds the underlying ``bigdl_tpu.nn``
module only once the input shape is known (at ``Sequential.build`` /
``compile`` time).  Output-shape inference is NOT hand-written per layer:
``jax.eval_shape`` abstractly traces the built module, so every wrapper
gets exact shape inference for free from XLA's abstract interpreter.

Keras conventions honored (Keras 1.2.2, the version the reference imports):
- images are channels-first here (``dim_ordering="th"``) to match the
  reference's default NCHW zoo; pass ``dim_ordering="tf"`` for NHWC (the
  TPU-preferred layout).
- ``input_shape`` excludes the batch dimension.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module

_ACTIVATIONS = {
    "relu": nn.ReLU, "tanh": nn.Tanh, "sigmoid": nn.Sigmoid,
    "softmax": nn.SoftMax, "log_softmax": nn.LogSoftMax,
    "softplus": nn.SoftPlus, "softsign": nn.SoftSign, "linear": None,
    "hard_sigmoid": nn.HardSigmoid, "gelu": nn.GELU, "silu": nn.SiLU,
    "elu": nn.ELU,
}


def activation_module(name: Optional[str]) -> Optional[Module]:
    if name is None or name == "linear":
        return None
    try:
        cls = _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(f"unknown activation {name!r}") from None
    return cls() if cls is not None else None


def infer_output_shape(module: Module, input_shape: Tuple[int, ...],
                       batch: int = 2) -> Tuple[int, ...]:
    """Output shape (sans batch) of ``module`` on ``(batch, *input_shape)``
    inputs, via abstract tracing — no FLOPs, no device memory."""
    x = jax.ShapeDtypeStruct((batch,) + tuple(input_shape), jnp.float32)

    def fwd(x):
        params, state = module.init(jax.random.PRNGKey(0))
        out, _ = module.apply(params, state, x, training=False)
        return out

    out = jax.eval_shape(fwd, x)
    return tuple(out.shape[1:])


class KerasLayer:
    """Deferred layer: Keras hyper-params now, core module at build time."""

    def __init__(self, input_shape: Optional[Sequence[int]] = None,
                 name: Optional[str] = None):
        self.input_shape = None if input_shape is None else tuple(input_shape)
        self.name = name or type(self).__name__

    def build(self, input_shape: Tuple[int, ...]) -> Module:
        """Return the core module for inputs of ``input_shape`` (no batch)."""
        raise NotImplementedError(type(self).__name__)

    def output_shape(self, input_shape: Tuple[int, ...]) -> Tuple[int, ...]:
        return infer_output_shape(self.build(input_shape), input_shape)


class InputLayer(KerasLayer):
    def __init__(self, input_shape: Sequence[int], name=None):
        super().__init__(input_shape=input_shape, name=name)

    def build(self, input_shape):
        return nn.Identity()


class _WithActivation(KerasLayer):
    """Helper: wrap a core module with an optional trailing activation."""

    def _maybe_activate(self, core: Module) -> Module:
        act = activation_module(getattr(self, "activation", None))
        if act is None:
            return core
        return nn.Sequential(core, act)


class Dense(_WithActivation):
    """Keras ``Dense`` → ``nn.Linear`` (reference ``DL/nn/keras/Dense.scala``)."""

    def __init__(self, output_dim: int, activation: Optional[str] = None,
                 bias: bool = True, input_shape=None, input_dim=None,
                 name=None):
        if input_dim is not None:
            input_shape = (input_dim,)
        super().__init__(input_shape=input_shape, name=name)
        self.output_dim = output_dim
        self.activation = activation
        self.bias = bias

    def build(self, input_shape):
        return self._maybe_activate(
            nn.Linear(int(input_shape[-1]), self.output_dim,
                      with_bias=self.bias))


class Activation(KerasLayer):
    def __init__(self, activation: str, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.activation = activation

    def build(self, input_shape):
        return activation_module(self.activation) or nn.Identity()


class Dropout(KerasLayer):
    def __init__(self, p: float, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.p = p

    def build(self, input_shape):
        return nn.Dropout(self.p)


class Flatten(KerasLayer):
    def build(self, input_shape):
        return nn.Flatten()


class Reshape(KerasLayer):
    def __init__(self, target_shape: Sequence[int], input_shape=None,
                 name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.target_shape = tuple(target_shape)

    def build(self, input_shape):
        return nn.Reshape(self.target_shape)


class Convolution2D(_WithActivation):
    """Keras ``Convolution2D`` → ``nn.SpatialConvolution``
    (reference ``DL/nn/keras/Convolution2D.scala``)."""

    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation: Optional[str] = None,
                 border_mode: str = "valid",
                 subsample: Tuple[int, int] = (1, 1),
                 dim_ordering: str = "th", bias: bool = True,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.activation = activation
        self.border_mode = border_mode
        self.subsample = subsample
        self.dim_ordering = dim_ordering
        self.bias = bias

    def build(self, input_shape):
        ch_axis = 0 if self.dim_ordering == "th" else -1
        in_ch = int(input_shape[ch_axis])
        # Keras "same" = ceil(in/stride) output with asymmetric padding —
        # exactly XLA's SAME mode, which the core conv selects on pad=-1
        pad = -1 if self.border_mode == "same" else 0
        return self._maybe_activate(nn.SpatialConvolution(
            in_ch, self.nb_filter, self.nb_col, self.nb_row,
            stride_w=self.subsample[1], stride_h=self.subsample[0],
            pad_w=pad, pad_h=pad, with_bias=self.bias,
            format="NCHW" if self.dim_ordering == "th" else "NHWC"))


class Convolution1D(_WithActivation):
    def __init__(self, nb_filter: int, filter_length: int,
                 activation: Optional[str] = None, subsample_length: int = 1,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = nb_filter
        self.filter_length = filter_length
        self.activation = activation
        self.subsample_length = subsample_length

    def build(self, input_shape):
        return self._maybe_activate(nn.TemporalConvolution(
            int(input_shape[-1]), self.nb_filter, self.filter_length,
            stride_w=self.subsample_length))


class _Pooling2D(KerasLayer):
    core_cls: Any = None

    def __init__(self, pool_size: Tuple[int, int] = (2, 2),
                 strides: Optional[Tuple[int, int]] = None,
                 border_mode: str = "valid", dim_ordering: str = "th",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.pool_size = pool_size
        self.strides = strides or pool_size
        self.border_mode = border_mode
        self.dim_ordering = dim_ordering

    def build(self, input_shape):
        fmt = "NCHW" if self.dim_ordering == "th" else "NHWC"
        if self.border_mode == "same":
            # Keras/TF "same" pooling: ceil(in/stride) output, asymmetric
            # padding, padded cells excluded — lax.reduce_window SAME mode
            return self._same_pool(fmt)
        return self.core_cls(
            self.pool_size[1], self.pool_size[0],
            self.strides[1], self.strides[0], 0, 0, format=fmt)

    def _same_pool(self, fmt: str) -> Module:
        ph, pw = self.pool_size
        sh, sw = self.strides
        if fmt == "NCHW":
            dims, strides = (1, 1, ph, pw), (1, 1, sh, sw)
        else:
            dims, strides = (1, ph, pw, 1), (1, sh, sw, 1)
        is_max = self.core_cls is nn.SpatialMaxPooling

        def pool(x):
            from jax import lax
            if is_max:
                return lax.reduce_window(x, -jnp.inf, lax.max, dims,
                                         strides, "SAME")
            total = lax.reduce_window(x, 0.0, lax.add, dims, strides, "SAME")
            count = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims,
                                      strides, "SAME")
            return total / count

        return nn.Lambda(pool)


class MaxPooling2D(_Pooling2D):
    core_cls = nn.SpatialMaxPooling


class AveragePooling2D(_Pooling2D):
    core_cls = nn.SpatialAveragePooling


class GlobalAveragePooling2D(KerasLayer):
    def __init__(self, dim_ordering: str = "th", input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.dim_ordering = dim_ordering

    def build(self, input_shape):
        axes = (2, 3) if self.dim_ordering == "th" else (1, 2)
        return nn.Lambda(lambda x: jnp.mean(x, axis=axes))


class GlobalMaxPooling2D(GlobalAveragePooling2D):
    def build(self, input_shape):
        axes = (2, 3) if self.dim_ordering == "th" else (1, 2)
        return nn.Lambda(lambda x: jnp.max(x, axis=axes))


class ZeroPadding2D(KerasLayer):
    def __init__(self, padding: Tuple[int, int] = (1, 1),
                 dim_ordering: str = "th", input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.padding = padding
        self.dim_ordering = dim_ordering

    def build(self, input_shape):
        ph, pw = self.padding
        if self.dim_ordering == "th":
            pads = ((0, 0), (0, 0), (ph, ph), (pw, pw))
        else:
            pads = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        return nn.Lambda(lambda x: jnp.pad(x, pads))


class BatchNormalization(KerasLayer):
    def __init__(self, epsilon: float = 1e-3, momentum: float = 0.99,
                 dim_ordering: str = "th", input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.epsilon = epsilon
        self.momentum = momentum
        self.dim_ordering = dim_ordering

    def build(self, input_shape):
        if len(input_shape) == 3:  # image: per-channel BN
            n = input_shape[0 if self.dim_ordering == "th" else -1]
            return nn.SpatialBatchNormalization(
                int(n), eps=self.epsilon, momentum=1.0 - self.momentum,
                format="NCHW" if self.dim_ordering == "th" else "NHWC")
        return nn.BatchNormalization(int(input_shape[-1]), eps=self.epsilon,
                                     momentum=1.0 - self.momentum)


class Embedding(KerasLayer):
    def __init__(self, input_dim: int, output_dim: int, input_shape=None,
                 input_length=None, name=None):
        if input_length is not None:
            input_shape = (input_length,)
        super().__init__(input_shape=input_shape, name=name)
        self.input_dim = input_dim
        self.output_dim = output_dim

    def build(self, input_shape):
        return nn.LookupTable(self.input_dim, self.output_dim)


class _Recurrent(KerasLayer):
    cell_cls: Any = None

    def __init__(self, output_dim: int, return_sequences: bool = False,
                 go_backwards: bool = False, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.output_dim = output_dim
        self.return_sequences = return_sequences
        self.go_backwards = go_backwards

    def build(self, input_shape):
        cell = self.cell_cls(int(input_shape[-1]), self.output_dim)
        rec = nn.Recurrent(cell, reverse=self.go_backwards)
        if self.return_sequences:
            return rec
        return nn.Sequential(rec, nn.Lambda(lambda x: x[:, -1]))


class SimpleRNN(_Recurrent):
    cell_cls = nn.RnnCell


class LSTM(_Recurrent):
    cell_cls = nn.LSTM


class GRU(_Recurrent):
    cell_cls = nn.GRU


class Bidirectional(KerasLayer):
    def __init__(self, layer: _Recurrent, merge_mode: str = "concat",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape or layer.input_shape,
                         name=name)
        self.layer = layer
        self.merge_mode = merge_mode

    def build(self, input_shape):
        fwd = self.layer.cell_cls(int(input_shape[-1]),
                                  self.layer.output_dim)
        bwd = self.layer.cell_cls(int(input_shape[-1]),
                                  self.layer.output_dim)
        rec = nn.BiRecurrent(fwd, bwd, merge=self.merge_mode)
        if self.layer.return_sequences:
            return rec
        return nn.Sequential(rec, nn.Lambda(lambda x: x[:, -1]))


class TimeDistributed(KerasLayer):
    def __init__(self, layer: KerasLayer, input_shape=None, name=None):
        super().__init__(input_shape=input_shape or layer.input_shape,
                         name=name)
        self.layer = layer

    def build(self, input_shape):
        inner = self.layer.build(tuple(input_shape[1:]))
        return nn.TimeDistributed(inner)


# ---------------------------------------------------------------- round-2b
# breadth wrappers mapping onto existing core modules (reference
# ``DL/nn/keras/`` has 71 named layers; the deferred-build pattern makes
# each a few lines here)
class RepeatVector(KerasLayer):
    """(N, D) → (N, n, D) (reference ``RepeatVector.scala``)."""

    def __init__(self, n: int, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.n = n

    def build(self, input_shape):
        n = self.n
        return nn.Lambda(lambda x: jnp.repeat(x[:, None], n, axis=1))


class Permute(KerasLayer):
    """Permute non-batch dims, 1-based like Keras (reference
    ``Permute.scala``)."""

    def __init__(self, dims, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.dims = tuple(dims)

    def build(self, input_shape):
        perm = (0,) + tuple(d for d in self.dims)
        return nn.Lambda(lambda x: jnp.transpose(x, perm))


class Cropping2D(KerasLayer):
    def __init__(self, cropping=((0, 0), (0, 0)), dim_ordering="th",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.cropping = cropping
        self.dim_ordering = dim_ordering

    def build(self, input_shape):
        (t, b), (l, r) = self.cropping
        if self.dim_ordering == "th":
            return nn.Cropping2D((t, b), (l, r))
        return nn.Lambda(lambda x: x[:, t:x.shape[1] - b,
                                     l:x.shape[2] - r, :])


class UpSampling2D(KerasLayer):
    def __init__(self, size=(2, 2), dim_ordering="th", input_shape=None,
                 name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.size = tuple(size)
        self.dim_ordering = dim_ordering

    def build(self, input_shape):
        if self.dim_ordering != "th":
            sh, sw = self.size
            return nn.Lambda(lambda x: jnp.repeat(
                jnp.repeat(x, sh, axis=1), sw, axis=2))
        return nn.UpSampling2D(self.size)


class ZeroPadding1D(KerasLayer):
    def __init__(self, padding: int = 1, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.padding = padding

    def build(self, input_shape):
        p = self.padding
        return nn.Lambda(lambda x: jnp.pad(x, ((0, 0), (p, p), (0, 0))))


class MaxPooling1D(KerasLayer):
    def __init__(self, pool_length: int = 2, stride=None,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.pool_length = pool_length
        self.stride = stride or pool_length

    def build(self, input_shape):
        return nn.TemporalMaxPooling(self.pool_length, self.stride)


class GlobalMaxPooling1D(KerasLayer):
    def build(self, input_shape):
        return nn.Lambda(lambda x: jnp.max(x, axis=1))


class GlobalAveragePooling1D(KerasLayer):
    def build(self, input_shape):
        return nn.Lambda(lambda x: jnp.mean(x, axis=1))


class Highway(KerasLayer):
    def __init__(self, activation=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.activation = activation

    def build(self, input_shape):
        act_mod = activation_module(self.activation)
        act = None
        if act_mod is not None:
            # nn.Highway takes the g function itself
            act = lambda x: act_mod.apply({}, {}, x)[0]
        return nn.Highway(int(input_shape[-1]), activation=act)


class MaxoutDense(KerasLayer):
    def __init__(self, output_dim: int, nb_feature: int = 4,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.output_dim = output_dim
        self.nb_feature = nb_feature

    def build(self, input_shape):
        return nn.Maxout(int(input_shape[-1]), self.output_dim,
                         self.nb_feature)


class SeparableConvolution2D(_WithActivation):
    def __init__(self, nb_filter: int, nb_row: int, nb_col: int,
                 activation=None, depth_multiplier: int = 1,
                 dim_ordering="th", input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter, self.nb_row, self.nb_col = nb_filter, nb_row, nb_col
        self.activation = activation
        self.depth_multiplier = depth_multiplier
        self.dim_ordering = dim_ordering

    def build(self, input_shape):
        if self.dim_ordering != "th":
            raise NotImplementedError(
                "SeparableConvolution2D supports dim_ordering='th' only "
                "(the core module is NCHW); transpose inputs or use "
                "nn.SpatialSeparableConvolution directly")
        ch = int(input_shape[0])
        return self._maybe_activate(nn.SpatialSeparableConvolution(
            ch, self.nb_filter, self.depth_multiplier,
            self.nb_col, self.nb_row))


class Merge(KerasLayer):
    """Merge a list of inputs (reference ``Merge.scala``).  Use via
    ``.build(...)`` on table-valued inputs or in a core ``nn.Graph`` —
    NOT inside a Keras ``Sequential`` (its layers are single-tensor;
    shape inference raises to prevent silent miswiring)."""

    def __init__(self, mode: str = "sum", concat_axis: int = -1,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.mode = mode
        self.concat_axis = concat_axis

    def output_shape(self, input_shape):
        raise TypeError(
            "Merge cannot appear in a Keras Sequential (single-tensor "
            "pipeline); apply its .build(...) module to a table of "
            "tensors or use nn.Graph")

    def build(self, input_shape):
        if self.mode == "sum":
            return nn.CAddTable()
        if self.mode == "mul":
            return nn.CMulTable()
        if self.mode == "max":
            return nn.CMaxTable()
        if self.mode == "concat":
            return nn.JoinTable(self.concat_axis)
        if self.mode == "ave":
            return nn.CAveTable()
        raise ValueError(f"unknown merge mode {self.mode!r}")
