"""One-call Keras-model surface over import + training.

Reference: ``pyspark/bigdl/keras/backend.py`` ``KerasModelWrapper`` —
wrap a (compiled) Keras model so fit/evaluate/predict run on the BigDL
backend in one object, converting the Keras loss/optimizer/metrics.

Here the wrapper glues the Keras-1.2 importer
(``interop.keras_format``: JSON definition + HDF5 weights) to the
Keras-style topology's compile/fit/evaluate/predict
(``keras.topology``), so a model exported from Keras trains/serves
with one construction call::

    m = KerasModelWrapper("model.json", "weights.h5",
                          optimizer="adam", loss="categorical_crossentropy")
    m.fit(x, y, nb_epoch=2)
    m.evaluate(x, y)
    m.predict(x)

Loss/optimizer/metrics accept the same string names as
``keras.topology.compile`` (the reference's ``OptimConverter`` role);
without a ``loss`` the model is import-only until :meth:`compile`.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import numpy as np


class KerasModelWrapper:
    """(reference ``KerasModelWrapper``) import + train/evaluate/predict
    in one object."""

    def __init__(self, json_path: str, hdf5_path: Optional[str] = None,
                 optimizer: Union[str, object] = "sgd",
                 loss: Union[str, object, None] = None,
                 metrics: Optional[Sequence] = None):
        from bigdl_tpu.interop.keras_format import (load_keras_hdf5_weights,
                                                    load_keras_json)
        self.bmodel = load_keras_json(json_path)
        if hdf5_path is not None:
            load_keras_hdf5_weights(self.bmodel, hdf5_path)
        if loss is not None:
            self.bmodel.compile(optimizer, loss, metrics)

    # ------------------------------------------------------ delegation
    def compile(self, optimizer, loss, metrics=None) -> "KerasModelWrapper":
        self.bmodel.compile(optimizer, loss, metrics)
        return self

    def fit(self, x, y, batch_size: int = 32, nb_epoch: int = 10,
            validation_data=None, distributed: bool = False
            ) -> "KerasModelWrapper":
        if y is None:
            raise ValueError("fit() needs labels y (the reference's "
                             "y=None form is its RDD[Sample] path, which "
                             "has no equivalent here)")
        self.bmodel.fit(x, y, batch_size=batch_size, nb_epoch=nb_epoch,
                        validation_data=validation_data,
                        distributed=distributed)
        return self

    def evaluate(self, x, y, batch_size: int = 32) -> dict:
        return self.bmodel.evaluate(x, y, batch_size=batch_size)

    def predict(self, x, batch_size: int = 32) -> np.ndarray:
        return self.bmodel.predict(x, batch_size=batch_size)

    def predict_classes(self, x, batch_size: int = 32) -> np.ndarray:
        return self.bmodel.predict_classes(x, batch_size=batch_size)

    def set_weights(self, weights) -> "KerasModelWrapper":
        """Install a flat Keras-order weight list (each layer's
        ``get_weights()`` concatenated)."""
        from bigdl_tpu.interop.keras_format import set_keras_weights
        set_keras_weights(self.bmodel, list(weights))
        return self


def load_model(json_path: str, hdf5_path: Optional[str] = None,
               **compile_kw) -> KerasModelWrapper:
    """Convenience constructor mirroring the reference's
    ``with_bigdl_backend`` role for file-exported models."""
    return KerasModelWrapper(json_path, hdf5_path, **compile_kw)
