"""bigdl_tpu.keras — Keras-1.2-style sugar over the core module system.

Reference: ``DL/nn/keras/`` (71 files, 6,229 LoC) — ``KerasLayer`` wrappers
with shape inference plus a ``Sequential``/``Model`` topology exposing
``compile/fit/evaluate/predict`` (``DL/nn/keras/Topology.scala:55,89,116``).

TPU redesign: the reference re-implements shape inference per layer
(``InferShape``); here a single ``jax.eval_shape`` trace over the wrapped
core module replaces all of it — the XLA abstract interpreter IS the shape
inference engine, so each wrapper only declares how to *build* its core
module once the input shape is known.
"""

from bigdl_tpu.keras.layers import (
    KerasLayer, Dense, Activation, Dropout, Flatten, Reshape,
    Convolution1D, Convolution2D, MaxPooling2D, AveragePooling2D,
    GlobalAveragePooling2D, GlobalMaxPooling2D, ZeroPadding2D,
    BatchNormalization, Embedding, SimpleRNN, LSTM, GRU, Bidirectional,
    TimeDistributed, InputLayer,
    RepeatVector, Permute, Cropping2D, UpSampling2D, ZeroPadding1D,
    MaxPooling1D, GlobalMaxPooling1D, GlobalAveragePooling1D, Highway,
    MaxoutDense, SeparableConvolution2D, Merge,
)
from bigdl_tpu.keras.topology import Sequential, Model
from bigdl_tpu.keras.backend import KerasModelWrapper, load_model

__all__ = [
    "KerasLayer", "Dense", "Activation", "Dropout", "Flatten", "Reshape",
    "Convolution1D", "Convolution2D", "MaxPooling2D", "AveragePooling2D",
    "GlobalAveragePooling2D", "GlobalMaxPooling2D", "ZeroPadding2D",
    "BatchNormalization", "Embedding", "SimpleRNN", "LSTM", "GRU",
    "Bidirectional", "TimeDistributed", "InputLayer",
    "RepeatVector", "Permute", "Cropping2D", "UpSampling2D",
    "ZeroPadding1D", "MaxPooling1D", "GlobalMaxPooling1D",
    "GlobalAveragePooling1D", "Highway", "MaxoutDense",
    "SeparableConvolution2D", "Merge",
    "Sequential", "Model",
    "KerasModelWrapper", "load_model",
]
