"""Keras-style ``Sequential``/``Model`` topology with compile/fit/evaluate/
predict.

Reference: ``DL/nn/keras/Topology.scala`` — ``compile:55`` resolves
string-named optimizer/loss/metrics, ``fit:89`` wraps the Optimizer,
``evaluate:116``/``predict`` wrap Evaluator/Predictor.  The pyspark mirror
is ``pyspark/bigdl/keras/backend.py`` (``KerasModelWrapper``).

Here the topology compiles down to the core functional stack: building a
``Sequential`` walks the deferred ``KerasLayer``s forward, inferring each
input shape with ``jax.eval_shape`` (see ``keras/layers.py``), and ``fit``
drives ``LocalOptimizer``/``DistriOptimizer`` on an in-memory ``DataSet``.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import numpy as np

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.keras.layers import KerasLayer
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.predictor import Predictor

_OPTIMIZERS = {
    "sgd": lambda: optim.SGD(learning_rate=0.01),
    "adam": lambda: optim.Adam(),
    "adagrad": lambda: optim.Adagrad(),
    "adadelta": lambda: optim.Adadelta(),
    "adamax": lambda: optim.Adamax(),
    "rmsprop": lambda: optim.RMSprop(),
}

_LOSSES = {
    # Keras contract: probability inputs (pair with activation="softmax"),
    # one-hot OR integer targets (CategoricalCrossEntropy handles both)
    "categorical_crossentropy": nn.CategoricalCrossEntropy,
    "sparse_categorical_crossentropy": nn.CategoricalCrossEntropy,
    "mse": nn.MSECriterion, "mean_squared_error": nn.MSECriterion,
    "mae": nn.AbsCriterion, "mean_absolute_error": nn.AbsCriterion,
    "binary_crossentropy": nn.BCECriterion,
    "hinge": nn.MarginCriterion,
    # Keras kld takes PROBABILITY predictions (reference
    # pyspark/bigdl/keras/optimization.py pairs it with
    # KullbackLeiblerDivergenceCriterion); DistKLDivCriterion would
    # require log-probability inputs.
    "kld": nn.KullbackLeiblerDivergenceCriterion,
    "kullback_leibler_divergence": nn.KullbackLeiblerDivergenceCriterion,
}

_METRICS = {
    "accuracy": optim.Top1Accuracy, "acc": optim.Top1Accuracy,
    "top5": optim.Top5Accuracy,
    "mae": optim.MAE,
    "loss": optim.Loss,
}


def _resolve(table, value, kind):
    if isinstance(value, str):
        try:
            return table[value.lower()]()
        except KeyError:
            raise ValueError(f"unknown {kind} {value!r}") from None
    return value


class _Topology:
    """Shared compile/fit/evaluate/predict machinery."""

    def __init__(self, name: Optional[str] = None):
        self.name = name or type(self).__name__
        self.optim_method = None
        self.criterion = None
        self.metrics: Sequence = ()
        self._params = None
        self._mstate = None

    # ------------------------------------------------------------ compile
    def compile(self, optimizer: Union[str, Any], loss: Union[str, Any],
                metrics: Optional[Sequence] = None) -> "_Topology":
        """Resolve optimizer/loss/metrics (reference ``Topology.scala:55``)."""
        self.optim_method = _resolve(_OPTIMIZERS, optimizer, "optimizer")
        self.criterion = _resolve(_LOSSES, loss, "loss")
        if isinstance(self.criterion, type):
            self.criterion = self.criterion()
        self.metrics = [_resolve(_METRICS, m, "metric")
                        for m in (metrics or [])]
        return self

    # ---------------------------------------------------------- core hook
    def core_module(self) -> Module:
        raise NotImplementedError

    @staticmethod
    def _to_dataset(x, y, batch_size, drop_remainder=True):
        x = np.asarray(x)
        y = None if y is None else np.asarray(y)
        samples = [Sample(x[i], None if y is None else y[i])
                   for i in range(len(x))]
        return DataSet.array(samples) >> SampleToMiniBatch(
            batch_size, drop_remainder=drop_remainder)

    # ---------------------------------------------------------------- fit
    def fit(self, x, y, batch_size: int = 32, nb_epoch: int = 10,
            validation_data: Optional[Tuple] = None,
            distributed: bool = False) -> "_Topology":
        """Train (reference ``Topology.scala:89``; pyspark
        ``keras/backend.py`` fit)."""
        if self.criterion is None:
            raise RuntimeError("call compile(...) before fit(...)")
        model = self.core_module()
        train_set = self._to_dataset(x, y, batch_size)
        cls = optim.DistriOptimizer if distributed else optim.LocalOptimizer
        optimizer = (cls(model, train_set, self.criterion)
                     .set_optim_method(self.optim_method)
                     .set_end_when(optim.max_epoch(nb_epoch)))
        if validation_data is not None:
            vx, vy = validation_data
            val_set = self._to_dataset(vx, vy, batch_size,
                                       drop_remainder=False)
            optimizer.set_validation(
                optim.every_epoch(), val_set,
                self.metrics or [optim.Loss(self.criterion)])
        optimizer.optimize()
        self._params = model._params
        self._mstate = model._state
        return self

    # ----------------------------------------------------------- evaluate
    def evaluate(self, x, y, batch_size: int = 32) -> dict:
        """Metric name → value (reference ``Topology.scala:116``)."""
        model = self.core_module()
        val_set = self._to_dataset(x, y, batch_size, drop_remainder=False)
        from bigdl_tpu.optim.predictor import Evaluator
        ev = Evaluator(model, params=self._params, state=self._mstate)
        methods = self.metrics or [optim.Loss(self.criterion)]
        results = ev.evaluate(val_set, methods)
        return {name: r.result for name, r in results.items()}

    # ------------------------------------------------------------ predict
    def predict(self, x, batch_size: int = 32) -> np.ndarray:
        model = self.core_module()
        pred = Predictor(model, params=self._params, state=self._mstate,
                         batch_size=batch_size)
        return pred.predict(np.asarray(x))

    def predict_classes(self, x, batch_size: int = 32) -> np.ndarray:
        return np.argmax(self.predict(x, batch_size), axis=-1)


class Sequential(_Topology):
    """Keras Sequential: stack of deferred layers, built via eval_shape."""

    def __init__(self, layers: Optional[Sequence[KerasLayer]] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self.layers: list = []
        self._core: Optional[Module] = None
        for l in layers or []:
            self.add(l)

    def add(self, layer: KerasLayer) -> "Sequential":
        if not self.layers and layer.input_shape is None:
            raise ValueError(
                "first layer needs input_shape= (Keras 1.2 convention)")
        self.layers.append(layer)
        self._core = None  # invalidate built core
        return self

    def build(self) -> Module:
        shape = self.layers[0].input_shape
        core = nn.Sequential()
        for layer in self.layers:
            if layer.input_shape is not None:
                shape = layer.input_shape
            mod = layer.build(shape)
            from bigdl_tpu.keras.layers import infer_output_shape
            shape = infer_output_shape(mod, shape)
            core.add(mod)
        self._core = core
        return core

    def core_module(self) -> Module:
        if self._core is None:
            self.build()
        if self._params is not None:
            self._core._params = self._params
            self._core._state = self._mstate
        return self._core

    @property
    def output_shape(self) -> Tuple[int, ...]:
        shape = self.layers[0].input_shape
        for layer in self.layers:
            shape = layer.output_shape(shape)
        return (None,) + tuple(shape)


class Model(_Topology):
    """Keras functional ``Model``: wraps an already-built core module or
    ``nn.Graph`` (reference ``Model`` in ``Topology.scala``)."""

    def __init__(self, module: Module, name: Optional[str] = None):
        super().__init__(name)
        self._core = module

    def core_module(self) -> Module:
        if self._params is not None:
            self._core._params = self._params
            self._core._state = self._mstate
        return self._core
