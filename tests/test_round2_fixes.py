"""Round-2 regression tests: ADVICE fixes + multi-host-safe validation.

Covers: shared-module state threading in Graph, set_validation batch_size,
the data-only npz checkpoint format, DistriOptimizer's sharded eval
forward (incl. ragged last batch), and donation safety of warm starts.
"""

import json
import zipfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.nn.graph import Input, Graph
from bigdl_tpu.utils import checkpoint as ckpt


def _samples(n, shape=(784,), classes=10, seed=0):
    rng = np.random.default_rng(seed)
    return [Sample(rng.normal(0, 1, shape).astype(np.float32),
                   np.int32(i % classes)) for i in range(n)]


def small_mlp():
    return (nn.Sequential()
            .add(nn.Linear(784, 32)).add(nn.ReLU())
            .add(nn.Linear(32, 10)).add(nn.LogSoftMax()))


class TestGraphSharedState:
    def test_shared_bn_state_threads_through_occurrences(self):
        """A BN module used at two graph positions must apply its running-
        stat updates sequentially (second occurrence sees the first's
        update), not last-writer-wins."""
        bn = nn.SpatialBatchNormalization(4)
        inp = Input()
        h1 = bn(inp)
        h2 = bn(h1)
        g = Graph([inp], [h2])
        params, state = g.init(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.default_rng(0)
                        .normal(3, 2, (8, 4, 5, 5)).astype(np.float32))
        _, new_state = g.apply(params, state, x, training=True)
        (key,) = {k for k in new_state if "batchnorm" in k.lower()
                  or True}  # single shared key
        # manual: two sequential applications of the same module
        p_bn, s_bn = bn.init(jax.random.PRNGKey(0))
        s_after1 = bn.apply(p_bn, s_bn, x, training=True)[1]
        y1 = bn.apply(p_bn, s_bn, x, training=True)[0]
        s_after2 = bn.apply(p_bn, s_after1, y1, training=True)[1]
        got = jax.tree_util.tree_leaves(new_state)
        want = jax.tree_util.tree_leaves(s_after2)
        for a, b in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-5)


class TestSetValidationBatchSize:
    def test_batch_size_rebatches_sample_dataset(self):
        train = DataSet.array(_samples(64)) >> SampleToMiniBatch(16)
        val = DataSet.array(_samples(40, seed=1))  # UNBATCHED samples
        model = small_mlp()
        opt = (optim.LocalOptimizer(model, train, nn.ClassNLLCriterion())
               .set_optim_method(optim.SGD(learning_rate=0.01))
               .set_end_when(optim.max_epoch(1))
               .set_validation(optim.every_epoch(), val,
                               [optim.Top1Accuracy()], batch_size=16))
        opt.optimize()
        assert "score" in opt.state  # validation actually ran


class TestNpzCheckpoint:
    def test_round_trip_and_data_only(self, tmp_path):
        params = {"layer": {"weight": np.arange(6, dtype=np.float32)
                            .reshape(2, 3),
                            "bias": np.zeros(2, np.float32)}}
        ostate = {"m": {"layer": {"weight": np.ones((2, 3), np.float32),
                                  "bias": np.ones(2, np.float32)}},
                  "step": 7}
        f = ckpt.save_checkpoint(str(tmp_path / "ck"), params,
                                 model_state={"bn": {"mean": np.ones(3)}},
                                 opt_state=ostate,
                                 driver_state={"neval": 7, "loss": 0.5},
                                 neval=7)
        blob = ckpt.load_checkpoint(f)
        np.testing.assert_array_equal(
            np.asarray(blob["params"]["layer"]["weight"]),
            params["layer"]["weight"])
        assert blob["opt_state"]["step"] == 7
        assert blob["driver_state"]["loss"] == 0.5
        # the file is a plain npz zip — no pickle opcode stream anywhere
        assert zipfile.is_zipfile(f)
        with np.load(f, allow_pickle=False) as z:
            assert "__meta__" in z.files  # loads fine with pickle OFF

    def test_bfloat16_leaves_round_trip(self, tmp_path):
        p = {"w": jnp.arange(4, dtype=jnp.bfloat16)}
        f = ckpt.save_checkpoint(str(tmp_path / "bf"), p, neval=0)
        blob = ckpt.load_checkpoint(f)
        w = blob["params"]["w"]
        assert w.dtype == jnp.bfloat16
        np.testing.assert_array_equal(np.asarray(w, np.float32),
                                      [0, 1, 2, 3])

    def test_tuple_structure_preserved(self, tmp_path):
        p = {"pair": (np.zeros(2), [np.ones(3), 5])}
        f = ckpt.save_checkpoint(str(tmp_path / "t"), p, neval=0)
        blob = ckpt.load_checkpoint(f)
        assert isinstance(blob["params"]["pair"], tuple)
        assert isinstance(blob["params"]["pair"][1], list)
        assert blob["params"]["pair"][1][1] == 5


class TestDistriEval:
    def test_sharded_eval_matches_local(self, devices):
        train = DataSet.array(_samples(64)) >> SampleToMiniBatch(16)
        # 40 samples, batch 16, keep remainder → last batch ragged (8)
        val = (DataSet.array(_samples(40, seed=2))
               >> SampleToMiniBatch(16, drop_remainder=False))
        model = small_mlp()
        opt = (optim.DistriOptimizer(model, train, nn.ClassNLLCriterion())
               .set_optim_method(optim.SGD(learning_rate=0.01))
               .set_end_when(optim.max_iteration(1))
               .set_validation(optim.every_epoch(), val,
                               [optim.Top1Accuracy(), optim.Loss()]))
        opt.optimize()
        params, mstate = opt.model._params, opt.model._state
        res = opt.evaluate_with(params, mstate)
        # compare against an unsharded forward
        correct = total = 0
        for b in val.data(train=False):
            out, _ = model.apply(params, mstate, jnp.asarray(b.input),
                                 training=False)
            correct += int((jnp.argmax(out, -1)
                            == jnp.asarray(b.target)).sum())
            total += b.size()
        assert res["Top1Accuracy"].result == pytest.approx(correct / total)
