"""Golden-parity replay: every fixture in tests/fixtures/data is checked
forward AND backward against the JAX layer.

The oracle is torch-CPU float64 (see tests/fixtures/generate_fixtures.py)
— the analog of the reference's Torch7 golden tests (``TEST/torch/``,
driven by ``TH.scala:35-44``).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn

DATA_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "data")

# fixture name -> module factory.  The module's apply(params, state, x)
# must reproduce the recorded torch computation; ``s_*`` fixture entries
# feed the state dict (no grads), everything else is a trained param.
MODULES = {
    "volumetric_convolution": lambda: nn.VolumetricConvolution(
        3, 4, 2, 3, 3, 1, 2, 2, 0, 1, 1),
    "volumetric_max_pooling": lambda: nn.VolumetricMaxPooling(2, 2, 2),
    "volumetric_avg_pooling": lambda: nn.VolumetricAveragePooling(2, 2, 2),
    "volumetric_full_convolution": lambda: nn.VolumetricFullConvolution(
        4, 3, 2, 3, 3, 2, 2, 2, 0, 1, 1, 1, 0, 0),
    "spatial_dilated_convolution": lambda: nn.SpatialDilatedConvolution(
        3, 5, 3, 3, 1, 1, 2, 2, 2, 2),
    "spatial_separable_convolution": lambda: nn.SpatialSeparableConvolution(
        3, 4, 2, 3, 3, 1, 1, 1, 1),
    "locally_connected_2d": lambda: nn.LocallyConnected2D(
        3, 6, 6, 4, 3, 3),
    "locally_connected_1d": lambda: nn.LocallyConnected1D(7, 5, 4, 3, 2),
    "spatial_within_channel_lrn": lambda: nn.SpatialWithinChannelLRN(5),
    "upsampling_2d": lambda: nn.UpSampling2D((2, 3)),
    "upsampling_3d": lambda: nn.UpSampling3D((2, 2, 2)),
    "resize_bilinear_align": lambda: nn.ResizeBilinear(
        8, 9, align_corners=True),
    "temporal_max_pooling": lambda: nn.TemporalMaxPooling(2, 2),
    "temporal_convolution": lambda: nn.TemporalConvolution(5, 6, 3, 2),
    # round-2b batch
    "spatial_convolution_pad_stride": lambda: nn.SpatialConvolution(
        3, 5, 3, 3, 2, 2, 1, 1),
    "spatial_convolution_grouped": lambda: nn.SpatialConvolution(
        4, 6, 3, 3, n_group=2),
    "spatial_full_convolution": lambda: nn.SpatialFullConvolution(
        4, 3, 3, 3, 2, 2, 1, 1, 1, 1),
    "spatial_max_pooling_ceil": lambda: nn.SpatialMaxPooling(
        3, 3, 2, 2, ceil_mode=True),
    "spatial_avg_pooling_pad": lambda: nn.SpatialAveragePooling(
        3, 3, 2, 2, 1, 1, count_include_pad=True),
    "linear": lambda: nn.Linear(7, 5),
    "prelu": lambda: nn.PReLU(),
    "elu": lambda: nn.ELU(),
    "softplus": lambda: nn.SoftPlus(),
    "hard_tanh": lambda: nn.HardTanh(),
    "spatial_cross_map_lrn": lambda: nn.SpatialCrossMapLRN(
        5, 1.0, 0.75, 1.0),
    "spatial_batch_norm_eval": lambda: nn.SpatialBatchNormalization(4),
}


def _recurrent(cell_fn):
    def make():
        from bigdl_tpu.nn import recurrent as R
        return nn.Recurrent(cell_fn(R))
    return make


# round-3 batch: recurrent cells, BN TRAINING mode (ns_* entries compare
# the post-step running stats), embeddings, activation sweep
MODULES.update({
    "recurrent_lstm": _recurrent(lambda R: R.LSTM(4, 6)),
    "recurrent_lstm_native_oracle": _recurrent(lambda R: R.LSTM(3, 5)),
    "recurrent_gru": _recurrent(lambda R: R.GRU(4, 6)),
    "recurrent_lstm_peephole": _recurrent(lambda R: R.LSTMPeephole(3, 5)),
    "recurrent_rnn_tanh": _recurrent(lambda R: R.RnnCell(4, 5)),
    "spatial_batch_norm_train": lambda: nn.SpatialBatchNormalization(3),
    "batch_norm_1d_train": lambda: nn.BatchNormalization(6),
    "batch_norm_1d_eval": lambda: nn.BatchNormalization(6),
    "lookup_table": lambda: nn.LookupTable(10, 6),
    "act_softmax": lambda: nn.SoftMax(),
    "act_log_softmax": lambda: nn.LogSoftMax(),
    "act_sigmoid": lambda: nn.Sigmoid(),
    "act_tanh": lambda: nn.Tanh(),
    "act_relu6": lambda: nn.ReLU6(),
    "act_leaky_relu": lambda: nn.LeakyReLU(0.01),
    "act_softsign": lambda: nn.SoftSign(),
    "act_softshrink": lambda: nn.SoftShrink(0.5),
    "act_hardshrink": lambda: nn.HardShrink(0.5),
    "act_tanhshrink": lambda: nn.TanhShrink(),
    "act_log_sigmoid": lambda: nn.LogSigmoid(),
    "act_gelu": lambda: nn.GELU(),
    "act_softmin": lambda: nn.SoftMin(),
})

def _bi_recurrent():
    from bigdl_tpu.nn import recurrent as R
    return nn.BiRecurrent(R.LSTM(3, 5))


# fixtures whose torch-side params are stored FLAT; map to the module's
# nested tree (and back, for gradient comparison)
RESTRUCTURE = {
    "bi_recurrent_lstm": (
        lambda p: {"fwd": {"weight": p["fwd_weight"],
                           "bias": p["fwd_bias"]},
                   "bwd": {"weight": p["bwd_weight"],
                           "bias": p["bwd_bias"]}},
        lambda t: {"fwd_weight": t["fwd"]["weight"],
                   "fwd_bias": t["fwd"]["bias"],
                   "bwd_weight": t["bwd"]["weight"],
                   "bwd_bias": t["bwd"]["bias"]}),
}

# round-3b: tensor-math layer family (nn/tensor_extras.py)
MODULES.update({
    "layer_norm": lambda: nn.LayerNorm(8),
    "multi_head_attention": lambda: nn.MultiHeadAttention(8, 2),
    "multi_head_attention_causal":
        lambda: nn.MultiHeadAttention(8, 2, causal=True),
    "bi_recurrent_lstm": _bi_recurrent,
    "conv_lstm_peephole": _recurrent(
        lambda R: R.ConvLSTMPeephole(2, 4, kernel=3, spatial=(5, 5),
                                     with_peephole=False)),
    "conv_lstm_with_peephole": _recurrent(
        lambda R: R.ConvLSTMPeephole(2, 4, kernel=3, spatial=(5, 5),
                                     with_peephole=True)),
    "cosine_layer": lambda: nn.Cosine(4, 6),
    "euclidean_layer": lambda: nn.Euclidean(4, 6),
    "maxout": lambda: nn.Maxout(4, 3, 2),
    "highway": lambda: nn.Highway(5),
    "add_layer": lambda: nn.Add(6),
    "mul_layer": lambda: nn.Mul(),
    "cmul": lambda: nn.CMul((1, 6)),
    "cadd": lambda: nn.CAdd((1, 6)),
    "power": lambda: nn.Power(1.5, 2.0, 1.0),
    "clamp": lambda: nn.Clamp(-0.5, 0.8),
})

TOL = dict(rtol=2e-4, atol=2e-5)


def _load(name):
    path = os.path.join(DATA_DIR, f"{name}.npz")
    if not os.path.exists(path):
        pytest.skip(f"fixture {name} not generated")
    z = np.load(path)
    params = {k[2:]: z[k] for k in z.files if k.startswith("p_")}
    dparams = {k[3:]: z[k] for k in z.files if k.startswith("dp_")}
    state = {k[2:]: z[k] for k in z.files if k.startswith("s_")}
    new_state = {k[3:]: z[k] for k in z.files if k.startswith("ns_")}
    dx = z["dx"] if "dx" in z.files else None
    return z["x"], params, state, z["out"], dx, dparams, new_state


@pytest.mark.parametrize("name", sorted(MODULES))
def test_fixture_parity(name):
    x, params, state, want_out, want_dx, want_dp, want_ns = _load(name)
    mod = MODULES[name]()
    training = bool(want_ns)  # ns_* entries = training-mode fixture
    nest_flatten = RESTRUCTURE.get(name)
    if nest_flatten:
        params = nest_flatten[0](params)
    jparams = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jnp.float32), params)
    jstate = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jnp.float32), state)
    int_input = np.issubdtype(np.asarray(x).dtype, np.integer)
    jx = jnp.asarray(x) if int_input else jnp.asarray(x, jnp.float32)

    out, new_state = mod.apply(jparams, jstate, jx, training=training)
    np.testing.assert_allclose(np.asarray(out), want_out, **TOL,
                               err_msg=f"{name}: forward mismatch")
    for k, want in want_ns.items():
        np.testing.assert_allclose(
            np.asarray(new_state[k]), want, **TOL,
            err_msg=f"{name}: updated state {k} mismatch")

    if want_dx is None and not want_dp:
        return  # forward-only oracle

    def loss(p, xx):
        y, _ = mod.apply(p, jstate, xx, training=training)
        return jnp.sum(y)

    if int_input:
        dp = jax.grad(loss)(jparams, jx)
    else:
        dp, dx = jax.grad(loss, argnums=(0, 1))(jparams, jx)
        if want_dx is not None:
            np.testing.assert_allclose(np.asarray(dx), want_dx, **TOL,
                                       err_msg=f"{name}: grad_input "
                                               "mismatch")
    if nest_flatten:
        dp = nest_flatten[1](dp)
    for k, want in want_dp.items():
        np.testing.assert_allclose(np.asarray(dp[k]), want, **TOL,
                                   err_msg=f"{name}: grad_{k} mismatch")


# -------------------------------------------------------------- criterions
def _td_mse():
    c = nn.TimeDistributedCriterion(nn.MSECriterion())
    return c


CRITERIONS = {
    "mse": lambda: nn.MSECriterion(),
    "abs": lambda: nn.AbsCriterion(),
    "bce": lambda: nn.BCECriterion(),
    "smooth_l1": lambda: nn.SmoothL1Criterion(),
    "class_nll_weighted": lambda: nn.ClassNLLCriterion(
        weights=jnp.asarray([0.5, 1.0, 2.0, 1.5])),
    "dist_kl": lambda: nn.DistKLDivCriterion(),
    "soft_margin": lambda: nn.SoftMarginCriterion(),
    "hinge_embedding": lambda: nn.HingeEmbeddingCriterion(margin=1.0),
    "multilabel_soft_margin": lambda: nn.MultiLabelSoftMarginCriterion(),
    # round-3 batch: remaining criterion families
    "cross_entropy": lambda: nn.CrossEntropyCriterion(),
    "class_nll_ignore": lambda: nn.ClassNLLCriterion(ignore_index=-100),
    "bce_logits": lambda: nn.BCEWithLogitsCriterion(),
    "multilabel_margin": lambda: nn.MultiLabelMarginCriterion(),
    "multi_margin_p1": lambda: nn.MultiMarginCriterion(p=1),
    "multi_margin_p2": lambda: nn.MultiMarginCriterion(p=2),
    "margin": lambda: nn.MarginCriterion(),
    "poisson": lambda: nn.PoissonCriterion(),
    "mape": lambda: nn.MeanAbsolutePercentageCriterion(),
    "msle": lambda: nn.MeanSquaredLogarithmicCriterion(),
    "kl_probs": lambda: nn.KullbackLeiblerDivergenceCriterion(),
    "cosine_distance": lambda: nn.CosineDistanceCriterion(),
    "cosine_proximity": lambda: nn.CosineProximityCriterion(),
    "dot_product": lambda: nn.DotProductCriterion(),
    "l1_cost": lambda: nn.L1Cost(),
    "dice": lambda: nn.DiceCoefficientCriterion(epsilon=1.0),
    "pg": lambda: nn.PGCriterion(),
    "categorical_ce": lambda: nn.CategoricalCrossEntropy(),
    "softmax_with": lambda: nn.SoftmaxWithCriterion(),
    "time_distributed_mse": _td_mse,
    "class_simplex": lambda: nn.ClassSimplexCriterion(4),
}


@pytest.mark.parametrize("name", sorted(CRITERIONS))
def test_criterion_fixture_parity(name):
    path = os.path.join(DATA_DIR, f"crit_{name}.npz")
    if not os.path.exists(path):
        pytest.skip("fixture not generated")
    z = np.load(path)
    crit = CRITERIONS[name]()
    x = jnp.asarray(z["x"], jnp.float32)
    t = jnp.asarray(z["target"])
    loss = crit.apply(x, t)
    np.testing.assert_allclose(float(loss), float(z["loss"]), rtol=2e-4,
                               atol=1e-6, err_msg=f"{name}: loss mismatch")
    dx = jax.grad(lambda xx: crit.apply(xx, t))(x)
    np.testing.assert_allclose(np.asarray(dx), z["dx"], **TOL,
                               err_msg=f"{name}: grad mismatch")


# ------------------------------------------------ pair-input modules
MODULES2 = {
    "bilinear": lambda: nn.Bilinear(3, 4, 5),
    "mm": lambda: nn.MM(),
    "dot_product": lambda: nn.DotProduct(),
    "pairwise_distance": lambda: nn.PairwiseDistance(norm=2),
    "cosine_distance": lambda: nn.CosineDistance(),
}


@pytest.mark.parametrize("name", sorted(MODULES2))
def test_pair_module_fixture_parity(name):
    path = os.path.join(DATA_DIR, f"mod2_{name}.npz")
    if not os.path.exists(path):
        pytest.skip("fixture not generated")
    z = np.load(path)
    mod = MODULES2[name]()
    params = {k[2:]: jnp.asarray(z[k], jnp.float32)
              for k in z.files if k.startswith("p_")}
    x1 = jnp.asarray(z["x1"], jnp.float32)
    x2 = jnp.asarray(z["x2"], jnp.float32)
    out, _ = mod.apply(params, {}, (x1, x2))
    np.testing.assert_allclose(np.asarray(out), z["out"], **TOL,
                               err_msg=f"{name}: forward mismatch")

    def loss(p, a, b):
        y, _ = mod.apply(p, {}, (a, b))
        return jnp.sum(y)

    dp, d1, d2 = jax.grad(loss, argnums=(0, 1, 2))(params, x1, x2)
    np.testing.assert_allclose(np.asarray(d1), z["dx1"], **TOL,
                               err_msg=f"{name}: grad x1 mismatch")
    np.testing.assert_allclose(np.asarray(d2), z["dx2"], **TOL,
                               err_msg=f"{name}: grad x2 mismatch")
    for k in params:
        np.testing.assert_allclose(np.asarray(dp[k]), z[f"dp_{k}"], **TOL,
                                   err_msg=f"{name}: grad_{k} mismatch")


# ---------------------------------------------- pair-input criterions
CRITERIONS2 = {
    "margin_ranking": lambda: nn.MarginRankingCriterion(margin=1.0),
    "cosine_embedding": lambda: nn.CosineEmbeddingCriterion(margin=0.2),
    "l1_hinge_embedding": lambda: nn.L1HingeEmbeddingCriterion(margin=1.0),
    "kld_vae": lambda: nn.KLDCriterion(),
    "gaussian": lambda: nn.GaussianCriterion(),
}


@pytest.mark.parametrize("name", sorted(CRITERIONS2))
def test_pair_criterion_fixture_parity(name):
    path = os.path.join(DATA_DIR, f"crit2_{name}.npz")
    if not os.path.exists(path):
        pytest.skip("fixture not generated")
    z = np.load(path)
    crit = CRITERIONS2[name]()
    x1 = jnp.asarray(z["x1"], jnp.float32)
    x2 = jnp.asarray(z["x2"], jnp.float32)
    t = jnp.asarray(z["target"])
    loss = crit.apply((x1, x2), t)
    np.testing.assert_allclose(float(loss), float(z["loss"]), rtol=2e-4,
                               err_msg=f"{name}: loss mismatch")
    d1, d2 = jax.grad(lambda a, b: crit.apply((a, b), t),
                      argnums=(0, 1))(x1, x2)
    np.testing.assert_allclose(np.asarray(d1), z["dx1"], **TOL,
                               err_msg=f"{name}: grad x1 mismatch")
    np.testing.assert_allclose(np.asarray(d2), z["dx2"], **TOL,
                               err_msg=f"{name}: grad x2 mismatch")
