"""Golden-parity replay: every fixture in tests/fixtures/data is checked
forward AND backward against the JAX layer.

The oracle is torch-CPU float64 (see tests/fixtures/generate_fixtures.py)
— the analog of the reference's Torch7 golden tests (``TEST/torch/``,
driven by ``TH.scala:35-44``).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn

DATA_DIR = os.path.join(os.path.dirname(__file__), "fixtures", "data")

# fixture name -> module factory.  The module's apply(params, state, x)
# must reproduce the recorded torch computation; ``s_*`` fixture entries
# feed the state dict (no grads), everything else is a trained param.
MODULES = {
    "volumetric_convolution": lambda: nn.VolumetricConvolution(
        3, 4, 2, 3, 3, 1, 2, 2, 0, 1, 1),
    "volumetric_max_pooling": lambda: nn.VolumetricMaxPooling(2, 2, 2),
    "volumetric_avg_pooling": lambda: nn.VolumetricAveragePooling(2, 2, 2),
    "volumetric_full_convolution": lambda: nn.VolumetricFullConvolution(
        4, 3, 2, 3, 3, 2, 2, 2, 0, 1, 1, 1, 0, 0),
    "spatial_dilated_convolution": lambda: nn.SpatialDilatedConvolution(
        3, 5, 3, 3, 1, 1, 2, 2, 2, 2),
    "spatial_separable_convolution": lambda: nn.SpatialSeparableConvolution(
        3, 4, 2, 3, 3, 1, 1, 1, 1),
    "locally_connected_2d": lambda: nn.LocallyConnected2D(
        3, 6, 6, 4, 3, 3),
    "locally_connected_1d": lambda: nn.LocallyConnected1D(7, 5, 4, 3, 2),
    "spatial_within_channel_lrn": lambda: nn.SpatialWithinChannelLRN(5),
    "upsampling_2d": lambda: nn.UpSampling2D((2, 3)),
    "upsampling_3d": lambda: nn.UpSampling3D((2, 2, 2)),
    "resize_bilinear_align": lambda: nn.ResizeBilinear(
        8, 9, align_corners=True),
    "temporal_max_pooling": lambda: nn.TemporalMaxPooling(2, 2),
    "temporal_convolution": lambda: nn.TemporalConvolution(5, 6, 3, 2),
    # round-2b batch
    "spatial_convolution_pad_stride": lambda: nn.SpatialConvolution(
        3, 5, 3, 3, 2, 2, 1, 1),
    "spatial_convolution_grouped": lambda: nn.SpatialConvolution(
        4, 6, 3, 3, n_group=2),
    "spatial_full_convolution": lambda: nn.SpatialFullConvolution(
        4, 3, 3, 3, 2, 2, 1, 1, 1, 1),
    "spatial_max_pooling_ceil": lambda: nn.SpatialMaxPooling(
        3, 3, 2, 2, ceil_mode=True),
    "spatial_avg_pooling_pad": lambda: nn.SpatialAveragePooling(
        3, 3, 2, 2, 1, 1, count_include_pad=True),
    "linear": lambda: nn.Linear(7, 5),
    "prelu": lambda: nn.PReLU(),
    "elu": lambda: nn.ELU(),
    "softplus": lambda: nn.SoftPlus(),
    "hard_tanh": lambda: nn.HardTanh(),
    "spatial_cross_map_lrn": lambda: nn.SpatialCrossMapLRN(
        5, 1.0, 0.75, 1.0),
    "spatial_batch_norm_eval": lambda: nn.SpatialBatchNormalization(4),
}

TOL = dict(rtol=2e-4, atol=2e-5)


def _load(name):
    path = os.path.join(DATA_DIR, f"{name}.npz")
    if not os.path.exists(path):
        pytest.skip(f"fixture {name} not generated")
    z = np.load(path)
    params = {k[2:]: z[k] for k in z.files if k.startswith("p_")}
    dparams = {k[3:]: z[k] for k in z.files if k.startswith("dp_")}
    state = {k[2:]: z[k] for k in z.files if k.startswith("s_")}
    return z["x"], params, state, z["out"], z["dx"], dparams


@pytest.mark.parametrize("name", sorted(MODULES))
def test_fixture_parity(name):
    x, params, state, want_out, want_dx, want_dp = _load(name)
    mod = MODULES[name]()
    jparams = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jnp.float32), params)
    jstate = jax.tree_util.tree_map(
        lambda a: jnp.asarray(a, jnp.float32), state)
    jx = jnp.asarray(x, jnp.float32)

    out, _ = mod.apply(jparams, jstate, jx, training=False)
    np.testing.assert_allclose(np.asarray(out), want_out, **TOL,
                               err_msg=f"{name}: forward mismatch")

    def loss(p, xx):
        y, _ = mod.apply(p, jstate, xx, training=False)
        return jnp.sum(y)

    dp, dx = jax.grad(loss, argnums=(0, 1))(jparams, jx)
    np.testing.assert_allclose(np.asarray(dx), want_dx, **TOL,
                               err_msg=f"{name}: grad_input mismatch")
    for k, want in want_dp.items():
        np.testing.assert_allclose(np.asarray(dp[k]), want, **TOL,
                                   err_msg=f"{name}: grad_{k} mismatch")


# -------------------------------------------------------------- criterions
CRITERIONS = {
    "mse": lambda: nn.MSECriterion(),
    "abs": lambda: nn.AbsCriterion(),
    "bce": lambda: nn.BCECriterion(),
    "smooth_l1": lambda: nn.SmoothL1Criterion(),
    "class_nll_weighted": lambda: nn.ClassNLLCriterion(
        weights=jnp.asarray([0.5, 1.0, 2.0, 1.5])),
    "dist_kl": lambda: nn.DistKLDivCriterion(),
    "soft_margin": lambda: nn.SoftMarginCriterion(),
    "hinge_embedding": lambda: nn.HingeEmbeddingCriterion(margin=1.0),
    "multilabel_soft_margin": lambda: nn.MultiLabelSoftMarginCriterion(),
}


@pytest.mark.parametrize("name", sorted(CRITERIONS))
def test_criterion_fixture_parity(name):
    path = os.path.join(DATA_DIR, f"crit_{name}.npz")
    if not os.path.exists(path):
        pytest.skip("fixture not generated")
    z = np.load(path)
    crit = CRITERIONS[name]()
    x = jnp.asarray(z["x"], jnp.float32)
    t = jnp.asarray(z["target"])
    loss = crit.apply(x, t)
    np.testing.assert_allclose(float(loss), float(z["loss"]), rtol=2e-4,
                               err_msg=f"{name}: loss mismatch")
    dx = jax.grad(lambda xx: crit.apply(xx, t))(x)
    np.testing.assert_allclose(np.asarray(dx), z["dx"], **TOL,
                               err_msg=f"{name}: grad mismatch")
