"""Criterion unit tests (reference: per-criterion Specs in ``TEST/nn/``)."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn


def test_class_nll():
    logp = jnp.log(jnp.array([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1]]))
    target = jnp.array([0, 1])
    loss = nn.ClassNLLCriterion().forward(logp, target)
    np.testing.assert_allclose(loss, -(np.log(0.7) + np.log(0.8)) / 2, rtol=1e-4)


def test_cross_entropy_equals_logsoftmax_plus_nll():
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 5))
    target = jnp.array([0, 2, 4, 1])
    ce = nn.CrossEntropyCriterion().forward(logits, target)
    manual = nn.ClassNLLCriterion().forward(jax.nn.log_softmax(logits), target)
    np.testing.assert_allclose(ce, manual, rtol=1e-5)


def test_nll_ignore_index():
    logp = jnp.log(jnp.array([[0.5, 0.5], [0.9, 0.1]]))
    loss = nn.ClassNLLCriterion(ignore_index=-100).forward(
        logp, jnp.array([0, -100]))
    np.testing.assert_allclose(loss, -np.log(0.5), rtol=1e-5)


def test_mse():
    loss = nn.MSECriterion().forward(jnp.array([1.0, 2.0]), jnp.array([0.0, 0.0]))
    np.testing.assert_allclose(loss, 2.5)
    loss_sum = nn.MSECriterion(size_average=False).forward(
        jnp.array([1.0, 2.0]), jnp.array([0.0, 0.0]))
    np.testing.assert_allclose(loss_sum, 5.0)


def test_bce_matches_manual():
    x = jnp.array([0.8, 0.3])
    t = jnp.array([1.0, 0.0])
    loss = nn.BCECriterion().forward(x, t)
    np.testing.assert_allclose(loss, -(np.log(0.8) + np.log(0.7)) / 2, rtol=1e-5)


def test_bce_with_logits_matches_bce():
    logits = jnp.array([1.5, -0.5, 0.2])
    t = jnp.array([1.0, 0.0, 1.0])
    a = nn.BCEWithLogitsCriterion().forward(logits, t)
    b = nn.BCECriterion().forward(jax.nn.sigmoid(logits), t)
    np.testing.assert_allclose(a, b, rtol=1e-5)


def test_smooth_l1():
    loss = nn.SmoothL1Criterion().forward(jnp.array([0.5, 3.0]), jnp.array([0.0, 0.0]))
    np.testing.assert_allclose(loss, (0.5 * 0.25 + 2.5) / 2)


def test_margin():
    loss = nn.MarginCriterion().forward(jnp.array([0.5, 2.0]), jnp.array([1.0, 1.0]))
    np.testing.assert_allclose(loss, 0.25)


def test_kld_vae():
    mean = jnp.zeros((2, 3))
    log_var = jnp.zeros((2, 3))
    np.testing.assert_allclose(nn.KLDCriterion().forward((mean, log_var), None), 0.0)


def test_criterion_backward_is_grad():
    logits = jax.random.normal(jax.random.PRNGKey(1), (3, 4))
    target = jnp.array([0, 1, 2])
    c = nn.CrossEntropyCriterion()
    gi = c.backward(logits, target)
    assert gi.shape == logits.shape
    # gradient of mean-CE sums to ~0 per row minus one-hot/N
    np.testing.assert_allclose(jnp.sum(gi), 0.0, atol=1e-5)


def test_parallel_criterion():
    pc = nn.ParallelCriterion().add(nn.MSECriterion(), 0.5).add(nn.MSECriterion(), 1.0)
    x = (jnp.array([1.0]), jnp.array([2.0]))
    t = (jnp.array([0.0]), jnp.array([0.0]))
    np.testing.assert_allclose(pc.forward(x, t), 0.5 * 1.0 + 1.0 * 4.0)


def test_time_distributed_criterion():
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 5, 4))
    t = jnp.zeros((2, 5), dtype=jnp.int32)
    loss = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion()).forward(x, t)
    assert loss.shape == ()


def test_time_distributed_sum_inner_no_average():
    # inner sum-reducing criterion, size_average=False (default): plain sum
    x = jnp.ones((2, 3, 4))
    t = jnp.zeros((2, 3, 4))
    loss = nn.TimeDistributedCriterion(
        nn.MSECriterion(size_average=False)).forward(x, t)
    np.testing.assert_allclose(loss, 24.0)
    # size_average=True divides by timesteps
    loss_avg = nn.TimeDistributedCriterion(
        nn.MSECriterion(size_average=False), size_average=True).forward(x, t)
    np.testing.assert_allclose(loss_avg, 8.0)


def test_multilabel_margin_class_zero_with_padding():
    # single true class 0, padded with -1: perfect score -> zero loss
    x = jnp.array([[1.0, 0.0, 0.0]])
    t = jnp.array([[0, -1, -1]])
    loss = nn.MultiLabelMarginCriterion().forward(x, t)
    np.testing.assert_allclose(loss, 0.0)
