"""Criterion unit tests (reference: per-criterion Specs in ``TEST/nn/``)."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn


def test_class_nll():
    logp = jnp.log(jnp.array([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1]]))
    target = jnp.array([0, 1])
    loss = nn.ClassNLLCriterion().forward(logp, target)
    np.testing.assert_allclose(loss, -(np.log(0.7) + np.log(0.8)) / 2, rtol=1e-4)


def test_cross_entropy_equals_logsoftmax_plus_nll():
    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 5))
    target = jnp.array([0, 2, 4, 1])
    ce = nn.CrossEntropyCriterion().forward(logits, target)
    manual = nn.ClassNLLCriterion().forward(jax.nn.log_softmax(logits), target)
    np.testing.assert_allclose(ce, manual, rtol=1e-5)


def test_nll_ignore_index():
    logp = jnp.log(jnp.array([[0.5, 0.5], [0.9, 0.1]]))
    loss = nn.ClassNLLCriterion(ignore_index=-100).forward(
        logp, jnp.array([0, -100]))
    np.testing.assert_allclose(loss, -np.log(0.5), rtol=1e-5)


def test_mse():
    loss = nn.MSECriterion().forward(jnp.array([1.0, 2.0]), jnp.array([0.0, 0.0]))
    np.testing.assert_allclose(loss, 2.5)
    loss_sum = nn.MSECriterion(size_average=False).forward(
        jnp.array([1.0, 2.0]), jnp.array([0.0, 0.0]))
    np.testing.assert_allclose(loss_sum, 5.0)


def test_bce_matches_manual():
    x = jnp.array([0.8, 0.3])
    t = jnp.array([1.0, 0.0])
    loss = nn.BCECriterion().forward(x, t)
    np.testing.assert_allclose(loss, -(np.log(0.8) + np.log(0.7)) / 2, rtol=1e-5)


def test_bce_with_logits_matches_bce():
    logits = jnp.array([1.5, -0.5, 0.2])
    t = jnp.array([1.0, 0.0, 1.0])
    a = nn.BCEWithLogitsCriterion().forward(logits, t)
    b = nn.BCECriterion().forward(jax.nn.sigmoid(logits), t)
    np.testing.assert_allclose(a, b, rtol=1e-5)


def test_smooth_l1():
    loss = nn.SmoothL1Criterion().forward(jnp.array([0.5, 3.0]), jnp.array([0.0, 0.0]))
    np.testing.assert_allclose(loss, (0.5 * 0.25 + 2.5) / 2)


def test_margin():
    loss = nn.MarginCriterion().forward(jnp.array([0.5, 2.0]), jnp.array([1.0, 1.0]))
    np.testing.assert_allclose(loss, 0.25)


def test_kld_vae():
    mean = jnp.zeros((2, 3))
    log_var = jnp.zeros((2, 3))
    np.testing.assert_allclose(nn.KLDCriterion().forward((mean, log_var), None), 0.0)


def test_criterion_backward_is_grad():
    logits = jax.random.normal(jax.random.PRNGKey(1), (3, 4))
    target = jnp.array([0, 1, 2])
    c = nn.CrossEntropyCriterion()
    gi = c.backward(logits, target)
    assert gi.shape == logits.shape
    # gradient of mean-CE sums to ~0 per row minus one-hot/N
    np.testing.assert_allclose(jnp.sum(gi), 0.0, atol=1e-5)


def test_parallel_criterion():
    pc = nn.ParallelCriterion().add(nn.MSECriterion(), 0.5).add(nn.MSECriterion(), 1.0)
    x = (jnp.array([1.0]), jnp.array([2.0]))
    t = (jnp.array([0.0]), jnp.array([0.0]))
    np.testing.assert_allclose(pc.forward(x, t), 0.5 * 1.0 + 1.0 * 4.0)


def test_time_distributed_criterion():
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 5, 4))
    t = jnp.zeros((2, 5), dtype=jnp.int32)
    loss = nn.TimeDistributedCriterion(nn.CrossEntropyCriterion()).forward(x, t)
    assert loss.shape == ()


def test_time_distributed_sum_inner_no_average():
    # inner sum-reducing criterion, size_average=False (default): plain sum
    x = jnp.ones((2, 3, 4))
    t = jnp.zeros((2, 3, 4))
    loss = nn.TimeDistributedCriterion(
        nn.MSECriterion(size_average=False)).forward(x, t)
    np.testing.assert_allclose(loss, 24.0)
    # size_average=True divides by timesteps
    loss_avg = nn.TimeDistributedCriterion(
        nn.MSECriterion(size_average=False), size_average=True).forward(x, t)
    np.testing.assert_allclose(loss_avg, 8.0)


def test_multilabel_margin_class_zero_with_padding():
    # single true class 0, padded with -1: perfect score -> zero loss
    x = jnp.array([[1.0, 0.0, 0.0]])
    t = jnp.array([[0, -1, -1]])
    loss = nn.MultiLabelMarginCriterion().forward(x, t)
    np.testing.assert_allclose(loss, 0.0)


# ----------------------------------------------------------- round-2 breadth


def test_cosine_distance_criterion():
    x = jnp.array([[1.0, 0.0], [0.0, 2.0]])
    # identical directions -> 0; orthogonal -> 1
    np.testing.assert_allclose(
        nn.CosineDistanceCriterion().forward(x, x), 0.0, atol=1e-6)
    y = jnp.array([[0.0, 1.0], [2.0, 0.0]])
    np.testing.assert_allclose(
        nn.CosineDistanceCriterion().forward(x, y), 1.0, atol=1e-6)


def test_cosine_proximity_matches_torch():
    import torch
    rng = np.random.RandomState(0)
    x = rng.randn(4, 6).astype(np.float32)
    y = rng.randn(4, 6).astype(np.float32)
    ours = nn.CosineProximityCriterion().forward(jnp.asarray(x),
                                                 jnp.asarray(y))
    ref = -torch.nn.functional.cosine_similarity(
        torch.tensor(x), torch.tensor(y)).mean().item()
    np.testing.assert_allclose(float(ours), ref, rtol=1e-5)


def test_dot_product_criterion_grad_is_target():
    x = jnp.array([[1.0, 2.0], [3.0, 4.0]])
    t = jnp.array([[0.5, 0.5], [1.0, -1.0]])
    c = nn.DotProductCriterion()
    np.testing.assert_allclose(c.forward(x, t), float(np.sum(x * t)),
                               rtol=1e-6)
    np.testing.assert_allclose(c.backward(x, t), t, rtol=1e-6)


def test_kld_probability_form():
    p = jnp.array([[0.5, 0.5]])
    q = jnp.array([[0.25, 0.75]])
    # KL(target||input): target=p, input=q
    expected = float(np.sum(p * np.log(p / q)))
    np.testing.assert_allclose(
        nn.KullbackLeiblerDivergenceCriterion().forward(q, p), expected,
        rtol=1e-5)


def test_l1_hinge_embedding():
    x1 = jnp.array([[1.0, 1.0]])
    x2 = jnp.array([[0.0, 0.0]])
    c = nn.L1HingeEmbeddingCriterion(margin=3.0)
    np.testing.assert_allclose(c.forward((x1, x2), jnp.array([1])), 2.0)
    np.testing.assert_allclose(c.forward((x1, x2), jnp.array([-1])), 1.0)


def test_mape_msle_poisson():
    t = jnp.array([[2.0, 4.0]])
    x = jnp.array([[1.0, 5.0]])
    np.testing.assert_allclose(
        nn.MeanAbsolutePercentageCriterion().forward(x, t),
        100.0 * (0.5 + 0.25) / 2, rtol=1e-5)
    np.testing.assert_allclose(
        nn.MeanSquaredLogarithmicCriterion().forward(x, t),
        np.mean((np.log([2.0, 6.0]) - np.log([3.0, 5.0])) ** 2), rtol=1e-5)
    np.testing.assert_allclose(
        nn.PoissonCriterion().forward(x, t),
        np.mean([1.0 - 2.0 * np.log(1.0), 5.0 - 4.0 * np.log(5.0)]),
        rtol=1e-5)


def test_multi_margin_matches_torch():
    import torch
    rng = np.random.RandomState(1)
    x = rng.randn(5, 7).astype(np.float32)
    y = rng.randint(0, 7, size=5)
    for p in (1, 2):
        ours = nn.MultiMarginCriterion(p=p).forward(
            jnp.asarray(x), jnp.asarray(y))
        ref = torch.nn.MultiMarginLoss(p=p)(
            torch.tensor(x), torch.tensor(y)).item()
        np.testing.assert_allclose(float(ours), ref, rtol=1e-5)


def test_class_simplex_properties():
    c = nn.ClassSimplexCriterion(5)
    s = np.asarray(c.simplex)
    # vertices unit-norm, mutual dot products all equal
    np.testing.assert_allclose(np.linalg.norm(s, axis=1), 1.0, atol=1e-5)
    dots = s @ s.T
    off = dots[~np.eye(5, dtype=bool)]
    np.testing.assert_allclose(off, off[0], atol=1e-5)
    # loss is zero when input == embedding
    t = jnp.array([0, 3])
    emb = jnp.zeros((2, 5)).at[:, :4].set(jnp.asarray(s[np.array([0, 3])]))
    np.testing.assert_allclose(c.forward(emb, t), 0.0, atol=1e-10)


def test_smooth_l1_with_weights():
    sigma = 2.0
    x = jnp.array([[0.1, 2.0]])
    gt = jnp.array([[0.0, 0.0]])
    w_in = jnp.array([[1.0, 1.0]])
    w_out = jnp.array([[2.0, 0.5]])
    c = nn.SmoothL1CriterionWithWeights(sigma=sigma)
    # |0.1| < 1/4 -> quad: 0.5*4*0.01 = 0.02 * w_out 2 = 0.04
    # |2| >= 1/4 -> lin: 2 - 0.125 = 1.875 * 0.5 = 0.9375
    np.testing.assert_allclose(
        c.forward(x, (gt, w_in, w_out)), 0.04 + 0.9375, rtol=1e-5)


def test_time_distributed_mask():
    # (N=1, T=3, C=2) log-probs, last step padded (target 0 = padding)
    logp = jnp.log(jnp.array([[[0.9, 0.1], [0.2, 0.8], [0.5, 0.5]]]))
    tgt = jnp.array([[1, 1, 0]])
    c = nn.TimeDistributedMaskCriterion(nn.ClassNLLCriterion(),
                                        padding_value=0)
    expected = -(np.log(0.1) + np.log(0.8)) / 2
    np.testing.assert_allclose(c.forward(logp, tgt), expected, rtol=1e-5)


def test_transformer_criterion():
    double = nn.Lambda(lambda x: 2.0 * x)
    c = nn.TransformerCriterion(nn.MSECriterion(),
                                input_transformer=double,
                                target_transformer=double)
    x = jnp.array([[1.0, 2.0]])
    t = jnp.array([[0.0, 0.0]])
    np.testing.assert_allclose(c.forward(x, t), 4.0 * 2.5, rtol=1e-6)
