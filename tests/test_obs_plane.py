"""Observability round-2 tests (ISSUE-11 acceptance surface).

- RequestContext: minting, uniqueness, hop history, flow ids;
- Tracer flow events: Chrome ``s``/``f`` schema, id pairing;
- FlightRecorder: bounded ring, crash-surviving JSONL stream (torn
  tails skipped), one-shot dump, rotation, trace_id correlation;
- Prometheus rendering: exposition-format validity, escaping, summary
  quantiles incl. per-bucket serving reservoirs;
- AdminServer: /metrics, /healthz (200/503), /trace, /flight, 404s,
  loopback binding, config-driven maybe_start inertness;
- E2E (the acceptance demo): a live ReplicaSet under threaded load is
  scraped mid-flight — /metrics contains serving latency quantiles and
  resilience counters; a replica-kill run leaves a flight dump in
  which the victim request's trace_id links its original dispatch, the
  quarantine, and the successful failover hop;
- SIGKILL survival: a subprocess is SIGKILL'd after staging failover
  traffic; the parent parses the surviving dump with tools/obs_report;
- obs_report: hand-computed fixture timeline (the trace_report fixture
  pattern) and CLI exit codes;
- trace_report satellite: resilience instants folded into the stall
  picture, events-by-category accounting, --events CLI section;
- ServingMetrics window-bias audit + ReplicaSet.stats() aggregation
  regression;
- inertness: everything off → no context objects, no extra threads.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.serving import InferenceService
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.telemetry import (AdminServer, FlightRecorder,
                                 MetricRegistry, RequestContext, Tracer,
                                 render_prometheus)
from bigdl_tpu.telemetry import admin as admin_mod
from bigdl_tpu.telemetry import flight as flight_mod
from bigdl_tpu.telemetry.context import flow_id, new_trace_id
from bigdl_tpu.telemetry.flight import load_dump
from tools import obs_report, trace_report

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")
FLIGHT_FIXTURE = os.path.join(FIXTURES, "flight_postmortem.jsonl")
TRACE_FIXTURE = os.path.join(FIXTURES, "trace_postmortem.json")
T1 = "aabbccdd00000001"
T2 = "aabbccdd00000002"


@pytest.fixture(autouse=True)
def _isolate_singletons():
    """No test may leak a process-wide admin server or flight recorder
    into its neighbors (they are config-driven singletons)."""
    admin_mod.reset()
    flight_mod.reset()
    yield
    admin_mod.reset()
    flight_mod.reset()


def small_model(din=8, dout=4):
    return nn.Sequential(nn.Linear(din, 16), nn.ReLU(),
                         nn.Linear(16, dout), nn.SoftMax()).initialize(0)


def _get(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


# ==========================================================================
# RequestContext
# ==========================================================================
class TestRequestContext:
    def test_mint_unique_and_flow_id(self):
        ids = {new_trace_id() for _ in range(500)}
        assert len(ids) == 500
        for t in list(ids)[:5]:
            assert len(t) == 16 and int(t, 16) >= 0
            assert 0 < flow_id(t) < 2 ** 63
        c = RequestContext(tenant="acme", parent="p0")
        assert c.flow_id == flow_id(c.trace_id)
        assert c.tenant == "acme" and c.parent == "p0"

    def test_hop_history(self):
        c = RequestContext()
        h0 = c.add_hop(0)
        h0["outcome"] = "ReplicaDeadError"
        h1 = c.add_hop(2, probe=True)
        h1["outcome"] = "ok"
        snap = c.snapshot()
        assert snap["hops"] == [
            {"replica": 0, "probe": False, "outcome": "ReplicaDeadError"},
            {"replica": 2, "probe": True, "outcome": "ok"}]
        assert "r0:ReplicaDeadError" in repr(c) and "r2:ok" in repr(c)


# ==========================================================================
# tracer flow events
# ==========================================================================
class TestTracerFlows:
    def test_flow_schema_and_pairing(self):
        t = Tracer()
        c = RequestContext()
        with t.span("request_submit", cat="serving"):
            t.flow_start("req", c.flow_id, cat="serving")
        with t.span("dispatch", cat="serving"):
            t.flow_end("req", c.flow_id, cat="serving")
        evs = t.to_chrome_trace()["traceEvents"]
        s = next(e for e in evs if e["ph"] == "s")
        f = next(e for e in evs if e["ph"] == "f")
        assert s["id"] == f["id"] == c.flow_id
        assert f["bp"] == "e" and "bp" not in s
        assert "dur" not in s and "s" not in s  # not an instant
        # disabled tracer: flows are free no-ops
        off = Tracer(enabled=False)
        off.flow_start("req", 1)
        off.flow_end("req", 1)
        assert off.events() == []


# ==========================================================================
# flight recorder
# ==========================================================================
class TestFlightRecorder:
    def test_ring_bounded(self):
        fl = FlightRecorder(capacity=4)
        for i in range(10):
            fl.record("e", n=i)
        evs = fl.events()
        assert len(evs) == 4 and evs[-1]["n"] == 9 and evs[0]["n"] == 6

    def test_stream_survives_and_torn_tail_skipped(self, tmp_path):
        p = str(tmp_path / "fl.jsonl")
        fl = FlightRecorder(p)
        fl.record("failover", cat="resilience", trace_id="t1", replica=0)
        fl.record("revival", cat="resilience", replica=0)
        # simulate the SIGKILL torn tail: half a JSON line
        with open(p, "a") as f:
            f.write('{"event": "lost_to_the_k')
        blob = load_dump(p)
        assert blob["meta"]["pid"] == os.getpid()
        assert {"unix_ns", "perf_ns"} <= set(blob["meta"])
        assert [e["event"] for e in blob["events"]] == ["failover",
                                                        "revival"]
        assert blob["events"][0]["trace_id"] == "t1"

    def test_dump_object_form_roundtrip(self, tmp_path):
        fl = FlightRecorder()  # memory-only
        fl.record("breaker_trip", cat="resilience", version="m:v2")
        path = fl.dump(str(tmp_path / "dump.json"))
        blob = load_dump(path)
        assert blob["events"][0]["event"] == "breaker_trip"

    def test_rotation_bounds_disk(self, tmp_path):
        p = str(tmp_path / "fl.jsonl")
        fl = FlightRecorder(p, max_bytes=1 << 16)
        for i in range(2000):
            fl.record("spam", payload="x" * 64, n=i)
        assert os.path.exists(p + ".1")
        assert os.path.getsize(p) <= (1 << 16) + 4096
        # the live file is still a valid stream after rotation
        blob = load_dump(p)
        assert blob["events"] and blob["meta"].get("pid")

    def test_events_for_and_counts(self):
        fl = FlightRecorder()
        fl.record("failover", trace_id="a", replica=0)
        fl.record("failover", trace_id="b", replica=1)
        fl.record("shed")
        assert [e["trace_id"] for e in fl.events_for("a")] == ["a"]
        assert fl.counts() == {"failover": 2, "shed": 1}

    def test_restart_respects_existing_file_size(self, tmp_path):
        """The rotation bound must hold ACROSS process restarts: a
        fresh recorder appending to an existing file inherits its size
        into the rotation accounting instead of starting from zero."""
        p = str(tmp_path / "fl.jsonl")
        fl1 = FlightRecorder(p, max_bytes=1 << 16)
        for i in range(300):
            fl1.record("run1", payload="x" * 64, n=i)
        fl1.close()
        size_before = os.path.getsize(p)
        fl2 = FlightRecorder(p, max_bytes=1 << 16)
        for i in range(300):
            fl2.record("run2", payload="x" * 64, n=i)
        fl2.close()
        assert os.path.exists(p + ".1")  # rotated across the restart
        assert os.path.getsize(p) < size_before + (1 << 16)

    def test_from_config_inert_and_live(self, tmp_path):
        from bigdl_tpu.utils.config import configure, reset_config
        assert flight_mod.from_config() is None  # default: off
        p = str(tmp_path / "cfg.jsonl")
        configure(flight_recorder_path=p)
        try:
            fl = flight_mod.from_config()
            assert fl is not None and fl.path == p
            assert flight_mod.from_config() is fl  # singleton
        finally:
            reset_config()
            flight_mod.reset()


# ==========================================================================
# prometheus rendering
# ==========================================================================
_PROM_LINE = (r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
              r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"'
              r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"]*")*\})? [0-9eE+.\-]+$')


class TestPrometheusRender:
    def test_families_types_and_format(self):
        import re
        reg = MetricRegistry()
        reg.counter("resilience/failovers").inc(2)
        reg.gauge("driver/device_wait_fraction").set(0.75)
        h = reg.histogram("serving/latency_s")
        for v in (0.001, 0.002, 0.01):
            h.observe(v)
        reg.histogram("serving/latency_s_bucket4").observe(0.004)
        text = render_prometheus({"m/r0": reg.snapshot()})
        lines = text.strip().split("\n")
        pat = re.compile(_PROM_LINE)
        for ln in lines:
            assert ln.startswith("# TYPE ") or pat.match(ln), ln
        assert "# TYPE bigdl_tpu_resilience_failovers counter" in text
        assert 'bigdl_tpu_resilience_failovers{source="m/r0"} 2' in text
        assert "# TYPE bigdl_tpu_serving_latency_s summary" in text
        assert ('bigdl_tpu_serving_latency_s{source="m/r0",'
                'quantile="0.99"}') in text
        # the per-bucket serving reservoir is its own family
        assert "bigdl_tpu_serving_latency_s_bucket4_count" in text
        assert 'bigdl_tpu_serving_latency_s_count{source="m/r0"} 3' in text

    def test_label_escaping_and_merge(self):
        reg1, reg2 = MetricRegistry(), MetricRegistry()
        reg1.counter("c").inc()
        reg2.counter("c").inc(5)
        text = render_prometheus({'a"b\\c': reg1.snapshot(),
                                  "r1": reg2.snapshot()})
        assert text.count("# TYPE bigdl_tpu_c counter") == 1  # merged
        assert r'{source="a\"b\\c"} 1' in text


# ==========================================================================
# admin server
# ==========================================================================
class TestAdminServer:
    def test_endpoints(self, tmp_path):
        reg = MetricRegistry()
        reg.counter("serving/requests_completed").inc(7)
        tr = Tracer()
        with tr.span("x", cat="serving"):
            pass
        fl = FlightRecorder()
        fl.record("shed", cat="resilience")
        with AdminServer(port=0) as srv:
            srv.add_registry("m", reg).add_tracer("m", tr)
            srv.add_health("m", lambda: {"ok": True, "detail": 1})
            srv.set_flight(fl)
            assert srv.host == "127.0.0.1" and srv.port > 0
            code, text = _get(srv.url("/metrics"))
            assert code == 200
            assert ('bigdl_tpu_serving_requests_completed{source="m"} 7'
                    in text)
            code, body = _get(srv.url("/healthz"))
            hz = json.loads(body)
            assert code == 200 and hz["ok"] is True
            assert hz["sources"]["m"]["detail"] == 1
            code, body = _get(srv.url("/trace"))
            assert code == 200
            assert any(e.get("name") == "x"
                       for e in json.loads(body)["traceEvents"])
            code, body = _get(srv.url("/flight"))
            assert json.loads(body)["events"][0]["event"] == "shed"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.url("/nope"))
            assert ei.value.code == 404

    def test_healthz_503_on_unhealthy_source(self):
        with AdminServer(port=0) as srv:
            srv.add_health("sick", lambda: {"ok": False, "why": "dead"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.url("/healthz"))
            assert ei.value.code == 503
            hz = json.loads(ei.value.read().decode())
            assert hz["ok"] is False

    def test_broken_health_provider_is_a_health_signal(self):
        def boom():
            raise RuntimeError("probe exploded")

        with AdminServer(port=0) as srv:
            srv.add_health("broken", boom)
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(srv.url("/healthz"))
            assert ei.value.code == 503
            hz = json.loads(ei.value.read().decode())
            assert "probe exploded" in hz["sources"]["broken"]["error"]

    def test_remove_source_and_unique_names(self):
        with AdminServer(port=0) as srv:
            reg = MetricRegistry()
            reg.counter("c").inc()
            srv.add_registry("m", reg)
            srv.add_health("m", lambda: {"ok": False})
            # a second instance with the same natural name gets a
            # distinct slot instead of silently overwriting the first;
            # names are RESERVED at mint time, so two racing callers
            # can't both be handed the same one
            assert srv.unique_source_name("m") == "m-2"
            assert srv.unique_source_name("m") == "m-3"
            assert srv.unique_source_name("fresh") == "fresh"
            assert srv.unique_source_name("fresh") == "fresh-2"
            assert srv.health_json()["ok"] is False
            # a stopped source deregisters: health recovers and its
            # metrics leave the scrape page
            srv.remove_source("m")
            assert srv.health_json() == {"ok": True, "sources": {}}
            assert 'source="m"' not in srv.metrics_text()

    def test_shared_tracer_exports_once_in_trace_json(self):
        """A ReplicaSet and its replicas register the SAME tracer
        under N+1 names — /trace must export it once, not N+1 times."""
        tr = Tracer()
        with tr.span("x", cat="serving"):
            pass
        with AdminServer(port=0) as srv:
            srv.add_tracer("set", tr)
            srv.add_tracer("set/r0", tr)
            srv.add_tracer("set/r1", tr)
            out = srv.trace_json()
            spans = [e for e in out["traceEvents"]
                     if e.get("name") == "x"]
            assert len(spans) == 1
            assert out["otherData"]["sources"] == ["set"]

    def test_bind_failure_degrades_monitoring_not_serving(self):
        """A taken admin port must not crash product constructors —
        maybe_start() logs once and returns None."""
        import socket
        from bigdl_tpu.utils.config import configure, reset_config
        blocker = socket.socket()
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        port = blocker.getsockname()[1]
        try:
            configure(admin_port=port)
            assert admin_mod.maybe_start() is None  # degraded, no raise
            assert admin_mod.maybe_start() is None  # remembered
            # ... and a service still constructs fine through the path
            svc = InferenceService(small_model(),
                                   input_spec=((8,), np.float32),
                                   max_batch_size=2,
                                   batch_timeout_ms=0.0, name="degraded")
            svc.predict(np.zeros((1, 8), np.float32))
            svc.stop()
        finally:
            blocker.close()
            reset_config()

    def test_maybe_start_inert_by_default(self):
        assert admin_mod.maybe_start() is None  # admin_port=0
        assert admin_mod.current() is None
        assert not any(t.name == "bigdl-tpu-admin"
                       for t in threading.enumerate())

    def test_maybe_start_from_config(self):
        from bigdl_tpu.utils.config import configure, reset_config
        configure(admin_port=0)  # explicit off first
        assert admin_mod.maybe_start() is None
        try:
            # port 0 means off by contract, so pick an ephemeral port
            # by starting a throwaway server and reusing its port is
            # racy — instead configure a high odd port
            import socket
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            configure(admin_port=port)
            srv = admin_mod.maybe_start()
            assert srv is not None and srv.port == port
            assert admin_mod.maybe_start() is srv  # idempotent
        finally:
            reset_config()
            admin_mod.reset()


# ==========================================================================
# E2E acceptance: live scrape during serving load
# ==========================================================================
class TestServingScrapeE2E:
    def test_metrics_scrape_during_live_replica_set_load(self):
        from bigdl_tpu.resilience import ReplicaSet
        srv = AdminServer(port=0)
        srv.start()
        admin_mod.install(srv)
        model = small_model()
        rng = np.random.default_rng(0)
        try:
            rs = ReplicaSet(model, n_replicas=2,
                            input_spec=((8,), np.float32),
                            max_batch_size=8, batch_timeout_ms=1.0,
                            deadline_ms=0, name="scrape")
            stop = threading.Event()
            errs = []

            def worker():
                x = rng.normal(0, 1, (1, 8)).astype(np.float32)
                while not stop.is_set():
                    try:
                        rs.predict(x, timeout=30)
                    except Exception as e:
                        errs.append(e)
                        return

            threads = [threading.Thread(target=worker) for _ in range(4)]
            for t in threads:
                t.start()
            try:
                deadline = time.monotonic() + 10.0
                text = ""
                while time.monotonic() < deadline:
                    # scrape MID-LOAD: quantiles appear once completions
                    # land in the reservoir
                    _, text = _get(srv.url("/metrics"))
                    if ('quantile="0.99"' in text
                            and "bigdl_tpu_serving_latency_s" in text):
                        break
                    time.sleep(0.05)
            finally:
                stop.set()
                for t in threads:
                    t.join(10)
            assert not errs, errs
            # serving latency quantiles, per replica source
            assert "bigdl_tpu_serving_latency_s" in text
            assert 'quantile="0.99"' in text
            assert 'source="scrape/r0"' in text
            # resilience counters from the set-level registry
            assert "bigdl_tpu_resilience_failovers" in text
            assert "bigdl_tpu_resilience_sheds" in text
            # healthz agrees the set is healthy
            code, body = _get(srv.url("/healthz"))
            hz = json.loads(body)
            assert code == 200 and hz["sources"]["scrape"]["ok"] is True
            rs.stop()
            # a stopped set deregisters — its parked replicas must not
            # read as a permanent 503 (and its metrics leave /metrics)
            code, body = _get(srv.url("/healthz"))
            assert code == 200
            assert "scrape" not in json.loads(body)["sources"]
            _, text = _get(srv.url("/metrics"))
            assert 'source="scrape/r0"' not in text
        finally:
            admin_mod.reset()


# ==========================================================================
# connection-plane scrape schema (ISSUE 19): the C100K wire plane's
# gauge + counters are PRE-created — a zero-traffic scrape already
# shows the whole schema, so dashboards never see metrics pop into
# existence mid-incident
# ==========================================================================
class TestConnPlaneScrapeSchema:
    def test_connection_metrics_precreated_at_zero_traffic(self):
        from bigdl_tpu.frontend import FrontendServer
        from bigdl_tpu.serving import ModelRegistry
        srv = AdminServer(port=0)
        srv.start()
        admin_mod.install(srv)
        reg = ModelRegistry()
        fe = FrontendServer(reg, port=0)
        try:
            fe.start()
            _, text = _get(srv.url("/metrics"))
            assert ("# TYPE bigdl_tpu_frontend_open_connections gauge"
                    in text)
            assert "bigdl_tpu_frontend_open_connections" in text
            for c in ("conns_accepted", "conns_closed", "conns_reaped",
                      "conns_refused"):
                assert f"bigdl_tpu_frontend_{c}" in text, c
                assert (f"# TYPE bigdl_tpu_frontend_{c} counter"
                        in text), c
        finally:
            fe.stop()
            reg.stop_all()
            admin_mod.reset()


# ==========================================================================
# E2E acceptance: replica-kill story in the flight dump
# ==========================================================================
class TestFailoverStory:
    # the injected ReplicaDeathFault kills the batcher thread ON
    # PURPOSE (that is the scenario); pytest must not flag the planned
    # thread death as an unhandled-exception warning
    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_trace_id_links_dispatch_quarantine_and_failover(
            self, tmp_path):
        from bigdl_tpu.resilience import ReplicaSet
        from bigdl_tpu.resilience.faults import FaultInjector
        from bigdl_tpu.resilience.health import HealthPolicy
        fl = FlightRecorder(str(tmp_path / "fl.jsonl"))
        tr = Tracer()
        rs = ReplicaSet(
            small_model(), n_replicas=2, input_spec=((8,), np.float32),
            max_batch_size=4, batch_timeout_ms=0.0, deadline_ms=0,
            fault_injector=FaultInjector("replica_death@target=0,at=0",
                                         seed=0),
            tracer=tr, flight=fl, request_tracing=True,
            health=HealthPolicy(probe_backoff_s=0.05))
        x = np.zeros((1, 8), np.float32)
        ctx = RequestContext(tenant="t")
        y = rs.submit(x, ctx=ctx, timeout=30).result(30)
        assert y.shape == (1, 4)
        # hop history: victim hop then the successful failover hop
        assert ctx.hops[0]["replica"] == 0
        assert ctx.hops[0]["outcome"] == "ReplicaDeadError"
        assert ctx.hops[-1]["outcome"] == "ok"
        assert len(ctx.hops) == 2
        # the dump links the story BY TRACE ID: the failover (carrying
        # the original-dispatch replica in its hops), then the retry
        # route.  First attempts are deliberately NOT flight events —
        # routine traffic must not evict the rare events from the ring.
        story = fl.events_for(ctx.trace_id)
        assert [e["event"] for e in story] == ["failover",
                                               "request_route"]
        failover = story[0]
        assert failover["replica"] == 0  # the original dispatch
        assert failover["hops"] == ["r0:ReplicaDeadError"]
        assert story[1]["replica"] == 1 and story[1]["attempt"] == 2
        # ... and the un-keyed resilience events are there too.  The
        # death is handled on the SUPERVISOR thread, which may still be
        # mid-bookkeeping when the caller's future resolves via the
        # failover — poll boundedly instead of racing it
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            all_events = fl.counts()
            if (all_events.get("replica_death", 0) >= 1
                    and all_events.get("health_transition", 0) >= 1):
                break
            time.sleep(0.01)
        assert all_events.get("replica_death", 0) >= 1, all_events
        assert all_events.get("health_transition", 0) >= 1, all_events
        rs.stop()
        # the tracer saw the dispatch spans + flow edges for this id
        trace = tr.to_chrome_trace()["traceEvents"]
        flows = [e for e in trace if e.get("ph") in ("s", "f")
                 and e.get("id") == ctx.flow_id]
        assert any(e["ph"] == "s" for e in flows)
        assert any(e["ph"] == "f" for e in flows)
        # obs_report joins both into one story
        tp = str(tmp_path / "trace.json")
        tr.dump(tp)
        report = obs_report.summarize(
            load_dump(fl.path), trace=trace_report.load_trace(tp))
        req = next(r for r in report["requests"]
                   if r["trace_id"] == ctx.trace_id)
        assert req["failed_over"] is True
        assert "dispatch" in req["events"]  # the original dispatch span


# ==========================================================================
# SIGKILL survival (subprocess)
# ==========================================================================
class TestSigkillSurvival:
    def test_flight_dump_survives_sigkill(self, tmp_path):
        flight_path = str(tmp_path / "kill.jsonl")
        trace_path = str(tmp_path / "kill_trace.json")
        child = os.path.join(os.path.dirname(__file__),
                             "obs_kill_child.py")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env["PYTHONPATH"] = (repo + os.pathsep + env.get("PYTHONPATH", "")
                             ).rstrip(os.pathsep)
        proc = subprocess.Popen(
            [sys.executable, child, flight_path, trace_path],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env, cwd=repo)
        try:
            line = proc.stdout.readline()
            assert line.startswith("READY "), (
                line, proc.stderr.read() if proc.poll() is not None
                else "")
            trace_id = line.split()[1]
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(30)
            assert proc.returncode == -signal.SIGKILL
        finally:
            if proc.poll() is None:
                proc.kill()
        # the stream survived the kill and tells the whole story
        blob = load_dump(flight_path)
        events = [e["event"] for e in blob["events"]
                  if e.get("trace_id") == trace_id]
        assert events == ["failover", "request_route"]
        # ... and obs_report parses it (CLI, with the trace joined)
        r = subprocess.run(
            [sys.executable, "-m", "tools.obs_report", flight_path,
             "--trace", trace_path, "--json"],
            capture_output=True, text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))))
        assert r.returncode == 0, r.stderr
        report = json.loads(r.stdout)
        assert report["n_failed_over"] == 1
        victim = next(q for q in report["requests"]
                      if q["trace_id"] == trace_id)
        assert victim["failed_over"] is True


# ==========================================================================
# obs_report fixture (hand-computed timeline)
# ==========================================================================
class TestObsReportFixture:
    def test_fixture_timeline_exact(self):
        report = obs_report.summarize(
            load_dump(FLIGHT_FIXTURE),
            trace=trace_report.load_trace(TRACE_FIXTURE))
        assert report["meta"] == {"pid": 4242, "schema": 1,
                                  "trace_joined": True}
        # 5 flight events + (2 submits + 2 fan-in dispatch rows +
        # 1 failover instant) from the trace; the driver-pipeline span
        # in the fixture must NOT appear
        assert report["n_rows"] == 10
        assert report["event_counts"] == {
            "checkpoint_commit": 1, "dispatch": 2, "failover": 2,
            "replica_death": 1, "request_route": 2, "request_submit": 2}
        assert report["categories"] == {"driver": 1, "resilience": 5,
                                        "serving": 4}
        assert report["n_requests"] == 3  # T1, T2, the run's trace id
        assert report["n_failed_over"] == 1
        t1 = next(r for r in report["requests"]
                  if r["trace_id"] == T1)
        # hand-computed ordering on the unified wall clock: submit
        # (.005) < dispatch (.008) < route (.010) < flight failover
        # (.021) < trace failover (.0215) < retry route (.022)
        assert t1["events"] == ["request_submit", "dispatch",
                                "request_route", "failover", "failover",
                                "request_route"]
        assert t1["failed_over"] is True
        t2 = next(r for r in report["requests"]
                  if r["trace_id"] == T2)
        assert t2["events"] == ["request_submit", "dispatch"]
        assert t2["failed_over"] is False
        # clock alignment: the first timeline row is the T1 submit at
        # wall 1700000000.005 exactly (µs-exact anchor arithmetic)
        first = report["timeline"][0]
        assert first["name"] == "request_submit"
        assert first["t_unix"] == pytest.approx(1_700_000_000.005,
                                                abs=1e-6)

    def test_trace_id_filter(self):
        report = obs_report.summarize(load_dump(FLIGHT_FIXTURE),
                                      trace_id=T1)
        assert report["n_rows"] == 3  # route, failover, route
        assert list(report["event_counts"]) == ["failover",
                                                "request_route"]

    def test_cli_exit_codes(self, tmp_path, capsys):
        assert obs_report.main([FLIGHT_FIXTURE]) == 0
        out = capsys.readouterr().out
        assert "failed-over requests" in out and T1 in out
        assert obs_report.main(
            [FLIGHT_FIXTURE, "--trace", TRACE_FIXTURE, "--json"]) == 0
        json.loads(capsys.readouterr().out)
        assert obs_report.main([str(tmp_path / "missing.jsonl")]) == 2
        empty = tmp_path / "empty.jsonl"
        empty.write_text("")
        assert obs_report.main([str(empty)]) == 2


# ==========================================================================
# trace_report satellite: resilience instants folded in, --events
# ==========================================================================
class TestTraceReportEvents:
    def _trace_with_resilience(self, tmp_path):
        t = Tracer()
        with t.span("dispatch", cat="dispatch"):
            pass
        t.instant("recompile", key="k")  # cat watchdog (default)
        t.instant("failover", cat="resilience", replica=0,
                  error="ReplicaDeadError")
        t.instant("replica_death", cat="resilience", replica=0)
        t.instant("shed", cat="resilience")
        t.instant("mystery", cat="something_new")
        p = str(tmp_path / "t.json")
        t.dump(p)
        return trace_report.summarize(trace_report.load_trace(p))

    def test_resilience_fold_and_category_accounting(self, tmp_path):
        report = self._trace_with_resilience(tmp_path)
        assert report["resilience_events"] == {"failover": 1,
                                               "replica_death": 1,
                                               "shed": 1}
        assert report["stall"]["disruption_events"] == 3
        # watchdog split keeps its historical content
        assert report["watchdog_events"] == {"recompile": 1}
        # NOTHING is silently ignored: unknown categories are accounted
        assert report["events_by_category"]["something_new"] == {
            "mystery": 1}
        assert report["events_by_category"]["resilience"][
            "failover"] == 1
        names = [r["name"] for r in report["event_timeline"]]
        assert set(names) == {"recompile", "failover", "replica_death",
                              "shed", "mystery"}

    def test_events_cli_section(self, tmp_path, capsys):
        t = Tracer()
        with t.span("dispatch", cat="dispatch"):
            pass
        t.instant("failover", cat="resilience", replica=3)
        p = str(tmp_path / "t.json")
        t.dump(p)
        assert trace_report.main([p, "--events"]) == 0
        out = capsys.readouterr().out
        assert "instant-event timeline" in out
        assert "[resilience] failover" in out and '"replica": 3' in out
        # without the flag the timeline section is absent
        assert trace_report.main([p]) == 0
        out = capsys.readouterr().out
        assert "instant-event timeline" not in out
        assert "disruption event(s)" in out

    def test_pipeline_fixture_has_zero_disruptions(self):
        # the PR-6 fixture (watchdog instants only) reads as a clean
        # run under the new fold
        fix = os.path.join(FIXTURES, "trace_pipeline.json")
        report = trace_report.summarize(trace_report.load_trace(fix))
        assert report["stall"]["disruption_events"] == 0
        assert report["resilience_events"] == {}
        assert report["watchdog_events"] == {"recompile": 2,
                                             "stager_starvation": 1}


# ==========================================================================
# ServingMetrics window bias + ReplicaSet aggregation (satellite audit)
# ==========================================================================
class TestThroughputWindowAudit:
    def test_snapshot_uses_activity_window_not_uptime(self):
        m = ServingMetrics()
        # an idle service reports 0, not 0/uptime noise
        assert m.snapshot()["throughput_rps"] == 0.0
        m.record_submit(1)
        m.record_done(10, 0.001)
        time.sleep(0.05)
        m.record_done(10, 0.001)
        snap = m.snapshot()
        # trailing idle must NOT dilute the rate: wait well past the
        # activity window, re-snapshot, the rate is unchanged
        time.sleep(0.25)
        snap2 = m.snapshot()
        assert snap2["throughput_rps"] == pytest.approx(
            snap["throughput_rps"], rel=0.01)
        assert snap2["throughput_window_s"] == snap["throughput_window_s"]
        assert snap2["uptime_s"] > snap2["throughput_window_s"]

    def test_aggregate_is_not_replica_zero(self):
        m0, m1 = ServingMetrics(), ServingMetrics()
        m0.record_submit(1)
        m0.record_done(5, 0.001, bucket=1)
        time.sleep(0.12)
        m1.record_submit(1)
        m1.record_done(45, 0.009, bucket=4)
        agg = ServingMetrics.aggregate([m0, m1], queue_depth=3)
        assert agg["requests_completed"] == 50
        assert agg["n_sources"] == 2 and agg["queue_depth"] == 3
        # rate over the UNION window (>= the 0.12 s stagger), so it is
        # far below the per-replica burst rates a naive replica-0 (or
        # sum-of-rates) read would report
        assert agg["throughput_window_s"] >= 0.12
        assert agg["throughput_rps"] <= 50 / 0.12 + 1
        r0_rps = m0.snapshot()["throughput_rps"]
        assert r0_rps > agg["throughput_rps"]  # replica-0 bias is real
        # latency percentiles come from the CONCATENATED windows: the
        # max must be replica 1's 9 ms even though replica 0 never saw
        # it, and both buckets appear
        assert agg["latency_ms"]["max"] == pytest.approx(9.0)
        assert set(agg["latency_ms_by_bucket"]) == {1, 4}

    def test_replica_set_stats_aggregate(self):
        from bigdl_tpu.resilience import ReplicaSet
        rs = ReplicaSet(small_model(), n_replicas=2,
                        input_spec=((8,), np.float32),
                        max_batch_size=4, batch_timeout_ms=0.0,
                        deadline_ms=0, name="aggtest")
        x = np.zeros((1, 8), np.float32)
        for _ in range(6):
            rs.predict(x, timeout=30)
        stats = rs.stats()
        agg = stats["aggregate"]
        per_replica = sum(r["requests_completed"]
                          for r in stats["replicas"])
        assert agg["requests_completed"] == per_replica == 6
        assert agg["throughput_rps"] > 0
        assert agg["latency_ms"]["count"] if "count" in (
            agg["latency_ms"] or {}) else agg["latency_ms"] is not None
        rs.stop()


# ==========================================================================
# inertness: everything off
# ==========================================================================
class TestObsInertness:
    def test_config_defaults_are_off(self):
        from bigdl_tpu.utils.config import Config
        cfg = Config()
        assert cfg.admin_port == 0
        assert cfg.request_tracing is False
        assert cfg.flight_recorder_path == ""

    def test_serving_path_allocates_nothing_when_off(self):
        before = {t.name for t in threading.enumerate()}
        svc = InferenceService(small_model(), input_spec=((8,),
                                                          np.float32),
                               max_batch_size=4, batch_timeout_ms=0.0,
                               name="inert")
        captured = []
        orig = svc._dispatch

        def spy(requests):
            captured.extend(requests)
            orig(requests)

        svc._batcher._dispatch_fn = spy
        svc.predict(np.zeros((2, 8), np.float32))
        svc.stop()
        # no context was ever allocated, no tracer attached
        assert captured and all(r.ctx is None for r in captured)
        assert svc.tracer is None and svc._request_tracing is False
        # no admin/flight singletons came alive
        assert admin_mod.current() is None
        assert flight_mod.current() is None
        after = {t.name for t in threading.enumerate()}
        assert "bigdl-tpu-admin" not in after
        # only the (now stopped) batcher thread ever existed beyond the
        # baseline set
        assert not {n for n in after - before
                    if not n.startswith("inert-batcher")}


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
