"""Multi-host (2-process) training + failure-retry path tests.

Reference analogs: the local-mode-cluster trick in
``TEST/optim/DistriOptimizerSpec.scala:139`` (distributed without a real
cluster) and the retry-from-checkpoint loop
(``DistriOptimizer.scala:981-1061``).  VERDICT weak #4/#5: these paths
previously had zero coverage.
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset.dataset import AbstractDataSet, DistributedDataSet
from bigdl_tpu.dataset.sample import Sample

HERE = os.path.dirname(os.path.abspath(__file__))


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestMultiHost:
    def _run_pair(self, tmp_path, ckpt=False):
        port = _free_port()
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)  # child sets its own device count
        args_extra = [str(tmp_path / "ckpt")] if ckpt else []
        procs = [subprocess.Popen(
            [sys.executable, os.path.join(HERE, "mh_train_child.py"),
             str(pid), str(port)] + args_extra,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for pid in (0, 1)]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=480)
            outs.append(out)
        for p, out in zip(procs, outs):
            assert p.returncode == 0, out[-3000:]
        results = {}
        for out in outs:
            for line in out.splitlines():
                if line.startswith("RESULT"):
                    _, pid, loss, score = line.split()
                    results[int(pid)] = (float(loss), float(score))
        assert set(results) == {0, 1}, outs
        return results

    def test_two_process_training_agrees(self, tmp_path):
        """Both processes run the SPMD step over one 8-device global mesh:
        losses and validation scores must be bit-identical (lock-step
        collectives), and the model must actually learn."""
        results = self._run_pair(tmp_path)
        (l0, s0), (l1, s1) = results[0], results[1]
        assert l0 == pytest.approx(l1, abs=1e-6)
        assert s0 == pytest.approx(s1, abs=1e-6)
        assert l0 < 0.3, "multi-host training did not learn"
        assert s0 > 0.9

    def test_two_process_checkpoint_written_once(self, tmp_path):
        self._run_pair(tmp_path, ckpt=True)
        ckpts = os.listdir(tmp_path / "ckpt")
        assert any(c.startswith("model") for c in ckpts), ckpts


class _FailOnce(AbstractDataSet):
    """Wraps a dataset; its train iterator raises once at batch N of the
    first pass (the fault-injection the reference only gets implicitly
    from Spark task failures)."""

    def __init__(self, base: AbstractDataSet, fail_at: int):
        self.base = base
        self.fail_at = fail_at
        self.failed = False
        self.count = 0  # global across data() calls (the optimizer
        # recreates the train iterator at each epoch rollover)

    def size(self):
        return self.base.size()

    def shuffle(self):
        self.base.shuffle()

    def data(self, train):
        it = self.base.data(train)
        if not train:
            return it

        def gen():
            for batch in it:
                if not self.failed and self.count == self.fail_at:
                    self.failed = True
                    raise RuntimeError("injected mid-training failure")
                self.count += 1
                yield batch
        return gen()


class TestFailureRetry:
    def _blobs(self):
        rng = np.random.RandomState(0)
        centers = rng.randn(3, 8) * 4.0
        y = rng.randint(0, 3, 256)
        x = (centers[y] + rng.randn(256, 8)).astype(np.float32)
        return [Sample(x[i], np.int32(y[i])) for i in range(256)], x, y

    def _model(self):
        return nn.Sequential(nn.Linear(8, 32), nn.ReLU(),
                             nn.Linear(32, 3), nn.LogSoftMax())

    def test_retry_from_checkpoint_recovers(self, tmp_path):
        samples, x, y = self._blobs()
        base = DataSet.array(samples) >> SampleToMiniBatch(32)
        failing = _FailOnce(base, fail_at=12)  # after epoch-1 checkpoint
        model = self._model()
        opt = (optim.DistriOptimizer(model, failing, nn.ClassNLLCriterion())
               .set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9,
                                           dampening=0.0))
               .set_end_when(optim.max_epoch(4))
               .set_checkpoint(str(tmp_path), optim.every_epoch()))
        opt.optimize()  # must survive the injected failure
        assert failing.failed, "fault was never injected"
        model.training = False
        acc = (np.argmax(np.asarray(model.forward(x)), -1) == y).mean()
        assert acc > 0.9, acc
        # epoch accounting resumed, not restarted
        assert opt.state["epoch"] == 4

    def test_no_checkpoint_propagates_failure(self):
        samples, _, _ = self._blobs()
        failing = _FailOnce(DataSet.array(samples) >> SampleToMiniBatch(32),
                            fail_at=2)
        opt = (optim.DistriOptimizer(self._model(), failing,
                                     nn.ClassNLLCriterion())
               .set_end_when(optim.max_epoch(2)))
        with pytest.raises(RuntimeError, match="injected"):
            opt.optimize()

    def test_optimizer_state_restored_on_retry(self, tmp_path):
        """After retry the momentum buffer comes from the checkpoint —
        the resumed step must not spike the loss (reference reloads the
        OptimMethod state table)."""
        samples, x, y = self._blobs()
        base = DataSet.array(samples) >> SampleToMiniBatch(32)
        failing = _FailOnce(base, fail_at=10)
        model = self._model()
        losses = []

        class Spy(optim.SGD):
            def __init__(self):
                super().__init__(learning_rate=0.1, momentum=0.9,
                                 dampening=0.0)

        opt = (optim.DistriOptimizer(model, failing, nn.ClassNLLCriterion())
               .set_optim_method(Spy())
               .set_end_when(optim.max_epoch(3))
               .set_checkpoint(str(tmp_path), optim.every_epoch()))
        opt.optimize()
        # sanity: completed and converged (state restore means no divergence)
        assert opt.state["loss"] < 0.4
