"""Checked-in SARIF fixture: two violations at FIXED lines.

The test copies this file under a synthetic ``bigdl_tpu/parallel/``
path (library scope, so traced rules are live) and compares the CLI's
``--format sarif`` output against ``sarif_fixture.expected.json``.
Editing this file means regenerating the expected results.
"""

import os

import numpy as np
from jax.experimental import multihost_utils


def maybe_sync(arr, flag_path):
    if os.path.exists(flag_path):                # per-host predicate
        return multihost_utils.process_allgather(arr)  # GL401 (line 17)
    return arr


def noisy_init(shape):
    return np.random.normal(0, 1, shape)         # GL105 (line 22)
