"""Golden-fixture generator — torch (CPU) is the independent oracle.

Analog of the reference's Torch7 parity harness (``TEST/torch/TH.scala:
35-44``: write inputs as .t7, shell out to ``th``, read results back).
Here: initialize the bigdl_tpu layer's params, copy them into the
equivalent torch module, record (input, params, output, grad_input,
grad_params) as an npz fixture.  ``tests/test_fixture_parity.py`` replays
every fixture against the JAX layer — forward AND backward — so layer
semantics are pinned to an independently-computed reference, not to
whatever the implementation happens to produce.

Run from the repo root:  python tests/fixtures/generate_fixtures.py
Regenerates tests/fixtures/data/*.npz deterministically (seeded).
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import torch  # noqa: E402
import torch.nn.functional as F  # noqa: E402

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")

CASES = {}


def case(name):
    def deco(fn):
        CASES[name] = fn
        return fn
    return deco


def _t(x):
    return torch.tensor(np.asarray(x), dtype=torch.float64,
                        requires_grad=False)


def _record(name, params, x, torch_fwd, state=None):
    """Run torch_fwd(params as torch tensors, x) -> out; record fixture.

    grad targets: d(sum(out))/d(x) and /d(each param).  ``state`` entries
    (e.g. BN running stats) reach torch_fwd via ``p`` too but are stored
    as ``s_*`` and replayed through the module STATE dict, without grads.
    All torch math in float64 so the fixture is a high-precision oracle;
    the replay asserts float32-level tolerance.
    """
    state = state or {}
    tp = {k: _t(v).requires_grad_(True) for k, v in params.items()}
    tp.update({k: _t(v) for k, v in state.items()})
    tx = _t(x).requires_grad_(True)
    out = torch_fwd(tp, tx)
    loss = out.sum()
    loss.backward()
    blob = {
        "x": np.asarray(x, np.float64),
        "out": out.detach().numpy(),
        "dx": tx.grad.numpy(),
    }
    for k, v in params.items():
        blob[f"p_{k}"] = np.asarray(v, np.float64)
        blob[f"dp_{k}"] = tp[k].grad.numpy()
    for k, v in state.items():
        blob[f"s_{k}"] = np.asarray(v, np.float64)
    os.makedirs(DATA_DIR, exist_ok=True)
    np.savez(os.path.join(DATA_DIR, f"{name}.npz"), **blob)
    print(f"  {name}: out{tuple(out.shape)}")


# --------------------------------------------------------------- conv 3D
@case("volumetric_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 5, 8, 7))
    params = {"weight": rng.normal(0, 0.1, (4, 3, 2, 3, 3)),
              "bias": rng.normal(0, 0.1, (4,))}

    def fwd(p, x):
        # our kernel is (O, I, kT, kH, kW); torch conv3d wants
        # (O, I, kT, kH, kW) with input (N, C, D, H, W) — same layout
        return F.conv3d(x, p["weight"], p["bias"], stride=(1, 2, 2),
                        padding=(0, 1, 1))
    _record("volumetric_convolution", params, x, fwd)


@case("volumetric_max_pooling")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 6, 8, 8))
    _record("volumetric_max_pooling", {}, x,
            lambda p, x: F.max_pool3d(x, (2, 2, 2), stride=(2, 2, 2),
                                      padding=0))


@case("volumetric_avg_pooling")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 6, 8, 8))
    _record("volumetric_avg_pooling", {}, x,
            lambda p, x: F.avg_pool3d(x, (2, 2, 2), stride=(2, 2, 2)))


@case("volumetric_full_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 4, 4, 5, 5))
    params = {"weight": rng.normal(0, 0.1, (4, 3, 2, 3, 3)),  # (I,O,kT,kH,kW)
              "bias": rng.normal(0, 0.1, (3,))}

    def fwd(p, x):
        return F.conv_transpose3d(x, p["weight"], p["bias"],
                                  stride=(2, 2, 2), padding=(0, 1, 1),
                                  output_padding=(1, 0, 0))
    _record("volumetric_full_convolution", params, x, fwd)


# ---------------------------------------------------------- spatial extras
@case("spatial_dilated_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 9, 9))
    params = {"weight": rng.normal(0, 0.1, (5, 3, 3, 3)),
              "bias": rng.normal(0, 0.1, (5,))}
    _record("spatial_dilated_convolution", params, x,
            lambda p, x: F.conv2d(x, p["weight"], p["bias"], stride=1,
                                  padding=2, dilation=2))


@case("spatial_separable_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 8, 8))
    params = {"depth_weight": rng.normal(0, 0.1, (6, 1, 3, 3)),
              "point_weight": rng.normal(0, 0.1, (4, 6, 1, 1)),
              "bias": rng.normal(0, 0.1, (4,))}

    def fwd(p, x):
        y = F.conv2d(x, p["depth_weight"], None, stride=1, padding=1,
                     groups=3)
        return F.conv2d(y, p["point_weight"], p["bias"])
    _record("spatial_separable_convolution", params, x, fwd)


@case("locally_connected_2d")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 6, 6))
    kh = kw = 3
    oh = ow = 4  # (6 - 3)//1 + 1
    params = {"weight": rng.normal(0, 0.1, (oh, ow, 4, 3 * kh * kw)),
              "bias": rng.normal(0, 0.1, (4, oh, ow))}

    def fwd(p, x):
        patches = F.unfold(x, (kh, kw))  # (N, C*kh*kw, L)
        patches = patches.reshape(x.shape[0], -1, oh, ow)
        y = torch.einsum("nkhw,hwok->nohw", patches, p["weight"])
        return y + p["bias"][None]
    _record("locally_connected_2d", params, x, fwd)


@case("locally_connected_1d")
def _(rng):
    x = rng.normal(0, 1, (2, 7, 5))  # (N, T, C)
    kw, stride, ot = 3, 2, 3  # (7-3)//2+1
    params = {"weight": rng.normal(0, 0.1, (ot, 4, kw * 5)),
              "bias": rng.normal(0, 0.1, (ot, 4))}

    def fwd(p, x):
        wins = torch.stack([x[:, t * stride:t * stride + kw].reshape(
            x.shape[0], -1) for t in range(ot)], dim=1)  # (N, oT, kw*C)
        y = torch.einsum("ntk,tok->nto", wins, p["weight"])
        return y + p["bias"][None]
    _record("locally_connected_1d", params, x, fwd)


@case("spatial_within_channel_lrn")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 7, 7))
    size, alpha, beta = 5, 1.0, 0.75

    def fwd(p, x):
        sq = x * x
        summed = F.avg_pool2d(sq, size, stride=1, padding=size // 2,
                              count_include_pad=True) * (size * size)
        return x / (1.0 + alpha / (size * size) * summed) ** beta
    _record("spatial_within_channel_lrn", {}, x, fwd)


@case("upsampling_2d")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 4, 5))
    _record("upsampling_2d", {}, x,
            lambda p, x: F.interpolate(x, scale_factor=(2, 3),
                                       mode="nearest"))


@case("upsampling_3d")
def _(rng):
    x = rng.normal(0, 1, (2, 2, 3, 4, 4))
    _record("upsampling_3d", {}, x,
            lambda p, x: F.interpolate(x, scale_factor=(2, 2, 2),
                                       mode="nearest"))


@case("resize_bilinear_align")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 5, 5))
    _record("resize_bilinear_align", {}, x,
            lambda p, x: F.interpolate(x, size=(8, 9), mode="bilinear",
                                       align_corners=True))


@case("temporal_max_pooling")
def _(rng):
    x = rng.normal(0, 1, (2, 8, 4))  # (N, T, C)

    def fwd(p, x):
        return F.max_pool1d(x.transpose(1, 2), 2, 2).transpose(1, 2)
    _record("temporal_max_pooling", {}, x, fwd)


@case("temporal_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 9, 5))  # (N, T, C)
    params = {"weight": rng.normal(0, 0.1, (6, 5, 3)),  # (O, C, kw)
              "bias": rng.normal(0, 0.1, (6,))}

    def fwd(p, x):
        return F.conv1d(x.transpose(1, 2), p["weight"], p["bias"],
                        stride=2).transpose(1, 2)
    _record("temporal_convolution", params, x, fwd)


def main(only=None):
    import zlib
    for name, fn in CASES.items():
        if only and only not in name:
            continue
        # crc32 is stable across processes/machines (Python's str hash is
        # salted per process), so regeneration is byte-reproducible
        fn(np.random.default_rng(zlib.crc32(name.encode()) % (2**31)))
    print(f"{len(CASES)} fixtures written to {DATA_DIR}")



# ====================================================== round-2b batch
# core 2-D layers, normalization, activations, criterions — the grind
# toward VERDICT item 4's "each with a fixture test"
@case("spatial_convolution_pad_stride")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 9, 9))
    params = {"weight": rng.normal(0, 0.2, (5, 3, 3, 3)),
              "bias": rng.normal(0, 0.1, (5,))}

    def fwd(p, x):
        return F.conv2d(x, p["weight"], p["bias"], stride=2, padding=1)
    _record("spatial_convolution_pad_stride", params, x, fwd)


@case("spatial_convolution_grouped")
def _(rng):
    x = rng.normal(0, 1, (2, 4, 8, 8))
    params = {"weight": rng.normal(0, 0.2, (6, 2, 3, 3)),
              "bias": rng.normal(0, 0.1, (6,))}

    def fwd(p, x):
        return F.conv2d(x, p["weight"], p["bias"], groups=2)
    _record("spatial_convolution_grouped", params, x, fwd)


@case("spatial_full_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 4, 5, 5))
    params = {"weight": rng.normal(0, 0.2, (4, 3, 3, 3)),  # (in, out, kh, kw)
              "bias": rng.normal(0, 0.1, (3,))}

    def fwd(p, x):
        return F.conv_transpose2d(x, p["weight"], p["bias"], stride=2,
                                  padding=1, output_padding=1)
    _record("spatial_full_convolution", params, x, fwd)


@case("spatial_max_pooling_ceil")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 7, 7))

    def fwd(p, x):
        return F.max_pool2d(x, 3, stride=2, ceil_mode=True)
    _record("spatial_max_pooling_ceil", {}, x, fwd)


@case("spatial_avg_pooling_pad")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 8, 8))

    def fwd(p, x):
        return F.avg_pool2d(x, 3, stride=2, padding=1,
                            count_include_pad=True)
    _record("spatial_avg_pooling_pad", {}, x, fwd)


@case("linear")
def _(rng):
    x = rng.normal(0, 1, (4, 7))
    params = {"weight": rng.normal(0, 0.3, (5, 7)),
              "bias": rng.normal(0, 0.1, (5,))}

    def fwd(p, x):
        return F.linear(x, p["weight"], p["bias"])
    _record("linear", params, x, fwd)


@case("spatial_batch_norm_eval")
def _(rng):
    x = rng.normal(0, 1, (3, 4, 5, 5))
    params = {"weight": rng.uniform(0.5, 1.5, (4,)),
              "bias": rng.normal(0, 0.2, (4,))}
    state = {"running_mean": rng.normal(0, 0.3, (4,)),
             "running_var": rng.uniform(0.5, 2.0, (4,))}

    def fwd(p, x):
        return F.batch_norm(x, p["running_mean"], p["running_var"],
                            p["weight"], p["bias"], training=False,
                            eps=1e-5)
    _record("spatial_batch_norm_eval", params, x, fwd, state=state)


@case("prelu")
def _(rng):
    x = rng.normal(0, 1, (3, 4, 5))
    params = {"weight": rng.uniform(0.1, 0.4, (1,))}  # our PReLU key

    def fwd(p, x):
        return F.prelu(x, p["weight"])
    _record("prelu", params, x, fwd)


@case("elu")
def _(rng):
    x = rng.normal(0, 2, (3, 6))

    def fwd(p, x):
        return F.elu(x, alpha=1.0)
    _record("elu", {}, x, fwd)


@case("softplus")
def _(rng):
    x = rng.normal(0, 2, (3, 6))

    def fwd(p, x):
        return F.softplus(x)
    _record("softplus", {}, x, fwd)


@case("hard_tanh")
def _(rng):
    x = rng.normal(0, 2, (3, 6))

    def fwd(p, x):
        return F.hardtanh(x, -1.0, 1.0)
    _record("hard_tanh", {}, x, fwd)


@case("spatial_cross_map_lrn")
def _(rng):
    x = rng.uniform(0.1, 1.0, (2, 8, 5, 5))

    def fwd(p, x):
        return F.local_response_norm(x, size=5, alpha=1.0, beta=0.75, k=1.0)
    _record("spatial_cross_map_lrn", {}, x, fwd)


# ------------------------------------------------------------ criterions
def _record_criterion(name, x, target, torch_loss):
    tx = _t(x).requires_grad_(True)
    tt = torch.tensor(np.asarray(target))
    loss = torch_loss(tx, tt)
    loss.backward()
    os.makedirs(DATA_DIR, exist_ok=True)
    np.savez(os.path.join(DATA_DIR, f"crit_{name}.npz"),
             x=np.asarray(x, np.float64), target=np.asarray(target),
             loss=loss.detach().numpy(), dx=tx.grad.numpy())
    print(f"  crit_{name}: loss={float(loss):.6f}")


@case("crit_mse")
def _(rng):
    _record_criterion("mse", rng.normal(0, 1, (4, 5)),
                      rng.normal(0, 1, (4, 5)),
                      lambda x, t: F.mse_loss(x, t))


@case("crit_abs")
def _(rng):
    _record_criterion("abs", rng.normal(0, 1, (4, 5)),
                      rng.normal(0, 1, (4, 5)),
                      lambda x, t: F.l1_loss(x, t))


@case("crit_bce")
def _(rng):
    _record_criterion("bce", rng.uniform(0.05, 0.95, (4, 5)),
                      rng.integers(0, 2, (4, 5)).astype(np.float64),
                      lambda x, t: F.binary_cross_entropy(x, t))


@case("crit_smooth_l1")
def _(rng):
    _record_criterion("smooth_l1", rng.normal(0, 2, (4, 5)),
                      rng.normal(0, 2, (4, 5)),
                      lambda x, t: F.smooth_l1_loss(x, t))


@case("crit_class_nll_weighted")
def _(rng):
    logits = rng.normal(0, 1, (6, 4))
    logp = np.log(np.exp(logits) / np.exp(logits).sum(1, keepdims=True))
    target = rng.integers(0, 4, (6,)).astype(np.int64)
    w = torch.tensor([0.5, 1.0, 2.0, 1.5], dtype=torch.float64)
    _record_criterion("class_nll_weighted", logp, target,
                      lambda x, t: F.nll_loss(x, t, weight=w))


@case("crit_dist_kl")
def _(rng):
    logp = np.log(rng.dirichlet(np.ones(5), size=4))
    q = rng.dirichlet(np.ones(5), size=4)
    _record_criterion("dist_kl", logp, q,
                      lambda x, t: F.kl_div(x, t, reduction="batchmean"))


@case("crit_soft_margin")
def _(rng):
    x = rng.normal(0, 1, (4, 5))
    t = rng.choice([-1.0, 1.0], (4, 5))
    _record_criterion("soft_margin", x, t,
                      lambda x, t: F.soft_margin_loss(x, t))


@case("crit_hinge_embedding")
def _(rng):
    x = rng.uniform(0, 2, (8,))
    t = rng.choice([-1.0, 1.0], (8,))
    _record_criterion("hinge_embedding", x, t,
                      lambda x, t: F.hinge_embedding_loss(x, t,
                                                          margin=1.0))


@case("crit_multilabel_soft_margin")
def _(rng):
    x = rng.normal(0, 1, (4, 6))
    t = rng.integers(0, 2, (4, 6)).astype(np.float64)
    _record_criterion("multilabel_soft_margin", x, t,
                      lambda x, t: F.multilabel_soft_margin_loss(x, t))


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
