"""Golden-fixture generator — torch (CPU) is the independent oracle.

Analog of the reference's Torch7 parity harness (``TEST/torch/TH.scala:
35-44``: write inputs as .t7, shell out to ``th``, read results back).
Here: initialize the bigdl_tpu layer's params, copy them into the
equivalent torch module, record (input, params, output, grad_input,
grad_params) as an npz fixture.  ``tests/test_fixture_parity.py`` replays
every fixture against the JAX layer — forward AND backward — so layer
semantics are pinned to an independently-computed reference, not to
whatever the implementation happens to produce.

Run from the repo root:  python tests/fixtures/generate_fixtures.py
Regenerates tests/fixtures/data/*.npz deterministically (seeded).
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import torch  # noqa: E402
import torch.nn.functional as F  # noqa: E402

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")

CASES = {}


def case(name):
    def deco(fn):
        CASES[name] = fn
        return fn
    return deco


def _t(x):
    return torch.tensor(np.asarray(x), dtype=torch.float64,
                        requires_grad=False)


def _record(name, params, x, torch_fwd, extra_inputs=None):
    """Run torch_fwd(params as torch tensors, x) -> out; record fixture.

    grad targets: d(sum(out))/d(x) and /d(each param).
    All torch math in float64 so the fixture is a high-precision oracle;
    the replay asserts float32-level tolerance.
    """
    tp = {k: _t(v).requires_grad_(True) for k, v in params.items()}
    tx = _t(x).requires_grad_(True)
    out = torch_fwd(tp, tx)
    loss = out.sum()
    loss.backward()
    blob = {
        "x": np.asarray(x, np.float64),
        "out": out.detach().numpy(),
        "dx": tx.grad.numpy(),
    }
    for k, v in params.items():
        blob[f"p_{k}"] = np.asarray(v, np.float64)
        blob[f"dp_{k}"] = tp[k].grad.numpy()
    os.makedirs(DATA_DIR, exist_ok=True)
    np.savez(os.path.join(DATA_DIR, f"{name}.npz"), **blob)
    print(f"  {name}: out{tuple(out.shape)}")


# --------------------------------------------------------------- conv 3D
@case("volumetric_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 5, 8, 7))
    params = {"weight": rng.normal(0, 0.1, (4, 3, 2, 3, 3)),
              "bias": rng.normal(0, 0.1, (4,))}

    def fwd(p, x):
        # our kernel is (O, I, kT, kH, kW); torch conv3d wants
        # (O, I, kT, kH, kW) with input (N, C, D, H, W) — same layout
        return F.conv3d(x, p["weight"], p["bias"], stride=(1, 2, 2),
                        padding=(0, 1, 1))
    _record("volumetric_convolution", params, x, fwd)


@case("volumetric_max_pooling")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 6, 8, 8))
    _record("volumetric_max_pooling", {}, x,
            lambda p, x: F.max_pool3d(x, (2, 2, 2), stride=(2, 2, 2),
                                      padding=0))


@case("volumetric_avg_pooling")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 6, 8, 8))
    _record("volumetric_avg_pooling", {}, x,
            lambda p, x: F.avg_pool3d(x, (2, 2, 2), stride=(2, 2, 2)))


@case("volumetric_full_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 4, 4, 5, 5))
    params = {"weight": rng.normal(0, 0.1, (4, 3, 2, 3, 3)),  # (I,O,kT,kH,kW)
              "bias": rng.normal(0, 0.1, (3,))}

    def fwd(p, x):
        return F.conv_transpose3d(x, p["weight"], p["bias"],
                                  stride=(2, 2, 2), padding=(0, 1, 1),
                                  output_padding=(1, 0, 0))
    _record("volumetric_full_convolution", params, x, fwd)


# ---------------------------------------------------------- spatial extras
@case("spatial_dilated_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 9, 9))
    params = {"weight": rng.normal(0, 0.1, (5, 3, 3, 3)),
              "bias": rng.normal(0, 0.1, (5,))}
    _record("spatial_dilated_convolution", params, x,
            lambda p, x: F.conv2d(x, p["weight"], p["bias"], stride=1,
                                  padding=2, dilation=2))


@case("spatial_separable_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 8, 8))
    params = {"depth_weight": rng.normal(0, 0.1, (6, 1, 3, 3)),
              "point_weight": rng.normal(0, 0.1, (4, 6, 1, 1)),
              "bias": rng.normal(0, 0.1, (4,))}

    def fwd(p, x):
        y = F.conv2d(x, p["depth_weight"], None, stride=1, padding=1,
                     groups=3)
        return F.conv2d(y, p["point_weight"], p["bias"])
    _record("spatial_separable_convolution", params, x, fwd)


@case("locally_connected_2d")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 6, 6))
    kh = kw = 3
    oh = ow = 4  # (6 - 3)//1 + 1
    params = {"weight": rng.normal(0, 0.1, (oh, ow, 4, 3 * kh * kw)),
              "bias": rng.normal(0, 0.1, (4, oh, ow))}

    def fwd(p, x):
        patches = F.unfold(x, (kh, kw))  # (N, C*kh*kw, L)
        patches = patches.reshape(x.shape[0], -1, oh, ow)
        y = torch.einsum("nkhw,hwok->nohw", patches, p["weight"])
        return y + p["bias"][None]
    _record("locally_connected_2d", params, x, fwd)


@case("locally_connected_1d")
def _(rng):
    x = rng.normal(0, 1, (2, 7, 5))  # (N, T, C)
    kw, stride, ot = 3, 2, 3  # (7-3)//2+1
    params = {"weight": rng.normal(0, 0.1, (ot, 4, kw * 5)),
              "bias": rng.normal(0, 0.1, (ot, 4))}

    def fwd(p, x):
        wins = torch.stack([x[:, t * stride:t * stride + kw].reshape(
            x.shape[0], -1) for t in range(ot)], dim=1)  # (N, oT, kw*C)
        y = torch.einsum("ntk,tok->nto", wins, p["weight"])
        return y + p["bias"][None]
    _record("locally_connected_1d", params, x, fwd)


@case("spatial_within_channel_lrn")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 7, 7))
    size, alpha, beta = 5, 1.0, 0.75

    def fwd(p, x):
        sq = x * x
        summed = F.avg_pool2d(sq, size, stride=1, padding=size // 2,
                              count_include_pad=True) * (size * size)
        return x / (1.0 + alpha / (size * size) * summed) ** beta
    _record("spatial_within_channel_lrn", {}, x, fwd)


@case("upsampling_2d")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 4, 5))
    _record("upsampling_2d", {}, x,
            lambda p, x: F.interpolate(x, scale_factor=(2, 3),
                                       mode="nearest"))


@case("upsampling_3d")
def _(rng):
    x = rng.normal(0, 1, (2, 2, 3, 4, 4))
    _record("upsampling_3d", {}, x,
            lambda p, x: F.interpolate(x, scale_factor=(2, 2, 2),
                                       mode="nearest"))


@case("resize_bilinear_align")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 5, 5))
    _record("resize_bilinear_align", {}, x,
            lambda p, x: F.interpolate(x, size=(8, 9), mode="bilinear",
                                       align_corners=True))


@case("temporal_max_pooling")
def _(rng):
    x = rng.normal(0, 1, (2, 8, 4))  # (N, T, C)

    def fwd(p, x):
        return F.max_pool1d(x.transpose(1, 2), 2, 2).transpose(1, 2)
    _record("temporal_max_pooling", {}, x, fwd)


@case("temporal_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 9, 5))  # (N, T, C)
    params = {"weight": rng.normal(0, 0.1, (6, 5, 3)),  # (O, C, kw)
              "bias": rng.normal(0, 0.1, (6,))}

    def fwd(p, x):
        return F.conv1d(x.transpose(1, 2), p["weight"], p["bias"],
                        stride=2).transpose(1, 2)
    _record("temporal_convolution", params, x, fwd)


def main(only=None):
    import zlib
    for name, fn in CASES.items():
        if only and only not in name:
            continue
        # crc32 is stable across processes/machines (Python's str hash is
        # salted per process), so regeneration is byte-reproducible
        fn(np.random.default_rng(zlib.crc32(name.encode()) % (2**31)))
    print(f"{len(CASES)} fixtures written to {DATA_DIR}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
