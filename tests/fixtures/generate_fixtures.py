"""Golden-fixture generator — torch (CPU) is the independent oracle.

Analog of the reference's Torch7 parity harness (``TEST/torch/TH.scala:
35-44``: write inputs as .t7, shell out to ``th``, read results back).
Here: initialize the bigdl_tpu layer's params, copy them into the
equivalent torch module, record (input, params, output, grad_input,
grad_params) as an npz fixture.  ``tests/test_fixture_parity.py`` replays
every fixture against the JAX layer — forward AND backward — so layer
semantics are pinned to an independently-computed reference, not to
whatever the implementation happens to produce.

Run from the repo root:  python tests/fixtures/generate_fixtures.py
Regenerates tests/fixtures/data/*.npz deterministically (seeded).
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import torch  # noqa: E402
import torch.nn.functional as F  # noqa: E402

DATA_DIR = os.path.join(os.path.dirname(__file__), "data")

CASES = {}


def case(name):
    def deco(fn):
        CASES[name] = fn
        return fn
    return deco


def _t(x):
    return torch.tensor(np.asarray(x), dtype=torch.float64,
                        requires_grad=False)


def _record(name, params, x, torch_fwd, state=None):
    """Run torch_fwd(params as torch tensors, x) -> out; record fixture.

    grad targets: d(sum(out))/d(x) and /d(each param).  ``state`` entries
    (e.g. BN running stats) reach torch_fwd via ``p`` too but are stored
    as ``s_*`` and replayed through the module STATE dict, without grads.
    All torch math in float64 so the fixture is a high-precision oracle;
    the replay asserts float32-level tolerance.
    """
    state = state or {}
    tp = {k: _t(v).requires_grad_(True) for k, v in params.items()}
    tp.update({k: _t(v) for k, v in state.items()})
    tx = _t(x).requires_grad_(True)
    out = torch_fwd(tp, tx)
    loss = out.sum()
    loss.backward()
    blob = {
        "x": np.asarray(x, np.float64),
        "out": out.detach().numpy(),
        "dx": tx.grad.numpy(),
    }
    for k, v in params.items():
        blob[f"p_{k}"] = np.asarray(v, np.float64)
        blob[f"dp_{k}"] = tp[k].grad.numpy()
    for k, v in state.items():
        blob[f"s_{k}"] = np.asarray(v, np.float64)
    os.makedirs(DATA_DIR, exist_ok=True)
    np.savez(os.path.join(DATA_DIR, f"{name}.npz"), **blob)
    print(f"  {name}: out{tuple(out.shape)}")


# --------------------------------------------------------------- conv 3D
@case("volumetric_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 5, 8, 7))
    params = {"weight": rng.normal(0, 0.1, (4, 3, 2, 3, 3)),
              "bias": rng.normal(0, 0.1, (4,))}

    def fwd(p, x):
        # our kernel is (O, I, kT, kH, kW); torch conv3d wants
        # (O, I, kT, kH, kW) with input (N, C, D, H, W) — same layout
        return F.conv3d(x, p["weight"], p["bias"], stride=(1, 2, 2),
                        padding=(0, 1, 1))
    _record("volumetric_convolution", params, x, fwd)


@case("volumetric_max_pooling")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 6, 8, 8))
    _record("volumetric_max_pooling", {}, x,
            lambda p, x: F.max_pool3d(x, (2, 2, 2), stride=(2, 2, 2),
                                      padding=0))


@case("volumetric_avg_pooling")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 6, 8, 8))
    _record("volumetric_avg_pooling", {}, x,
            lambda p, x: F.avg_pool3d(x, (2, 2, 2), stride=(2, 2, 2)))


@case("volumetric_full_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 4, 4, 5, 5))
    params = {"weight": rng.normal(0, 0.1, (4, 3, 2, 3, 3)),  # (I,O,kT,kH,kW)
              "bias": rng.normal(0, 0.1, (3,))}

    def fwd(p, x):
        return F.conv_transpose3d(x, p["weight"], p["bias"],
                                  stride=(2, 2, 2), padding=(0, 1, 1),
                                  output_padding=(1, 0, 0))
    _record("volumetric_full_convolution", params, x, fwd)


# ---------------------------------------------------------- spatial extras
@case("spatial_dilated_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 9, 9))
    params = {"weight": rng.normal(0, 0.1, (5, 3, 3, 3)),
              "bias": rng.normal(0, 0.1, (5,))}
    _record("spatial_dilated_convolution", params, x,
            lambda p, x: F.conv2d(x, p["weight"], p["bias"], stride=1,
                                  padding=2, dilation=2))


@case("spatial_separable_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 8, 8))
    params = {"depth_weight": rng.normal(0, 0.1, (6, 1, 3, 3)),
              "point_weight": rng.normal(0, 0.1, (4, 6, 1, 1)),
              "bias": rng.normal(0, 0.1, (4,))}

    def fwd(p, x):
        y = F.conv2d(x, p["depth_weight"], None, stride=1, padding=1,
                     groups=3)
        return F.conv2d(y, p["point_weight"], p["bias"])
    _record("spatial_separable_convolution", params, x, fwd)


@case("locally_connected_2d")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 6, 6))
    kh = kw = 3
    oh = ow = 4  # (6 - 3)//1 + 1
    params = {"weight": rng.normal(0, 0.1, (oh, ow, 4, 3 * kh * kw)),
              "bias": rng.normal(0, 0.1, (4, oh, ow))}

    def fwd(p, x):
        patches = F.unfold(x, (kh, kw))  # (N, C*kh*kw, L)
        patches = patches.reshape(x.shape[0], -1, oh, ow)
        y = torch.einsum("nkhw,hwok->nohw", patches, p["weight"])
        return y + p["bias"][None]
    _record("locally_connected_2d", params, x, fwd)


@case("locally_connected_1d")
def _(rng):
    x = rng.normal(0, 1, (2, 7, 5))  # (N, T, C)
    kw, stride, ot = 3, 2, 3  # (7-3)//2+1
    params = {"weight": rng.normal(0, 0.1, (ot, 4, kw * 5)),
              "bias": rng.normal(0, 0.1, (ot, 4))}

    def fwd(p, x):
        wins = torch.stack([x[:, t * stride:t * stride + kw].reshape(
            x.shape[0], -1) for t in range(ot)], dim=1)  # (N, oT, kw*C)
        y = torch.einsum("ntk,tok->nto", wins, p["weight"])
        return y + p["bias"][None]
    _record("locally_connected_1d", params, x, fwd)


@case("spatial_within_channel_lrn")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 7, 7))
    size, alpha, beta = 5, 1.0, 0.75

    def fwd(p, x):
        sq = x * x
        summed = F.avg_pool2d(sq, size, stride=1, padding=size // 2,
                              count_include_pad=True) * (size * size)
        return x / (1.0 + alpha / (size * size) * summed) ** beta
    _record("spatial_within_channel_lrn", {}, x, fwd)


@case("upsampling_2d")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 4, 5))
    _record("upsampling_2d", {}, x,
            lambda p, x: F.interpolate(x, scale_factor=(2, 3),
                                       mode="nearest"))


@case("upsampling_3d")
def _(rng):
    x = rng.normal(0, 1, (2, 2, 3, 4, 4))
    _record("upsampling_3d", {}, x,
            lambda p, x: F.interpolate(x, scale_factor=(2, 2, 2),
                                       mode="nearest"))


@case("resize_bilinear_align")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 5, 5))
    _record("resize_bilinear_align", {}, x,
            lambda p, x: F.interpolate(x, size=(8, 9), mode="bilinear",
                                       align_corners=True))


@case("temporal_max_pooling")
def _(rng):
    x = rng.normal(0, 1, (2, 8, 4))  # (N, T, C)

    def fwd(p, x):
        return F.max_pool1d(x.transpose(1, 2), 2, 2).transpose(1, 2)
    _record("temporal_max_pooling", {}, x, fwd)


@case("temporal_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 9, 5))  # (N, T, C)
    params = {"weight": rng.normal(0, 0.1, (6, 5, 3)),  # (O, C, kw)
              "bias": rng.normal(0, 0.1, (6,))}

    def fwd(p, x):
        return F.conv1d(x.transpose(1, 2), p["weight"], p["bias"],
                        stride=2).transpose(1, 2)
    _record("temporal_convolution", params, x, fwd)


def main(only=None):
    import zlib
    for name, fn in CASES.items():
        if only and only not in name:
            continue
        # crc32 is stable across processes/machines (Python's str hash is
        # salted per process), so regeneration is byte-reproducible
        fn(np.random.default_rng(zlib.crc32(name.encode()) % (2**31)))
    print(f"{len(CASES)} fixtures written to {DATA_DIR}")



# ====================================================== round-2b batch
# core 2-D layers, normalization, activations, criterions — the grind
# toward VERDICT item 4's "each with a fixture test"
@case("spatial_convolution_pad_stride")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 9, 9))
    params = {"weight": rng.normal(0, 0.2, (5, 3, 3, 3)),
              "bias": rng.normal(0, 0.1, (5,))}

    def fwd(p, x):
        return F.conv2d(x, p["weight"], p["bias"], stride=2, padding=1)
    _record("spatial_convolution_pad_stride", params, x, fwd)


@case("spatial_convolution_grouped")
def _(rng):
    x = rng.normal(0, 1, (2, 4, 8, 8))
    params = {"weight": rng.normal(0, 0.2, (6, 2, 3, 3)),
              "bias": rng.normal(0, 0.1, (6,))}

    def fwd(p, x):
        return F.conv2d(x, p["weight"], p["bias"], groups=2)
    _record("spatial_convolution_grouped", params, x, fwd)


@case("spatial_full_convolution")
def _(rng):
    x = rng.normal(0, 1, (2, 4, 5, 5))
    params = {"weight": rng.normal(0, 0.2, (4, 3, 3, 3)),  # (in, out, kh, kw)
              "bias": rng.normal(0, 0.1, (3,))}

    def fwd(p, x):
        return F.conv_transpose2d(x, p["weight"], p["bias"], stride=2,
                                  padding=1, output_padding=1)
    _record("spatial_full_convolution", params, x, fwd)


@case("spatial_max_pooling_ceil")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 7, 7))

    def fwd(p, x):
        return F.max_pool2d(x, 3, stride=2, ceil_mode=True)
    _record("spatial_max_pooling_ceil", {}, x, fwd)


@case("spatial_avg_pooling_pad")
def _(rng):
    x = rng.normal(0, 1, (2, 3, 8, 8))

    def fwd(p, x):
        return F.avg_pool2d(x, 3, stride=2, padding=1,
                            count_include_pad=True)
    _record("spatial_avg_pooling_pad", {}, x, fwd)


@case("linear")
def _(rng):
    x = rng.normal(0, 1, (4, 7))
    params = {"weight": rng.normal(0, 0.3, (5, 7)),
              "bias": rng.normal(0, 0.1, (5,))}

    def fwd(p, x):
        return F.linear(x, p["weight"], p["bias"])
    _record("linear", params, x, fwd)


@case("spatial_batch_norm_eval")
def _(rng):
    x = rng.normal(0, 1, (3, 4, 5, 5))
    params = {"weight": rng.uniform(0.5, 1.5, (4,)),
              "bias": rng.normal(0, 0.2, (4,))}
    state = {"running_mean": rng.normal(0, 0.3, (4,)),
             "running_var": rng.uniform(0.5, 2.0, (4,))}

    def fwd(p, x):
        return F.batch_norm(x, p["running_mean"], p["running_var"],
                            p["weight"], p["bias"], training=False,
                            eps=1e-5)
    _record("spatial_batch_norm_eval", params, x, fwd, state=state)


@case("prelu")
def _(rng):
    x = rng.normal(0, 1, (3, 4, 5))
    params = {"weight": rng.uniform(0.1, 0.4, (1,))}  # our PReLU key

    def fwd(p, x):
        return F.prelu(x, p["weight"])
    _record("prelu", params, x, fwd)


@case("elu")
def _(rng):
    x = rng.normal(0, 2, (3, 6))

    def fwd(p, x):
        return F.elu(x, alpha=1.0)
    _record("elu", {}, x, fwd)


@case("softplus")
def _(rng):
    x = rng.normal(0, 2, (3, 6))

    def fwd(p, x):
        return F.softplus(x)
    _record("softplus", {}, x, fwd)


@case("hard_tanh")
def _(rng):
    x = rng.normal(0, 2, (3, 6))

    def fwd(p, x):
        return F.hardtanh(x, -1.0, 1.0)
    _record("hard_tanh", {}, x, fwd)


@case("spatial_cross_map_lrn")
def _(rng):
    x = rng.uniform(0.1, 1.0, (2, 8, 5, 5))

    def fwd(p, x):
        return F.local_response_norm(x, size=5, alpha=1.0, beta=0.75, k=1.0)
    _record("spatial_cross_map_lrn", {}, x, fwd)


# ------------------------------------------------------------ criterions
def _record_criterion(name, x, target, torch_loss):
    tx = _t(x).requires_grad_(True)
    tt = torch.tensor(np.asarray(target))
    loss = torch_loss(tx, tt)
    loss.backward()
    os.makedirs(DATA_DIR, exist_ok=True)
    np.savez(os.path.join(DATA_DIR, f"crit_{name}.npz"),
             x=np.asarray(x, np.float64), target=np.asarray(target),
             loss=loss.detach().numpy(), dx=tx.grad.numpy())
    print(f"  crit_{name}: loss={float(loss):.6f}")


@case("crit_mse")
def _(rng):
    _record_criterion("mse", rng.normal(0, 1, (4, 5)),
                      rng.normal(0, 1, (4, 5)),
                      lambda x, t: F.mse_loss(x, t))


@case("crit_abs")
def _(rng):
    _record_criterion("abs", rng.normal(0, 1, (4, 5)),
                      rng.normal(0, 1, (4, 5)),
                      lambda x, t: F.l1_loss(x, t))


@case("crit_bce")
def _(rng):
    _record_criterion("bce", rng.uniform(0.05, 0.95, (4, 5)),
                      rng.integers(0, 2, (4, 5)).astype(np.float64),
                      lambda x, t: F.binary_cross_entropy(x, t))


@case("crit_smooth_l1")
def _(rng):
    _record_criterion("smooth_l1", rng.normal(0, 2, (4, 5)),
                      rng.normal(0, 2, (4, 5)),
                      lambda x, t: F.smooth_l1_loss(x, t))


@case("crit_class_nll_weighted")
def _(rng):
    logits = rng.normal(0, 1, (6, 4))
    logp = np.log(np.exp(logits) / np.exp(logits).sum(1, keepdims=True))
    target = rng.integers(0, 4, (6,)).astype(np.int64)
    w = torch.tensor([0.5, 1.0, 2.0, 1.5], dtype=torch.float64)
    _record_criterion("class_nll_weighted", logp, target,
                      lambda x, t: F.nll_loss(x, t, weight=w))


@case("crit_dist_kl")
def _(rng):
    logp = np.log(rng.dirichlet(np.ones(5), size=4))
    q = rng.dirichlet(np.ones(5), size=4)
    _record_criterion("dist_kl", logp, q,
                      lambda x, t: F.kl_div(x, t, reduction="batchmean"))


@case("crit_soft_margin")
def _(rng):
    x = rng.normal(0, 1, (4, 5))
    t = rng.choice([-1.0, 1.0], (4, 5))
    _record_criterion("soft_margin", x, t,
                      lambda x, t: F.soft_margin_loss(x, t))


@case("crit_hinge_embedding")
def _(rng):
    x = rng.uniform(0, 2, (8,))
    t = rng.choice([-1.0, 1.0], (8,))
    _record_criterion("hinge_embedding", x, t,
                      lambda x, t: F.hinge_embedding_loss(x, t,
                                                          margin=1.0))


@case("crit_multilabel_soft_margin")
def _(rng):
    x = rng.normal(0, 1, (4, 6))
    t = rng.integers(0, 2, (4, 6)).astype(np.float64)
    _record_criterion("multilabel_soft_margin", x, t,
                      lambda x, t: F.multilabel_soft_margin_loss(x, t))


# ====================================================== round-3 batch
# recurrent cells, BN TRAINING mode, embeddings, the remaining
# criterions, activation sweep — VERDICT r2 "Next #3" (35 → ~110)
def _save(name, **blob):
    os.makedirs(DATA_DIR, exist_ok=True)
    np.savez(os.path.join(DATA_DIR, f"{name}.npz"),
             **{k: np.asarray(v) for k, v in blob.items()})
    print(f"  {name}")


def _record_train_state(name, params, x, torch_fwd, state):
    """Like _record but torch_fwd also mutates running-stat tensors
    (BN training): records the UPDATED stats as ns_* entries."""
    tp = {k: _t(v).requires_grad_(True) for k, v in params.items()}
    ts = {k: _t(v) for k, v in state.items()}
    tx = _t(x).requires_grad_(True)
    out = torch_fwd(tp, ts, tx)
    out.sum().backward()
    blob = {"x": np.asarray(x, np.float64), "out": out.detach().numpy(),
            "dx": tx.grad.numpy()}
    for k, v in params.items():
        blob[f"p_{k}"] = np.asarray(v, np.float64)
        blob[f"dp_{k}"] = tp[k].grad.numpy()
    for k, v in state.items():
        blob[f"s_{k}"] = np.asarray(v, np.float64)
        blob[f"ns_{k}"] = ts[k].detach().numpy()  # post-update value
    _save(name, **blob)


# ------------------------------------------------------------- recurrent
@case("recurrent_lstm")
def _(rng):
    N, T, D, H = 3, 5, 4, 6
    x = rng.normal(0, 1, (N, T, D))
    params = {"weight": rng.normal(0, 0.3, (4 * H, D + H)),
              "bias": rng.normal(0, 0.1, (4 * H,))}

    def fwd(p, x):
        # standard LSTM (i,f,g,o fused over [x,h]) unrolled in torch f64
        h = torch.zeros(N, H, dtype=torch.float64)
        c = torch.zeros(N, H, dtype=torch.float64)
        ys = []
        for t in range(T):
            z = F.linear(torch.cat([x[:, t], h], dim=1), p["weight"],
                         p["bias"])
            i, f, g, o = z.chunk(4, dim=1)
            i, f, o = torch.sigmoid(i), torch.sigmoid(f), torch.sigmoid(o)
            c = f * c + i * torch.tanh(g)
            h = o * torch.tanh(c)
            ys.append(h)
        return torch.stack(ys, dim=1)
    _record("recurrent_lstm", params, x, fwd)


@case("recurrent_lstm_native_oracle")
def _(rng):
    """torch.nn.LSTM as a fully INDEPENDENT oracle (not our formula):
    weights mapped onto our fused (4H, D+H) layout."""
    N, T, D, H = 2, 4, 3, 5
    x = rng.normal(0, 1, (N, T, D))
    w = rng.normal(0, 0.3, (4 * H, D + H))
    b = rng.normal(0, 0.1, (4 * H,))
    lstm = torch.nn.LSTM(D, H, batch_first=True).double()
    with torch.no_grad():
        lstm.weight_ih_l0.copy_(_t(w[:, :D]))
        lstm.weight_hh_l0.copy_(_t(w[:, D:]))
        lstm.bias_ih_l0.copy_(_t(b))
        lstm.bias_hh_l0.zero_()
    out, _ = lstm(_t(x))
    _save("recurrent_lstm_native_oracle", x=x, p_weight=w, p_bias=b,
          out=out.detach().numpy())


@case("recurrent_gru")
def _(rng):
    N, T, D, H = 3, 5, 4, 6
    x = rng.normal(0, 1, (N, T, D))
    params = {"w_gates": rng.normal(0, 0.3, (2 * H, D + H)),
              "b_gates": rng.normal(0, 0.1, (2 * H,)),
              "w_cand": rng.normal(0, 0.3, (H, D + H)),
              "b_cand": rng.normal(0, 0.1, (H,))}

    def fwd(p, x):
        # Keras-convention GRU (reset applied to h BEFORE the candidate
        # projection — the reference GRU.scala convention)
        h = torch.zeros(N, H, dtype=torch.float64)
        ys = []
        for t in range(T):
            z = F.linear(torch.cat([x[:, t], h], dim=1), p["w_gates"],
                         p["b_gates"])
            r, u = torch.sigmoid(z).chunk(2, dim=1)
            cand = torch.tanh(F.linear(torch.cat([x[:, t], r * h], dim=1),
                                       p["w_cand"], p["b_cand"]))
            h = u * h + (1 - u) * cand
            ys.append(h)
        return torch.stack(ys, dim=1)
    _record("recurrent_gru", params, x, fwd)


@case("recurrent_lstm_peephole")
def _(rng):
    N, T, D, H = 2, 4, 3, 5
    x = rng.normal(0, 1, (N, T, D))
    params = {"weight": rng.normal(0, 0.3, (4 * H, D + H)),
              "bias": rng.normal(0, 0.1, (4 * H,)),
              "peep": rng.normal(0, 0.2, (3, H))}

    def fwd(p, x):
        h = torch.zeros(N, H, dtype=torch.float64)
        c = torch.zeros(N, H, dtype=torch.float64)
        ys = []
        for t in range(T):
            z = F.linear(torch.cat([x[:, t], h], dim=1), p["weight"],
                         p["bias"])
            i, f, g, o = z.chunk(4, dim=1)
            i = torch.sigmoid(i + p["peep"][0] * c)
            f = torch.sigmoid(f + p["peep"][1] * c)
            c = f * c + i * torch.tanh(g)
            o = torch.sigmoid(o + p["peep"][2] * c)
            h = o * torch.tanh(c)
            ys.append(h)
        return torch.stack(ys, dim=1)
    _record("recurrent_lstm_peephole", params, x, fwd)


@case("recurrent_rnn_tanh")
def _(rng):
    N, T, D, H = 3, 6, 4, 5
    x = rng.normal(0, 1, (N, T, D))
    params = {"w_ih": rng.normal(0, 0.3, (H, D)),
              "w_hh": rng.normal(0, 0.3, (H, H)),
              "bias": rng.normal(0, 0.1, (H,))}

    def fwd(p, x):
        h = torch.zeros(N, H, dtype=torch.float64)
        ys = []
        for t in range(T):
            h = torch.tanh(F.linear(x[:, t], p["w_ih"])
                           + F.linear(h, p["w_hh"]) + p["bias"])
            ys.append(h)
        return torch.stack(ys, dim=1)
    _record("recurrent_rnn_tanh", params, x, fwd)


# ----------------------------------------------------- BN training mode
@case("spatial_batch_norm_train")
def _(rng):
    x = rng.normal(0, 1, (4, 3, 5, 5))
    params = {"weight": rng.uniform(0.5, 1.5, (3,)),
              "bias": rng.normal(0, 0.2, (3,))}
    state = {"running_mean": rng.normal(0, 0.3, (3,)),
             "running_var": rng.uniform(0.5, 2.0, (3,))}

    def fwd(p, s, x):
        return F.batch_norm(x, s["running_mean"], s["running_var"],
                            p["weight"], p["bias"], training=True,
                            momentum=0.1, eps=1e-5)
    _record_train_state("spatial_batch_norm_train", params, x, fwd, state)


@case("batch_norm_1d_train")
def _(rng):
    x = rng.normal(0, 1, (8, 6))
    params = {"weight": rng.uniform(0.5, 1.5, (6,)),
              "bias": rng.normal(0, 0.2, (6,))}
    state = {"running_mean": rng.normal(0, 0.3, (6,)),
             "running_var": rng.uniform(0.5, 2.0, (6,))}

    def fwd(p, s, x):
        return F.batch_norm(x, s["running_mean"], s["running_var"],
                            p["weight"], p["bias"], training=True,
                            momentum=0.1, eps=1e-5)
    _record_train_state("batch_norm_1d_train", params, x, fwd, state)


@case("batch_norm_1d_eval")
def _(rng):
    x = rng.normal(0, 1, (8, 6))
    params = {"weight": rng.uniform(0.5, 1.5, (6,)),
              "bias": rng.normal(0, 0.2, (6,))}
    state = {"running_mean": rng.normal(0, 0.3, (6,)),
             "running_var": rng.uniform(0.5, 2.0, (6,))}

    def fwd(p, x):
        return F.batch_norm(x, p["running_mean"], p["running_var"],
                            p["weight"], p["bias"], training=False,
                            eps=1e-5)
    _record("batch_norm_1d_eval", params, x, fwd, state=state)


# ----------------------------------------------------------- embeddings
@case("lookup_table")
def _(rng):
    idx = rng.integers(0, 10, (4, 7)).astype(np.int64)
    w = rng.normal(0, 0.5, (10, 6))
    tw = _t(w).requires_grad_(True)
    out = F.embedding(torch.tensor(idx), tw)
    out.sum().backward()
    _save("lookup_table", x=idx, p_weight=w, out=out.detach().numpy(),
          dp_weight=tw.grad.numpy())


# -------------------------------------------------- activation sweep r3
def _act(name, torch_fn, x):
    _record(name, {}, x, lambda p, xx: torch_fn(xx))


@case("act_softmax")
def _(rng):
    _act("act_softmax", lambda x: F.softmax(x, dim=-1),
         rng.normal(0, 2, (4, 7)))


@case("act_log_softmax")
def _(rng):
    _act("act_log_softmax", lambda x: F.log_softmax(x, dim=-1),
         rng.normal(0, 2, (4, 7)))


@case("act_sigmoid")
def _(rng):
    _act("act_sigmoid", torch.sigmoid, rng.normal(0, 2, (4, 7)))


@case("act_tanh")
def _(rng):
    _act("act_tanh", torch.tanh, rng.normal(0, 2, (4, 7)))


@case("act_relu6")
def _(rng):
    _act("act_relu6", F.relu6, rng.normal(0, 4, (4, 7)))


@case("act_leaky_relu")
def _(rng):
    _act("act_leaky_relu", lambda x: F.leaky_relu(x, 0.01),
         rng.normal(0, 2, (4, 7)))


@case("act_softsign")
def _(rng):
    _act("act_softsign", F.softsign, rng.normal(0, 2, (4, 7)))


@case("act_softshrink")
def _(rng):
    _act("act_softshrink", lambda x: F.softshrink(x, 0.5),
         rng.normal(0, 2, (4, 7)))


@case("act_hardshrink")
def _(rng):
    _act("act_hardshrink", lambda x: F.hardshrink(x, 0.5),
         rng.normal(0, 2, (4, 7)))


@case("act_tanhshrink")
def _(rng):
    _act("act_tanhshrink", F.tanhshrink, rng.normal(0, 2, (4, 7)))


@case("act_log_sigmoid")
def _(rng):
    _act("act_log_sigmoid", F.logsigmoid, rng.normal(0, 2, (4, 7)))


@case("act_gelu")
def _(rng):
    # our GELU uses the tanh approximation (the TPU-cheap form)
    _act("act_gelu", lambda x: F.gelu(x, approximate="tanh"),
         rng.normal(0, 2, (4, 7)))


@case("act_softmin")
def _(rng):
    _act("act_softmin", lambda x: F.softmin(x, dim=-1),
         rng.normal(0, 2, (4, 7)))


# --------------------------------------------- criterion sweep r3: torch
@case("crit_cross_entropy")
def _(rng):
    _record_criterion("cross_entropy", rng.normal(0, 1, (6, 5)),
                      rng.integers(0, 5, (6,)).astype(np.int64),
                      lambda x, t: F.cross_entropy(x, t))


@case("crit_class_nll_ignore")
def _(rng):
    logits = rng.normal(0, 1, (6, 4))
    logp = logits - np.log(np.exp(logits).sum(1, keepdims=True))
    t = rng.integers(0, 4, (6,)).astype(np.int64)
    t[1] = -100
    t[4] = -100
    _record_criterion("class_nll_ignore", logp, t,
                      lambda x, t: F.nll_loss(x, t, ignore_index=-100))


@case("crit_bce_logits")
def _(rng):
    _record_criterion("bce_logits", rng.normal(0, 2, (4, 5)),
                      rng.integers(0, 2, (4, 5)).astype(np.float64),
                      lambda x, t: F.binary_cross_entropy_with_logits(x, t))


@case("crit_multilabel_margin")
def _(rng):
    x = rng.normal(0, 1, (3, 6))
    # -1-terminated target lists (torch convention; pad only at the end)
    t = np.full((3, 6), -1, np.int64)
    t[0, :2] = [1, 4]
    t[1, :3] = [0, 2, 5]
    t[2, :1] = [3]
    _record_criterion("multilabel_margin", x, t,
                      lambda x, t: F.multilabel_margin_loss(x, t))


@case("crit_multi_margin_p1")
def _(rng):
    _record_criterion("multi_margin_p1", rng.normal(0, 1, (5, 4)),
                      rng.integers(0, 4, (5,)).astype(np.int64),
                      lambda x, t: F.multi_margin_loss(x, t, p=1,
                                                       margin=1.0))


@case("crit_multi_margin_p2")
def _(rng):
    _record_criterion("multi_margin_p2", rng.normal(0, 1, (5, 4)),
                      rng.integers(0, 4, (5,)).astype(np.int64),
                      lambda x, t: F.multi_margin_loss(x, t, p=2,
                                                       margin=1.0))


@case("crit_margin")
def _(rng):
    x = rng.normal(0, 1, (4, 5))
    t = rng.choice([-1.0, 1.0], (4, 5))
    _record_criterion("margin", x, t,
                      lambda x, t: torch.clamp(1.0 - x * t, min=0).mean())


@case("crit_poisson")
def _(rng):
    x = rng.uniform(0.1, 3.0, (4, 5))
    t = rng.uniform(0.0, 3.0, (4, 5))
    _record_criterion("poisson", x, t,
                      lambda x, t: (x - t * torch.log(x)).mean())


@case("crit_mape")
def _(rng):
    x = rng.uniform(0.5, 3.0, (4, 5))
    t = rng.uniform(0.5, 3.0, (4, 5))
    _record_criterion("mape", x, t,
                      lambda x, t: (100.0 * ((t - x).abs()
                                             / t.abs())).mean())


@case("crit_msle")
def _(rng):
    x = rng.uniform(0.1, 3.0, (4, 5))
    t = rng.uniform(0.1, 3.0, (4, 5))
    _record_criterion(
        "msle", x, t,
        lambda x, t: ((torch.log1p(x) - torch.log1p(t)) ** 2).mean())


@case("crit_kl_probs")
def _(rng):
    p = rng.dirichlet(np.ones(5), size=4)
    q = rng.dirichlet(np.ones(5), size=4)
    _record_criterion(
        "kl_probs", p, q,
        lambda x, t: (t * torch.log(t / x)).sum(-1).mean())


@case("crit_cosine_distance")
def _(rng):
    x = rng.normal(0, 1, (4, 6))
    t = rng.normal(0, 1, (4, 6))
    _record_criterion(
        "cosine_distance", x, t,
        lambda x, t: (1.0 - F.cosine_similarity(x, t, dim=-1)).mean())


@case("crit_cosine_proximity")
def _(rng):
    x = rng.normal(0, 1, (4, 6))
    t = rng.normal(0, 1, (4, 6))
    _record_criterion(
        "cosine_proximity", x, t,
        lambda x, t: -F.cosine_similarity(x, t, dim=-1).mean())


@case("crit_dot_product")
def _(rng):
    x = rng.normal(0, 1, (4, 6))
    t = rng.normal(0, 1, (4, 6))
    _record_criterion("dot_product", x, t, lambda x, t: (x * t).sum())


@case("crit_l1_cost")
def _(rng):
    x = rng.normal(0, 1, (4, 6))
    _record_criterion("l1_cost", x, np.zeros((4, 6)),
                      lambda x, t: x.abs().sum())


@case("crit_dice")
def _(rng):
    x = rng.uniform(0, 1, (3, 8))
    t = rng.integers(0, 2, (3, 8)).astype(np.float64)

    def loss(x, t):
        num = 2.0 * (x * t).sum(-1) + 1.0
        den = x.sum(-1) + t.sum(-1) + 1.0
        return (1.0 - num / den).mean()
    _record_criterion("dice", x, t, loss)


@case("crit_pg")
def _(rng):
    x = rng.uniform(0.05, 0.95, (5, 3))
    r = rng.normal(0, 1, (5, 3))
    _record_criterion("pg", x, r,
                      lambda x, t: (-torch.log(x) * t).sum())


@case("crit_categorical_ce")
def _(rng):
    p = rng.dirichlet(np.ones(5), size=4)
    t = np.eye(5)[rng.integers(0, 5, (4,))]
    _record_criterion(
        "categorical_ce", p, t,
        lambda x, t: -(t * torch.log(x)).sum(-1).mean())


@case("crit_softmax_with")
def _(rng):
    x = rng.normal(0, 1, (2, 4, 3, 3))
    t = rng.integers(0, 4, (2, 3, 3)).astype(np.int64)
    _record_criterion(
        "softmax_with", x, t,
        lambda x, t: F.cross_entropy(x, t, reduction="mean"))


@case("crit_time_distributed_mse")
def _(rng):
    x = rng.normal(0, 1, (3, 4, 5))
    t = rng.normal(0, 1, (3, 4, 5))
    # our TimeDistributedCriterion(MSE mean-inner, size_average=False)
    # = T * mse(flat)
    _record_criterion(
        "time_distributed_mse", x, t,
        lambda x, t: 4 * F.mse_loss(x.reshape(-1, 5), t.reshape(-1, 5)))


@case("crit_class_simplex")
def _(rng):
    x = rng.normal(0, 1, (6, 4))
    t = rng.integers(0, 4, (6,)).astype(np.int64)

    def regsplex(n):
        a = torch.zeros(n + 1, n, dtype=torch.float64)
        for k in range(n):
            prior = a[k, :k].norm()
            a[k, k] = 1.0 if k == 0 else torch.sqrt(1.0 - prior * prior)
            c = (a[k, k] ** 2 - 1.0 - 1.0 / n) / a[k, k]
            a[k + 1:, k] = c
        return a

    def loss(x, t):
        simplex = regsplex(3)
        emb = torch.zeros(t.shape[0], 4, dtype=torch.float64)
        emb[:, :3] = simplex[t]
        return ((x - emb) ** 2).mean()
    _record_criterion("class_simplex", x, t, loss)


# --------------------------------------- pair-input criterions (crit2_*)
def _record_criterion2(name, x1, x2, target, torch_loss):
    t1 = _t(x1).requires_grad_(True)
    t2 = _t(x2).requires_grad_(True)
    tt = torch.tensor(np.asarray(target))
    loss = torch_loss(t1, t2, tt)
    loss.backward()
    _save(f"crit2_{name}", x1=np.asarray(x1, np.float64),
          x2=np.asarray(x2, np.float64), target=np.asarray(target),
          loss=loss.detach().numpy(), dx1=t1.grad.numpy(),
          dx2=t2.grad.numpy())


@case("crit2_margin_ranking")
def _(rng):
    _record_criterion2(
        "margin_ranking", rng.normal(0, 1, (6,)), rng.normal(0, 1, (6,)),
        rng.choice([-1.0, 1.0], (6,)),
        lambda a, b, y: F.margin_ranking_loss(a, b, y, margin=1.0))


@case("crit2_cosine_embedding")
def _(rng):
    _record_criterion2(
        "cosine_embedding", rng.normal(0, 1, (4, 5)),
        rng.normal(0, 1, (4, 5)), rng.choice([-1.0, 1.0], (4,)),
        lambda a, b, y: F.cosine_embedding_loss(a, b, y, margin=0.2))


@case("crit2_l1_hinge_embedding")
def _(rng):
    def loss(a, b, y):
        d = (a - b).abs().sum(-1)
        per = torch.where(y > 0, d, torch.clamp(1.0 - d, min=0.0))
        return per.mean()
    _record_criterion2(
        "l1_hinge_embedding", rng.normal(0, 1, (4, 5)),
        rng.normal(0, 1, (4, 5)), rng.choice([-1.0, 1.0], (4,)), loss)


@case("crit2_kld_vae")
def _(rng):
    def loss(mu, lv, _):
        return (0.5 * (mu ** 2 + lv.exp() - 1.0 - lv).sum(-1)).mean()
    _record_criterion2("kld_vae", rng.normal(0, 1, (4, 6)),
                       rng.normal(0, 0.5, (4, 6)), np.zeros((4,)), loss)


@case("crit2_gaussian")
def _(rng):
    target = rng.normal(0, 1, (4, 6))

    def loss(mu, lv, t):
        nll = 0.5 * (np.log(2 * np.pi) + lv + (t - mu) ** 2 / lv.exp())
        return nll.sum() / t.shape[0]
    _record_criterion2("gaussian", rng.normal(0, 1, (4, 6)),
                       rng.normal(0, 0.5, (4, 6)), target, loss)


# ============================================= round-3b: tensor-math layers
# (nn/tensor_extras.py family — MM/Bilinear/Cosine/Euclidean/Maxout/...)
def _record_module2(name, params, x1, x2, torch_fwd):
    """Pair-INPUT module fixture (mod2_*): input = (x1, x2)."""
    tp = {k: _t(v).requires_grad_(True) for k, v in params.items()}
    t1 = _t(x1).requires_grad_(True)
    t2 = _t(x2).requires_grad_(True)
    out = torch_fwd(tp, t1, t2)
    out.sum().backward()
    blob = {"x1": np.asarray(x1, np.float64),
            "x2": np.asarray(x2, np.float64),
            "out": out.detach().numpy(), "dx1": t1.grad.numpy(),
            "dx2": t2.grad.numpy()}
    for k, v in params.items():
        blob[f"p_{k}"] = np.asarray(v, np.float64)
        blob[f"dp_{k}"] = tp[k].grad.numpy()
    _save(f"mod2_{name}", **blob)


@case("mod2_bilinear")
def _(rng):
    # torch F.bilinear is the INDEPENDENT oracle (same (O, I1, I2) layout)
    params = {"weight": rng.normal(0, 0.3, (5, 3, 4)),
              "bias": rng.normal(0, 0.1, (5,))}
    _record_module2("bilinear", params, rng.normal(0, 1, (6, 3)),
                    rng.normal(0, 1, (6, 4)),
                    lambda p, a, b: F.bilinear(a, b, p["weight"],
                                               p["bias"]))


@case("mod2_mm")
def _(rng):
    _record_module2("mm", {}, rng.normal(0, 1, (2, 3, 4)),
                    rng.normal(0, 1, (2, 4, 5)),
                    lambda p, a, b: torch.bmm(a, b))


@case("mod2_dot_product")
def _(rng):
    _record_module2("dot_product", {}, rng.normal(0, 1, (4, 6)),
                    rng.normal(0, 1, (4, 6)),
                    lambda p, a, b: (a * b).sum(-1))


@case("mod2_pairwise_distance")
def _(rng):
    _record_module2("pairwise_distance", {}, rng.normal(0, 1, (4, 6)),
                    rng.normal(0, 1, (4, 6)),
                    lambda p, a, b: F.pairwise_distance(a, b, p=2,
                                                        eps=0.0))


@case("mod2_cosine_distance")
def _(rng):
    _record_module2("cosine_distance", {}, rng.normal(0, 1, (4, 6)),
                    rng.normal(0, 1, (4, 6)),
                    lambda p, a, b: F.cosine_similarity(a, b, dim=-1))


@case("cosine_layer")
def _(rng):
    params = {"weight": rng.normal(0, 0.5, (6, 4))}
    _record("cosine_layer", params, rng.normal(0, 1, (5, 4)),
            lambda p, x: F.cosine_similarity(
                x[:, None, :], p["weight"][None], dim=-1))


@case("euclidean_layer")
def _(rng):
    params = {"weight": rng.normal(0, 0.5, (6, 4))}
    _record("euclidean_layer", params, rng.normal(0, 1, (5, 4)),
            lambda p, x: (x[:, None, :] - p["weight"][None])
            .pow(2).sum(-1).sqrt())


@case("maxout")
def _(rng):
    # pool=2, output=3: weight rows grouped (pool, out)
    params = {"weight": rng.normal(0, 0.3, (6, 4)),
              "bias": rng.normal(0, 0.1, (6,))}

    def fwd(p, x):
        y = F.linear(x, p["weight"], p["bias"])
        return y.reshape(x.shape[0], 2, 3).max(dim=1).values
    _record("maxout", params, rng.normal(0, 1, (5, 4)), fwd)


@case("highway")
def _(rng):
    params = {"weight": rng.normal(0, 0.3, (5, 5)),
              "bias": rng.normal(0, 0.1, (5,)),
              "gate_weight": rng.normal(0, 0.3, (5, 5)),
              "gate_bias": rng.normal(0, 0.1, (5,))}

    def fwd(p, x):
        t = torch.sigmoid(F.linear(x, p["gate_weight"], p["gate_bias"]))
        h = torch.tanh(F.linear(x, p["weight"], p["bias"]))
        return t * h + (1.0 - t) * x
    _record("highway", params, rng.normal(0, 1, (4, 5)), fwd)


@case("add_layer")
def _(rng):
    params = {"bias": rng.normal(0, 0.5, (6,))}
    _record("add_layer", params, rng.normal(0, 1, (4, 6)),
            lambda p, x: x + p["bias"])


@case("mul_layer")
def _(rng):
    params = {"weight": np.asarray(1.7)}
    _record("mul_layer", params, rng.normal(0, 1, (4, 6)),
            lambda p, x: x * p["weight"])


@case("cmul")
def _(rng):
    params = {"weight": rng.normal(0, 0.5, (1, 6))}
    _record("cmul", params, rng.normal(0, 1, (4, 6)),
            lambda p, x: x * p["weight"])


@case("cadd")
def _(rng):
    params = {"bias": rng.normal(0, 0.5, (1, 6))}
    _record("cadd", params, rng.normal(0, 1, (4, 6)),
            lambda p, x: x + p["bias"])


@case("power")
def _(rng):
    _record("power", {}, rng.uniform(0.1, 2.0, (4, 6)),
            lambda p, x: (2.0 * x + 1.0).pow(1.5))


@case("clamp")
def _(rng):
    _record("clamp", {}, rng.normal(0, 2, (4, 6)),
            lambda p, x: x.clamp(-0.5, 0.8))


@case("layer_norm")
def _(rng):
    params = {"weight": rng.uniform(0.5, 1.5, (8,)),
              "bias": rng.normal(0, 0.2, (8,))}
    _record("layer_norm", params, rng.normal(0, 2, (3, 5, 8)),
            lambda p, x: F.layer_norm(x, (8,), p["weight"], p["bias"],
                                      eps=1e-5))


def _mha_fixture(name, causal, rng):
    """torch.nn.functional.multi_head_attention_forward is the
    INDEPENDENT oracle; our (in, out)-layout weights map to torch's
    (out, in) in_proj/out_proj via transposes."""
    N, T, D, H = 2, 5, 8, 2
    x = rng.normal(0, 1, (N, T, D))
    params = {k: rng.normal(0, 0.3, (D, D)) for k in
              ("wq", "wk", "wv", "wo")}
    params.update({k: rng.normal(0, 0.1, (D,)) for k in
                   ("bq", "bk", "bv", "bo")})

    def fwd(p, x):
        in_w = torch.cat([p["wq"].T, p["wk"].T, p["wv"].T], dim=0)
        in_b = torch.cat([p["bq"], p["bk"], p["bv"]])
        mask = None
        if causal:
            mask = torch.triu(torch.full((T, T), float("-inf"),
                                         dtype=torch.float64), diagonal=1)
        xt = x.transpose(0, 1)  # (T, N, D)
        out, _ = F.multi_head_attention_forward(
            xt, xt, xt, D, H, in_w, in_b, None, None, False, 0.0,
            p["wo"].T, p["bo"], need_weights=False, attn_mask=mask)
        return out.transpose(0, 1)
    _record(name, params, x, fwd)


@case("multi_head_attention")
def _(rng):
    _mha_fixture("multi_head_attention", False, rng)


@case("multi_head_attention_causal")
def _(rng):
    _mha_fixture("multi_head_attention_causal", True, rng)


@case("bi_recurrent_lstm")
def _(rng):
    """BiRecurrent(LSTM): forward + time-reversed backward pass, outputs
    concatenated on features."""
    N, T, D, H = 2, 4, 3, 5
    x = rng.normal(0, 1, (N, T, D))

    def lstm(p, x_seq):
        h = torch.zeros(N, H, dtype=torch.float64)
        c = torch.zeros(N, H, dtype=torch.float64)
        ys = []
        for t in range(x_seq.shape[1]):
            z = F.linear(torch.cat([x_seq[:, t], h], dim=1),
                         p["weight"], p["bias"])
            i, f, g, o = z.chunk(4, dim=1)
            i, f, o = torch.sigmoid(i), torch.sigmoid(f), torch.sigmoid(o)
            c = f * c + i * torch.tanh(g)
            h = o * torch.tanh(c)
            ys.append(h)
        return torch.stack(ys, dim=1)

    def fwd(p, x):
        yf = lstm({"weight": p["fwd_weight"], "bias": p["fwd_bias"]}, x)
        yb = lstm({"weight": p["bwd_weight"], "bias": p["bwd_bias"]},
                  torch.flip(x, dims=(1,)))
        yb = torch.flip(yb, dims=(1,))
        return torch.cat([yf, yb], dim=-1)

    flat = {"fwd_weight": rng.normal(0, 0.3, (4 * H, D + H)),
            "fwd_bias": rng.normal(0, 0.1, (4 * H,)),
            "bwd_weight": rng.normal(0, 0.3, (4 * H, D + H)),
            "bwd_bias": rng.normal(0, 0.1, (4 * H,))}
    _record("bi_recurrent_lstm", flat, x, fwd)


@case("conv_lstm_peephole")
def _(rng):
    """ConvLSTM (withPeephole=false mode): per-step SAME conv over
    [x, h] channels, i,f,g,o gate maps."""
    N, T, Ci, Co, K, S = 2, 3, 2, 4, 3, 5
    x = rng.normal(0, 1, (N, T, Ci, S, S))
    params = {"weight": rng.normal(0, 0.2, (4 * Co, Ci + Co, K, K)),
              "bias": rng.normal(0, 0.1, (4 * Co,))}

    def fwd(p, x):
        h = torch.zeros(N, Co, S, S, dtype=torch.float64)
        c = torch.zeros(N, Co, S, S, dtype=torch.float64)
        ys = []
        for t in range(T):
            z = F.conv2d(torch.cat([x[:, t], h], dim=1), p["weight"],
                         p["bias"], padding=K // 2)
            i, f, g, o = z.chunk(4, dim=1)
            c = torch.sigmoid(f) * c + torch.sigmoid(i) * torch.tanh(g)
            h = torch.sigmoid(o) * torch.tanh(c)
            ys.append(h)
        return torch.stack(ys, dim=1)
    _record("conv_lstm_peephole", params, x, fwd)


@case("conv_lstm_with_peephole")
def _(rng):
    """ConvLSTM WITH the reference's per-channel peephole terms
    (ConvLSTMPeephole.scala withPeephole=true default): Wci/Wcf gate on
    c, Wco on the new c."""
    N, T, Ci, Co, K, S = 2, 3, 2, 4, 3, 5
    x = rng.normal(0, 1, (N, T, Ci, S, S))
    params = {"weight": rng.normal(0, 0.2, (4 * Co, Ci + Co, K, K)),
              "bias": rng.normal(0, 0.1, (4 * Co,)),
              "peep": rng.normal(0, 0.2, (3, Co))}

    def fwd(p, x):
        h = torch.zeros(N, Co, S, S, dtype=torch.float64)
        c = torch.zeros(N, Co, S, S, dtype=torch.float64)
        pe = p["peep"][:, None, :, None, None]
        ys = []
        for t in range(T):
            z = F.conv2d(torch.cat([x[:, t], h], dim=1), p["weight"],
                         p["bias"], padding=K // 2)
            i, f, g, o = z.chunk(4, dim=1)
            i = i + pe[0] * c
            f = f + pe[1] * c
            c = torch.sigmoid(f) * c + torch.sigmoid(i) * torch.tanh(g)
            o = o + pe[2] * c
            h = torch.sigmoid(o) * torch.tanh(c)
            ys.append(h)
        return torch.stack(ys, dim=1)
    _record("conv_lstm_with_peephole", params, x, fwd)


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
