"""spmdcheck — the runtime half of the SPMD divergence story (ISSUE 17).

Layout:
- THE POSITIVE GATE: two emulated processes whose collective schedules
  diverge produce one report naming both entries, both stacks and both
  full schedules;
- negatives: identical schedules across K participants record nothing;
- THE INERTNESS GATE: with the sanitizer off, ``note()`` is a single
  global read (zero notes, zero allocations visible) and the driver
  loop is bitwise identical (loss sequence + dispatch count) for
  K ∈ {1, 4} — the lockdep/FaultInjector empty-plan discipline;
- the real-driver emulation: the SAME ``tiny_run`` under
  ``participant(pid)`` per pid records identical schedules; an
  injected one-sided clause (the PR-7 ``last_saved_step`` class) fails
  with both schedules rendered;
- composition: lockdep + spmdcheck installed in ONE subprocess session
  — both report headers, both summary lines, neither clobbers the
  other's gate.

Unlike lockdep, spmdcheck patches nothing: the off state is one module
global being None.  Tests therefore isolate by SWAPPING the recorder
(save/restore ``_RECORDER``) instead of skipping under the session
opt-in — every test here runs under ``BIGDL_TPU_SPMDCHECK=1`` too.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.transformer import Sample, SampleToMiniBatch
from bigdl_tpu.utils import spmdcheck
from bigdl_tpu.utils.config import configure, reset_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def sandbox():
    """Fresh recorder for one test; the previous recorder (the session
    one, under BIGDL_TPU_SPMDCHECK=1) is restored untouched after."""
    prev = spmdcheck._RECORDER
    spmdcheck._RECORDER = None
    spmdcheck.install()
    try:
        yield spmdcheck
    finally:
        spmdcheck._RECORDER = prev


@pytest.fixture
def off_sandbox():
    """The sanitizer provably OFF for one test, session state restored
    after — no skip needed even under the session opt-in."""
    prev = spmdcheck._RECORDER
    spmdcheck._RECORDER = None
    try:
        yield spmdcheck
    finally:
        spmdcheck._RECORDER = prev


class RecordingSummary:
    def __init__(self):
        self.losses = []

    def add_train_step(self, step, loss, lr, throughput):
        self.losses.append(loss)

    def add_scalar(self, *a):
        pass

    def trigger_for(self, name):
        return None


def tiny_run(iters=6, k=1):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(0, 1, (16,)).astype(np.float32),
                      np.int32(rng.integers(0, 4)))
               for _ in range(64)]
    model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(),
                          nn.Linear(16, 4), nn.LogSoftMax())
    rec = RecordingSummary()
    opt = (optim.LocalOptimizer(model,
                                DataSet.array(samples)
                                >> SampleToMiniBatch(16),
                                nn.ClassNLLCriterion())
           .set_optim_method(optim.SGD(learning_rate=0.1))
           .set_seed(7)
           .set_train_summary(rec)
           .set_steps_per_dispatch(k)
           .set_end_when(optim.max_iteration(iters)))
    opt.optimize()
    return np.asarray(rec.losses), opt


# ===========================================================================
class TestDivergenceDetection:
    def test_identical_schedules_are_clean(self, sandbox):
        for pid in (0, 1, 2):
            with spmdcheck.participant(pid):
                spmdcheck.note("dispatch", axis="k4",
                               payload=np.zeros((4, 8), np.float32))
                spmdcheck.note("block_fetch",
                               payload=np.zeros((4,), np.float32))
        assert spmdcheck.divergences(final=True) == []
        assert spmdcheck.notes_recorded() == 6
        spmdcheck.check_clean()  # no raise

    def test_one_sided_clause_names_both_schedules_and_stacks(
            self, sandbox):
        """THE ISSUE-17 acceptance gate: one process takes a branch the
        other never does (the PR-7 ``last_saved_step`` class) — the
        report carries both entries, both stacks, both schedules."""
        loss = np.zeros((3,), np.float32)
        for pid in (0, 1):
            with spmdcheck.participant(pid):
                spmdcheck.note("dispatch", axis="k1", payload=loss)
                if pid == 0:      # the one-sided clause
                    spmdcheck.note("checkpoint", payload=loss)
                spmdcheck.note("allgather", payload=loss)
        divs = spmdcheck.divergences(final=True)
        assert len(divs) == 1
        rep = divs[0].render()
        assert "checkpoint" in rep and "allgather" in rep
        assert "schedule of process 0" in rep
        assert "schedule of process 1" in rep
        assert rep.count("test_spmdcheck.py") >= 2  # both stacks
        with pytest.raises(spmdcheck.SpmdDivergenceError):
            spmdcheck.check_clean()

    def test_payload_fingerprint_mismatch_is_a_divergence(self, sandbox):
        with spmdcheck.participant(0):
            spmdcheck.note("dispatch",
                           payload=np.zeros((4,), np.float32))
        with spmdcheck.participant(1):
            spmdcheck.note("dispatch", payload=np.zeros((4,), np.int32))
        (d,) = spmdcheck.divergences()
        rep = d.render()
        assert "float32" in rep and "int32" in rep

    def test_axis_mismatch_is_a_divergence(self, sandbox):
        with spmdcheck.participant(0):
            spmdcheck.note("allgather", axis="data")
        with spmdcheck.participant(1):
            spmdcheck.note("allgather", axis="model")
        assert len(spmdcheck.divergences()) == 1

    def test_one_report_per_pair_not_per_entry(self, sandbox):
        # a schedule that slid out of phase mismatches at EVERY later
        # index; the pair reports once
        with spmdcheck.participant(0):
            for kind in ("a", "b", "c", "d"):
                spmdcheck.note(kind)
        with spmdcheck.participant(1):
            for kind in ("b", "c", "d", "a"):
                spmdcheck.note(kind)
        assert len(spmdcheck.divergences(final=True)) == 1

    def test_length_mismatch_only_reported_at_finalize(self, sandbox):
        with spmdcheck.participant(0):
            spmdcheck.note("dispatch")
            spmdcheck.note("allgather")
        with spmdcheck.participant(1):
            spmdcheck.note("dispatch")   # then stops noting
        # mid-run: schedules legitimately grow at different rates
        assert spmdcheck.divergences() == []
        (d,) = spmdcheck.divergences(final=True)
        assert d.entry_b is None  # participant 1 ended early
        assert "<schedule ended>" in d.render()

    def test_participant_nesting_restores_previous_pid(self, sandbox):
        with spmdcheck.participant(3):
            with spmdcheck.participant(5):
                spmdcheck.note("inner")
            spmdcheck.note("outer")
        scheds = spmdcheck.schedules()
        assert [e.kind for e in scheds[5]] == ["inner"]
        assert [e.kind for e in scheds[3]] == ["outer"]


# ===========================================================================
class TestInertness:
    """The acceptance gate: spmdcheck off is ONE global read in
    ``note()`` — nothing recorded, nothing imported, driver bitwise."""

    def test_off_state_records_and_allocates_nothing(self, off_sandbox):
        assert not spmdcheck.installed()
        configure(spmdcheck=False)
        try:
            assert spmdcheck.maybe_install() is False
        finally:
            reset_config()
        assert not spmdcheck.installed()
        spmdcheck.note("dispatch", axis="k1", payload=object())
        assert spmdcheck.notes_recorded() == 0
        assert spmdcheck.schedules() == {}
        assert spmdcheck.divergences(final=True) == []
        spmdcheck.check_clean()  # vacuously clean, no raise

    @pytest.mark.parametrize("k", [1, 4])
    def test_driver_bitwise_identical_off_vs_on(self, k):
        prev = spmdcheck._RECORDER
        spmdcheck._RECORDER = None
        try:
            configure(spmdcheck=False)
            try:
                assert spmdcheck.maybe_install() is False
            finally:
                reset_config()
            off_l, off_o = tiny_run(iters=6, k=k)
            assert spmdcheck.notes_recorded() == 0
            spmdcheck.install()
            on_l, on_o = tiny_run(iters=6, k=k)
            assert spmdcheck.notes_recorded() > 0
            assert spmdcheck.divergences(final=True) == []
        finally:
            spmdcheck._RECORDER = prev
        np.testing.assert_array_equal(off_l, on_l)
        assert off_o._dispatch_count == on_o._dispatch_count

    def test_config_gate_installs_when_on(self, off_sandbox):
        configure(spmdcheck=True)
        try:
            assert spmdcheck.maybe_install() is True
            assert spmdcheck.installed()
        finally:
            reset_config()

    def test_env_gate_maps_to_config(self, monkeypatch, off_sandbox):
        monkeypatch.setenv("BIGDL_TPU_SPMDCHECK", "1")
        reset_config()
        try:
            from bigdl_tpu.utils.config import get_config
            assert get_config().spmdcheck is True
            assert spmdcheck.maybe_install() is True
        finally:
            reset_config()

    def test_install_uninstall_idempotent(self, off_sandbox):
        spmdcheck.install()
        rec = spmdcheck._RECORDER
        spmdcheck.install()
        assert spmdcheck._RECORDER is rec  # second install is a no-op
        spmdcheck.uninstall()
        spmdcheck.uninstall()
        assert not spmdcheck.installed()


# ===========================================================================
class TestDriverEmulation:
    """The virtual-mesh trick, applied to schedules: run the REAL fused
    driver once per emulated process over the same data and compare
    what the note sites recorded."""

    def test_emulated_processes_record_identical_schedules(self,
                                                           sandbox):
        for pid in (0, 1):
            with spmdcheck.participant(pid):
                tiny_run(iters=4, k=2)
        scheds = spmdcheck.schedules()
        assert set(scheds) == {0, 1}
        assert len(scheds[0]) > 0
        briefs = {p: [e.brief() for e in s] for p, s in scheds.items()}
        assert briefs[0] == briefs[1]
        assert spmdcheck.divergences(final=True) == []
        # the driver notes both boundaries: dispatch and the replay
        # fetch, in dispatch-then-fetch order
        kinds = {e.kind for e in scheds[0]}
        assert kinds == {"dispatch", "block_fetch"}
        assert scheds[0][0].kind == "dispatch"

    def test_mismatched_block_shapes_across_processes_diverge(
            self, sandbox):
        # one host staging K=1 blocks while the other runs K=2 is
        # exactly the out-of-phase failure the fingerprint catches
        with spmdcheck.participant(0):
            tiny_run(iters=4, k=1)
        with spmdcheck.participant(1):
            tiny_run(iters=4, k=2)
        divs = spmdcheck.divergences(final=True)
        assert divs
        rep = divs[0].render()
        assert "k1" in rep and "k2" in rep

    def test_injected_one_sided_clause_around_the_real_driver(
            self, sandbox):
        for pid in (0, 1):
            with spmdcheck.participant(pid):
                losses, _opt = tiny_run(iters=3, k=1)
                if pid == 0:   # the injected one-sided clause
                    spmdcheck.note("checkpoint", payload=losses)
                spmdcheck.note("allgather", payload=losses)
        divs = spmdcheck.divergences(final=True)
        assert len(divs) == 1
        rep = divs[0].render()
        assert "checkpoint" in rep
        assert "schedule of process 0" in rep
        assert "schedule of process 1" in rep


# ===========================================================================
class TestComposition:
    """ISSUE-17 satellite: both sanitizers live in ONE pytest session
    (BIGDL_TPU_LOCKDEP=1 BIGDL_TPU_SPMDCHECK=1) without clobbering each
    other — both report headers, both summary lines, exit 0 on a
    clean threaded suite."""

    def test_both_sanitizers_in_one_session(self):
        env = dict(os.environ,
                   BIGDL_TPU_LOCKDEP="1",
                   BIGDL_TPU_SPMDCHECK="1",
                   JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO)
        # no -q: quiet mode suppresses pytest_report_header output,
        # which is half of what this test asserts on
        r = subprocess.run(
            [sys.executable, "-m", "pytest",
             os.path.join(REPO, "tests", "test_membership.py"),
             "-p", "no:cacheprovider", "-p", "no:randomly"],
            capture_output=True, text=True, env=env, cwd=REPO,
            timeout=600)
        assert r.returncode == 0, r.stdout + r.stderr
        # both header lines (pytest_report_header is additive)
        assert "lockdep: lock-order sanitizer INSTALLED" in r.stdout
        assert "spmdcheck: collective-schedule sanitizer INSTALLED" \
            in r.stdout
        # both summary lines (pytest_sessionfinish reports per gate)
        assert "locks instrumented" in r.stdout
        assert "divergences" in r.stdout
