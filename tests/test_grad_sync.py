"""grad_sync (explicit bucketed gradient synchronization) tests — the
ISSUE-4 acceptance surface, on the virtual 8-device CPU mesh:

- f32 wire is BITWISE-equal to a plain f32 psum step (reduce-scatter +
  owned-slice update + all-gather ≡ all-reduce + full update);
- bf16 wire tracks the f32 loss trajectory within tolerance and still
  learns;
- ZeRO-1 slice-update equality: grad_sync-trained params match the
  replicated-update baseline, and the per-chip f32 master slices
  reassemble exactly into the published params (f32 wire);
- K ∈ {1, 4} dispatch fusion is invariant through grad_sync;
- bucket planning round-trips arbitrary pytrees and caps bucket sizes;
- the shared stochastic_round hoist (utils/precision.py) keeps the
  optim_method back-compat alias and its unbiasedness;
- config/engine surface: grad_bucket_bytes / grad_wire_dtype fields,
  Engine.set_xla_async_collectives flag plumbing.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset import image, mnist
from bigdl_tpu.parallel import grad_sync as gs


def mnist_pipeline(n, batch, seed=0):
    imgs, labels = mnist.synthetic_mnist(n, seed=seed)
    samples = mnist.to_samples(imgs, labels)
    return (DataSet.array(samples)
            >> image.BytesToGreyImg()
            >> image.GreyImgNormalizer(mnist.TRAIN_MEAN, mnist.TRAIN_STD)
            >> SampleToMiniBatch(batch))


def small_mlp():
    return (nn.Sequential()
            .add(nn.Reshape((784,)))
            .add(nn.Linear(784, 64)).add(nn.ReLU())
            .add(nn.Linear(64, 10)).add(nn.LogSoftMax()))


class RecordingSummary:
    def __init__(self):
        self.losses = []

    def add_train_step(self, step, loss, lr, throughput):
        self.losses.append(loss)

    def add_scalar(self, *a):
        pass

    def trigger_for(self, name):
        return None


def train_distri(seed=5, iters=6, k=None, lr=0.05, momentum=0.9,
                 summary=None, **kw):
    model = small_mlp()
    opt = (optim.DistriOptimizer(model, mnist_pipeline(512, 64),
                                 nn.ClassNLLCriterion(), **kw)
           .set_optim_method(optim.SGD(learning_rate=lr,
                                       momentum=momentum))
           .set_seed(seed)
           .set_end_when(optim.max_iteration(iters)))
    if k is not None:
        opt.set_steps_per_dispatch(k)
    if summary is not None:
        opt.set_train_summary(summary)
    opt.optimize()
    return model, opt


class TestBucketPlan:
    def tree(self):
        r = np.random.default_rng(0)
        return {
            "a": jnp.asarray(r.normal(0, 1, (7, 5)).astype(np.float32)),
            "b": [jnp.asarray(r.normal(0, 1, (33,)).astype(np.float32)),
                  jnp.asarray(r.normal(0, 1, (4, 4, 2))
                              .astype(np.float32))],
            "c": jnp.asarray(r.normal(0, 1, (3,)).astype(np.float32)),
        }

    def test_round_trip(self):
        t = self.tree()
        plan = gs.build_plan(t, n_shard=8, bucket_bytes=1 << 20)
        buckets = gs.flatten_to_buckets(plan, t)
        back = gs.unflatten_from_buckets(plan, buckets)
        for a, b in zip(jax.tree_util.tree_leaves(t),
                        jax.tree_util.tree_leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            assert a.dtype == b.dtype

    def test_padding_divides_shards(self):
        t = self.tree()
        for n in (2, 4, 8):
            plan = gs.build_plan(t, n_shard=n, bucket_bytes=1 << 20)
            for sz in plan.bucket_sizes:
                assert sz % n == 0 and sz >= n

    def test_size_cap_splits_buckets(self):
        t = self.tree()  # leaf sizes 35, 33, 32, 3
        # 40 f32 elements per bucket: leaves may not merge beyond cap,
        # but an oversized leaf still gets (its own) bucket
        plan = gs.build_plan(t, n_shard=2, bucket_bytes=40 * 4)
        assert plan.num_buckets >= 3
        covered = sorted(i for b in plan.buckets for i in b)
        assert covered == [0, 1, 2, 3]
        # and a huge cap packs everything into one bucket
        plan1 = gs.build_plan(t, n_shard=2, bucket_bytes=1 << 30)
        assert plan1.num_buckets == 1
        # degenerate caps floor at one ELEMENT (not zero): every leaf
        # gets its own bucket, and the round-trip still holds
        plan0 = gs.build_plan(t, n_shard=2, bucket_bytes=1)
        assert plan0.num_buckets == 4
        back = gs.unflatten_from_buckets(
            plan0, gs.flatten_to_buckets(plan0, t))
        for a, b in zip(jax.tree_util.tree_leaves(t),
                        jax.tree_util.tree_leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_wire_dtype_resolution(self):
        assert gs.resolve_wire_dtype("f32") is jnp.float32
        assert gs.resolve_wire_dtype("bfloat16") is jnp.bfloat16
        assert gs.resolve_wire_dtype("f16") is jnp.float16
        with pytest.raises(ValueError, match="wire dtype"):
            gs.resolve_wire_dtype("int8")


class TestStochasticRoundHoist:
    def test_backcompat_alias(self):
        from bigdl_tpu.optim import optim_method
        from bigdl_tpu.utils import precision
        assert optim_method._stochastic_round is precision.stochastic_round

    def test_unbiased_in_expectation(self):
        from bigdl_tpu.utils.precision import stochastic_round
        x = jnp.full((512,), 1.0 + 2 ** -12, jnp.float32)  # between ulps
        acc = np.zeros((512,), np.float64)
        n = 64
        for i in range(n):
            r = stochastic_round(x, jnp.bfloat16,
                                 jax.random.PRNGKey(i))
            acc += np.asarray(r, np.float64)
        mean = acc.mean() / n
        assert abs(mean - float(x[0])) < 2e-4, mean
        # plain round-to-nearest would pin every element to 1.0 exactly
        assert mean != 1.0

    def test_identity_paths(self):
        from bigdl_tpu.utils.precision import stochastic_round
        x = jnp.ones((4,), jnp.float32)
        assert stochastic_round(x, jnp.float32,
                                jax.random.PRNGKey(0)) is x
        y = stochastic_round(x, jnp.float16, jax.random.PRNGKey(0))
        assert y.dtype == jnp.float16

    def test_f16_wire_saturates_instead_of_inf(self):
        # a gradient spike must clamp on the wire — an inf would psum
        # into the masters and train NaNs silently
        x = jnp.asarray([1e6, -1e6, 1.0], jnp.float32)
        w = gs.wire_cast(x, jnp.float16, jax.random.PRNGKey(0))
        assert w.dtype == jnp.float16
        assert np.all(np.isfinite(np.asarray(w, np.float32)))
        assert float(w[0]) == float(jnp.finfo(jnp.float16).max)
        assert float(w[2]) == 1.0

    def test_f16_wire_clamp_budgets_the_psum(self):
        # the clamp must bound the n-chip SUM, not just each chip's
        # value: n chips each at 6e4 (individually within f16 range)
        # would overflow the f16 accumulation without the /n budget
        n = 8
        x = jnp.full((4,), 6e4, jnp.float32)
        w = gs.wire_cast(x, jnp.float16, jax.random.PRNGKey(0), n_sum=n)
        lim = float(jnp.finfo(jnp.float16).max) / n
        assert float(np.max(np.asarray(w, np.float32))) <= lim
        total = np.float16(0)
        for _ in range(n):  # worst-case coherent f16 accumulation
            total = np.float16(total + np.asarray(w, np.float16)[0])
        assert np.isfinite(total)


class TestGradSyncNumerics:
    """The core acceptance gates: explicit reduce-scatter/update/gather
    vs plain psum, driven through the exact shard_map machinery."""

    def _setup(self, devices):
        mesh = Mesh(np.array(devices), ("data",))
        model = small_mlp()
        params, mstate = model.init(jax.random.PRNGKey(0))
        crit = nn.ClassNLLCriterion()
        method = optim.SGD(learning_rate=0.05, momentum=0.9)
        r = np.random.default_rng(0)
        xs = jnp.asarray(r.normal(0, 1, (6, 64, 1, 28, 28))
                         .astype(np.float32))
        ys = jnp.asarray(r.integers(0, 10, (6, 64)).astype(np.int32))

        def loss_fn(p, ms, x, y):
            out, ms2 = model.apply(p, ms, x, training=True)
            return crit.apply(out, y), ms2

        return mesh, params, mstate, method, \
            jax.value_and_grad(loss_fn, has_aux=True), xs, ys

    def test_f32_wire_bitwise_vs_psum(self, devices):
        mesh, params, mstate, method, grad_fn, xs, ys = \
            self._setup(devices)
        n = 8
        plan = gs.build_plan(params, n, 1 << 14)  # force several buckets
        assert plan.num_buckets > 1
        gstate = gs.init_state(plan, params, method)
        repl = jax.tree_util.tree_map(lambda _: P(), params)
        replm = jax.tree_util.tree_map(lambda _: P(), mstate)
        gspec = jax.tree_util.tree_map(lambda _: P("data"), gstate)

        def gs_step(p, ms, st, x, y, it):
            (loss, ms2), g = grad_fn(p, ms, x, y)
            p2, st2 = gs.sync_and_update(plan, g, st, method, 0.05, it,
                                         wire_dtype=jnp.float32,
                                         axis_name="data")
            return p2, ms2, st2, lax.pmean(loss, "data")

        ostate = method.init_state(params)
        ospec = jax.tree_util.tree_map(lambda _: P(), ostate)

        def psum_step(p, ms, os_, x, y, it):
            (loss, ms2), g = grad_fn(p, ms, x, y)
            g = jax.tree_util.tree_map(
                lambda a: lax.psum(a / n, "data"), g)
            p2, os2 = method.update(g, p, os_, 0.05, it)
            return p2, ms2, os2, lax.pmean(loss, "data")

        f_gs = jax.jit(gs.shard_map_compat(
            gs_step, mesh, (repl, replm, gspec, P("data"), P("data"),
                            P()), (repl, replm, gspec, P())))
        f_ps = jax.jit(gs.shard_map_compat(
            psum_step, mesh, (repl, replm, ospec, P("data"), P("data"),
                              P()), (repl, replm, ospec, P())))

        pa, pb = params, params
        sa, sb = gstate, ostate
        ma = mb = mstate
        for t in range(xs.shape[0]):
            pa, ma, sa, la = f_gs(pa, ma, sa, xs[t], ys[t], t)
            pb, mb, sb, lb = f_ps(pb, mb, sb, xs[t], ys[t], t)
            assert np.asarray(la) == np.asarray(lb), t
            for a, b in zip(jax.tree_util.tree_leaves(pa),
                            jax.tree_util.tree_leaves(pb)):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))
        # master slices reassemble bitwise into the published params
        masters = [np.asarray(m) for m in sa["master"]]
        flat_params = [np.asarray(b) for b in
                       gs.flatten_to_buckets(plan, pa)]
        for m, fp in zip(masters, flat_params):
            np.testing.assert_array_equal(m, fp)

    def test_bf16_wire_tracks_f32_within_tol(self, devices):
        rec32, rec16 = RecordingSummary(), RecordingSummary()
        train_distri(iters=8, summary=rec32, grad_wire_dtype="f32")
        m16, o16 = train_distri(iters=8, summary=rec16,
                                grad_wire_dtype="bf16")
        l32, l16 = np.array(rec32.losses), np.array(rec16.losses)
        assert l32.shape == l16.shape == (8,)
        np.testing.assert_allclose(l16, l32, rtol=0.05, atol=0.02)
        assert np.all(np.isfinite(l16))
        # masters stay exact f32 even under the compressed wire
        for m in o16._final_opt_state["master"]:
            assert m.dtype == jnp.float32


class TestGradSyncDriver:
    def test_enabled_by_default_for_pure_dp(self, devices):
        _, opt = train_distri(iters=2)
        assert opt._use_grad_sync
        assert opt._gs_plan is not None

    def test_zero1_slice_update_equality_vs_replicated(self, devices):
        m1, o1 = train_distri(iters=4, seed=5)  # grad_sync ZeRO-1
        m2, o2 = train_distri(iters=4, seed=5,
                              parameter_sharding=False)  # replicated
        assert o1._use_grad_sync and not o2._use_grad_sync
        for a, b in zip(jax.tree_util.tree_leaves(m1._params),
                        jax.tree_util.tree_leaves(m2._params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_k_invariance_through_grad_sync(self, devices):
        recs = {}
        for k in (1, 4):
            rec = RecordingSummary()
            _, opt = train_distri(iters=8, k=k, summary=rec)
            assert opt._use_grad_sync
            recs[k] = (np.array(rec.losses), opt)
        l1, o1 = recs[1]
        l4, o4 = recs[4]
        np.testing.assert_allclose(l1, l4, rtol=1e-5, atol=1e-7)
        assert o4._dispatch_count < o1._dispatch_count
        for a, b in zip(jax.tree_util.tree_leaves(o1.model._params),
                        jax.tree_util.tree_leaves(o4.model._params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)

    @pytest.mark.parametrize("clip", ["l2", "value"])
    def test_clip_matches_replicated_baseline(self, clip, devices):
        """Both clip kinds, applied to owned slices of the REDUCED
        gradient, must reproduce the replicated-baseline clipping
        (value = elementwise; l2 = psum of per-slice square sums)."""
        def run(**kw):
            model = small_mlp()
            opt = (optim.DistriOptimizer(model, mnist_pipeline(512, 64),
                                         nn.ClassNLLCriterion(), **kw)
                   .set_optim_method(optim.SGD(learning_rate=0.5))
                   .set_seed(5)
                   .set_end_when(optim.max_iteration(4)))
            if clip == "l2":
                opt.set_gradient_clipping_by_l2_norm(0.5)
            else:
                opt.set_gradient_clipping_by_value(-3e-3, 3e-3)
            opt.optimize()
            return model, opt

        m1, o1 = run()
        m2, _ = run(parameter_sharding=False)
        assert o1._use_grad_sync
        for a, b in zip(jax.tree_util.tree_leaves(m1._params),
                        jax.tree_util.tree_leaves(m2._params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)

    def test_lbfgs_rejected_with_clear_error(self, devices):
        model = small_mlp()
        opt = (optim.DistriOptimizer(model, mnist_pipeline(64, 32),
                                     nn.ClassNLLCriterion())
               .set_optim_method(optim.LBFGS())
               .set_end_when(optim.max_iteration(1)))
        with pytest.raises(ValueError, match="elementwise"):
            opt.optimize()

    def test_explicit_grad_sync_on_tp_mesh_rejected(self, devices):
        from bigdl_tpu.parallel import create_mesh
        mesh = create_mesh(data=2, model=4)
        with pytest.raises(ValueError, match="pure data-parallel"):
            (optim.DistriOptimizer(small_mlp(), mnist_pipeline(64, 32),
                                   nn.ClassNLLCriterion(), mesh=mesh,
                                   grad_sync=True)
             .set_end_when(optim.max_iteration(1))
             .optimize())

    def test_stale_non_gs_checkpoint_rejected_loudly(self, devices):
        """A retry/resume checkpoint written by the pre-grad_sync path
        must fail with a format message, not an opaque trace-time
        KeyError."""
        opt = (optim.DistriOptimizer(small_mlp(), mnist_pipeline(64, 32),
                                     nn.ClassNLLCriterion())
               .set_optim_method(optim.SGD(learning_rate=0.05,
                                           momentum=0.9))
               .set_end_when(optim.max_iteration(1)))
        opt._resume_opt_state = {"velocity": {"0": np.zeros((4,),
                                                           np.float32)}}
        with pytest.raises(ValueError, match="not grad_sync-format"):
            opt.optimize()

    def test_checkpoint_resume_roundtrips_gs_state(self, tmp_path,
                                                   devices):
        from bigdl_tpu.utils import checkpoint as ckpt
        path = str(tmp_path / "ck")
        model = small_mlp()
        opt = (optim.DistriOptimizer(model, mnist_pipeline(256, 32),
                                     nn.ClassNLLCriterion())
               .set_optim_method(optim.SGD(learning_rate=0.05,
                                           momentum=0.9))
               .set_seed(5)
               .set_end_when(optim.max_iteration(4))
               .set_checkpoint(path, optim.several_iteration(2)))
        opt.optimize()
        blob = ckpt.load_checkpoint(ckpt.latest_checkpoint(path))
        st = blob["opt_state"]
        assert set(st) == {"master", "opt"}
        assert isinstance(st["master"], list)
        # masters in the checkpoint equal the final published params
        plan = opt._gs_plan
        for m, fp in zip(st["master"],
                         gs.flatten_to_buckets(plan, model._params)):
            np.testing.assert_allclose(np.asarray(m), np.asarray(fp),
                                       rtol=0, atol=0)


class TestConfigEngineSurface:
    def test_config_fields(self):
        from bigdl_tpu.utils.config import Config
        c = Config()
        assert c.grad_bucket_bytes == 4 << 20
        assert c.grad_wire_dtype == "f32"

    def test_env_overlay(self, monkeypatch):
        from bigdl_tpu.utils.config import Config
        monkeypatch.setenv("BIGDL_TPU_GRAD_WIRE_DTYPE", "bf16")
        monkeypatch.setenv("BIGDL_TPU_GRAD_BUCKET_BYTES", "1048576")
        c = Config.from_env()
        assert c.grad_wire_dtype == "bf16"
        assert c.grad_bucket_bytes == 1 << 20

    def test_wire_dtype_constructor_override(self, devices):
        _, opt = train_distri(iters=1, grad_wire_dtype="bf16")
        assert opt._gs_wire is jnp.bfloat16

    def test_set_xla_async_collectives(self, monkeypatch):
        from bigdl_tpu.engine import Engine
        monkeypatch.setenv("XLA_FLAGS", "--foo=1")
        prev = Engine._state.xla_async_collectives
        try:
            # this process's backend IS live (conftest initialized jax):
            # an unforced late call must refuse — no probe child fights
            # for a chip, no env mutation, intent still recorded
            assert Engine._backend_live()
            Engine.set_xla_async_collectives(True)
            assert os.environ["XLA_FLAGS"] == "--foo=1"
            assert Engine.xla_async_collectives() is True
            # pre-init path, probe refuses: env still untouched (probe
            # outcomes are pinned so the test is deterministic — the
            # real probe spawns a jax subprocess)
            monkeypatch.setattr(Engine, "_backend_live",
                                staticmethod(lambda: False))
            monkeypatch.setattr(Engine, "_xla_flags_survive",
                                staticmethod(lambda _f: False))
            Engine.set_xla_async_collectives(True)
            assert os.environ["XLA_FLAGS"] == "--foo=1"
            # pre-init path, probe survives: flags committed
            monkeypatch.setattr(Engine, "_xla_flags_survive",
                                staticmethod(lambda _f: True))
            Engine.set_xla_async_collectives(True)
            flags = os.environ["XLA_FLAGS"]
            assert "--foo=1" in flags
            assert "--xla_tpu_enable_latency_hiding_scheduler=true" \
                in flags
            # identical re-call short-circuits (no second probe)
            monkeypatch.setattr(
                Engine, "_xla_flags_survive",
                staticmethod(lambda _f: pytest.fail("re-probed")))
            Engine.set_xla_async_collectives(True)
            # force=True writes with no probe and never duplicates
            Engine.set_xla_async_collectives(False, force=True)
            flags = os.environ["XLA_FLAGS"].split()
            assert flags.count("--xla_tpu_enable_latency_hiding_"
                               "scheduler=false") == 1
            assert not any(f.endswith("=true") for f in flags
                           if f.startswith("--xla_tpu_enable_"))
            assert Engine.xla_async_collectives() is False
        finally:
            Engine._state.xla_async_collectives = prev


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
