"""Subprocess self-healing child for tests/test_resilience.py.

Runs a 4-replica :class:`~bigdl_tpu.resilience.ReplicaSet` under
multi-threaded closed-loop traffic while a seeded fault plan kills
replica 0's batcher thread mid-sweep (``replica_death@target=0`` — a
BaseException escapes the dispatch handler, exactly like a real thread
crash).  Every request is accounted one-by-one; the parent asserts on
the JSON this prints:

- ``lost`` must be 0: every accepted request resolved with a result or
  an explicit error (the join proves no future was stranded);
- ``wrong`` must be 0: every successful result allclose-equals the
  precomputed expected output (a failover must never fabricate rows);
- the death → quarantine → failover → revival → probation →
  readmission cycle must appear in the ``resilience/*`` counters and
  the final health states must be all-healthy (re-admitted).

A real subprocess (not a thread in the test runner) so the injected
BaseException's thread-kill semantics can't poison the pytest process.

Exit codes: 0 = ran to completion (the parent asserts on the JSON),
1 = crashed (traceback on stderr).
"""

import json
import os
import sys
import threading
import time

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from bigdl_tpu import nn  # noqa: E402
from bigdl_tpu.resilience import ReplicaSet  # noqa: E402
from bigdl_tpu.resilience.faults import FaultInjector  # noqa: E402
from bigdl_tpu.resilience.health import HealthPolicy  # noqa: E402
from bigdl_tpu.serving import (DeadlineExceeded,  # noqa: E402
                               ServiceOverloaded)

N_REPLICAS, N_THREADS, DIN = 4, 4, 16
KILL_AFTER = 5       # replica-0 dispatch index floor for the kill
RUN_S = 4.0          # long enough for probation + readmission
PROBE_BACKOFF_S = 0.2


def main():
    model = nn.Sequential(nn.Linear(DIN, 32), nn.ReLU(),
                          nn.Linear(32, 4), nn.SoftMax()).initialize(0)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (1, DIN)).astype(np.float32)
    rs = ReplicaSet(
        model, n_replicas=N_REPLICAS,
        input_spec=((DIN,), np.float32), max_batch_size=8,
        batch_timeout_ms=1.0, queue_capacity=1024, name="child",
        deadline_ms=3000.0, max_retries=2,
        health=HealthPolicy(probe_backoff_s=PROBE_BACKOFF_S, seed=0),
        fault_injector=FaultInjector(
            f"replica_death@target=0,after={KILL_AFTER},count=1",
            seed=0))
    expected = np.asarray(rs.predict(x, timeout=30))

    counts = {"ok": 0, "wrong": 0, "shed": 0, "deadline": 0, "error": 0}
    lock = threading.Lock()
    deadline = time.monotonic() + RUN_S

    def worker():
        while time.monotonic() < deadline:
            try:
                got = rs.predict(x, timeout=2.0)
            except ServiceOverloaded:
                with lock:
                    counts["shed"] += 1
                time.sleep(0.005)
                continue
            except (DeadlineExceeded, TimeoutError):
                with lock:
                    counts["deadline"] += 1
                continue
            except Exception:
                with lock:
                    counts["error"] += 1
                continue
            good = np.allclose(np.asarray(got), expected,
                               rtol=1e-5, atol=1e-7)
            with lock:
                counts["ok" if good else "wrong"] += 1

    saw_quarantine = [False]

    def monitor():
        while time.monotonic() < deadline:
            if "quarantined" in rs.health_states():
                saw_quarantine[0] = True
            time.sleep(0.01)

    threads = [threading.Thread(target=worker)
               for _ in range(N_THREADS)] \
        + [threading.Thread(target=monitor)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()  # every accepted request resolved — nothing stranded

    stats = rs.stats()
    final_health = rs.health_states()
    rs.stop()
    print(json.dumps({
        "counts": counts,
        "lost": 0,  # the joins above prove it: no call still blocked
        "saw_quarantine": saw_quarantine[0],
        "final_health": final_health,
        "resilience": {k: v for k, v in
                       sorted(stats["resilience"].items()) if v},
    }))


if __name__ == "__main__":
    main()
