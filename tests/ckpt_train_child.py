"""Subprocess fault-injection child for tests/test_checkpoint.py.

Trains a small MLP on synthetic MNIST with checkpointing enabled and
appends one ``<step> <repr(loss)>`` line per replayed iteration to
``--losses`` (line-buffered, so the parent can watch progress live and
SIGKILL/SIGTERM the process mid-epoch).  With ``--resume`` it restores
the latest valid snapshot first and trains to ``--iters``; with
``--params-out`` it dumps the final params for bitwise comparison
against the parent's uninterrupted reference run.

The builders (``mlp``/``pipeline``/``build_optimizer``) are imported by
the parent test so both processes construct byte-identical runs.

Exit codes: 0 ok (including a clean preemption exit), 3 = --resume
found no valid snapshot.
"""

import argparse
import os
import sys

os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from bigdl_tpu import nn, optim  # noqa: E402
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch  # noqa: E402
from bigdl_tpu.dataset import image, mnist  # noqa: E402

N_SAMPLES, BATCH = 320, 32  # 10-step epochs — kills land mid-epoch


def pipeline():
    imgs, labels = mnist.synthetic_mnist(N_SAMPLES, seed=0)
    return (DataSet.array(mnist.to_samples(imgs, labels))
            >> image.BytesToGreyImg()
            >> image.GreyImgNormalizer(mnist.TRAIN_MEAN, mnist.TRAIN_STD)
            >> SampleToMiniBatch(BATCH))


def mlp():
    return (nn.Sequential()
            .add(nn.Reshape((784,)))
            .add(nn.Linear(784, 32)).add(nn.ReLU())
            .add(nn.Linear(32, 10)).add(nn.LogSoftMax()))


class LossLog:
    """TrainSummary stand-in writing one line per replayed iteration."""

    def __init__(self, path, fh=None):
        self._fh = fh or open(path, "a", buffering=1)

    def add_train_step(self, step, loss, lr, throughput):
        self._fh.write(f"{step} {loss!r}\n")
        self._fh.flush()

    def add_scalar(self, tag, value, step):
        pass

    def trigger_for(self, name):
        return None


def build_optimizer(ckpt_dir, iters, k, grad_sync, every=3, summary=None):
    cls = optim.DistriOptimizer if grad_sync else optim.LocalOptimizer
    opt = (cls(mlp(), pipeline(), nn.ClassNLLCriterion())
           .set_optim_method(optim.Adam(1e-3))
           .set_steps_per_dispatch(k)
           .set_seed(7)
           .set_end_when(optim.max_iteration(iters))
           .set_checkpoint(ckpt_dir, optim.several_iteration(every)))
    if summary is not None:
        opt.set_train_summary(summary)
    return opt


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--dir", required=True)
    p.add_argument("--losses", required=True)
    p.add_argument("--iters", type=int, default=16)
    p.add_argument("--k", type=int, default=1)
    p.add_argument("--every", type=int, default=3)
    p.add_argument("--grad-sync", action="store_true")
    p.add_argument("--resume", action="store_true")
    p.add_argument("--preemption", action="store_true")
    p.add_argument("--params-out")
    args = p.parse_args(argv)

    opt = build_optimizer(args.dir, args.iters, args.k, args.grad_sync,
                          every=args.every,
                          summary=LossLog(args.losses))
    if args.preemption:
        opt.set_preemption_handling()
    if args.resume and not opt.resume():
        return 3
    opt.optimize()
    if args.params_out:
        leaves = jax.tree_util.tree_leaves(opt.model._params)
        np.savez(args.params_out,
                 **{f"p{i}": np.asarray(l) for i, l in enumerate(leaves)})
    if opt.state.get("preempted"):
        print(f"PREEMPTED {opt.state['neval']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
