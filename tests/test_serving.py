"""bigdl_tpu.serving — dynamic-batching inference engine tests.

The load-bearing gates (ISSUE 5 acceptance):

- **Coalescing proof**: 16 threads × 4 single-row submits resolve in
  ``ceil(requests / max_batch_size)`` device dispatches (≪ request
  count), with ZERO new compiles after warmup (trace-counter assertion
  — the serving analog of graftlint GL106).
- **Bitwise correctness**: every coalesced, bucket-padded result equals
  a direct per-request ``model.apply`` forward bit for bit (zero-pad
  rows provably don't leak into real rows).
- **Backpressure**: a full bounded queue raises ``ServiceOverloaded``
  with the depth in the message; shutdown drains cleanly.

All concurrency tests are event-driven (barriers, futures, the
``start=False`` staging hook) — no sleep-based synchronization.
"""

import math
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.nn.module import Module
from bigdl_tpu.optim.predictor import PredictionService, Predictor
from bigdl_tpu.serving import (
    InferenceService, LatencyReservoir, ModelRegistry, ServiceClosed,
    ServiceOverloaded, row_buckets,
)


def make_model(din=16, dout=4):
    return nn.Sequential(nn.Linear(din, 32), nn.ReLU(),
                         nn.Linear(32, dout), nn.SoftMax()).initialize(0)


def rows(rng, n, din=16):
    return rng.normal(0, 1, (n, din)).astype(np.float32)


SPEC16 = ((16,), np.float32)


class TestBuckets:
    def test_power_of_two_ladder(self):
        assert row_buckets(8) == (1, 2, 4, 8)
        assert row_buckets(1) == (1,)

    def test_non_pow2_max_is_top_bucket(self):
        assert row_buckets(12) == (1, 2, 4, 8, 12)

    def test_warmup_compiles_each_bucket_once(self):
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=8, start=False)
        assert svc.warmed_up
        # one trace per bucket executable, nothing else
        assert svc.compile_count == len(svc.buckets)
        assert svc.output_row_shape() == (4,)
        # warmup is idempotent — no second compile sweep
        assert svc.warmup(SPEC16) == {}
        assert svc.compile_count == len(svc.buckets)
        svc.stop()


class TestCoalescing:
    """The acceptance gate: 16-thread single-row load."""

    N_THREADS, PER_THREAD, MAX_BATCH = 16, 4, 8

    def _staged_load(self):
        model = make_model()
        svc = InferenceService(model, input_spec=SPEC16,
                               max_batch_size=self.MAX_BATCH,
                               queue_capacity=256, start=False)
        warm_compiles = svc.compile_count
        rng = np.random.default_rng(7)
        xs = [rows(rng, 1) for _ in range(self.N_THREADS * self.PER_THREAD)]
        futs = [None] * len(xs)
        barrier = threading.Barrier(self.N_THREADS)

        def worker(t):
            barrier.wait()
            for i in range(self.PER_THREAD):
                k = t * self.PER_THREAD + i
                futs[k] = svc.submit(xs[k])

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(self.N_THREADS)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        # everything queued BEFORE the first dispatch — deterministic
        assert svc.queue_depth() == len(xs)
        svc.start()
        outs = [f.result(timeout=60) for f in futs]
        return model, svc, xs, outs, warm_compiles

    def test_dispatch_budget_and_bitwise_outputs(self):
        model, svc, xs, outs, warm = self._staged_load()
        n_req = len(xs)
        stats = svc.stats()
        budget = math.ceil(n_req / self.MAX_BATCH) + len(svc.buckets)
        assert stats["dispatch_count"] <= budget, stats
        assert stats["dispatch_count"] < n_req  # coalescing, not 1:1
        # bitwise equality against per-request direct forwards
        for x, out in zip(xs, outs):
            direct, _ = model.apply(svc.params, svc.state, x,
                                    training=False)
            np.testing.assert_array_equal(out, np.asarray(direct))
        # zero new compiles after warmup (GL106-for-serving)
        assert svc.compile_count == warm
        assert stats["compile_count"] == warm
        # fully staged queue → perfectly occupied buckets
        assert stats["mean_batch_occupancy"] == 1.0
        assert stats["requests_completed"] == n_req
        svc.stop()

    def test_live_threads_blocking_predict(self):
        """predict() (blocking sugar) from concurrent threads: pure
        correctness under live interleaving, no dispatch-count claim."""
        model = make_model()
        svc = InferenceService(model, input_spec=SPEC16, max_batch_size=8,
                               batch_timeout_ms=1.0)
        rng = np.random.default_rng(3)
        xs = [rows(rng, n) for n in (1, 3, 5, 8, 2, 1, 7, 4)]
        errs = []

        def worker(x):
            try:
                out = svc.predict(x, timeout=60)
                direct, _ = model.apply(svc.params, svc.state, x,
                                        training=False)
                np.testing.assert_array_equal(out, np.asarray(direct))
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(x,)) for x in xs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert svc.stats()["requests_completed"] == sum(
            x.shape[0] for x in xs)
        svc.stop()

    def test_mixed_sizes_pad_to_bucket_bitwise(self):
        """Odd-sized coalesced groups pad with zeros to the bucket; the
        pad provably does not leak into real rows (bitwise equality
        between bucket sizes IS the invariant check)."""
        model = make_model()
        svc = InferenceService(model, input_spec=SPEC16, max_batch_size=8,
                               start=False)
        rng = np.random.default_rng(11)
        xs = [rows(rng, n) for n in (3, 2)]  # coalesce to 5 → bucket 8
        futs = [svc.submit(x) for x in xs]
        svc.start()
        outs = [f.result(timeout=60) for f in futs]
        assert svc.stats()["dispatch_count"] == 1
        for x, out in zip(xs, outs):
            direct, _ = model.apply(svc.params, svc.state, x,
                                    training=False)
            np.testing.assert_array_equal(out, np.asarray(direct))
        svc.stop()


class TestBackpressure:
    def test_overloaded_then_drain(self):
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=4, queue_capacity=4,
                               start=False)
        x = rows(np.random.default_rng(0), 1)
        futs = [svc.submit(x) for _ in range(4)]
        with pytest.raises(ServiceOverloaded) as ei:
            svc.submit(x)
        assert ei.value.queue_depth == 4 and ei.value.capacity == 4
        assert "depth=4" in str(ei.value)
        assert svc.stats()["requests_rejected"] == 1
        # backpressure clears once the batcher runs
        svc.start()
        for f in futs:
            assert f.result(timeout=60).shape == (1, 4)
        svc.stop()
        assert svc.stats()["queue_depth"] == 0

    def test_stop_drains_accepted_work(self):
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=4, start=False)
        x = rows(np.random.default_rng(1), 2)
        futs = [svc.submit(x) for _ in range(5)]
        svc.stop(drain=True)  # never-started batcher drains inline
        for f in futs:
            assert f.result(timeout=0).shape == (2, 4)
        with pytest.raises(ServiceClosed):
            svc.submit(x)

    def test_stop_no_drain_cancels_backlog(self):
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=4, start=False)
        x = rows(np.random.default_rng(2), 1)
        futs = [svc.submit(x) for _ in range(3)]
        svc.stop(drain=False)
        assert all(f.cancelled() for f in futs)
        assert svc.stats()["requests_cancelled"] == 3

    def test_stop_no_drain_cancels_on_running_batcher(self):
        """Regression: with the batcher RUNNING, drain=False must cancel
        the backlog, not quietly dispatch it.  The first dispatch is
        gated on an Event so the backlog deterministically builds while
        the batcher thread is busy."""
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=1, start=False)
        gate = threading.Event()
        entered = threading.Event()
        inner = svc._batcher._dispatch_fn

        def gated(reqs):
            entered.set()
            gate.wait(timeout=60)
            inner(reqs)

        svc._batcher._dispatch_fn = gated
        x = rows(np.random.default_rng(12), 1)
        first = svc.submit(x)
        svc.start()
        assert entered.wait(timeout=60)  # batcher busy inside dispatch 1
        backlog = [svc.submit(x) for _ in range(3)]
        stopper = threading.Thread(target=svc.stop,
                                   kwargs={"drain": False})
        stopper.start()
        gate.set()
        stopper.join(timeout=60)
        assert not stopper.is_alive()
        assert first.result(timeout=60).shape == (1, 4)  # in-flight wins
        assert all(f.cancelled() for f in backlog)
        assert svc.stats()["requests_cancelled"] == 3

    def test_running_service_stop_resolves_everything(self):
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=8, batch_timeout_ms=1.0)
        x = rows(np.random.default_rng(3), 1)
        futs = [svc.submit(x) for _ in range(20)]
        svc.stop(drain=True)
        assert all(f.done() and not f.cancelled() for f in futs)
        stats = svc.stats()
        assert stats["requests_completed"] == 20
        assert stats["queue_depth"] == 0


class TestServiceSurface:
    def test_oversized_submit_rejected_predict_chunks(self):
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=4)
        x = rows(np.random.default_rng(5), 11)
        with pytest.raises(ValueError, match="max_batch_size"):
            svc.submit(x)
        out = svc.predict(x, timeout=60)
        direct, _ = svc.model.apply(svc.params, svc.state, x,
                                    training=False)
        np.testing.assert_array_equal(out, np.asarray(direct))
        svc.stop()

    def test_huge_predict_through_tiny_queue(self):
        """Regression: predict() must window its chunk submissions so a
        large input can't self-overflow the bounded queue (the old
        submit-everything loop raised ServiceOverloaded at ~capacity
        chunks)."""
        model = make_model()
        svc = InferenceService(model, input_spec=SPEC16, max_batch_size=2,
                               queue_capacity=4, batch_timeout_ms=0.0)
        x = rows(np.random.default_rng(15), 64)  # 32 chunks >> capacity
        out = svc.predict(x, timeout=120)
        direct, _ = model.apply(svc.params, svc.state, x, training=False)
        np.testing.assert_array_equal(out, np.asarray(direct))
        svc.stop()

    def test_predict_timeout_is_a_shared_deadline(self):
        """Regression: timeout bounds the whole predict(), not each
        chunk future — a parked batcher must time the call out in ~one
        timeout, not chunks x timeout."""
        import concurrent.futures
        import time as _time
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=2, queue_capacity=64,
                               start=False)
        x = rows(np.random.default_rng(16), 32)  # 16 chunks
        t0 = _time.monotonic()
        with pytest.raises((TimeoutError, concurrent.futures.TimeoutError)):
            svc.predict(x, timeout=0.3)
        assert _time.monotonic() - t0 < 3.0  # not 16 x 0.3 compounding
        svc.stop(drain=False)

    def test_empty_input_shape(self):
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=4, start=False)
        out = svc.predict(np.empty((0, 16), np.float32))
        assert out.shape == (0, 4) and out.dtype == np.float32
        svc.stop()

    def test_deferred_spec_warms_on_first_request(self):
        svc = InferenceService(make_model(), max_batch_size=4)
        assert not svc.warmed_up
        out = svc.predict(rows(np.random.default_rng(6), 2), timeout=60)
        assert out.shape == (2, 4)
        assert svc.warmed_up
        assert svc.compile_count == len(svc.buckets)
        svc.stop()

    def test_deferred_warmup_concurrent_first_requests(self):
        """Regression: concurrent FIRST requests must all block until
        every bucket is compiled — a submitter must never observe a
        partially-populated executable dict (KeyError on dispatch)."""
        svc = InferenceService(make_model(), max_batch_size=8,
                               batch_timeout_ms=1.0)
        rng = np.random.default_rng(13)
        sizes = [1, 5, 3, 8, 2, 7, 4, 6]
        xs = [rows(rng, n) for n in sizes]
        barrier = threading.Barrier(len(sizes))
        errs = []

        def worker(x):
            barrier.wait()
            try:
                out = svc.predict(x, timeout=60)
                assert out.shape == (x.shape[0], 4)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(x,))
                   for x in xs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs
        assert svc.compile_count == len(svc.buckets)
        svc.stop()

    def test_pytree_input_model(self):
        class TwoTower(Module):
            def init(self, rng):
                k1, k2 = jax.random.split(rng)
                return {"a": jax.random.normal(k1, (6, 3)),
                        "b": jax.random.normal(k2, (5, 3))}, {}

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                a, b = input
                return a @ params["a"] + b @ params["b"], state

        model = TwoTower().initialize(0)
        svc = InferenceService(
            model, input_spec=(((6,), np.float32), ((5,), np.float32)),
            max_batch_size=4, start=False)
        rng = np.random.default_rng(8)
        x = (rng.normal(0, 1, (3, 6)).astype(np.float32),
             rng.normal(0, 1, (3, 5)).astype(np.float32))
        fut = svc.submit(x)
        svc.start()
        out = fut.result(timeout=60)
        direct, _ = model.apply(svc.params, svc.state, x, training=False)
        np.testing.assert_array_equal(out, np.asarray(direct))
        svc.stop()

    def test_malformed_request_fails_alone(self):
        """A bad request must be rejected at submit — not poison the
        coalesced group it would have joined."""
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=8, start=False)
        good = svc.submit(rows(np.random.default_rng(19), 2))
        with pytest.raises(ValueError, match="input_spec"):
            svc.submit(np.ones((1, 8), np.float32))  # wrong trailing dim
        svc.start()
        assert good.result(timeout=60).shape == (2, 4)  # unharmed
        svc.stop()

    def test_dtype_mismatch_coerced_like_jnp_asarray(self):
        """float64 (the numpy default) serves as f32 — the historical
        jnp.asarray behavior — instead of poisoning the group through
        np.concatenate's silent promotion."""
        model = make_model()
        svc = InferenceService(model, input_spec=SPEC16, max_batch_size=4)
        x32 = rows(np.random.default_rng(20), 2)
        out64 = svc.predict(x32.astype(np.float64), timeout=60)
        out32 = svc.predict(x32, timeout=60)
        assert out64.dtype == np.float32
        np.testing.assert_array_equal(out64, out32)
        svc.stop()

    def test_non_row_tracking_model_refused_at_deploy(self):
        """A model whose output rows come from static metadata cannot
        be served by per-request slicing — warmup must refuse it."""

        class StaticRows(Module):
            def init(self, rng):
                return {"w": jax.random.normal(rng, (3, 3))}, {}

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                # output rows fixed at 4 regardless of input rows
                pooled = jnp.sum(input, axis=0, keepdims=True)
                return jnp.tile(pooled @ params["w"], (4, 1)), state

        with pytest.raises(ValueError, match="not servable"):
            InferenceService(StaticRows().initialize(0),
                             input_spec=((3,), np.float32),
                             max_batch_size=4, start=False)

    def test_stats_schema(self):
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=8)
        svc.predict(rows(np.random.default_rng(9), 3), timeout=60)
        s = svc.stats()
        for key in ("requests_submitted", "requests_completed",
                    "dispatch_count", "mean_batch_occupancy",
                    "throughput_rps", "queue_depth", "latency_ms",
                    "latency_ms_by_bucket", "compile_count", "buckets",
                    "model"):
            assert key in s, key
        assert s["latency_ms"] is not None
        assert {"p50", "p95", "p99", "mean"} <= set(s["latency_ms"])
        assert s["latency_ms"]["p50"] <= s["latency_ms"]["p95"] \
            <= s["latency_ms"]["p99"]
        # per-row-bucket reservoirs (telemetry PR): the 3-row request
        # dispatched into the 4-bucket; only exercised buckets appear
        assert set(s["latency_ms_by_bucket"]) == {4}
        assert {"p50", "p95", "p99"} <= set(s["latency_ms_by_bucket"][4])
        assert 0 < s["mean_batch_occupancy"] <= 1.0
        assert s["throughput_rps"] > 0
        svc.stop()

    def test_zero_knobs_rejected_not_defaulted(self):
        """Regression: an explicit 0 must hit the batcher's >= 1
        validation, not silently fall through to the config default."""
        with pytest.raises(ValueError, match="max_batch_size"):
            InferenceService(make_model(), max_batch_size=0, start=False)
        with pytest.raises(ValueError, match="queue_capacity"):
            InferenceService(make_model(), queue_capacity=0, start=False)

    def test_dropped_service_stops_batcher_thread(self):
        """Regression: a service dropped without stop() (every
        historical PredictionService caller) must not strand its
        batcher thread for the life of the process."""
        import gc
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=2)
        batcher = svc._batcher
        assert batcher.running
        del svc
        gc.collect()
        assert not batcher.running

    def test_zero_timeout_is_adaptive_batching(self):
        """timeout 0: a lone request dispatches without waiting out a
        coalescing window, but a staged backlog still coalesces."""
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=8, batch_timeout_ms=0.0,
                               start=False)
        x = rows(np.random.default_rng(14), 1)
        futs = [svc.submit(x) for _ in range(8)]
        svc.start()
        for f in futs:
            assert f.result(timeout=60).shape == (1, 4)
        assert svc.stats()["dispatch_count"] == 1  # still coalesces
        svc.stop()

    def test_latency_reservoir_percentiles(self):
        r = LatencyReservoir(capacity=64)
        for v in range(1, 101):  # ring keeps the last 64: 37..100
            r.record(v / 1000.0)
        p = r.percentiles()
        assert p["p50"] <= p["p95"] <= p["p99"] <= p["max"]
        assert p["max"] == 100 / 1000.0


class TestModelRegistry:
    def test_versioning_and_routing(self):
        reg = ModelRegistry()
        m1, m2 = make_model(), make_model(dout=3)
        reg.deploy("clf", m1, input_spec=SPEC16, max_batch_size=4)
        reg.deploy("clf", m2, input_spec=SPEC16, max_batch_size=4)
        assert reg.list_models() == {"clf": [1, 2]}
        x = rows(np.random.default_rng(0), 2)
        assert reg.predict("clf", x, timeout=60).shape == (2, 3)  # latest
        assert reg.predict("clf", x, version=1, timeout=60).shape == (2, 4)
        reg.undeploy("clf", version=2)
        assert reg.predict("clf", x, timeout=60).shape == (2, 4)  # back to v1
        with pytest.raises(KeyError):
            reg.get("clf", version=2)
        reg.stop_all()
        with pytest.raises(KeyError):
            reg.get("clf")

    def test_duplicate_version_and_unknown_name(self):
        reg = ModelRegistry()
        reg.deploy("m", make_model(), version=7, input_spec=SPEC16)
        with pytest.raises(ValueError, match="already deployed"):
            reg.deploy("m", make_model(), version=7)
        with pytest.raises(KeyError, match="no model"):
            reg.get("ghost")
        reg.stop_all()

    def test_quantized_deploy(self):
        reg = ModelRegistry()
        svc = reg.deploy("q", make_model(), quantize=True,
                         input_spec=SPEC16, max_batch_size=4)
        x = rows(np.random.default_rng(1), 3)
        out = reg.predict("q", x, timeout=60)
        assert out.shape == (3, 4)
        direct, _ = svc.model.apply(svc.params, svc.state, x,
                                    training=False)
        np.testing.assert_array_equal(out, np.asarray(direct))
        reg.stop_all()

    def test_deploy_from_bigdl_wire_format(self, tmp_path):
        from bigdl_tpu.interop import save_bigdl_module
        path = str(tmp_path / "model.bigdl")
        save_bigdl_module(make_model(), path)
        reg = ModelRegistry()
        reg.deploy("wire", path=path, format="bigdl", input_spec=SPEC16,
                   max_batch_size=4)
        assert reg.predict(
            "wire", rows(np.random.default_rng(2), 2),
            timeout=60).shape == (2, 4)
        reg.stop_all()

    def test_concurrent_deploys_get_distinct_versions(self):
        """Regression: deploy reserves its (name, version) key before
        the slow AOT warmup, so concurrent auto-versioned deploys can't
        collide and orphan a service's batcher thread."""
        reg = ModelRegistry()
        barrier = threading.Barrier(4)
        results, errs = [], []

        def worker():
            barrier.wait()
            try:
                results.append(reg.deploy("race", make_model(),
                                          input_spec=SPEC16,
                                          max_batch_size=2))
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs and len(results) == 4
        assert reg.list_models() == {"race": [1, 2, 3, 4]}
        # every returned service is routable (none orphaned)
        routable = {id(reg.get("race", version=v)) for v in (1, 2, 3, 4)}
        assert routable == {id(s) for s in results}
        reg.stop_all()

    def test_registry_stats(self):
        reg = ModelRegistry()
        reg.deploy("a", make_model(), input_spec=SPEC16)
        reg.deploy("b", make_model(), input_spec=SPEC16)
        reg.predict("a", rows(np.random.default_rng(3), 1), timeout=60)
        stats = reg.stats()
        assert set(stats) == {"a:v1", "b:v1"}
        assert stats["a:v1"]["requests_completed"] == 1
        reg.stop_all()


class TestPredictorSatellites:
    def test_partial_tail_batch_single_compile(self):
        """GL106 regression: the trailing partial batch must reuse the
        steady-state executable (zero-pad + slice), not compile a second
        shape.  Gated on the jit's REAL compile-cache size (eval_shape
        probes trace but never compile, so a wrapped-fn trace counter
        would over-count)."""
        model = make_model(din=4, dout=3)
        pred = Predictor(model, batch_size=4)
        from bigdl_tpu.dataset.sample import Sample
        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(0, 1, (4,)).astype(np.float32))
                   for _ in range(10)]  # 4 + 4 + 2-row tail
        out = pred.predict(samples)
        assert out.shape == (10, 3)
        assert pred._fwd._cache_size() == 1, (
            f"expected ONE compiled executable for the whole dataset, "
            f"got {pred._fwd._cache_size()} (tail batch recompiled)")

    def test_partial_tail_rows_exact(self):
        model = make_model(din=4, dout=3)
        pred = Predictor(model, batch_size=4)
        from bigdl_tpu.dataset.sample import Sample
        rng = np.random.default_rng(1)
        xs = rng.normal(0, 1, (6, 4)).astype(np.float32)
        out = pred.predict([Sample(x) for x in xs])
        direct, _ = model.apply(pred.params, pred.state, xs,
                                training=False)
        np.testing.assert_allclose(out, np.asarray(direct), rtol=1e-6,
                                    atol=1e-7)

    def test_sparse_mixed_leading_dims_fall_back_to_legacy(self):
        """Regression: SparseMiniBatch-style inputs — (ids(nnz), dense(N))
        leaves with DIFFERENT leading dims — must dispatch as-is (no row
        accounting), exactly like the pre-PR Predictor."""
        from bigdl_tpu.dataset.dataset import AbstractDataSet
        from bigdl_tpu.dataset.sample import MiniBatch

        class BagModel(Module):
            """Embedding-bag + dense tower: input (flat_ids(nnz),
            seg(nnz), dense(N, 2)) -> (N, 3)."""

            def init(self, rng):
                k1, k2 = jax.random.split(rng)
                return {"emb": jax.random.normal(k1, (10, 3)),
                        "w": jax.random.normal(k2, (2, 3))}, {}

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                ids, seg, dense = input
                bags = jax.ops.segment_sum(
                    params["emb"][ids], seg,
                    num_segments=dense.shape[0])
                return bags + dense @ params["w"], state

        class FakeDS(AbstractDataSet):
            def __init__(self, batches):
                self.batches = batches

            def data(self, train=False):
                return iter(self.batches)

            def size(self):
                return sum(b.size() for b in self.batches)

        rng = np.random.default_rng(17)
        batches, expect = [], []
        model = BagModel().initialize(0)
        for n, nnz in ((4, 9), (4, 5)):  # second batch: smaller nnz
            ids = rng.integers(0, 10, nnz).astype(np.int32)
            seg = np.sort(rng.integers(0, n, nnz)).astype(np.int32)
            dense = rng.normal(0, 1, (n, 2)).astype(np.float32)
            batches.append(MiniBatch((ids, seg, dense)))
            out, _ = model.apply(model._params, model._state,
                                 (ids, seg, dense), training=False)
            expect.append(np.asarray(out))
        got = Predictor(model).predict(FakeDS(batches))
        np.testing.assert_array_equal(got, np.concatenate(expect, axis=0))

    def test_coo_nnz_coincidence_keeps_all_rows(self):
        """Regression (confirmed repro in review): COO-only batches
        whose FIRST nnz bucket coincides with the sample count must not
        have real output rows sliced away when a later batch's nnz is
        smaller — the two-point eval_shape probe detects that output
        rows come from static metadata, and the tail dispatches
        unpadded."""
        from bigdl_tpu.dataset.dataset import AbstractDataSet
        from bigdl_tpu.dataset.sample import MiniBatch

        N = 8

        class StaticBag(Module):
            """(ids(nnz), seg(nnz)) -> (8, 3): output rows are a static
            constant, NOT the input leading dim."""

            def init(self, rng):
                return {"emb": jax.random.normal(rng, (10, 3))}, {}

            def apply(self, params, state, input, *, training=False,
                      rng=None):
                ids, seg = input
                return jax.ops.segment_sum(params["emb"][ids], seg,
                                           num_segments=N), state

        class FakeDS(AbstractDataSet):
            def __init__(self, batches):
                self.batches = batches

            def data(self, train=False):
                return iter(self.batches)

            def size(self):
                return N * len(self.batches)

        rng = np.random.default_rng(18)
        model = StaticBag().initialize(0)
        batches, expect = [], []
        for nnz in (N, 4):  # first batch nnz == N: the coincidence
            ids = rng.integers(0, 10, nnz).astype(np.int32)
            seg = np.sort(rng.integers(0, N, nnz)).astype(np.int32)
            batches.append(MiniBatch((ids, seg)))
            out, _ = model.apply(model._params, model._state, (ids, seg),
                                 training=False)
            expect.append(np.asarray(out))
        got = Predictor(model).predict(FakeDS(batches))
        assert got.shape == (2 * N, 3), got.shape
        np.testing.assert_array_equal(got,
                                      np.concatenate(expect, axis=0))

    def test_empty_iterable_output_rank(self):
        model = make_model(din=4, dout=3)
        pred = Predictor(model, batch_size=4,
                         input_spec=((4,), np.float32))
        out = pred.predict([])
        assert out.shape == (0, 3) and out.dtype == np.float32
        # without a spec the legacy rank-less fallback survives
        assert Predictor(model, batch_size=4).predict([]).shape == (0,)


class TestPredictionServiceShim:
    def test_back_compat_surface(self):
        svc = PredictionService(make_model(), batch_size=4)
        out1 = svc.predict(np.ones((1, 16), np.float32))
        out9 = svc.predict(np.ones((9, 16), np.float32))
        assert out1.shape == (1, 4) and out9.shape == (9, 4)
        np.testing.assert_allclose(out9[0], out1[0], rtol=1e-6)
        assert svc.request_count == 2
        stats = svc.stats()
        assert stats["model"] == "PredictionService"
        assert stats["dispatch_count"] >= 1
        # the shim keeps its historical lone-caller latency: adaptive
        # mode, no coalescing-timeout tax on sequential predicts
        assert svc.service.batch_timeout_ms == 0.0
        svc.stop()

    def test_shim_accepts_list_of_lists(self):
        """Regression: the historical service np.asarray'd its input, so
        plain nested lists must keep working through the shim."""
        svc = PredictionService(make_model(din=4), batch_size=4)
        out = svc.predict([[1.0, 2.0, 3.0, 4.0],
                           [5.0, 6.0, 7.0, 8.0]])
        assert out.shape == (2, 4)
        svc.stop()

    def test_shim_coalesces_concurrent_callers(self):
        model = make_model()
        svc = PredictionService(model, batch_size=8,
                                batch_timeout_ms=1.0)
        rng = np.random.default_rng(4)
        xs = [rows(rng, 1) for _ in range(12)]
        errs = []

        def worker(x):
            try:
                out = svc.predict(x)
                direct, _ = model.apply(svc.params, svc.state, x,
                                        training=False)
                np.testing.assert_array_equal(out, np.asarray(direct))
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(x,)) for x in xs]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert svc.request_count == 12
        svc.stop()
