"""bigdl_tpu.frontend — wire-level serving front end tests.

The load-bearing gates (ISSUE 14 acceptance):

- **Wire E2E**: concurrent HTTP clients against a live
  ``FrontendServer`` get BITWISE-equal outputs to direct
  ``model.apply``, coalesced into shared dispatches (dispatch-count
  budget), with 429 + ``Retry-After`` on overload and deadline expiry
  surfaced as 504.
- **Zero-dropped cutover**: hot deploys under sustained wire load
  complete with every accepted request resolved correctly — no 5xx,
  no lost futures.
- **Autoscaler**: a load spike scales replicas up within the
  hysteresis/cooldown budget and back down when load subsides
  (deterministic fake-clock controller tests + a live ReplicaSet
  integration).
- **Inertness**: with no frontend constructed, training is
  bitwise-identical with equal dispatch counts and zero extra threads
  (K ∈ {1, 4}).

Event-driven staging throughout (``start=False`` services, barriers,
injected clocks); the only waits are bounded queue-depth settles on
genuinely asynchronous HTTP client threads.
"""

import http.client
import json
import logging
import math
import threading
import time
from io import BytesIO

import numpy as np
import pytest

import bigdl_tpu.frontend  # noqa: F401  (the inertness gate imports it)
from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.frontend import (BATCH, LATENCY, CutoverDrainTimeout,
                                FrontendServer, HotCutover, QosAdmission,
                                ReplicaAutoscaler, TenantRateLimited,
                                TenantSpec, TokenBucket,
                                UnknownTenantError)
from bigdl_tpu.resilience import ReplicaSet
from bigdl_tpu.serving import InferenceService, ModelRegistry
from bigdl_tpu.telemetry.context import RequestContext
from bigdl_tpu.telemetry.registry import MetricRegistry


def make_model(din=16, dout=4):
    return nn.Sequential(nn.Linear(din, 32), nn.ReLU(),
                         nn.Linear(32, dout), nn.SoftMax()).initialize(0)


SPEC16 = ((16,), np.float32)


def rows(rng, n, din=16):
    return rng.normal(0, 1, (n, din)).astype(np.float32)


def post(port, path, body, headers=None, timeout=60):
    """One POST via http.client → (status, headers dict, raw body)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def wait_until(pred, timeout=10.0, what="condition"):
    """Bounded settle on genuinely-async external state (HTTP client
    threads enqueueing)."""
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() >= deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.005)


# ===========================================================================
class TestTokenBucket:
    def test_refill_math_deterministic(self):
        t = [0.0]
        b = TokenBucket(rate=2.0, depth=4.0, clock=lambda: t[0])
        for _ in range(4):
            assert b.try_take() is None  # burst drains the bucket
        wait = b.try_take()
        assert wait == 500.0  # 1 token deficit at 2 tok/s = 500 ms
        t[0] = 0.25  # half a token refilled
        assert b.try_take() == 250.0
        t[0] = 0.75  # 1.5 tokens at refill rate 2
        assert b.try_take() is None
        assert b.tokens() == pytest.approx(0.5)

    def test_depth_caps_refill(self):
        t = [0.0]
        b = TokenBucket(rate=10.0, depth=3.0, clock=lambda: t[0])
        t[0] = 100.0
        assert b.tokens() == 3.0


class TestQosAdmission:
    def test_spec_validation(self):
        with pytest.raises(ValueError):
            TenantSpec("x", qos_class="bogus")
        with pytest.raises(ValueError):
            TenantSpec("x", burst=0)
        with pytest.raises(ValueError):
            QosAdmission([TenantSpec("a"), TenantSpec("a")])

    def test_rate_limit_sheds_with_retry_after(self):
        t = [0.0]
        qos = QosAdmission(
            [TenantSpec("acme", rate_rps=1.0, burst=1)],
            clock=lambda: t[0])
        assert qos.admit("acme").name == "acme"
        with pytest.raises(TenantRateLimited) as ei:
            qos.admit("acme")
        assert ei.value.retry_after_ms == 1000.0
        assert ei.value.tenant == "acme"
        t[0] = 1.0
        qos.admit("acme")  # bucket refilled
        snap = qos.registry.snapshot()["counters"]
        assert snap["serving/tenant=acme/requests"] == 2
        assert snap["serving/tenant=acme/shed"] == 1

    def test_undeclared_folds_into_other_and_shares_default_bucket(self):
        t = [0.0]
        qos = QosAdmission(
            [TenantSpec("vip")],
            default=TenantSpec("default", qos_class=BATCH,
                               rate_rps=1.0, burst=1),
            clock=lambda: t[0])
        qos.admit("rando-1")
        with pytest.raises(TenantRateLimited):
            qos.admit("rando-2")  # the SHARED default bucket is empty
        qos.admit("vip")  # declared + unlimited: untouched by default
        snap = qos.registry.snapshot()["counters"]
        assert snap["serving/tenant=_other/requests"] == 1
        assert snap["serving/tenant=_other/shed"] == 1
        assert snap["serving/tenant=vip/requests"] == 1

    def test_strict_refuses_undeclared_and_tenantless(self):
        qos = QosAdmission([TenantSpec("sekrit-vip")], strict=True)
        with pytest.raises(UnknownTenantError) as ei:
            qos.admit("b")
        # the 403 must not enumerate declared names — X-Tenant is a
        # tag, not a credential, so listing valid tags IS the bypass
        assert "sekrit-vip" not in str(ei.value)
        with pytest.raises(UnknownTenantError) as ei:
            # omitting the tenant must not bypass a strict gate
            qos.admit(None)
        assert "sekrit-vip" not in str(ei.value)
        qos.admit("sekrit-vip")

    def test_priority_ranks(self):
        qos = QosAdmission([TenantSpec("slo", qos_class=LATENCY),
                            TenantSpec("bulk", qos_class=BATCH)])

        class Req:
            def __init__(self, tenant):
                self.ctx = (RequestContext(tenant=tenant)
                            if tenant is not None else None)

        assert qos.priority_fn(Req("slo")) == 0
        assert qos.priority_fn(Req("bulk")) == 1
        assert qos.priority_fn(Req(None)) == 0  # default = latency
        assert qos.priority_fn(Req("unknown")) == 0

    def test_record_result_metrics(self):
        qos = QosAdmission([TenantSpec("a")])
        qos.record_result("a", 0.02, ok=True)
        qos.record_result("a", 0.03, ok=False)
        snap = qos.registry.snapshot()
        assert snap["counters"]["serving/tenant=a/failed"] == 1
        assert snap["histograms"]["serving/tenant=a/latency_s"][
            "count"] == 2


# ===========================================================================
class TestQosPreemption:
    """The batcher priority hook: latency tenants preempt batch
    backlog under pressure; FIFO otherwise."""

    def _staged(self, n_batch, n_latency, max_batch=4):
        qos = QosAdmission([TenantSpec("slo", qos_class=LATENCY),
                            TenantSpec("bulk", qos_class=BATCH)])
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=max_batch,
                               buckets="top", queue_capacity=64,
                               start=False,
                               priority_fn=qos.priority_fn)
        groups = []
        orig = svc._dispatch

        def spy(requests):
            groups.append([r.ctx.tenant if r.ctx else None
                           for r in requests])
            orig(requests)

        svc._batcher._dispatch_fn = spy
        rng = np.random.default_rng(0)
        futs = []
        # batch-tenant backlog first, then the latency arrivals
        for _ in range(n_batch):
            futs.append(svc.submit(rows(rng, 1),
                                   ctx=RequestContext(tenant="bulk")))
        for _ in range(n_latency):
            futs.append(svc.submit(rows(rng, 1),
                                   ctx=RequestContext(tenant="slo")))
        svc.start()
        for f in futs:
            f.result(timeout=30)
        svc.stop()
        return groups

    def test_latency_preempts_batch_under_pressure(self):
        # 6 bulk + 2 slo on a 4-row dispatch: pressure (8 > 4), so the
        # FIRST group carries both slo requests despite arriving last
        groups = self._staged(n_batch=6, n_latency=2)
        assert groups[0].count("slo") == 2, groups
        assert sum(g.count("slo") for g in groups) == 2
        assert sum(g.count("bulk") for g in groups) == 6

    def test_light_load_stays_fifo(self):
        # 2 bulk + 1 slo all fit one group: no pressure, FIFO order
        groups = self._staged(n_batch=2, n_latency=1)
        assert groups[0] == ["bulk", "bulk", "slo"]

    def test_aging_bounds_starvation(self):
        """A batch-class request that has waited one aging period
        competes as latency class — sustained latency pressure delays
        batch work, it cannot starve it."""
        qos = QosAdmission([TenantSpec("slo", qos_class=LATENCY),
                            TenantSpec("bulk", qos_class=BATCH)])
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=2, buckets="top",
                               queue_capacity=64, start=False,
                               priority_fn=qos.priority_fn)
        groups = []
        orig = svc._dispatch

        def spy(requests):
            groups.append([r.ctx.tenant for r in requests])
            orig(requests)

        svc._batcher._dispatch_fn = spy
        rng = np.random.default_rng(0)
        futs = [svc.submit(rows(rng, 1),
                           ctx=RequestContext(tenant="bulk"))
                for _ in range(3)]
        # bulk[0] has been queued for two aging periods (back-dated —
        # deterministic, no sleeping): effective rank -1 beats fresh
        # latency-class work
        with svc._batcher._cond:
            svc._batcher._q[0].t_enqueue -= 1.0
        futs += [svc.submit(rows(rng, 1),
                            ctx=RequestContext(tenant="slo"))
                 for _ in range(2)]
        svc.start()
        for f in futs:
            f.result(timeout=30)
        svc.stop()
        assert groups[0][0] == "bulk", groups  # the aged one leads


# ===========================================================================
@pytest.fixture(scope="class")
def wire():
    """A live frontend over a registry with one deployed model.
    Class-scoped (one AOT warmup + one server bill for the read-only
    E2E tests); tests that mutate routing state deploy later versions
    and run in definition order, or build their own stack."""
    model = make_model()
    reg = ModelRegistry()
    svc = reg.deploy("clf", model, input_spec=SPEC16, max_batch_size=8,
                     batch_timeout_ms=2.0, queue_capacity=256)
    fe = FrontendServer(reg, port=0)
    fe.start()
    yield fe, reg, svc, model
    fe.stop()
    reg.stop_all()


class TestWireE2E:
    def test_single_predict_bitwise_and_trace_echo(self, wire):
        fe, reg, svc, model = wire
        x = rows(np.random.default_rng(3), 2)
        status, hdrs, body = post(
            fe.port, "/v1/models/clf/predict",
            json.dumps({"inputs": x.tolist()}).encode(),
            headers={"X-Trace-Id": "cafe0000deadbeef",
                     "X-Tenant": "acme"})
        assert status == 200
        assert hdrs["X-Trace-Id"] == "cafe0000deadbeef"
        out = json.loads(body)
        assert out["version"] == 1 and out["trace_id"] == \
            "cafe0000deadbeef"
        ref, _ = model.apply(svc.params, svc.state, x, training=False)
        np.testing.assert_array_equal(
            np.asarray(out["outputs"], np.float32), np.asarray(ref))

    def test_zip_magic_npy_body_is_400_not_500(self, wire):
        # review regression: a body starting with zip magic routes
        # np.load through zipfile, whose BadZipFile is not in the
        # ValueError/OSError/EOFError family — it must still be the
        # client's 400, not a 500 + traceback any caller can flood
        fe, _reg, _svc, _model = wire
        status, _hdrs, body = post(
            fe.port, "/v1/models/clf/predict",
            b"PK\x03\x04garbage-not-a-real-zip",
            headers={"Content-Type": "application/x-npy"})
        assert status == 400
        assert "unreadable npy body" in json.loads(body)["error"]

    def test_concurrent_clients_bitwise_and_dispatch_budget(self):
        """THE acceptance gate: concurrent wire clients, bitwise
        outputs, coalesced into a bounded number of dispatches."""
        model = make_model()
        reg = ModelRegistry()
        svc = reg.deploy("clf", model, input_spec=SPEC16,
                         max_batch_size=8, queue_capacity=256,
                         start=False)  # parked: stage the whole load
        fe = FrontendServer(reg, port=0)
        fe.start()
        warm_compiles = svc.compile_count
        n = 12
        rng = np.random.default_rng(7)
        xs = [rows(rng, 1) for _ in range(n)]
        results = [None] * n

        def client(i):
            results[i] = post(
                fe.port, "/v1/models/clf/predict",
                json.dumps({"inputs": xs[i].tolist()}).encode())

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        # every wire request lands in the parked queue before the
        # first dispatch — deterministic coalescing
        wait_until(lambda: svc.queue_depth() == n,
                   what="wire load staged")
        svc.start()
        for t in threads:
            t.join()
        stats = svc.stats()
        fe.stop()
        reg.stop_all()
        for i in range(n):
            status, _h, body = results[i]
            assert status == 200
            got = np.asarray(json.loads(body)["outputs"], np.float32)
            ref, _ = model.apply(svc.params, svc.state, xs[i],
                                 training=False)
            np.testing.assert_array_equal(got, np.asarray(ref))
        budget = math.ceil(n / 8) + len(svc.buckets)
        assert stats["dispatch_count"] <= budget, stats
        assert stats["dispatch_count"] < n  # coalescing, not 1:1
        assert svc.compile_count == warm_compiles  # zero steady traces

    def test_streaming_chunked_multi_predict_bitwise(self, wire):
        fe, reg, svc, model = wire
        xs = rows(np.random.default_rng(5), 20)  # 20 > max_batch 8
        chunks_before = fe.metrics.counter(
            "frontend/stream_chunks").value
        status, hdrs, body = post(
            fe.port, "/v1/models/clf/predict",
            json.dumps({"inputs": xs.tolist()}).encode())
        assert status == 200
        assert hdrs["Content-Type"] == "application/x-ndjson"
        lines = [json.loads(ln) for ln in body.splitlines()]
        assert lines[-1]["done"] is True and lines[-1]["rows"] == 20
        chunks = lines[:-1]
        assert len(chunks) == math.ceil(20 / 8)
        assert [c["offset"] for c in chunks] == [0, 8, 16]  # in order
        got = np.concatenate(
            [np.asarray(c["outputs"], np.float32) for c in chunks])
        ref, _ = model.apply(svc.params, svc.state, xs, training=False)
        np.testing.assert_array_equal(got, np.asarray(ref))
        assert fe.metrics.counter(
            "frontend/stream_chunks").value - chunks_before == 3

    def test_streaming_prefail_gets_real_status_not_200(self):
        """A multi-chunk predict that fails BEFORE its first chunk
        result must answer with the real status code (here 504) — the
        200 chunked header is committed only by the first result."""
        reg = ModelRegistry()
        reg.deploy("bulk", make_model(), input_spec=SPEC16,
                   max_batch_size=4, buckets="top", start=False)
        fe = FrontendServer(reg, port=0)
        fe.start()
        xs = rows(np.random.default_rng(0), 10)  # 10 > 4 → stream path
        status, _h, body = post(
            fe.port, "/v1/models/bulk/predict",
            json.dumps({"inputs": xs.tolist()}).encode(),
            headers={"X-Deadline-Ms": "80"})
        assert status == 504, body
        fe.stop()
        reg.stop_all()

    def test_streaming_overload_midstream_flushes_and_completes(self):
        """Regression (REVIEW): a streaming predict that hits
        ServiceOverloaded with chunks in flight must flush the oldest
        chunk (committing the 200 chunked header) and keep going — the
        backpressure path used to call ``_flush_one`` with the
        ``ensure_started`` argument missing and crash with TypeError."""
        from bigdl_tpu.serving import ServiceOverloaded
        model = make_model()
        reg = ModelRegistry()
        svc = reg.deploy("narrow", model, input_spec=SPEC16,
                         max_batch_size=2, queue_capacity=2,
                         buckets="top", start=False)
        # parked + a filler occupying one of the two queue slots: the
        # stream's chunk 1 fills the queue, so chunk 2's submit sheds
        # while chunk 1 is still in flight — the exact branch under test
        rng = np.random.default_rng(11)
        f_fill = svc.submit(rows(rng, 1))
        overloads = []
        orig_submit = svc.submit

        def counting_submit(x, **kw):
            try:
                return orig_submit(x, **kw)
            except ServiceOverloaded:
                overloads.append(1)
                raise

        svc.submit = counting_submit
        fe = FrontendServer(reg, port=0)
        fe.start()
        xs = rows(rng, 8)  # 4 chunks of 2 > max_batch → stream path
        result = {}

        def client():
            result["r"] = post(
                fe.port, "/v1/models/narrow/predict",
                json.dumps({"inputs": xs.tolist()}).encode())

        t = threading.Thread(target=client)
        t.start()
        # the handler thread has provably entered the shed-with-
        # inflight branch before the service is allowed to drain
        wait_until(lambda: overloads, what="mid-stream overload")
        svc.start()
        t.join(timeout=60)
        assert not t.is_alive()
        f_fill.result(30)
        status, hdrs, body = result["r"]
        fe.stop()
        reg.stop_all()
        assert status == 200, body
        assert hdrs["Content-Type"] == "application/x-ndjson"
        lines = [json.loads(ln) for ln in body.splitlines()]
        assert lines[-1]["done"] is True and lines[-1]["rows"] == 8
        chunks = sorted(lines[:-1], key=lambda c: c["offset"])
        assert [c["offset"] for c in chunks] == [0, 2, 4, 6]
        got = np.concatenate(
            [np.asarray(c["outputs"], np.float32) for c in chunks])
        ref, _ = model.apply(svc.params, svc.state, xs, training=False)
        np.testing.assert_array_equal(got, np.asarray(ref))

    def test_internal_fault_is_500_and_releases_pin(self):
        """Regression (REVIEW): a server-side fault inside the pinned
        window (here: a backend with no ``max_batch_size``) must report
        500 — NOT masquerade as a client 400 — and must release the
        wire-inflight pin so ``drain_version``/HotCutover never wedge
        on a request that crashed."""

        class NoBatchBackend:  # max_batch_size lookup raises
            pass

        fe = FrontendServer(backends={"broken": NoBatchBackend()},
                            port=0)
        fe.start()
        x = json.dumps({"inputs": rows(np.random.default_rng(0),
                                       1).tolist()}).encode()
        status, _h, body = post(fe.port, "/v1/models/broken/predict", x)
        assert status == 500, body
        assert fe.inflight.count(("broken", 0)) == 0
        assert fe.drain_version("broken", 0, timeout=0.5)
        fe.stop()

    def test_classify_unexpected_errors_are_500(self):
        """Internal ValueError/TypeError are server bugs (500, logged
        with traceback) — only _HTTPError-wrapped parse/validation
        failures earn a 400."""
        assert FrontendServer._classify(TypeError("bug"))[0] == 500
        assert FrontendServer._classify(ValueError("bug"))[0] == 500

    def test_backend_valueerror_is_500_unless_spec_error(self):
        """Only the backend's RequestSpecError (spec validation — the
        client's fault) maps to 400; any other synchronous ValueError
        from submit (e.g. a deferred-spec warmup compile failure) is a
        server-side 500."""
        from bigdl_tpu.serving import RequestSpecError

        class Raising:
            max_batch_size = 8

            def __init__(self, exc):
                self.exc = exc

            def submit(self, x, **kw):
                raise self.exc

        fe = FrontendServer(backends={
            "buggy": Raising(ValueError("trace failed inside warmup")),
            "picky": Raising(RequestSpecError("row shape mismatch"))},
            port=0)
        fe.start()
        x = json.dumps({"inputs": rows(np.random.default_rng(0),
                                       1).tolist()}).encode()
        s_bug, _h, body = post(fe.port, "/v1/models/buggy/predict", x)
        s_spec, _h2, _b2 = post(fe.port, "/v1/models/picky/predict", x)
        fe.stop()
        assert s_bug == 500, body
        assert s_spec == 400

    def test_midstream_internal_fault_logs_and_error_line(self, caplog):
        """An internal bug AFTER the 200 chunked header is committed
        must leave a server-side traceback (same contract as the
        single-request 5xx path) and terminate the stream with an
        error line carrying status 500."""

        class HalfBad:
            max_batch_size = 2

            def __init__(self):
                self.calls = 0

            def submit(self, x, **kw):
                from concurrent.futures import Future
                self.calls += 1
                f = Future()
                # chunk 1 is fine (commits the header); chunk 2
                # resolves with an output json.dumps refuses
                f.set_result(np.zeros((2, 1), np.float32)
                             if self.calls == 1 else {"bad": set()})
                return f

        fe = FrontendServer(backends={"half": HalfBad()}, port=0)
        fe.start()
        xs = rows(np.random.default_rng(0), 4)  # 2 chunks of 2
        with caplog.at_level(logging.ERROR, "bigdl_tpu.frontend"):
            status, _h, body = post(
                fe.port, "/v1/models/half/predict",
                json.dumps({"inputs": xs.tolist()}).encode())
        fe.stop()
        assert status == 200  # header was committed by chunk 1
        lines = [json.loads(ln) for ln in body.splitlines()]
        assert lines[-1]["status"] == 500
        assert lines[-1]["rows_streamed"] == 2
        assert any("mid-stream" in r.getMessage()
                   for r in caplog.records)

    def test_midstream_client_disconnect_not_counted_5xx(self):
        """A client hanging up mid-stream is THEIR outcome: it lands in
        frontend/client_disconnects, never responses_5xx (which would
        corrupt the 5xx SLO signal on every reset)."""
        closed = threading.Event()

        class SlowTail:
            max_batch_size = 2

            def __init__(self):
                self.calls = 0

            def submit(self, x, **kw):
                from concurrent.futures import Future
                self.calls += 1
                f = Future()
                if self.calls == 1:
                    f.set_result(np.zeros((2, 1), np.float32))
                else:
                    # chunks 2+ resolve only after the client has hung
                    # up, so the stream writes provably race an RST
                    def settle():
                        closed.wait(30)
                        time.sleep(0.05)  # let the RST land
                        try:  # stream cancels stragglers on hang-up
                            f.set_result(np.zeros((2, 1), np.float32))
                        except Exception:
                            pass  # cancelled first — expected
                    threading.Thread(target=settle,
                                     daemon=True).start()
                return f

        fe = FrontendServer(backends={"s": SlowTail()}, port=0)
        fe.start()
        xs = rows(np.random.default_rng(0), 8)  # 4 chunks of 2
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=30)
        conn.request("POST", "/v1/models/s/predict",
                     body=json.dumps({"inputs": xs.tolist()}).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200  # chunk 1 committed the header
        resp.read(10)
        conn.close()  # hang up with 3 chunks still to stream
        closed.set()
        wait_until(lambda: fe.metrics.counter(
            "frontend/client_disconnects").value == 1,
            what="disconnect counted")
        assert fe.metrics.counter("frontend/responses_5xx").value == 0
        fe.stop()

    def test_npy_body_and_npy_accept(self, wire):
        fe, reg, svc, model = wire
        x = rows(np.random.default_rng(9), 3)
        buf = BytesIO()
        np.save(buf, x)
        status, hdrs, body = post(
            fe.port, "/v1/models/clf/predict", buf.getvalue(),
            headers={"Content-Type": "application/x-npy",
                     "Accept": "application/x-npy"})
        assert status == 200 and hdrs["Content-Type"] == \
            "application/x-npy"
        ref, _ = model.apply(svc.params, svc.state, x, training=False)
        np.testing.assert_array_equal(np.load(BytesIO(body)),
                                      np.asarray(ref))

    def test_deadline_header_maps_to_504(self):
        reg = ModelRegistry()
        reg.deploy("slow", make_model(), input_spec=SPEC16,
                   max_batch_size=8, buckets="top", start=False)  # parked: never serves
        fe = FrontendServer(reg, port=0)
        fe.start()
        x = rows(np.random.default_rng(0), 1)
        t0 = time.monotonic()
        status, _h, body = post(
            fe.port, "/v1/models/slow/predict",
            json.dumps({"inputs": x.tolist()}).encode(),
            headers={"X-Deadline-Ms": "80"})
        assert status == 504, body
        assert time.monotonic() - t0 < 5.0  # expired at the deadline
        assert fe.metrics.counter("frontend/deadline_504").value == 1
        fe.stop()
        reg.stop_all()

    def test_overload_maps_to_429_with_retry_after(self):
        reg = ModelRegistry()
        svc = reg.deploy("tiny", make_model(), input_spec=SPEC16,
                         max_batch_size=2, queue_capacity=2,
                         start=False)
        # seed the drain-rate EWMA so the shed carries a retry hint
        # (white-box: the rate normally comes from the first dispatch)
        svc._batcher._note_dispatch(1, 0.05)
        fe = FrontendServer(reg, port=0)
        fe.start()
        rng = np.random.default_rng(0)
        f1 = svc.submit(rows(rng, 1))
        f2 = svc.submit(rows(rng, 1))  # queue (capacity 2) now full
        status, hdrs, body = post(
            fe.port, "/v1/models/tiny/predict",
            json.dumps({"inputs": rows(rng, 1).tolist()}).encode())
        assert status == 429
        assert int(hdrs["Retry-After"]) >= 1
        assert float(hdrs["X-Retry-After-Ms"]) > 0
        assert json.loads(body)["retry_after_ms"] is not None
        assert fe.metrics.counter("frontend/sheds").value == 1
        svc.start()
        f1.result(30), f2.result(30)
        fe.stop()
        reg.stop_all()

    def test_tenant_rate_limit_maps_to_429(self):
        t = [0.0]
        qos = QosAdmission(
            [TenantSpec("metered", rate_rps=1.0, burst=1)],
            clock=lambda: t[0])
        reg = ModelRegistry()
        reg.deploy("clf", make_model(), input_spec=SPEC16,
                   max_batch_size=8, buckets="top")
        fe = FrontendServer(reg, qos=qos, port=0)
        fe.start()
        x = json.dumps({"inputs": rows(np.random.default_rng(0),
                                       1).tolist()}).encode()
        s1, _h, _b = post(fe.port, "/v1/models/clf/predict", x,
                          headers={"X-Tenant": "metered"})
        s2, hdrs, body = post(fe.port, "/v1/models/clf/predict", x,
                              headers={"X-Tenant": "metered"})
        assert (s1, s2) == (200, 429)
        assert "Retry-After" in hdrs
        snap = fe.metrics.snapshot()["counters"]
        assert snap["serving/tenant=metered/shed"] == 1
        fe.stop()
        reg.stop_all()

    def test_strict_unknown_tenant_403(self):
        qos = QosAdmission([TenantSpec("a")], strict=True)
        reg = ModelRegistry()
        reg.deploy("clf", make_model(), input_spec=SPEC16,
                   buckets="top")
        fe = FrontendServer(reg, qos=qos, port=0)
        fe.start()
        x = json.dumps({"inputs": rows(np.random.default_rng(0),
                                       1).tolist()}).encode()
        status, _h, _b = post(fe.port, "/v1/models/clf/predict", x,
                              headers={"X-Tenant": "nobody"})
        assert status == 403
        # no X-Tenant at all is refused the same way under strict
        status, _h, _b = post(fe.port, "/v1/models/clf/predict", x)
        assert status == 403
        status, _h, _b = post(fe.port, "/v1/models/clf/predict", x,
                              headers={"X-Tenant": "a"})
        assert status == 200
        fe.stop()
        reg.stop_all()

    def test_error_statuses(self, wire):
        fe, reg, svc, model = wire
        x = json.dumps({"inputs": rows(np.random.default_rng(0),
                                       1).tolist()}).encode()
        assert post(fe.port, "/v1/models/nope/predict", x)[0] == 404
        assert post(fe.port, "/v1/models/clf:9/predict", x)[0] == 404
        assert post(fe.port, "/v1/models/clf/predict",
                    b"not json")[0] == 400
        assert post(fe.port, "/v1/models/clf/predict",
                    json.dumps({"nope": 1}).encode())[0] == 400
        # wrong row shape fails THAT request with 400
        bad = json.dumps({"inputs": [[1.0, 2.0]]}).encode()
        assert post(fe.port, "/v1/models/clf/predict", bad)[0] == 400
        # ragged rows np.asarray refuses are the client's fault too
        ragged = json.dumps({"inputs": [[1.0], [1.0, 2.0]]}).encode()
        assert post(fe.port, "/v1/models/clf/predict", ragged)[0] == 400
        # dict leaves disagreeing on the leading batch dim → 400
        mism = json.dumps({"inputs": {"a": [[1.0]],
                                      "b": [[1.0], [2.0]]}}).encode()
        assert post(fe.port, "/v1/models/clf/predict", mism)[0] == 400
        # string data the spec dtype coercion refuses → 400
        strs = json.dumps({"inputs": [["x"] * 16]}).encode()
        assert post(fe.port, "/v1/models/clf/predict", strs)[0] == 400
        status, _h, body = post(fe.port, "/v1/models/bad/predict", x)
        assert status == 404 and "error" in json.loads(body)

    def test_version_pinning_and_models_listing(self, wire):
        fe, reg, svc, model = wire
        reg.deploy("clf", model, input_spec=SPEC16, max_batch_size=8)
        x = rows(np.random.default_rng(1), 1)
        body = json.dumps({"inputs": x.tolist()}).encode()
        _s, _h, b = post(fe.port, "/v1/models/clf:1/predict", body)
        assert json.loads(b)["version"] == 1  # pinned beats latest
        _s, _h, b = post(fe.port, "/v1/models/clf/predict", body)
        assert json.loads(b)["version"] == 2  # latest-wins
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=30)
        conn.request("GET", "/v1/models")
        resp = conn.getresponse()
        assert json.loads(resp.read())["models"]["clf"] == [1, 2]
        conn.close()

    def test_replica_set_backend_over_the_wire(self):
        model = make_model()
        rs = ReplicaSet(model, n_replicas=2, input_spec=SPEC16,
                        max_batch_size=8, buckets="top", name="rs")
        fe = FrontendServer(backends={"rs": rs}, port=0)
        fe.start()
        x = rows(np.random.default_rng(2), 2)
        status, _h, body = post(
            fe.port, "/v1/models/rs/predict",
            json.dumps({"inputs": x.tolist()}).encode())
        assert status == 200
        ref = np.asarray(rs.predict(x, timeout=30))
        np.testing.assert_array_equal(
            np.asarray(json.loads(body)["outputs"], np.float32), ref)
        fe.stop()
        rs.stop()


# ===========================================================================
class TestHotCutover:
    def test_zero_dropped_requests_through_three_deploys(self):
        """THE cutover acceptance gate: sustained wire load while 3 hot
        deploys run — every request 200 and BITWISE-correct, none
        dropped.  Every version serves identical params, but a live
        request coalesces into whichever row bucket the moment offers
        and bucket executables legally differ from eager ``apply`` by
        fusion order — so the bitwise reference is the set of JITTED
        per-bucket forwards (pad + slice, the engine's own padding
        invariant), one per bucket size.  A wrong version, wrong row,
        or torn response cannot match any of them."""
        import jax

        from bigdl_tpu.serving import pad_rows

        model = make_model()
        reg = ModelRegistry()
        svc = reg.deploy("hot", model, input_spec=SPEC16,
                         max_batch_size=8, queue_capacity=1024)
        fe = FrontendServer(reg, port=0)
        fe.start()
        n_threads, per_thread = 4, 40
        rng = np.random.default_rng(11)
        xs = [rows(rng, 1) for _ in range(n_threads)]
        jfwd = jax.jit(
            lambda p, s, xx: model.apply(p, s, xx, training=False)[0])
        refs = [[np.asarray(jfwd(svc.params, svc.state,
                                 pad_rows(x, b)))[:1]
                 for b in svc.buckets]
                for x in xs]
        bad = []
        barrier = threading.Barrier(n_threads + 1)

        def client(t):
            conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                              timeout=60)
            body = json.dumps({"inputs": xs[t].tolist()}).encode()
            barrier.wait()
            try:
                for i in range(per_thread):
                    conn.request("POST", "/v1/models/hot/predict",
                                 body=body,
                                 headers={"Content-Type":
                                          "application/json"})
                    resp = conn.getresponse()
                    payload = resp.read()
                    if resp.status != 200:
                        bad.append((t, i, resp.status,
                                    payload[:120]))
                        continue
                    got = np.asarray(
                        json.loads(payload)["outputs"], np.float32)
                    if not any(np.array_equal(got, r)
                               for r in refs[t]):
                        bad.append((t, i, "wrong output"))
            except Exception as e:
                bad.append((t, f"{type(e).__name__}: {e}"))
            finally:
                conn.close()

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(n_threads)]
        for th in threads:
            th.start()
        barrier.wait()
        cut = HotCutover(reg, fe)
        reports = [cut.deploy("hot", model, max_batch_size=8,
                              queue_capacity=1024)
                   for _ in range(3)]
        for th in threads:
            th.join()
        fe.stop()
        reg.stop_all()
        assert bad == []  # zero dropped, zero wrong — the guarantee
        assert [r["new_version"] for r in reports] == [2, 3, 4]
        assert all(r["old_undeployed"] for r in reports)
        assert all(r["wire_drained"] for r in reports)

    def test_first_deploy_and_spec_reuse(self):
        reg = ModelRegistry()
        cut = HotCutover(reg)
        rep = cut.deploy("fresh", make_model(), input_spec=SPEC16,
                         max_batch_size=4, buckets="top")
        assert rep["old_version"] is None and rep["new_version"] == 1
        # second deploy: no input_spec passed — the incumbent's warmed
        # row spec is reused, so v2 is AOT-warm before routing flips
        rep2 = cut.deploy("fresh", make_model(), max_batch_size=4,
                          buckets="top")
        assert reg.get("fresh", 2).warmed_up
        assert rep2["old_undeployed"]
        reg.stop_all()

    def test_drain_timeout_keeps_old_version(self):
        model = make_model()
        reg = ModelRegistry()
        reg.deploy("held", model, input_spec=SPEC16, max_batch_size=8,
                   buckets="top")
        fe = FrontendServer(reg, port=0)
        fe.start()
        # hold a wire exchange pinned to v1 (simulating a long
        # streaming predict) without real HTTP plumbing
        fe.inflight.enter(("held", 1))
        cut = HotCutover(reg, fe, drain_timeout_s=0.2)
        with pytest.raises(CutoverDrainTimeout):
            cut.deploy("held", model, max_batch_size=8, buckets="top")
        # the old version must still serve its straggler
        assert 1 in reg.list_models()["held"]
        fe.inflight.exit(("held", 1))
        assert fe.drain_version("held", 1, timeout=1.0)
        fe.stop()
        reg.stop_all()


# ===========================================================================
class _FakeReplica:
    def __init__(self, max_batch=8):
        self.depth = 0
        self.ewma = None
        self.max_batch_size = max_batch

    def queue_depth(self):
        return self.depth

    @property
    def drain_ewma_s(self):
        return self.ewma


class _FakeRS:
    """Signal-level ReplicaSet stand-in: the controller tests drive
    load deterministically without any serving machinery."""

    name = "fake"

    def __init__(self, n=2):
        self.registry = MetricRegistry()
        self._reps = [_FakeReplica() for _ in range(n)]
        self.scale_calls = []

    @property
    def n_replicas(self):
        return len(self._reps)

    def active_indices(self):
        return list(range(len(self._reps)))

    def replica(self, i):
        return self._reps[i]

    def set_replica_count(self, n, timeout=None):
        self.scale_calls.append(n)
        while len(self._reps) < n:
            self._reps.append(_FakeReplica())
        del self._reps[n:]


class TestAutoscaler:
    def _scaler(self, rs, **kw):
        t = [0.0]
        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 4)
        kw.setdefault("up_consecutive", 2)
        kw.setdefault("down_consecutive", 3)
        kw.setdefault("cooldown_s", 2.0)
        kw.setdefault("horizon_s", 1.0)
        return ReplicaAutoscaler(rs, clock=lambda: t[0], **kw), t

    def test_load_signal_ewma_and_fallback(self):
        rs = _FakeRS(2)
        asc, _t = self._scaler(rs)
        assert asc.load() == 0.0
        rs._reps[0].depth = 4  # no ewma yet: 4 queued / max_batch 8
        assert asc.load() == pytest.approx((4 / 8) / 2)
        rs._reps[0].ewma = 0.5  # 4 * 0.5s = 2s backlog vs 1s horizon
        assert asc.load() == pytest.approx((1.0 + 0.0) / 2)

    def test_spike_scales_up_with_hysteresis_and_cooldown(self):
        rs = _FakeRS(1)
        asc, t = self._scaler(rs)
        rs._reps[0].depth = 64  # saturated
        d = asc.step(now=t[0])
        assert d["action"] is None  # hysteresis: 1 of 2 samples
        t[0] += 0.25
        d = asc.step(now=t[0])
        assert d["action"] == "up" and rs.n_replicas == 2
        # still saturated, but inside the cooldown: no action
        for r in rs._reps:
            r.depth = 64
        t[0] += 0.25
        assert asc.step(now=t[0])["action"] is None
        # the in-cooldown hot sample still counted toward hysteresis;
        # once the cooldown lapses the next hot sample completes the
        # pair and fires
        t[0] += 2.5
        d = asc.step(now=t[0])
        assert d["action"] == "up" and rs.n_replicas == 3
        snap = rs.registry.snapshot()
        assert snap["counters"]["frontend/autoscale_up"] == 2
        assert snap["gauges"]["frontend/replicas"] == 3

    def test_idle_scales_down_to_min(self):
        rs = _FakeRS(3)
        asc, t = self._scaler(rs)
        for _ in range(20):
            t[0] += 1.0
            asc.step(now=t[0])
        assert rs.n_replicas == 1  # floor holds
        assert rs.registry.snapshot()["counters"][
            "frontend/autoscale_down"] == 2

    def test_max_bound_holds(self):
        rs = _FakeRS(4)
        asc, t = self._scaler(rs)
        for r in rs._reps:
            r.depth = 64
        for _ in range(10):
            t[0] += 3.0
            asc.step(now=t[0])
        assert rs.n_replicas == 4 and rs.scale_calls == []

    def test_bad_knobs_refused(self):
        rs = _FakeRS(1)
        with pytest.raises(ValueError):
            ReplicaAutoscaler(rs, min_replicas=0)
        with pytest.raises(ValueError):
            ReplicaAutoscaler(rs, min_replicas=2, max_replicas=1)
        with pytest.raises(ValueError):
            ReplicaAutoscaler(rs, high_watermark=0.2, low_watermark=0.5)

    def test_live_replica_set_spike_up_then_down(self):
        """Integration: a staged queue spike on a REAL ReplicaSet grows
        it (warmed replica), drain + idle shrinks it back."""
        rs = ReplicaSet(make_model(), n_replicas=1, input_spec=SPEC16,
                        max_batch_size=4, buckets="top",
                        queue_capacity=64, name="asc", start=False)
        t = [0.0]
        asc = ReplicaAutoscaler(
            rs, min_replicas=1, max_replicas=3, up_consecutive=2,
            down_consecutive=2, cooldown_s=1.0, horizon_s=1.0,
            clock=lambda: t[0])
        rng = np.random.default_rng(0)
        futs = [rs.submit(rows(rng, 1), timeout=60) for _ in range(12)]
        asc.step(now=t[0])
        t[0] += 0.5
        d = asc.step(now=t[0])
        assert d["action"] == "up" and rs.n_replicas == 2
        assert rs.replica(1).warmed_up  # grew warm, off the route path
        rs.start()  # drain the spike
        for f in futs:
            f.result(timeout=60)
        t[0] += 2.0
        asc.step(now=t[0])
        t[0] += 0.5
        d = asc.step(now=t[0])
        assert d["action"] == "down" and rs.n_replicas == 1
        snap = rs.registry.snapshot()["counters"]
        assert snap["resilience/replica_deaths"] == 0
        rs.stop()

    def test_sampling_thread_lifecycle(self):
        rs = _FakeRS(1)
        asc = ReplicaAutoscaler(rs, interval_s=0.01)
        asc.start()
        assert asc._thread.is_alive()
        asc.stop()
        assert asc._thread is None


# ===========================================================================
class TestObsReportTenant:
    META = {"schema": 1, "pid": 1, "unix_ns": 0, "perf_ns": 0}

    def _trace(self):
        # two wire requests, one per tenant, sharing one dispatch
        return {"traceEvents": [
            {"ph": "X", "cat": "serving", "name": "wire_request",
             "ts": 1000.0, "dur": 500.0,
             "args": {"trace_id": "aa01", "tenant": "acme"}},
            {"ph": "X", "cat": "serving", "name": "wire_request",
             "ts": 1100.0, "dur": 400.0,
             "args": {"trace_id": "bb02", "tenant": "globex"}},
            {"ph": "X", "cat": "serving", "name": "dispatch",
             "ts": 1200.0, "dur": 100.0,
             "args": {"trace_ids": ["aa01", "bb02"]}},
        ]}

    def _flight(self):
        return {"meta": self.META, "events": [
            {"event": "failover", "cat": "resilience",
             "t_unix": 2e-3, "trace_id": "aa01", "replica": 0}]}

    def test_tenant_filter_keeps_only_that_tenants_stories(self):
        from tools.obs_report import summarize
        rep = summarize(self._flight(), trace=self._trace(),
                        tenant="acme")
        tids = {r["trace_id"] for r in rep["requests"]}
        assert tids == {"aa01"}
        # the tenant's rows INCLUDE the flight failover and the shared
        # dispatch fan-in row
        names = [r["name"] for r in rep["timeline"]]
        assert "failover" in names and "dispatch" in names
        assert all(r.get("trace_id") == "aa01"
                   for r in rep["timeline"])

    def test_unknown_tenant_yields_empty_report(self):
        from tools.obs_report import summarize
        rep = summarize(self._flight(), trace=self._trace(),
                        tenant="nobody")
        assert rep["n_requests"] == 0 and rep["timeline"] == []

    def test_cli_tenant_flag(self, tmp_path, capsys):
        from tools.obs_report import main
        fl = tmp_path / "flight.jsonl"
        with open(fl, "w") as f:
            f.write(json.dumps({"meta": self.META}) + "\n")
            for e in self._flight()["events"]:
                f.write(json.dumps(e) + "\n")
        tr = tmp_path / "trace.json"
        tr.write_text(json.dumps(self._trace()))
        rc = main([str(fl), "--trace", str(tr), "--tenant", "acme",
                   "--json"])
        assert rc == 0
        rep = json.loads(capsys.readouterr().out)
        assert {r["trace_id"] for r in rep["requests"]} == {"aa01"}


# ===========================================================================
class RecordingSummary:
    def __init__(self):
        self.losses = []

    def add_train_step(self, step, loss, *rest, **kw):
        self.losses.append(float(loss))

    def add_scalar(self, *a, **k):
        pass

    def flush(self):
        pass


def tiny_train(iters=6, k=1):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(0, 1, (16,)).astype(np.float32),
                      np.int32(rng.integers(0, 4)))
               for _ in range(64)]
    model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(),
                          nn.Linear(16, 4), nn.LogSoftMax())
    rec = RecordingSummary()
    opt = (optim.LocalOptimizer(model,
                                DataSet.array(samples)
                                >> SampleToMiniBatch(16),
                                nn.ClassNLLCriterion())
           .set_optim_method(optim.SGD(learning_rate=0.1))
           .set_seed(7)
           .set_train_summary(rec)
           .set_steps_per_dispatch(k)
           .set_end_when(optim.max_iteration(iters)))
    opt.optimize()
    return np.asarray(rec.losses), opt


class TestFrontendInertness:
    """ISSUE 14's standing-discipline gate: the frontend package being
    importable (it is imported at this module's top) changes NOTHING
    unless a server is explicitly constructed."""

    def test_config_default_off(self):
        from bigdl_tpu.utils.config import Config
        assert Config().frontend_port == 0
        with pytest.raises(ValueError):
            FrontendServer(port=None)  # config-driven refuses at 0

    @pytest.mark.parametrize("k", [1, 4])
    def test_training_bitwise_and_thread_free(self, k):
        before = {t.name for t in threading.enumerate()}
        a_l, a_o = tiny_train(iters=6, k=k)
        # constructing pure-QoS objects (no server) must stay inert too
        QosAdmission([TenantSpec("t", rate_rps=5.0)]).admit("t")
        b_l, b_o = tiny_train(iters=6, k=k)
        np.testing.assert_array_equal(a_l, b_l)
        assert a_o._dispatch_count == b_o._dispatch_count
        after = {t.name for t in threading.enumerate()}
        assert "bigdl-tpu-frontend" not in after
        assert after - before == set()  # zero extra threads

    def test_no_server_thread_until_start(self):
        reg = ModelRegistry()
        fe = FrontendServer(reg, port=0)
        names = {t.name for t in threading.enumerate()}
        assert "bigdl-tpu-frontend" not in names  # constructed ≠ bound
        fe.start()
        assert fe.running
        fe.stop()
        assert not fe.running
        reg.stop_all()


# ===========================================================================
@pytest.fixture(scope="class")
def auth_wire():
    """A live frontend with bearer-token auth over one direct
    backend."""
    model = make_model()
    svc = InferenceService(model, input_spec=SPEC16, max_batch_size=8,
                           batch_timeout_ms=0.0, buckets="top",
                           name="authed")
    fe = FrontendServer(backends={"clf": svc}, port=0,
                        auth_token="s3cret-tok")
    fe.start()
    yield fe, svc, model
    fe.stop()
    svc.stop()


class TestWireAuth:
    """ISSUE-15 satellite (ROADMAP item 1's wire-auth gap): a
    non-loopback bind requires a bearer token, and a configured token
    is enforced on every route before the body is read.  X-Tenant
    stays a QoS tag, never a credential; loopback-without-token keeps
    the historical open behavior (every other class in this file)."""

    def test_non_loopback_bind_refused_without_token(self):
        with pytest.raises(ValueError, match="non-loopback"):
            FrontendServer(port=0, host="0.0.0.0")
        # refusal happens at CONSTRUCTION: no socket, no thread
        names = {t.name for t in threading.enumerate()}
        assert "bigdl-tpu-frontend" not in names

    def test_non_loopback_allowed_with_token(self):
        fe = FrontendServer(port=0, host="0.0.0.0",
                            auth_token="deadbeef")
        assert fe._auth_token == "deadbeef"
        assert not fe.running  # constructed, never started

    def test_config_token_resolution(self):
        from bigdl_tpu.utils.config import configure, reset_config
        configure(frontend_auth_token="cfg-tok")
        try:
            fe = FrontendServer(port=0, host="0.0.0.0")  # no raise
            assert fe._auth_token == "cfg-tok"
        finally:
            reset_config()

    def test_missing_token_is_401_before_body_read(self, auth_wire):
        fe, svc, model = auth_wire
        x = rows(np.random.default_rng(0), 2)
        status, hdrs, body = post(
            fe.port, "/v1/models/clf/predict",
            json.dumps({"inputs": x.tolist()}).encode())
        assert status == 401
        assert hdrs["WWW-Authenticate"] == "Bearer"
        assert "bearer" in json.loads(body)["error"]
        # the refusal never reached admission or the backend queue
        assert svc.stats()["requests_submitted"] == 0

    def test_wrong_and_malformed_tokens_are_401(self, auth_wire):
        fe, _svc, _model = auth_wire
        x = rows(np.random.default_rng(1), 1)
        body = json.dumps({"inputs": x.tolist()}).encode()
        for hdr in ({"Authorization": "Bearer wrong"},
                    {"Authorization": "s3cret-tok"},      # no scheme
                    {"Authorization": "Basic s3cret-tok"},
                    {"X-Tenant": "acme"}):                # tag ≠ cred
            status, _h, _b = post(fe.port,
                                  "/v1/models/clf/predict", body,
                                  headers=hdr)
            assert status == 401, hdr

    def test_correct_token_serves_bitwise(self, auth_wire):
        fe, svc, model = auth_wire
        x = rows(np.random.default_rng(2), 3)
        status, _hdrs, body = post(
            fe.port, "/v1/models/clf/predict",
            json.dumps({"inputs": x.tolist()}).encode(),
            headers={"Authorization": "Bearer s3cret-tok"})
        assert status == 200
        ref, _ = model.apply(svc.params, svc.state, x, training=False)
        np.testing.assert_array_equal(
            np.asarray(json.loads(body)["outputs"], np.float32),
            np.asarray(ref))

    def test_get_routes_enforced_too(self, auth_wire):
        fe, _svc, _model = auth_wire
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=30)
        try:
            conn.request("GET", "/v1/models")
            assert conn.getresponse().status == 401
        finally:
            conn.close()
        conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                          timeout=30)
        try:
            conn.request("GET", "/v1/models",
                         headers={"Authorization": "Bearer s3cret-tok"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert "clf" in json.loads(resp.read())["models"]
        finally:
            conn.close()

    def test_401s_counted_as_4xx_not_sheds(self, auth_wire):
        fe, _svc, _model = auth_wire
        scalars = fe.metrics.scalars()
        assert scalars["frontend/responses_4xx"] >= 5
        assert scalars["frontend/sheds"] == 0


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
