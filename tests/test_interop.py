"""Interop layer tests: BigDL protobuf checkpoints + TF GraphDef import.

Reference analogs: ``TEST/utils/serializer/`` round-trip specs and the
TF loader specs; golden inputs are the reference's own committed test
resources (real TF-written files), used read-only when present.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfgraph_util import (attr_tensor, enter, node, scalar_const,  # noqa: E501
                          shape_const)
from bigdl_tpu.utils import protowire as pw
from bigdl_tpu import nn
from bigdl_tpu.interop import (load_bigdl_module, load_tf_graph,
                               save_bigdl_module, decode_bigdl_module)

REF_TF = "/root/reference/spark/dl/src/test/resources/tf"


class TestBigDLFormat:
    def _roundtrip(self, model, x, tol=1e-6):
        import tempfile
        model.initialize(rng=7)
        model.training = False
        ref = np.asarray(model.forward(x))
        path = os.path.join(tempfile.mkdtemp(), "m.bigdl")
        save_bigdl_module(model, path)
        loaded = load_bigdl_module(path)
        loaded.training = False
        out = np.asarray(loaded.forward(x))
        np.testing.assert_allclose(out, ref, atol=tol)
        return path, loaded

    def test_lenet_roundtrip(self):
        from bigdl_tpu.models.lenet import lenet5
        x = np.random.RandomState(0).rand(2, 1, 28, 28).astype(np.float32)
        self._roundtrip(lenet5(class_num=10), x)

    def test_mlp_with_bn_roundtrip(self):
        m = nn.Sequential(
            nn.Linear(8, 16), nn.BatchNormalization(16), nn.ReLU(),
            nn.Dropout(0.3), nn.Linear(16, 4), nn.LogSoftMax())
        x = np.random.RandomState(1).rand(4, 8).astype(np.float32)
        # give BN non-trivial running stats first
        m.initialize(rng=7)
        m.training = True
        for _ in range(3):
            m.forward(x, rng=jax.random.PRNGKey(0))
        m.training = False
        ref = np.asarray(m.forward(x))
        import tempfile
        path = os.path.join(tempfile.mkdtemp(), "bn.bigdl")
        save_bigdl_module(m, path)
        loaded = load_bigdl_module(path)
        loaded.training = False
        np.testing.assert_allclose(np.asarray(loaded.forward(x)), ref,
                                   atol=1e-6)
        # running stats survived (stored as runningMean/runningVar attrs
        # exactly like the reference's BatchNormalization serializer)
        np.testing.assert_allclose(
            np.asarray(loaded._state["1"]["running_mean"]),
            np.asarray(m._state["1"]["running_mean"]), atol=1e-6)

    def test_grouped_conv_layout(self):
        # reference stores conv weights (g, out/g, in/g, kh, kw)
        m = nn.Sequential(nn.SpatialConvolution(4, 8, 3, 3, n_group=2))
        x = np.random.RandomState(2).rand(1, 4, 8, 8).astype(np.float32)
        self._roundtrip(m, x)

    def test_decoded_tree_structure(self):
        import tempfile
        from bigdl_tpu.models.lenet import lenet5
        m = lenet5(class_num=10)
        m.initialize()
        path = os.path.join(tempfile.mkdtemp(), "m.bigdl")
        save_bigdl_module(m, path)
        node = decode_bigdl_module(open(path, "rb").read())
        assert node["module_type"].endswith(".Sequential")
        types = [s["module_type"].rsplit(".", 1)[-1]
                 for s in node["sub_modules"]]
        assert "SpatialConvolution" in types and "Linear" in types
        conv = next(s for s in node["sub_modules"]
                    if s["module_type"].endswith("SpatialConvolution"))
        assert conv["attrs"]["nInputPlane"] == 1
        assert conv["has_parameters"]
        # stored in reference layout (group dim leading)
        assert conv["parameters"][0].ndim == 5

    def test_inception_roundtrip(self):
        from bigdl_tpu.models.inception import inception_v1
        x = np.random.RandomState(3).rand(1, 3, 224, 224).astype(np.float32)
        self._roundtrip(inception_v1(class_num=50), x, tol=1e-4)


class TestTFImport:
    def test_binary_pb_matches_manual(self):
        path = os.path.join(REF_TF, "test.pb")
        if not os.path.exists(path):
            pytest.skip("reference resources unavailable")
        import bigdl_tpu.interop.tf_format as tff
        m = load_tf_graph(path, inputs=["Placeholder"], outputs=["output"])
        x = np.random.RandomState(0).randn(3, 1).astype(np.float32)
        out = np.asarray(m.forward(x))
        nodes = tff.parse_graphdef_binary(open(path, "rb").read())
        consts = {n["name"]: n["attrs"]["value"] for n in nodes
                  if n["op"] == "Const"}
        h = np.tanh(x @ consts["Variable"] + consts["Variable_1"])
        ref = h @ consts["Variable_2"] + consts["Variable_3"]
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_lenet_pbtxt_trains(self):
        path = os.path.join(REF_TF, "lenet_batch_2.pbtxt")
        if not os.path.exists(path):
            pytest.skip("reference resources unavailable")
        m = load_tf_graph(path, inputs=["fifo_queue_Dequeue"],
                          outputs=["Predictions/Softmax"])
        # the graph bakes batch 32 into its flatten shape const
        x = np.random.RandomState(0).rand(32, 28, 28, 1).astype(np.float32)
        out = np.asarray(m.forward(x))
        assert out.shape == (32, 10)
        np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-5)
        assert len(m._params) == 8  # conv1/2 + fc3/4 weights+biases

        y = np.zeros(32, np.int64)

        def loss(p):
            probs, _ = m.apply(p, {}, jnp.asarray(x))
            return -jnp.log(probs[jnp.arange(32), y] + 1e-8).mean()

        l0 = float(loss(m._params))
        g = jax.jit(jax.grad(loss))(m._params)
        params = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g,
                                        m._params, g)
        l1 = float(loss(params))
        assert l1 < l0, "imported TF graph does not train"

    def test_synthetic_graph_ops(self, tmp_path):
        """Exercise the ops layer + pruning via a hand-built GraphDef."""


        w = np.random.RandomState(0).randn(4, 3).astype(np.float32)
        g = (node("x", "Placeholder")
             + node("w", "Const", value=attr_tensor(w))
             + node("mm", "MatMul", ["x", "w"])
             + node("act", "Relu", ["mm"])
             + node("dead", "Neg", ["act"]))   # pruned away
        path = str(tmp_path / "g.pb")
        open(path, "wb").write(g)
        m = load_tf_graph(path, inputs=["x"], outputs=["act"])
        assert "dead" not in m.needed
        x = np.random.RandomState(1).randn(2, 4).astype(np.float32)
        np.testing.assert_allclose(np.asarray(m.forward(x)),
                                   np.maximum(x @ w, 0.0), atol=1e-6)

    def test_missing_op_reports_clearly(self, tmp_path):
        g = (pw.enc_bytes(1, pw.enc_str(1, "x") + pw.enc_str(2, "Placeholder"))
             + pw.enc_bytes(1, pw.enc_str(1, "y")
                            + pw.enc_str(2, "SomeExoticOp")
                            + pw.enc_str(3, "x")))
        path = str(tmp_path / "g.pb")
        open(path, "wb").write(g)
        m = load_tf_graph(path, inputs=["x"], outputs=["y"])
        with pytest.raises(NotImplementedError, match="SomeExoticOp"):
            m.forward(np.zeros((1, 2), np.float32))


class TestInteropReviewFixes:
    """Regressions for the round-2 interop review findings."""

    def test_jointable_view_nhwc_roundtrip(self, tmp_path):
        # JoinTable inside ConcatTable + View + NHWC conv all round-trip
        m = nn.Sequential(
            nn.ConcatTable(nn.Identity(), nn.Identity()),
            nn.JoinTable(1),
            nn.View((8,)))
        m.initialize()
        m.training = False
        x = np.random.RandomState(0).rand(2, 4).astype(np.float32)
        ref = np.asarray(m.forward(x))
        p = str(tmp_path / "jt.bigdl")
        save_bigdl_module(m, p)
        loaded = load_bigdl_module(p)
        loaded.training = False
        np.testing.assert_allclose(np.asarray(loaded.forward(x)), ref,
                                   atol=1e-6)

    def test_nhwc_conv_roundtrip(self, tmp_path):
        m = nn.Sequential(nn.SpatialConvolution(3, 4, 3, 3, format="NHWC"))
        m.initialize()
        m.training = False
        x = np.random.RandomState(1).rand(1, 8, 8, 3).astype(np.float32)
        ref = np.asarray(m.forward(x))
        p = str(tmp_path / "nhwc.bigdl")
        save_bigdl_module(m, p)
        loaded = load_bigdl_module(p)
        assert loaded.modules[0].format == "NHWC"
        np.testing.assert_allclose(np.asarray(loaded.forward(x)), ref,
                                   atol=1e-6)

    def test_dilated_conv_roundtrip(self, tmp_path):
        m = nn.Sequential(nn.SpatialConvolution(2, 3, 3, 3, dilation_w=2,
                                                dilation_h=2))
        m.initialize()
        x = np.random.RandomState(2).rand(1, 2, 9, 9).astype(np.float32)
        ref = np.asarray(m.forward(x))
        p = str(tmp_path / "dil.bigdl")
        save_bigdl_module(m, p)
        loaded = load_bigdl_module(p)
        np.testing.assert_allclose(np.asarray(loaded.forward(x)), ref,
                                   atol=1e-6)

    def test_port_suffixed_feed(self, tmp_path):
        g = (pw.enc_bytes(1, pw.enc_str(1, "x") + pw.enc_str(2, "Placeholder"))
             + pw.enc_bytes(1, pw.enc_str(1, "y") + pw.enc_str(2, "Neg")
                            + pw.enc_str(3, "x:0")))
        path = str(tmp_path / "g.pb")
        open(path, "wb").write(g)
        m = load_tf_graph(path, inputs=["x:0"], outputs=["y"])
        x = np.ones((2, 2), np.float32)
        np.testing.assert_allclose(np.asarray(m.forward(x)), -x)
        out2, _ = m.apply(m._params, {}, {"x:0": x})
        np.testing.assert_allclose(np.asarray(out2), -x)

    def test_strided_slice_unsupported_masks_raise(self):
        from bigdl_tpu.ops import get_op
        op = get_op("StridedSlice")
        x = np.zeros((2, 3), np.float32)
        with pytest.raises(NotImplementedError):
            op({"ellipsis_mask": 1}, x, [0, 0], [1, 1], [1, 1])


REF_CAFFE = "/root/reference/spark/dl/src/test/resources/caffe"
REF_TORCH = "/root/reference/spark/dl/src/test/resources/torch"


class TestCaffeImport:
    def test_reference_fixture_loads_and_runs(self):
        if not os.path.exists(REF_CAFFE):
            pytest.skip("reference resources unavailable")
        from bigdl_tpu.interop import load_caffe_model
        m = load_caffe_model(
            os.path.join(REF_CAFFE, "test.prototxt"),
            os.path.join(REF_CAFFE, "test.caffemodel"),
            custom={"Dummy": lambda layer, blobs:
                    nn.Identity(name=layer["name"])})
        m.training = False
        x = np.random.RandomState(0).rand(1, 3, 5, 5).astype(np.float32)
        out = np.asarray(m.forward(x))
        assert out.shape == (1, 2)
        np.testing.assert_allclose(out.sum(), 1.0, rtol=1e-5)

    def test_weights_come_from_caffemodel(self):
        if not os.path.exists(REF_CAFFE):
            pytest.skip("reference resources unavailable")
        from bigdl_tpu.interop import load_caffe_model
        from bigdl_tpu.interop.caffe_format import _decode_caffemodel
        m = load_caffe_model(
            os.path.join(REF_CAFFE, "test.prototxt"),
            os.path.join(REF_CAFFE, "test.caffemodel"),
            custom={"Dummy": lambda layer, blobs: nn.Identity()})
        blobs = _decode_caffemodel(
            open(os.path.join(REF_CAFFE, "test.caffemodel"), "rb").read())
        key0 = m._param_keys[0]
        got = np.asarray(m._params[key0]["weight"])
        np.testing.assert_allclose(got, blobs["conv"][0].reshape(got.shape),
                                   atol=1e-6)

    def test_unknown_layer_raises_without_custom(self):
        if not os.path.exists(REF_CAFFE):
            pytest.skip("reference resources unavailable")
        from bigdl_tpu.interop import load_caffe_model
        with pytest.raises(NotImplementedError, match="Dummy"):
            load_caffe_model(os.path.join(REF_CAFFE, "test.prototxt"),
                             os.path.join(REF_CAFFE, "test.caffemodel"))


class TestTorchT7:
    def test_reads_reference_image_tensors(self):
        if not os.path.exists(REF_TORCH):
            pytest.skip("reference resources unavailable")
        from bigdl_tpu.interop import load_t7
        import glob
        files = sorted(glob.glob(os.path.join(REF_TORCH, "*.t7")))
        assert files
        arr = load_t7(files[0])
        assert isinstance(arr, np.ndarray)
        assert arr.shape == (3, 224, 224) and arr.dtype == np.float32
        assert np.isfinite(arr).all()

    def test_roundtrip_table_of_tensors(self, tmp_path):
        from bigdl_tpu.interop import load_t7, save_t7
        data = {"w": np.random.RandomState(0).rand(4, 3).astype(np.float32),
                "ids": np.arange(5, dtype=np.int64),
                "lr": 0.1, "tag": "oracle", "ok": True,
                "seq": [1.0, 2.0]}
        p = str(tmp_path / "x.t7")
        save_t7(p, data)
        back = load_t7(p)
        np.testing.assert_allclose(back["w"], data["w"])
        np.testing.assert_array_equal(back["ids"], data["ids"])
        assert back["tag"] == "oracle" and back["ok"] is True
        assert back["seq"] == [1, 2]


class TestKerasJSON:
    def _json(self):
        import json
        return json.dumps({
            "class_name": "Sequential",
            "config": [
                {"class_name": "Dense",
                 "config": {"output_dim": 8, "activation": "relu",
                            "batch_input_shape": [None, 4]}},
                {"class_name": "Dropout", "config": {"p": 0.5}},
                {"class_name": "Dense",
                 "config": {"output_dim": 3, "activation": "softmax"}},
            ]})

    def test_definition_import_and_forward(self):
        from bigdl_tpu.interop import load_keras_json
        m = load_keras_json(self._json())
        assert m.output_shape == (None, 3)
        core = m.core_module()
        core.training = False
        out = np.asarray(core.forward(np.zeros((2, 4), np.float32)))
        np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-5)

    def test_weight_install_keras_order(self):
        from bigdl_tpu.interop import load_keras_json, set_keras_weights
        m = load_keras_json(self._json())
        rng = np.random.RandomState(0)
        ws = [rng.rand(4, 8).astype(np.float32),   # Dense1 W (in,out)
              rng.rand(8).astype(np.float32),
              rng.rand(8, 3).astype(np.float32),
              rng.rand(3).astype(np.float32)]
        set_keras_weights(m, ws)
        x = rng.rand(2, 4).astype(np.float32)
        core = m.core_module()
        core.training = False
        out = np.asarray(core.forward(x))
        h = np.maximum(x @ ws[0] + ws[1], 0)
        logits = h @ ws[2] + ws[3]
        ref = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_unknown_layer_reports(self):
        from bigdl_tpu.interop import load_keras_json
        import json
        doc = json.dumps({"class_name": "Sequential", "config": [
            {"class_name": "Lambda", "config": {}}]})
        with pytest.raises(NotImplementedError, match="Lambda"):
            load_keras_json(doc)

    def _bn_json(self):
        import json
        return json.dumps({
            "class_name": "Sequential",
            "config": [
                {"class_name": "Dense",
                 "config": {"output_dim": 8, "activation": "linear",
                            "batch_input_shape": [None, 4]}},
                {"class_name": "BatchNormalization",
                 "config": {"epsilon": 1e-3}},
                {"class_name": "Dense", "config": {"output_dim": 3}},
            ]})

    def _bn_weights(self, rng):
        # Keras-1.2 save order; BN = gamma, beta, mean, "std" (= variance,
        # see set_keras_weights docstring)
        return [rng.rand(4, 8).astype(np.float32),
                rng.rand(8).astype(np.float32),
                rng.rand(8).astype(np.float32) + 0.5,     # gamma
                rng.rand(8).astype(np.float32),           # beta
                rng.rand(8).astype(np.float32),           # running mean
                rng.rand(8).astype(np.float32) + 0.5,     # running var
                rng.rand(8, 3).astype(np.float32),
                rng.rand(3).astype(np.float32)]

    def _bn_reference(self, ws, x):
        h = x @ ws[0] + ws[1]
        hn = ws[2] * (h - ws[4]) / np.sqrt(ws[5] + 1e-3) + ws[3]
        return hn @ ws[6] + ws[7]

    def test_batchnorm_consumes_four_arrays(self):
        # ADVICE r2: BN layers must consume gamma/beta/mean/var, not shift
        # the array stream by two
        from bigdl_tpu.interop import load_keras_json, set_keras_weights
        m = load_keras_json(self._bn_json())
        rng = np.random.RandomState(1)
        ws = self._bn_weights(rng)
        set_keras_weights(m, ws)
        x = rng.rand(2, 4).astype(np.float32)
        core = m.core_module()
        core.training = False
        out = np.asarray(core.forward(x))
        np.testing.assert_allclose(out, self._bn_reference(ws, x),
                                   rtol=2e-4, atol=1e-5)

    def test_hdf5_weight_loader(self, tmp_path):
        # reference pyspark/bigdl/keras/converter.py:32 WeightLoader
        h5py = pytest.importorskip("h5py")
        from bigdl_tpu.interop import load_keras_json, \
            load_keras_hdf5_weights
        rng = np.random.RandomState(2)
        ws = self._bn_weights(rng)
        path = str(tmp_path / "w.h5")
        layer_ws = [("dense_1", ws[0:2]), ("batchnormalization_1", ws[2:6]),
                    ("dense_2", ws[6:8])]
        with h5py.File(path, "w") as f:
            grp = f.create_group("model_weights")
            grp.attrs["layer_names"] = [n.encode()
                                        for n, _ in layer_ws]
            for name, arrays in layer_ws:
                g = grp.create_group(name)
                wn = [f"{name}_{i}".encode()
                      for i in range(len(arrays))]
                g.attrs["weight_names"] = wn
                for n, a in zip(wn, arrays):
                    g.create_dataset(n.decode(), data=a)
        m = load_keras_json(self._bn_json())
        load_keras_hdf5_weights(m, path)
        x = rng.rand(2, 4).astype(np.float32)
        core = m.core_module()
        core.training = False
        out = np.asarray(core.forward(x))
        np.testing.assert_allclose(out, self._bn_reference(ws, x),
                                   rtol=2e-4, atol=1e-5)


class TestReviewFixesE:
    def test_multi_output_op_inside_switch_branch(self, tmp_path):
        # Unpack (tuple-output) downstream of Switch: port indexing must
        # survive the branch tagging
        from bigdl_tpu.interop import load_tf_graph
        g = (node("x", "Placeholder")
             + node("pred", "Placeholder")
             + node("sw", "Switch", ["x", "pred"])
             + node("up", "Unpack", ["sw:1"])
             + node("second", "Identity", ["up:1"]))
        p = str(tmp_path / "g.pb")
        open(p, "wb").write(g)
        m = load_tf_graph(p, inputs=["x", "pred"], outputs=["second"])
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        out, _ = m.apply({}, {}, {"x": x, "pred": np.array(True)})
        np.testing.assert_allclose(np.asarray(out), x[1])

    def test_t7_int32_roundtrip(self, tmp_path):
        from bigdl_tpu.interop import load_t7, save_t7
        p = str(tmp_path / "i.t7")
        ids = np.arange(7, dtype=np.int32)
        save_t7(p, ids)
        back = load_t7(p)
        assert back.dtype == np.int32
        np.testing.assert_array_equal(back, ids)

    def test_caffe_dilation_honored(self):
        from bigdl_tpu.interop.caffe_format import _conv_module
        cp = {"num_output": [2], "kernel_size": [3], "dilation": [2]}
        blobs = [np.zeros((2, 3, 3, 3), np.float32)]
        m, _ = _conv_module("c", cp, blobs)
        assert m.dilation == (2, 2)


class TestTFExport:
    def test_lenet_roundtrip_through_graphdef(self, tmp_path):
        from bigdl_tpu.interop import load_tf_graph, save_tf_graph
        from bigdl_tpu.models.lenet import lenet5
        m = lenet5(class_num=10)
        m.initialize(rng=4)
        m.training = False
        x = np.random.RandomState(0).rand(2, 1, 28, 28).astype(np.float32)
        ref = np.asarray(m.forward(x))
        p = str(tmp_path / "lenet.pb")
        inp, out = save_tf_graph(m, p, input_shape=(2, 1, 28, 28))
        m2 = load_tf_graph(p, inputs=[inp], outputs=[out])
        np.testing.assert_allclose(np.asarray(m2.forward(x)), ref,
                                   atol=1e-5)

    def test_bn_folded_export(self, tmp_path):
        from bigdl_tpu.interop import load_tf_graph, save_tf_graph
        m = nn.Sequential(nn.Linear(4, 6), nn.BatchNormalization(6),
                          nn.ReLU())
        m.initialize(rng=1)
        x = np.random.RandomState(1).rand(8, 4).astype(np.float32)
        m.training = True
        for _ in range(3):
            m.forward(x, rng=jax.random.PRNGKey(0))
        m.training = False
        ref = np.asarray(m.forward(x))
        p = str(tmp_path / "bn.pb")
        inp, out = save_tf_graph(m, p, input_shape=(8, 4))
        m2 = load_tf_graph(p, inputs=[inp], outputs=[out])
        np.testing.assert_allclose(np.asarray(m2.forward(x)), ref,
                                   atol=1e-5)

    def test_unsupported_module_reports(self, tmp_path):
        from bigdl_tpu.interop import save_tf_graph
        m = nn.Sequential(nn.PReLU())
        m.initialize()
        with pytest.raises(NotImplementedError, match="PReLU"):
            save_tf_graph(m, str(tmp_path / "x.pb"), input_shape=(1, 4))


def test_temporal_convolution_roundtrip(tmp_path):
    # regression: exporter read m.stride (nonexistent) instead of stride_w
    m = nn.Sequential(nn.TemporalConvolution(5, 7, 3, 2))
    m.initialize()
    x = np.random.RandomState(0).rand(2, 9, 5).astype(np.float32)
    ref = np.asarray(m.forward(x))
    p = str(tmp_path / "tc.bigdl")
    save_bigdl_module(m, p)
    m2 = load_bigdl_module(p)
    np.testing.assert_allclose(np.asarray(m2.forward(x)), ref, atol=1e-6)


def test_dilated_conv_tf_export_roundtrip(tmp_path):
    # regression: exporter dropped the dilations attr
    from bigdl_tpu.interop import load_tf_graph, save_tf_graph
    m = nn.Sequential(nn.SpatialConvolution(2, 3, 3, 3, dilation_w=2,
                                            dilation_h=2))
    m.initialize()
    x = np.random.RandomState(0).rand(1, 2, 9, 9).astype(np.float32)
    ref = np.asarray(m.forward(x))
    p = str(tmp_path / "dil.pb")
    inp, out = save_tf_graph(m, p, input_shape=(1, 2, 9, 9))
    m2 = load_tf_graph(p, inputs=[inp], outputs=[out])
    got = np.asarray(m2.forward(x))
    assert got.shape == ref.shape
    np.testing.assert_allclose(got, ref, atol=1e-5)


def test_residual_block_tf_export_roundtrip(tmp_path):
    from bigdl_tpu.interop import load_tf_graph, save_tf_graph
    from bigdl_tpu.models.resnet import basic_block
    m = nn.Sequential(basic_block(4, 8, 2))
    m.initialize()
    m.training = False
    x = np.random.RandomState(1).rand(2, 4, 8, 8).astype(np.float32)
    ref = np.asarray(m.forward(x))
    p = str(tmp_path / "res.pb")
    inp, out = save_tf_graph(m, p, input_shape=(2, 4, 8, 8))
    m2 = load_tf_graph(p, inputs=[inp], outputs=[out])
    np.testing.assert_allclose(np.asarray(m2.forward(x)), ref, atol=1e-4)


class TestTFSession:
    def test_train_imported_graph(self):
        path = os.path.join(REF_TF, "lenet_batch_2.pbtxt")
        if not os.path.exists(path):
            pytest.skip("reference resources unavailable")
        from bigdl_tpu import optim
        from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.sample import Sample
        from bigdl_tpu.interop import TFSession

        sess = TFSession(path, inputs=["fifo_queue_Dequeue"],
                         outputs=["Predictions/Softmax"])
        rng = np.random.RandomState(1)
        imgs = rng.rand(64, 28, 28, 1).astype(np.float32)
        labels = rng.randint(0, 10, 64)
        for i, l in enumerate(labels):
            imgs[i, l * 2:(l + 1) * 2, :, 0] += 2.0
        samples = [Sample(imgs[i], np.int32(labels[i])) for i in range(64)]

        class LogNLL(nn.Criterion):
            def apply(self, input, target):
                return nn.ClassNLLCriterion().apply(
                    jnp.log(input + 1e-8), target)

        # graph bakes batch 32 into its flatten const
        opt = sess.train(DataSet.array(samples) >> SampleToMiniBatch(32),
                         LogNLL(),
                         optim_method=optim.SGD(learning_rate=0.01,
                                                momentum=0.9,
                                                dampening=0.0),
                         end_when=optim.max_epoch(5))
        assert opt.state["loss"] < 1.0, opt.state["loss"]
        # trained variables persisted onto the session's graph
        probs = sess.run(imgs[:32])
        acc = (np.argmax(probs, -1) == labels[:32]).mean()
        assert acc > 0.7, acc


class TestKerasFunctionalModel:
    def _doc(self, mode="concat"):
        import json
        return json.dumps({
            "class_name": "Model",
            "config": {
                "name": "branchy",
                "layers": [
                    {"class_name": "InputLayer", "name": "in1",
                     "config": {"name": "in1",
                                "batch_input_shape": [None, 6]}},
                    {"class_name": "Dense", "name": "a",
                     "config": {"name": "a", "output_dim": 8,
                                "activation": "relu"},
                     "inbound_nodes": [[["in1", 0, 0]]]},
                    {"class_name": "Dense", "name": "b",
                     "config": {"name": "b", "output_dim": 8,
                                "activation": "tanh"},
                     "inbound_nodes": [[["in1", 0, 0]]]},
                    {"class_name": "Merge", "name": "m",
                     "config": {"name": "m", "mode": mode,
                                "concat_axis": -1},
                     "inbound_nodes": [[["a", 0, 0], ["b", 0, 0]]]},
                    {"class_name": "Dense", "name": "out",
                     "config": {"name": "out", "output_dim": 3,
                                "activation": "softmax"},
                     "inbound_nodes": [[["m", 0, 0]]]},
                ],
                "input_layers": [["in1", 0, 0]],
                "output_layers": [["out", 0, 0]],
            }})

    def test_branching_model_imports_and_runs(self):
        from bigdl_tpu.interop import load_keras_json
        m = load_keras_json(self._doc())
        core = m.core_module()
        x = np.random.RandomState(0).rand(4, 6).astype(np.float32)
        out = np.asarray(core.forward(x))
        assert out.shape == (4, 3)
        np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-5)

    def test_sum_merge_and_training(self):
        from bigdl_tpu.interop import load_keras_json
        from bigdl_tpu import optim
        m = load_keras_json(self._doc(mode="sum"))
        rng = np.random.RandomState(1)
        centers = rng.randn(3, 6) * 4
        y = rng.randint(0, 3, 192)
        x = (centers[y] + rng.randn(192, 6)).astype(np.float32)
        m.compile(optim.Adam(learning_rate=0.01),
                  "categorical_crossentropy", ["accuracy"])
        m.fit(x, y, batch_size=32, nb_epoch=10)
        assert m.evaluate(x, y)["Top1Accuracy"] > 0.9


def test_keras_functional_positive_concat_axis():
    """Regression: Keras concat_axis counts the batch dim; positive axes
    must shift when indexing batch-less bookkeeping shapes."""
    import json
    from bigdl_tpu.interop import load_keras_json
    doc = json.dumps({
        "class_name": "Model",
        "config": {
            "name": "chan_concat",
            "layers": [
                {"class_name": "InputLayer", "name": "in1",
                 "config": {"name": "in1",
                            "batch_input_shape": [None, 3, 8, 8]}},
                {"class_name": "Convolution2D", "name": "ca",
                 "config": {"name": "ca", "nb_filter": 4, "nb_row": 3,
                            "nb_col": 3, "border_mode": "same"},
                 "inbound_nodes": [[["in1", 0, 0]]]},
                {"class_name": "Convolution2D", "name": "cb",
                 "config": {"name": "cb", "nb_filter": 5, "nb_row": 3,
                            "nb_col": 3, "border_mode": "same"},
                 "inbound_nodes": [[["in1", 0, 0]]]},
                {"class_name": "Merge", "name": "m",
                 "config": {"name": "m", "mode": "concat",
                            "concat_axis": 1},
                 "inbound_nodes": [[["ca", 0, 0], ["cb", 0, 0]]]},
                {"class_name": "Convolution2D", "name": "out",
                 "config": {"name": "out", "nb_filter": 2, "nb_row": 1,
                            "nb_col": 1},
                 "inbound_nodes": [[["m", 0, 0]]]},
            ],
            "input_layers": [["in1", 0, 0]],
            "output_layers": [["out", 0, 0]],
        }})
    m = load_keras_json(doc)
    out = m.core_module().forward(np.zeros((2, 3, 8, 8), np.float32))
    assert out.shape == (2, 2, 8, 8)


def test_keras_functional_shared_layer_tied_weights():
    """A layer called twice imports as ONE module applied at two graph
    positions; nn.Graph ties the weights (reference converter's
    multi-call layer path — was rejected before r3)."""
    import json
    import jax
    from bigdl_tpu.interop import load_keras_json
    doc = json.dumps({
        "class_name": "Model",
        "config": {
            "layers": [
                {"class_name": "InputLayer", "name": "i",
                 "config": {"name": "i", "batch_input_shape": [None, 4]}},
                {"class_name": "Dense", "name": "d",
                 "config": {"name": "d", "output_dim": 4},
                 "inbound_nodes": [[["i", 0, 0]], [["d", 0, 0]]]},
            ],
            "input_layers": [["i", 0, 0]],
            "output_layers": [["d", 1, 0]],
        }})
    m = load_keras_json(doc)
    core = m.core_module()
    x = np.random.RandomState(0).rand(3, 4).astype(np.float32)
    out = np.asarray(core.forward(x))
    # tied weights -> exactly one (weight, bias) pair in the whole tree
    leaves = jax.tree_util.tree_leaves(core._params)
    assert len(leaves) == 2

    def find(p, key):
        if isinstance(p, dict):
            if key in p and not isinstance(p[key], dict):
                return np.asarray(p[key])
            for v in p.values():
                got = find(v, key)
                if got is not None:
                    return got
        return None

    w, b = find(core._params, "weight"), find(core._params, "bias")
    y1 = x @ w.T + b
    np.testing.assert_allclose(out, y1 @ w.T + b, rtol=1e-5)


class TestTFWhileLoopImport:
    def _while_graph(self, tmp_path):
        # while (i < 5): i += 1; acc *= 2
        g = (node("i0", "Placeholder")
             + node("acc0", "Placeholder")
             + enter("i_ent", ["i0"], "loop")
             + enter("acc_ent", ["acc0"], "loop")
             + node("i_mrg", "Merge", ["i_ent", "i_nextit"])
             + node("acc_mrg", "Merge", ["acc_ent", "acc_nextit"])
             + node("five", "Const", value=scalar_const(5.0))
             + node("lt", "Less", ["i_mrg", "five"])
             + node("lc", "LoopCond", ["lt"])
             + node("i_sw", "Switch", ["i_mrg", "lc"])
             + node("acc_sw", "Switch", ["acc_mrg", "lc"])
             + node("one", "Const", value=scalar_const(1.0))
             + node("two", "Const", value=scalar_const(2.0))
             + node("i_add", "Add", ["i_sw:1", "one"])
             + node("acc_mul", "Mul", ["acc_sw:1", "two"])
             + node("i_nextit", "NextIteration", ["i_add"])
             + node("acc_nextit", "NextIteration", ["acc_mul"])
             + node("i_exit", "Exit", ["i_sw:0"])
             + node("acc_exit", "Exit", ["acc_sw:0"])
             + node("out", "Identity", ["acc_exit"]))
        p = str(tmp_path / "while.pb")
        open(p, "wb").write(g)
        return p

    def test_two_variable_loop(self, tmp_path):
        m = load_tf_graph(self._while_graph(tmp_path),
                          inputs=["i0", "acc0"],
                          outputs=["out", "i_exit"])
        (acc, i_final), _ = m.apply({}, {}, {"i0": np.float32(0.0),
                                             "acc0": np.float32(3.0)})
        assert float(acc) == 96.0     # 3 * 2^5
        assert float(i_final) == 5.0

    def test_loop_under_jit_with_traced_inputs(self, tmp_path):
        m = load_tf_graph(self._while_graph(tmp_path),
                          inputs=["i0", "acc0"],
                          outputs=["out", "i_exit"])
        f = jax.jit(lambda i, a: m.apply({}, {},
                                         {"i0": i, "acc0": a})[0])
        acc, i_final = f(np.float32(2.0), np.float32(1.0))
        assert float(acc) == 8.0      # 1 * 2^3
        assert float(i_final) == 5.0


def test_unreachable_malformed_frame_tolerated(tmp_path):
    """Regression: a broken loop frame OUTSIDE the requested subgraph must
    not block import (real v1 graphs carry training-only loops)."""
    g = (node("x", "Placeholder")
         + node("y", "Identity", ["x"])
         + node("stray", "Enter", ["x"]))   # malformed frame, unreachable
    p = str(tmp_path / "g.pb")
    open(p, "wb").write(g)
    m = load_tf_graph(p, inputs=["x"], outputs=["y"])
    out = np.asarray(m.forward(np.ones((2,), np.float32)))
    np.testing.assert_allclose(out, 1.0)


def test_keras_functional_input_layers_order(tmp_path):
    """Regression: inputs bind in cfg['input_layers'] order, not layer
    listing order."""
    import json
    from bigdl_tpu.interop import load_keras_json
    doc = json.dumps({
        "class_name": "Model",
        "config": {
            "layers": [
                {"class_name": "InputLayer", "name": "in_a",
                 "config": {"name": "in_a",
                            "batch_input_shape": [None, 2]}},
                {"class_name": "InputLayer", "name": "in_b",
                 "config": {"name": "in_b",
                            "batch_input_shape": [None, 2]}},
                {"class_name": "Merge", "name": "m",
                 "config": {"name": "m", "mode": "sum"},
                 "inbound_nodes": [[["in_a", 0, 0], ["in_b", 0, 0]]]},
            ],
            # declared order REVERSED vs listing order; output = in_a
            # alone, so a swapped binding is directly observable
            "input_layers": [["in_b", 0, 0], ["in_a", 0, 0]],
            "output_layers": [["in_a", 0, 0]],
        }})
    m = load_keras_json(doc)
    core = m.core_module()
    a = np.full((1, 2), 10.0, np.float32)
    b = np.full((1, 2), 1.0, np.float32)
    # positional feed follows the DECLARED order: (in_b, in_a)
    out = core.forward((b, a))
    np.testing.assert_allclose(np.asarray(out), 10.0)


def test_loop_interior_output_rejected(tmp_path):
    """Regression: asking for a loop-interior node as an output fails at
    LOAD with a clear message, not a KeyError at forward."""
    g = (node("i0", "Placeholder")
         + enter("i_ent", ["i0"], "f")
         + node("i_mrg", "Merge", ["i_ent", "i_ni"])
         + node("five", "Const", value=scalar_const(5.0))
         + node("lt", "Less", ["i_mrg", "five"])
         + node("lc", "LoopCond", ["lt"])
         + node("i_sw", "Switch", ["i_mrg", "lc"])
         + node("one", "Const", value=scalar_const(1.0))
         + node("i_add", "Add", ["i_sw:1", "one"])
         + node("i_ni", "NextIteration", ["i_add"])
         + node("i_exit", "Exit", ["i_sw:0"]))
    p = str(tmp_path / "g.pb")
    open(p, "wb").write(g)
    with pytest.raises(NotImplementedError, match="inside while frame"):
        load_tf_graph(p, inputs=["i0"], outputs=["i_mrg"])
