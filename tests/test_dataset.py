"""Data-pipeline tests (reference: dataset specs + transformer specs)."""

import numpy as np
import pytest

from bigdl_tpu.dataset import (
    DataSet, LocalDataSet, DistributedDataSet, Sample, MiniBatch,
    PaddingParam, SampleToMiniBatch, FnTransformer, batch_samples,
)
from bigdl_tpu.dataset import image, mnist


def make_samples(n=10):
    return [Sample(np.full((4,), i, np.float32), np.int32(i % 2))
            for i in range(n)]


class TestLocalDataSet:
    def test_infinite_train_iterator(self):
        ds = LocalDataSet(make_samples(5))
        it = ds.data(train=True)
        seen = [next(it).label for _ in range(12)]
        assert len(seen) == 12  # wraps past size

    def test_eval_iterator_one_pass(self):
        ds = LocalDataSet(make_samples(5))
        assert len(list(ds.data(train=False))) == 5

    def test_shuffle_permutes_indices_only(self):
        ds = LocalDataSet(make_samples(100))
        before = [next(ds.data(train=True)).feature[0] for _ in range(1)]
        ds.shuffle()
        order = [s.feature[0] for s in ds.data(train=False)]
        assert order == sorted(order)  # eval order untouched by shuffle


class TestDistributedDataSet:
    def test_shards_partition_indices(self):
        data = make_samples(8)
        shards = []
        for p in range(4):
            ds = DistributedDataSet(data, process_index=p, process_count=4)
            shards.append([s.feature[0] for s in ds.data(train=False)])
        flat = sorted(x for sh in shards for x in sh)
        assert flat == [float(i) for i in range(8)]
        assert all(len(sh) == 2 for sh in shards)

    def test_same_seed_same_permutation(self):
        data = make_samples(16)
        a = DistributedDataSet(data, seed=3, process_index=0, process_count=2)
        b = DistributedDataSet(data, seed=3, process_index=0, process_count=2)
        a.shuffle(), b.shuffle()
        assert np.array_equal(a._global_indexes, b._global_indexes)


class TestSampleToMiniBatch:
    def test_batching(self):
        ds = LocalDataSet(make_samples(10)) >> SampleToMiniBatch(4)
        batches = list(ds.data(train=False))
        assert len(batches) == 2  # drop_remainder
        assert batches[0].input.shape == (4, 4)
        assert batches[0].target.shape == (4,)

    def test_keep_remainder(self):
        ds = LocalDataSet(make_samples(10)) >> SampleToMiniBatch(
            4, drop_remainder=False)
        assert [b.size() for b in ds.data(train=False)] == [4, 4, 2]

    def test_padding(self):
        samples = [Sample(np.ones((3, 2), np.float32), np.int32(0)),
                   Sample(np.ones((5, 2), np.float32), np.int32(1))]
        mb = batch_samples(samples, feature_padding=PaddingParam(0.0))
        assert mb.input.shape == (2, 5, 2)
        np.testing.assert_allclose(mb.input[0, 3:], 0.0)

    def test_ragged_without_padding_raises(self):
        samples = [Sample(np.ones((3,), np.float32)),
                   Sample(np.ones((5,), np.float32))]
        with pytest.raises(ValueError):
            batch_samples(samples)

    def test_minibatch_slice(self):
        mb = MiniBatch(np.arange(12).reshape(6, 2), np.arange(6))
        sub = mb.slice(2, 3)
        assert sub.size() == 3
        np.testing.assert_allclose(sub.target, [2, 3, 4])


class TestTransformChaining:
    def test_chained_pipeline(self):
        imgs, labels = mnist.synthetic_mnist(32, seed=1)
        samples = mnist.to_samples(imgs, labels)
        ds = (DataSet.array(samples)
              >> image.BytesToGreyImg()
              >> image.GreyImgNormalizer(mnist.TRAIN_MEAN, mnist.TRAIN_STD)
              >> image.GreyImgToSample()
              >> SampleToMiniBatch(8))
        batch = next(ds.data(train=False))
        assert batch.input.shape == (8, 1, 28, 28)
        assert abs(float(batch.input.mean())) < 2.0  # roughly normalized

    def test_fn_transformer(self):
        ds = LocalDataSet(make_samples(4)) >> FnTransformer(
            lambda s: Sample(s.feature * 2, s.label))
        out = list(ds.data(train=False))
        np.testing.assert_allclose(out[1].feature, 2.0)


class TestImageOps:
    def test_random_cropper_pad(self):
        s = Sample(np.ones((32, 32, 3), np.float32), np.int32(0))
        out = image.RandomCropper(32, 32, pad=4)._map(s)
        assert out.feature.shape == (32, 32, 3)

    def test_hflip(self):
        f = np.arange(6, dtype=np.float32).reshape(2, 3)
        s = image.HFlip(threshold=1.1)._map(Sample(f, None))  # always flip
        np.testing.assert_allclose(s.feature[:, 0], [2, 5])

    def test_channel_order(self):
        s = Sample(np.zeros((8, 8, 3), np.float32), None)
        assert image.ChannelOrder("CHW")._map(s).feature.shape == (3, 8, 8)


class TestMnist:
    def test_synthetic_learnable_shapes(self):
        imgs, labels = mnist.synthetic_mnist(64)
        assert imgs.shape == (64, 28, 28) and imgs.dtype == np.uint8
        assert labels.shape == (64,)
        assert len(np.unique(labels)) > 2

    def test_idx_roundtrip(self, tmp_path):
        import struct
        imgs = np.random.default_rng(0).integers(
            0, 255, (3, 28, 28)).astype(np.uint8)
        labels = np.array([1, 2, 3], np.uint8)
        with open(tmp_path / "train-images-idx3-ubyte", "wb") as f:
            f.write(struct.pack(">IIII", 2051, 3, 28, 28))
            f.write(imgs.tobytes())
        with open(tmp_path / "train-labels-idx1-ubyte", "wb") as f:
            f.write(struct.pack(">II", 2049, 3))
            f.write(labels.tobytes())
        ri, rl = mnist.load_mnist(str(tmp_path), train=True)
        np.testing.assert_array_equal(ri, imgs)
        np.testing.assert_array_equal(rl, [1, 2, 3])
