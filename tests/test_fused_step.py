"""Fused K-step driver loop + device-prefetch tests (round-6 perf PR).

Covers the ISSUE-3 acceptance surface:
- fused-vs-unfused equivalence: K∈{1,4} produce the SAME per-iteration
  loss sequence (LeNet-synthetic, CPU) and the same final params;
- trigger/epoch-boundary exactness under partial final blocks:
  validation/checkpoint iteration numbers and shuffle cadence are
  K-invariant;
- device-prefetch determinism across two epochs (MT assembler + device
  block stager in the loop);
- the dispatch-overhead smoke: N iterations at K cost ≤ ceil(N/K)+O(1)
  jit dispatches, counted via a dispatch-counting wrapper.
"""

import math
import os

import jax
import numpy as np
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import (DataSet, MTSampleToMiniBatch,
                               SampleToMiniBatch)
from bigdl_tpu.dataset import image, mnist
from bigdl_tpu.engine import Engine
from bigdl_tpu.models.lenet import lenet5
from bigdl_tpu.optim.optimizer import LocalOptimizer
from bigdl_tpu.optim.trigger import Trigger, probe_fire_step


def mnist_pipeline(n, batch, seed=0, mt=False):
    imgs, labels = mnist.synthetic_mnist(n, seed=seed)
    samples = mnist.to_samples(imgs, labels)
    ds = (DataSet.array(samples)
          >> image.BytesToGreyImg()
          >> image.GreyImgNormalizer(mnist.TRAIN_MEAN, mnist.TRAIN_STD))
    if mt:
        return ds >> MTSampleToMiniBatch(batch, None, workers=2, prefetch=2)
    return ds >> SampleToMiniBatch(batch)


def small_mlp():
    return (nn.Sequential()
            .add(nn.Reshape((784,)))
            .add(nn.Linear(784, 32)).add(nn.ReLU())
            .add(nn.Linear(32, 10)).add(nn.LogSoftMax()))


class RecordingSummary:
    """TrainSummary stand-in: captures the per-iteration replay."""

    def __init__(self):
        self.rows = []  # (step, loss, lr)

    def add_train_step(self, step, loss, lr, throughput):
        self.rows.append((step, loss, lr))

    def add_scalar(self, tag, value, step):
        pass

    def trigger_for(self, name):
        return None

    @property
    def steps(self):
        return [s for s, _, _ in self.rows]

    @property
    def losses(self):
        return np.array([l for _, l, _ in self.rows])


class FiringSpy(Trigger):
    """Wraps a trigger; records the REAL iterations it fired at (probe
    simulations carry state["probe"] and are excluded)."""

    def __init__(self, inner):
        self.inner = inner
        self.fired_at = []

    def __call__(self, state):
        r = self.inner(state)
        if r and not state.get("probe"):
            self.fired_at.append(state["neval"])
        return r


def run_local(k, n=320, batch=32, iters=23, model_fn=small_mlp, mt=False,
              seed=0, **extra):
    rec = RecordingSummary()
    opt = (LocalOptimizer(model_fn(), mnist_pipeline(n, batch, seed=seed,
                                                     mt=mt),
                          nn.ClassNLLCriterion())
           .set_optim_method(optim.Adam(1e-3))
           .set_train_summary(rec)
           .set_end_when(optim.max_iteration(iters)))
    if k is not None:
        opt.set_steps_per_dispatch(k)
    for name, val in extra.items():
        setattr(opt, name, val)
    opt.optimize()
    return rec, opt


class TestFusedEquivalence:
    def test_lenet_synthetic_k4_matches_k1_loss_sequence(self):
        """The ISSUE acceptance bar: identical loss trajectory for
        K∈{1,4} on LeNet-synthetic (CPU), crossing an epoch boundary
        (64 samples / batch 16 = 4 steps per epoch) so partial-block
        flush is in play."""
        seqs = {}
        for k in (1, 4):
            rec, _ = run_local(k, n=64, batch=16, iters=9,
                               model_fn=lenet5)
            seqs[k] = rec
        assert seqs[1].steps == seqs[4].steps == list(range(1, 10))
        np.testing.assert_allclose(seqs[1].losses, seqs[4].losses,
                                   rtol=1e-5, atol=1e-7)

    def test_mlp_k4_matches_k1_params_and_lrs(self):
        r1, o1 = run_local(1)
        r4, o4 = run_local(4)
        assert r1.steps == r4.steps
        np.testing.assert_allclose(r1.losses, r4.losses,
                                   rtol=1e-5, atol=1e-7)
        assert [lr for _, _, lr in r1.rows] == [lr for _, _, lr in r4.rows]
        for a, b in zip(jax.tree_util.tree_leaves(o1.model._params),
                        jax.tree_util.tree_leaves(o4.model._params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)

    def test_oversized_k_flushes_to_epoch_and_end(self):
        # K far larger than the epoch (10 steps) AND the run: blocks
        # flush at every epoch boundary and at max_iteration exactly
        r, o = run_local(64)
        assert r.steps == list(range(1, 24))
        assert o.state["neval"] == 23
        # 10-step epochs: ceil-ish block structure 10|10|3
        assert o._dispatch_count == 3


class TestTriggerEpochExactness:
    def _run(self, k, tmp_path):
        val = mnist_pipeline(64, 32, seed=1)
        vspy = FiringSpy(optim.several_iteration(3))
        cspy = FiringSpy(optim.several_iteration(4))
        shuffles = {"n": 0}
        train = mnist_pipeline(320, 32)
        orig_shuffle = train.shuffle

        def counting_shuffle():
            shuffles["n"] += 1
            orig_shuffle()

        train.shuffle = counting_shuffle
        opt = (LocalOptimizer(small_mlp(), train, nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(1e-3))
               .set_steps_per_dispatch(k)
               .set_end_when(optim.max_iteration(23))
               .set_validation(vspy, val, [optim.Top1Accuracy()])
               .set_checkpoint(str(tmp_path / f"ck{k}"), cspy))
        opt.optimize()
        ckpts = sorted(os.listdir(str(tmp_path / f"ck{k}")))
        return vspy.fired_at, cspy.fired_at, shuffles["n"], ckpts, opt

    def test_fire_iterations_shuffles_and_checkpoints_k_invariant(
            self, tmp_path):
        """10-step epochs with K=4 force partial blocks (4|4|2) — the
        validation (every 3) and checkpoint (every 4) iterations, the
        shuffle cadence, and the checkpoint FILES must match K=1
        exactly."""
        v1, c1, s1, f1, o1 = self._run(1, tmp_path)
        v4, c4, s4, f4, o4 = self._run(4, tmp_path)
        assert v1 == [3, 6, 9, 12, 15, 18, 21]
        assert (v1, c1, s1) == (v4, c4, s4)
        assert f1 == f4  # same model.<neval> checkpoint set
        assert o1.state["epoch"] == o4.state["epoch"] == 2
        assert o1.state["records_processed_this_epoch"] \
            == o4.state["records_processed_this_epoch"] == 96

    def test_every_epoch_validation_fires_at_epoch_boundaries(self):
        val = mnist_pipeline(64, 32, seed=1)
        fired = {}
        for k in (1, 4):
            spy = FiringSpy(optim.every_epoch())
            opt = (LocalOptimizer(small_mlp(), mnist_pipeline(320, 32),
                                  nn.ClassNLLCriterion())
                   .set_optim_method(optim.Adam(1e-3))
                   .set_steps_per_dispatch(k)
                   .set_end_when(optim.max_epoch(2))
                   .set_validation(spy, val, [optim.Top1Accuracy()]))
            opt.optimize()
            fired[k] = spy.fired_at
        assert fired[1] == fired[4] == [10, 20]

    def test_probe_fire_step_caps_at_trigger_and_epoch(self):
        state = {"neval": 4, "epoch": 0,
                 "records_processed_this_epoch": 128}
        # several_iteration(6) fires at neval 6 → offset 2 from neval 4
        assert probe_fire_step(state, 8, 32, 99999,
                               [optim.several_iteration(6)]) == 2
        # epoch of 320 records ends after 6 more 32-record steps
        assert probe_fire_step(state, 8, 32, 320, []) == 6
        # unknown batch size (0): epoch invisible to the probe
        assert probe_fire_step(state, 8, 0, 320, []) is None
        # probed states are marked, and fire on the simulated epoch flag
        seen = []

        class Probe(Trigger):
            def __call__(self, s):
                seen.append(s.get("probe"))
                return False

        assert probe_fire_step(state, 2, 32, 99999, [Probe()]) is None
        assert seen == [True, True]

    def test_parameters_histogram_trigger_sees_exact_step_params(self,
                                                                 devices):
        """The Parameters summary trigger is probed like any other:
        its firing iteration must end a block, so the logged histogram
        holds THAT iteration's params, not end-of-block ones."""
        hist = {}
        for k in (1, 4):
            rec = RecordingSummary()
            captured = []
            rec.add_histogram = lambda tag, values, step, _c=captured: \
                _c.append((tag, np.array(values, copy=True), step))
            rec.trigger_for = lambda name: (
                optim.several_iteration(3) if name == "Parameters"
                else None)
            opt = (optim.DistriOptimizer(small_mlp(),
                                         mnist_pipeline(320, 32),
                                         nn.ClassNLLCriterion())
                   .set_optim_method(optim.SGD(learning_rate=0.05))
                   .set_steps_per_dispatch(k)
                   .set_seed(5)
                   .set_train_summary(rec)
                   .set_end_when(optim.max_iteration(8)))
            opt.optimize()
            hist[k] = captured
        assert [s for _, _, s in hist[1]] == [s for _, _, s in hist[4]] \
            == [3, 3, 3, 3, 6, 6, 6, 6]  # 4 param leaves × iters 3, 6
        for (t1, v1, s1), (t4, v4, s4) in zip(hist[1], hist[4]):
            assert t1 == t4
            np.testing.assert_allclose(v1, v4, rtol=1e-5, atol=1e-7)

    def test_mid_epoch_resume_fast_forward_k4(self):
        train = mnist_pipeline(256, 32)
        opt = (LocalOptimizer(small_mlp(), train, nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(1e-3))
               .set_steps_per_dispatch(4)
               .set_state({"records_processed_this_epoch": 128})
               .set_end_when(optim.max_iteration(4)))
        opt.optimize()
        # 128 skipped + 4*32 trained = 256 → exactly one epoch rollover
        assert opt.state["epoch"] == 1
        assert opt.state["records_processed_this_epoch"] == 0


class TestDevicePrefetchDeterminism:
    def test_two_epochs_reproducible_through_prefetch_stages(self):
        """Full pipeline (MT host assembler → device block stager) run
        twice over two epochs: identical loss sequence and identical
        final params — prefetch must not reorder or drop batches."""
        runs = []
        for _ in range(2):
            rec, opt = run_local(4, n=256, batch=32, iters=16, mt=True)
            runs.append((rec, opt))
        (ra, oa), (rb, ob) = runs
        assert ra.steps == rb.steps == list(range(1, 17))
        np.testing.assert_array_equal(ra.losses, rb.losses)
        for a, b in zip(jax.tree_util.tree_leaves(oa.model._params),
                        jax.tree_util.tree_leaves(ob.model._params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_prefetch_path_matches_plain_pipeline(self):
        rec_mt, _ = run_local(4, n=256, batch=32, iters=16, mt=True)
        rec_pl, _ = run_local(4, n=256, batch=32, iters=16, mt=False)
        np.testing.assert_allclose(rec_mt.losses, rec_pl.losses,
                                   rtol=1e-6, atol=1e-7)


class TestDispatchBudget:
    def test_fused_loop_dispatch_count_smoke(self, monkeypatch):
        """N iterations at steps_per_dispatch=K must issue
        ≤ ceil(N/K)+O(1) jit dispatches — counted via a wrapper around
        the built block fn, so the budget holds for the ACTUAL compiled
        callables, not a driver-side counter."""
        calls = {"n": 0}
        orig = LocalOptimizer._build_block_fn

        def counting_build(self, grad_fn, k):
            fn = orig(self, grad_fn, k)

            def wrapped(*a, **kw):
                calls["n"] += 1
                return fn(*a, **kw)

            return wrapped

        monkeypatch.setattr(LocalOptimizer, "_build_block_fn",
                            counting_build)
        N, K = 24, 4
        rec, opt = run_local(K, n=2048, batch=16, iters=N)
        assert rec.steps == list(range(1, N + 1))
        budget = math.ceil(N / K) + 2
        assert calls["n"] <= budget, (calls["n"], budget)
        assert opt._dispatch_count == calls["n"]

    def test_k1_still_one_dispatch_per_iteration(self):
        rec, opt = run_local(1, n=2048, batch=16, iters=8)
        assert opt._dispatch_count == 8


class TestDistriFused:
    def test_spmd_k4_matches_k1_with_zero1(self, devices):
        """The fused block through the SPMD path: batches sharded
        P(None, "data"), ZeRO-1 sharded optimizer update constrained
        inside the scanned step — must reproduce the K=1 trajectory."""
        recs = {}
        for k in (1, 4):
            rec = RecordingSummary()
            opt = (optim.DistriOptimizer(small_mlp(),
                                         mnist_pipeline(320, 32),
                                         nn.ClassNLLCriterion(),
                                         parameter_sharding=True)
                   .set_optim_method(optim.SGD(learning_rate=0.05,
                                               momentum=0.9))
                   .set_steps_per_dispatch(k)
                   .set_seed(5)
                   .set_train_summary(rec)
                   .set_end_when(optim.max_iteration(12)))
            opt.optimize()
            recs[k] = (rec, opt)
        (r1, o1), (r4, o4) = recs[1], recs[4]
        assert r1.steps == r4.steps == list(range(1, 13))
        np.testing.assert_allclose(r1.losses, r4.losses,
                                   rtol=1e-5, atol=1e-7)
        assert o4._dispatch_count < o1._dispatch_count
        for a, b in zip(jax.tree_util.tree_leaves(o1.model._params),
                        jax.tree_util.tree_leaves(o4.model._params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)


class TestConfigSurface:
    def test_engine_default_flows_into_driver(self):
        prev = Engine._state.steps_per_dispatch
        try:
            Engine.set_steps_per_dispatch(4)
            rec, opt = run_local(None, n=2048, batch=16, iters=8)
            assert opt._dispatch_count == 2  # 8 iters / K=4
        finally:
            Engine._state.steps_per_dispatch = prev

    def test_invalid_k_rejected(self):
        with pytest.raises(ValueError):
            Engine.set_steps_per_dispatch(0)
        with pytest.raises(ValueError):
            LocalOptimizer(small_mlp(), mnist_pipeline(64, 32),
                           nn.ClassNLLCriterion()).set_steps_per_dispatch(0)

    def test_config_env_field_exists(self):
        from bigdl_tpu.utils.config import Config
        assert Config().steps_per_dispatch == 1


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
