"""int8 quantized speed-path gates (the quantized-inference PR).

Acceptance surface:

- **Kernel parity**: the pallas int8 GEMM (interpret mode on CPU — the
  REAL kernel body) is BITWISE-equal to its XLA fallback under jit, in
  both activation modes, for f32 and bf16 activations, with and
  without bias, across row-block overrides and the N=1 gemv edge.
  Both sides are jitted: eager XLA constant-folds reductions in a
  different order, which is a property of eager dispatch, not of the
  kernel (ops/PALLAS_NOTES.md "int8 mixed-precision GEMM").
- **supported() gate**: unaligned K/O, oversized panels, non-float
  activation dtypes silently take the XLA quantized chain — same
  bitwise result through ``impl="pallas"`` as ``impl="xla"``.
- **kernel_impl resolution**: per-call ``impl=`` > Engine/Config/env,
  probed through the kernel builder's lru_cache (the only observable
  difference between the two bitwise-identical paths on CPU).
- **Model-level tolerance**: quantized LeNet-5 and Wide&Deep forward
  within documented bounds of their float twins, both modes.
- **Serving gate**: f32 -> int8 ``HotCutover`` under staged load with
  zero dropped/wrong requests; a poisoned int8 rollout trips its
  circuit breaker and latest-wins routing falls back to the f32
  incumbent; ``weights_dtype`` rides ``stats()`` and the /metrics
  scrape via the pre-created ``serving/weights_dtype_code`` gauge.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.engine import Engine
from bigdl_tpu.ops import pallas_int8_gemm, resolve_kernel_impl
from bigdl_tpu.ops.pallas_int8_gemm import (MODES, dyn_quantize,
                                            int8_matmul, supported)


@pytest.fixture
def _kernel_impl_guard():
    prev = Engine._state.kernel_impl
    yield
    Engine._state.kernel_impl = prev


def _panel(rng, k, o, bias=True):
    """A quantized weight panel + optional bias, reproducible."""
    wq = rng.integers(-127, 128, (o, k)).astype(np.int8)
    ws = rng.uniform(0.001, 0.02, (o, 1)).astype(np.float32)
    b = rng.normal(0, 1, (o,)).astype(np.float32) if bias else None
    return jnp.asarray(wq), jnp.asarray(ws), \
        None if b is None else jnp.asarray(b)


def _jit_matmul(**kw):
    """Jitted int8_matmul with static config baked — bitwise parity
    only holds jit-vs-jit (module docstring)."""
    return jax.jit(lambda x, wq, ws, b: int8_matmul(x, wq, ws, b, **kw))


# ===========================================================================
class TestSupportedGate:
    def test_alignment_and_budget(self):
        assert supported(4, 128, 128, jnp.float32)
        assert supported(1, 256, 512, jnp.bfloat16, mode="dynamic")
        # K and O must already be 128-multiples (no contraction padding)
        assert not supported(4, 130, 128, jnp.float32)
        assert not supported(4, 128, 100, jnp.float32)
        # panel element budget (PROVISIONAL, PALLAS_NOTES.md §int8)
        assert not supported(4, 2048, 4096, jnp.float32)  # 8.4M > 6M
        assert supported(4, 2048, 2048, jnp.float32)      # 4.2M fits
        # degenerate dims
        assert not supported(0, 128, 128, jnp.float32)

    def test_dtype_and_mode_gates(self):
        assert not supported(4, 128, 128, jnp.int8)
        assert not supported(4, 128, 128, jnp.float64)
        assert not supported(4, 128, 128, jnp.float32, mode="static")

    def test_bad_mode_raises_at_call(self):
        x = jnp.zeros((2, 128), jnp.float32)
        wq, ws, b = _panel(np.random.default_rng(0), 128, 128)
        with pytest.raises(ValueError, match="activation mode"):
            int8_matmul(x, wq, ws, b, mode="static")


# ===========================================================================
class TestKernelParityBitwise:
    """impl="pallas" (interpret on CPU) vs impl="xla", both jitted —
    must be ARRAY-EQUAL, not allclose."""

    @pytest.mark.parametrize("mode", MODES)
    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("n,k,o", [(1, 128, 128), (8, 128, 256),
                                       (300, 256, 128)])
    def test_modes_dtypes_shapes(self, mode, dtype, n, k, o):
        rng = np.random.default_rng(42)
        x = jnp.asarray(rng.normal(0, 1, (n, k)), dtype)
        wq, ws, b = _panel(rng, k, o)
        assert supported(n, k, o, dtype, mode)
        ys = {impl: np.asarray(_jit_matmul(mode=mode, impl=impl)(
            x, wq, ws, b)) for impl in ("pallas", "xla")}
        assert ys["pallas"].dtype == np.float32
        assert np.array_equal(ys["pallas"], ys["xla"])

    @pytest.mark.parametrize("bias", [True, False])
    @pytest.mark.parametrize("block_rows", [32, 64, 128])
    def test_block_row_overrides(self, bias, block_rows):
        rng = np.random.default_rng(3)
        n, k, o = 100, 128, 128
        x = jnp.asarray(rng.normal(0, 1, (n, k)), jnp.float32)
        wq, ws, b = _panel(rng, k, o, bias=bias)
        ref = np.asarray(_jit_matmul(mode="weight_only", impl="xla")(
            x, wq, ws, b))
        got = np.asarray(_jit_matmul(mode="weight_only", impl="pallas",
                                     block_rows=block_rows)(x, wq, ws, b))
        assert np.array_equal(got, ref)

    def test_dynamic_mode_is_integer_exact(self):
        """Activations already on the int8 grid round-trip exactly —
        int32 accumulation has no float rounding to hide behind."""
        rng = np.random.default_rng(5)
        k, o = 128, 128
        wq, ws, _ = _panel(rng, k, o, bias=False)
        xi = rng.integers(-127, 128, (4, k)).astype(np.float32)
        y = np.asarray(_jit_matmul(mode="dynamic", impl="pallas")(
            jnp.asarray(xi), wq, ws, None))
        # manual reference: per-tensor scale is amax/127, here amax=127
        want = (xi.astype(np.int64) @ np.asarray(wq).T.astype(np.int64)
                ).astype(np.float32) * np.asarray(ws).reshape(-1)
        np.testing.assert_allclose(y, want, rtol=1e-6)

    def test_dyn_quantize_scheme(self):
        x = jnp.asarray([[1.0, -2.0, 0.5, 127.0]], jnp.float32)
        q, s = dyn_quantize(x)
        assert q.dtype == jnp.int8
        np.testing.assert_allclose(np.asarray(s), 1.0)  # amax/127
        np.testing.assert_array_equal(np.asarray(q),
                                      [[1, -2, 0, 127]])


# ===========================================================================
class TestFallbackContract:
    def test_unsupported_shape_silently_falls_back_bitwise(self):
        """impl="pallas" on a shape supported() rejects must produce
        the UNTOUCHED baseline — bitwise-equal to impl="xla", no
        error, no warning path."""
        rng = np.random.default_rng(9)
        n, k, o = 4, 130, 96  # both dims unaligned
        assert not supported(n, k, o, jnp.float32)
        x = jnp.asarray(rng.normal(0, 1, (n, k)), jnp.float32)
        wq = jnp.asarray(rng.integers(-127, 128, (o, k)), jnp.int8)
        ws = jnp.asarray(rng.uniform(0.001, 0.02, (o, 1)), jnp.float32)
        for mode in MODES:
            ys = {impl: np.asarray(_jit_matmul(mode=mode, impl=impl)(
                x, wq, ws, None)) for impl in ("pallas", "xla")}
            assert np.array_equal(ys["pallas"], ys["xla"]), mode

    def test_kernel_engages_only_when_resolved_pallas(
            self, _kernel_impl_guard):
        """The lru_cached kernel builder is the observable boundary
        between the two bitwise-identical paths: xla resolution must
        never build a kernel; pallas resolution must."""
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(0, 1, (4, 128)), jnp.float32)
        wq, ws, b = _panel(rng, 128, 128)
        pallas_int8_gemm._gemm_fn.cache_clear()
        Engine.set_kernel_impl("xla")
        int8_matmul(x, wq, ws, b)  # engine default: xla
        assert pallas_int8_gemm._gemm_fn.cache_info().currsize == 0
        int8_matmul(x, wq, ws, b, impl="pallas")  # per-call wins
        assert pallas_int8_gemm._gemm_fn.cache_info().currsize == 1
        Engine.set_kernel_impl("pallas")
        int8_matmul(x, wq, ws, b)  # engine-level engages too
        assert pallas_int8_gemm._gemm_fn.cache_info().currsize == 1
        int8_matmul(x, wq, ws, b, impl="xla")  # per-call disables
        assert pallas_int8_gemm._gemm_fn.cache_info().currsize == 1

    def test_auto_resolves_xla_off_tpu(self, _kernel_impl_guard):
        Engine.set_kernel_impl("auto")
        assert resolve_kernel_impl(None) == "xla"


# ===========================================================================
class TestModelTolerance:
    """Whole-model quantized forward vs the float twin — the
    documented error bounds (weight_only: weight rounding only;
    dynamic: + per-tensor activation rounding)."""

    TOL = {"weight_only": 0.03, "dynamic": 0.05}
    # the deep MLP compounds per-layer rounding through two 128-wide
    # GEMMs before the sigmoid head, so its bound is looser than the
    # single-layer ones in test_quantized.py (observed ~0.043
    # weight_only on this fixture)
    DEEP_TOL = {"weight_only": 0.08, "dynamic": 0.12}

    @pytest.mark.parametrize("mode", MODES)
    def test_lenet5(self, mode):
        from bigdl_tpu.models.lenet import lenet5
        from bigdl_tpu.nn.quantized import quantize
        m = lenet5(10)
        m.initialize(0)
        m.training = False
        rng = np.random.default_rng(0)
        x = rng.normal(0, 1, (4, 28 * 28)).astype(np.float32)
        ref = np.asarray(m.forward(x))
        q = quantize(m, mode=mode)
        out = np.asarray(q.forward(x))
        err = np.max(np.abs(out - ref)) / (np.max(np.abs(ref)) + 1e-6)
        assert err < self.TOL[mode], (mode, err)
        # the prediction survives quantization wherever the float net
        # is actually decisive: rows whose top-2 softmax margin clears
        # twice the worst-case perturbation must keep their argmax
        # (near-ties on a random-init net may legitimately flip)
        top2 = np.sort(ref, -1)[:, -2:]
        decisive = (top2[:, 1] - top2[:, 0]) > 2 * np.max(
            np.abs(out - ref))
        assert (np.argmax(out, -1) == np.argmax(ref, -1))[decisive].all()

    @pytest.mark.parametrize("mode", MODES)
    def test_wide_deep_mlp(self, mode):
        """Wide&Deep with its deep MLP quantized (128-wide hidden
        layers so the GEMM gate passes) — the embedding/sparse paths
        stay float, matching the reference's mixed graph."""
        import copy

        from bigdl_tpu import models
        from bigdl_tpu.nn.quantized import QuantizedLinear, quantize
        from bigdl_tpu.nn.sparse import COOBatch
        rng = np.random.default_rng(2)
        wide_dim, fields, dense_dim = 80, [10, 8], 12
        m = models.WideAndDeep(wide_dim, fields, dense_dim,
                               embed_dim=58, hidden=(128, 128))
        m.initialize(0)
        m.training = False
        n = 6
        row = np.repeat(np.arange(n), 3).astype(np.int32)
        col = rng.integers(0, wide_dim, 3 * n).astype(np.int32)
        val = np.ones(3 * n, np.float32)
        x = (COOBatch(jnp.asarray(row), jnp.asarray(col),
                      jnp.asarray(val), (n, wide_dim)),
             jnp.asarray(rng.integers(0, 8, (n, len(fields))),
                         jnp.int32),
             jnp.asarray(rng.normal(0, 1, (n, dense_dim)), jnp.float32))
        ref = np.asarray(m.forward(x))
        q = copy.copy(m)
        q.deep = quantize(m.deep, mode=mode)  # deep in = 2*58+12 = 128
        assert isinstance(q.deep.modules[0], QuantizedLinear)
        q._params, q._state = m._params, m._state
        out = np.asarray(q.forward(x))
        err = np.max(np.abs(out - ref)) / (np.max(np.abs(ref)) + 1e-6)
        assert err < self.DEEP_TOL[mode], (mode, err)


# ===========================================================================
class TestServingGate:
    """deploy(quantize=True) + breaker-gated rollback + hot cutover."""

    DIN = 128  # kernel-eligible feature width

    def _model(self, din=None, seed=0):
        din = din or self.DIN
        return nn.Sequential(nn.Linear(din, 128), nn.ReLU(),
                             nn.Linear(128, 4),
                             nn.SoftMax()).initialize(seed)

    def _spec(self, din=None):
        return ((din or self.DIN,), np.float32)

    def test_weights_dtype_in_stats_and_metrics_scrape(self):
        from bigdl_tpu.serving import ModelRegistry
        from bigdl_tpu.serving.metrics import ServingMetrics
        from bigdl_tpu.telemetry.admin import render_prometheus
        reg = ModelRegistry()
        try:
            reg.deploy("m", self._model(), input_spec=self._spec())
            reg.deploy("m", self._model(), input_spec=self._spec(),
                       quantize=True)
            s1 = reg.get("m", 1).stats()
            s2 = reg.get("m", 2).stats()
            assert s1["weights_dtype"] == "f32"
            assert s2["weights_dtype"] == "int8"
            # the pre-created gauge renders on a /metrics scrape with
            # bounded cardinality (a dtype CODE, not a label per dtype)
            svc2 = reg.get("m", 2)
            text = render_prometheus(
                {"m:v2": svc2.metrics.registry.snapshot()})
            code = ServingMetrics.WEIGHTS_DTYPE_CODES["int8"]
            assert "serving_weights_dtype_code" in text
            assert f'{{source="m:v2"}} {float(code)}' in text
        finally:
            reg.stop_all()

    def test_quantize_mode_string_pins_mode(self):
        from bigdl_tpu.nn.quantized import QuantizedLinear
        from bigdl_tpu.serving import ModelRegistry
        reg = ModelRegistry()
        try:
            svc = reg.deploy("m", self._model(), input_spec=self._spec(),
                             quantize="dynamic")
            assert isinstance(svc.model.modules[0], QuantizedLinear)
            assert svc.model.modules[0].mode == "dynamic"
            assert svc.stats()["weights_dtype"] == "int8"
        finally:
            reg.stop_all()

    def test_breaker_trips_bad_int8_rollout_back_to_f32(self):
        """A misdeployed int8 version (its spec cannot serve the live
        traffic shape) fails requests until its breaker opens; latest-
        wins routing then falls back to the f32 incumbent WITHOUT
        callers pinning a version."""
        from bigdl_tpu.serving import ModelRegistry, RequestSpecError
        reg = ModelRegistry(breaker_trip_after=3, breaker_cooldown_s=60)
        try:
            reg.deploy("m", self._model(), input_spec=self._spec())
            # the bad rollout: quantized, but deployed for 64-wide rows
            reg.deploy("m", self._model(din=64), quantize=True,
                       input_spec=self._spec(din=64))
            rng = np.random.default_rng(0)
            x = rng.normal(0, 1, (2, self.DIN)).astype(np.float32)
            ref = np.asarray(reg.get("m", 1).predict(x, timeout=60))
            failures = 0
            for _ in range(3):  # trip_after consecutive failures
                with pytest.raises(RequestSpecError):
                    reg.predict("m", x, timeout=60)
                failures += 1
            assert failures == 3
            assert reg.breaker_state("m", 2)["open"]
            # breaker open -> latest-wins serves the f32 incumbent
            for _ in range(4):
                out = np.asarray(reg.predict("m", x, timeout=60))
                np.testing.assert_array_equal(out, ref)
            assert reg.get("m", 1).stats()["weights_dtype"] == "f32"
        finally:
            reg.stop_all()

    def test_hot_cutover_f32_to_int8_zero_drops(self):
        """Staged load while HotCutover flips f32 -> int8: every
        request answers (zero drops) and every answer matches either
        the float reference or the int8 reference within the
        weight_only bound — no torn/garbage outputs mid-flip."""
        from bigdl_tpu.frontend import HotCutover
        from bigdl_tpu.nn.quantized import quantize
        from bigdl_tpu.serving import ModelRegistry
        model = self._model()
        reg = ModelRegistry()
        try:
            reg.deploy("hot", model, input_spec=self._spec(),
                       max_batch_size=8, queue_capacity=1024)
            rng = np.random.default_rng(7)
            n_threads, per_thread = 4, 30
            xs = [rng.normal(0, 1, (1, self.DIN)).astype(np.float32)
                  for _ in range(n_threads)]
            f32_refs = [np.asarray(model.forward(x)) for x in xs]
            q_model = quantize(model, mode="weight_only")
            q_refs = [np.asarray(q_model.forward(x)) for x in xs]
            bad = []
            barrier = threading.Barrier(n_threads + 1)

            def client(t):
                barrier.wait()
                for i in range(per_thread):
                    try:
                        out = np.asarray(
                            reg.predict("hot", xs[t], timeout=60))
                    except Exception as e:  # a drop — the gate fails
                        bad.append((t, i, f"{type(e).__name__}: {e}"))
                        continue
                    d32 = np.max(np.abs(out - f32_refs[t]))
                    dq = np.max(np.abs(out - q_refs[t]))
                    if min(d32, dq) > 1e-4:
                        bad.append((t, i, "wrong output", d32, dq))

            threads = [threading.Thread(target=client, args=(t,))
                       for t in range(n_threads)]
            for th in threads:
                th.start()
            barrier.wait()
            cut = HotCutover(reg)
            report = cut.deploy("hot", model, quantize=True,
                                max_batch_size=8, queue_capacity=1024)
            for th in threads:
                th.join()
            assert bad == []  # zero dropped, zero wrong
            assert report["new_version"] == 2
            assert report["old_undeployed"]
            assert reg.get("hot").stats()["weights_dtype"] == "int8"
            # post-cutover traffic serves the int8 twin
            out = np.asarray(reg.predict("hot", xs[0], timeout=60))
            np.testing.assert_allclose(out, q_refs[0], atol=1e-5)
        finally:
            reg.stop_all()
