"""RowTransformer + feature columns (VERDICT r3 item 7; reference
RowTransformer.scala and nn/ops feature-column ops)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import (BucketizedCol, CategoricalColHashBucket,
                               CategoricalColVocaList, ColsToNumeric,
                               ColToTensor, CrossCol, IndicatorCol,
                               RowTransformer)
from bigdl_tpu.nn.sparse import COOBatch


class TestRowTransformer:
    ROWS = [("alice", "engineer", 34.0, 1.5),
            ("bob", "teacher", 28.0, -0.5)]
    FIELDS = ["name", "job", "age", "score"]

    def test_atomic(self):
        t = RowTransformer.atomic(self.FIELDS)
        out = list(t(iter(self.ROWS)))
        assert out[0]["name"] == "alice"
        assert float(out[1]["age"]) == 28.0

    def test_numeric_group(self):
        t = RowTransformer.numeric("feats", ["age", "score"])
        t.field_names = self.FIELDS
        out = list(t(iter(self.ROWS)))
        np.testing.assert_allclose(out[0]["feats"], [34.0, 1.5])

    def test_mixed_schemas_and_dict_rows(self):
        t = RowTransformer([ColToTensor("who", "name"),
                            ColsToNumeric("x", ["age", "score"])])
        row = dict(zip(self.FIELDS, self.ROWS[0]))
        out = t.transform_row(row)
        assert out["who"] == "alice"
        np.testing.assert_allclose(out["x"], [34.0, 1.5])

    def test_duplicate_keys_rejected(self):
        with pytest.raises(ValueError):
            RowTransformer([ColToTensor("k", "a"), ColToTensor("k", "b")])


class TestFeatureColumns:
    def test_bucketized_col_reference_example(self):
        # reference BucketizedCol doc example: boundaries [0, 10, 100]
        b = BucketizedCol([0, 10, 100])
        x = np.asarray([[-1, 1], [101, 10], [5, 100]], np.float64)
        np.testing.assert_array_equal(b(x), [[0, 1], [3, 2], [1, 3]])

    def test_hash_bucket_deterministic_and_in_range(self):
        h = CategoricalColHashBucket(hash_bucket_size=10)
        coo = h(["a,b", "c", ""])
        assert isinstance(coo, COOBatch)
        assert coo.dense_shape == (3, 10)
        dense = np.asarray(coo.to_dense())
        assert dense[0].sum() == 2 and dense[1].sum() == 1
        assert dense[2].sum() == 0  # missing value -> no ids
        coo2 = h(["a,b", "c", ""])
        np.testing.assert_array_equal(np.asarray(coo.col),
                                      np.asarray(coo2.col))

    def test_voca_list_oov_modes(self):
        v = CategoricalColVocaList(["cat", "dog"])
        assert np.asarray(v(["cat,hamster"]).to_dense()).sum() == 1  # dropped
        vd = CategoricalColVocaList(["cat", "dog"], is_set_default=True)
        d = np.asarray(vd(["hamster"]).to_dense())
        assert d[0, 2] == 1  # default id = len(vocab)
        vo = CategoricalColVocaList(["cat", "dog"], num_oov_buckets=3)
        d = np.asarray(vo(["hamster"]).to_dense())
        assert d.shape == (1, 5) and d[0, 2:].sum() == 1
        with pytest.raises(ValueError):
            CategoricalColVocaList(["x"], is_set_default=True,
                                   num_oov_buckets=2)

    def test_cross_col_cartesian(self):
        c = CrossCol(hash_bucket_size=50)
        coo = c([["A,D", "B", "A,C"], ["1", "2", "3,4"]])
        dense = np.asarray(coo.to_dense())
        # row 0: 2x1 combos, row 1: 1, row 2: 2x2 (reference doc example)
        assert dense[0].sum() == 2
        assert dense[1].sum() == 1
        assert dense[2].sum() == 4

    def test_indicator_col_count_semantics(self):
        coo = COOBatch(jnp.asarray([0, 0, 1, 2, 2], jnp.int32),
                       jnp.asarray([1, 2, 2, 3, 3], jnp.int32),
                       jnp.ones(5), (3, 4))
        ind = IndicatorCol(4)(coo)
        np.testing.assert_array_equal(
            ind, [[0, 1, 1, 0], [0, 0, 1, 0], [0, 0, 0, 2]])
        ind01 = IndicatorCol(4, is_count=False)(coo)
        assert ind01[2, 3] == 1.0


class TestWideDeepFromCSV:
    """The verdict's 'Done' case: Wide&Deep ingests a CSV-like table
    through RowTransformer + feature columns."""

    def test_csv_to_training(self):
        rng = np.random.default_rng(0)
        jobs = ["eng", "doc", "art", "law"]
        cities = ["nyc", "sfo", "chi"]
        rows = []
        for _ in range(256):
            j = jobs[rng.integers(0, 4)]
            c = cities[rng.integers(0, 3)]
            age = float(rng.integers(20, 70))
            # structured label: depends on the (job, city) cross
            label = 1.0 if (j in ("eng", "doc")) == (c == "nyc") else 0.0
            rows.append((j, c, age, label))

        rt = RowTransformer.atomic(["job", "city", "age", "label"])
        cols = {k: [r[k] for r in rt(iter(rows))]
                for k in ("job", "city", "age", "label")}

        job_col = CategoricalColVocaList(jobs)
        # 1024 buckets: with fewer, birthday collisions among the 12
        # true (job, city) crosses merge opposite-label combos and cap
        # the attainable accuracy (at 256, two such collisions occur)
        cross = CrossCol(hash_bucket_size=1024)
        bucket = BucketizedCol([30, 40, 50, 60])

        wide_join = nn.SparseJoinTable([len(jobs), 1024])
        coo_job = job_col(cols["job"])
        coo_cross = cross([cols["job"], cols["city"]])
        wide, _ = wide_join.apply({}, {}, [coo_job, coo_cross])

        deep_ids = np.stack([bucket(cols["age"])], 1).astype(np.int32)
        dense = (np.asarray(cols["age"], np.float32)[:, None] - 40.0) / 20.0
        y = jnp.asarray(np.asarray(cols["label"], np.float32))

        from bigdl_tpu import models
        model = models.WideAndDeep(len(jobs) + 1024, [5], 1, embed_dim=4,
                                   hidden=(16,))
        p, st = model.init(jax.random.PRNGKey(0))
        method = optim.Adam(learning_rate=0.03)
        os_ = method.init_state(p)
        crit = nn.BCECriterion()

        @jax.jit
        def step(p, os_, it):
            def loss_fn(p):
                out, _ = model.apply(
                    p, st, (wide, jnp.asarray(deep_ids),
                            jnp.asarray(dense)))
                return crit.apply(out[:, 0], y)
            loss, g = jax.value_and_grad(loss_fn)(p)
            p, os_ = method.update(g, p, os_, 0.03, it)
            return p, os_, loss

        losses = []
        for it in range(400):
            p, os_, loss = step(p, os_, it)
            losses.append(float(loss))
        assert losses[-1] < 0.25, (losses[0], losses[-1])
        out, _ = model.apply(p, st, (wide, jnp.asarray(deep_ids),
                                     jnp.asarray(dense)))
        acc = float(((np.asarray(out)[:, 0] > 0.5) ==
                     (np.asarray(y) > 0.5)).mean())
        assert acc > 0.9, acc
