"""Queue-fed TF graphs + TensorArray import (VERDICT r3 items 4;
reference Session.scala:111-165, DataFlowOps.scala)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu.dataset import tfrecord
from bigdl_tpu.interop.session import TFSession
from bigdl_tpu.utils import protowire as pw

from tfgraph_util import (node, attr_tensor, scalar_const, shape_const,
                          string_const, int_scalar_const, attr_int,
                          attr_type, enter, build_queue_graph)


def build_dynrnn_graph(T, B, I, H, rng):
    """Dynamic-RNN-style export: input scattered into a TensorArray,
    a while loop reading x_t / writing h_t via TensorArray ops, and a
    post-loop TensorArrayGather of the outputs (the tf.nn.dynamic_rnn
    wire pattern; reference DataFlowOps.scala)."""
    W = rng.normal(0, 0.5, (I, H)).astype(np.float32)
    U = rng.normal(0, 0.5, (H, H)).astype(np.float32)
    idx_t = pw.enc_bytes(8, (pw.enc_varint(1, 3)
                             + pw.enc_bytes(2, pw.enc_bytes(
                                 2, pw.enc_varint(1, T)))
                             + pw.enc_bytes(4, np.arange(
                                 T, dtype=np.int32).tobytes())))
    g = (node("x", "Placeholder")
         + node("Wc", "Const", value=attr_tensor(W))
         + node("Uc", "Const", value=attr_tensor(U))
         + node("h0", "Const", value=attr_tensor(np.zeros((B, H))))
         + node("T_n", "Const", value=int_scalar_const(T))
         + node("zero_i", "Const", value=int_scalar_const(0))
         + node("one_i", "Const", value=int_scalar_const(1))
         + node("range_t", "Const", value=idx_t)
         # input TA, filled before the loop
         + node("in_ta", "TensorArrayV3", ["T_n"], dtype=attr_type(1))
         + node("in_flow", "TensorArrayScatterV3",
                ["in_ta", "range_t", "x", "in_ta:1"])
         # output TA, written inside the loop
         + node("out_ta", "TensorArrayV3", ["T_n"], dtype=attr_type(1))
         # while frame
         + enter("t_ent", ["zero_i"], "rnn")
         + enter("h_ent", ["h0"], "rnn")
         + enter("of_ent", ["out_ta:1"], "rnn")
         + node("t_mrg", "Merge", ["t_ent", "t_ni"])
         + node("h_mrg", "Merge", ["h_ent", "h_ni"])
         + node("of_mrg", "Merge", ["of_ent", "of_ni"])
         + node("lt", "Less", ["t_mrg", "T_n"])
         + node("lc", "LoopCond", ["lt"])
         + node("t_sw", "Switch", ["t_mrg", "lc"])
         + node("h_sw", "Switch", ["h_mrg", "lc"])
         + node("of_sw", "Switch", ["of_mrg", "lc"])
         + node("x_t", "TensorArrayReadV3", ["in_ta", "t_sw:1", "in_flow"])
         + node("xw", "MatMul", ["x_t", "Wc"])
         + node("hu", "MatMul", ["h_sw:1", "Uc"])
         + node("s", "Add", ["xw", "hu"])
         + node("h_new", "Tanh", ["s"])
         + node("of_w", "TensorArrayWriteV3",
                ["out_ta", "t_sw:1", "h_new", "of_sw:1"])
         + node("t_add", "Add", ["t_sw:1", "one_i"])
         + node("t_ni", "NextIteration", ["t_add"])
         + node("h_ni", "NextIteration", ["h_new"])
         + node("of_ni", "NextIteration", ["of_w"])
         + node("t_exit", "Exit", ["t_sw:0"])
         + node("h_exit", "Exit", ["h_sw:0"])
         + node("of_exit", "Exit", ["of_sw:0"])
         # stack outputs after the loop
         + node("ys", "TensorArrayGatherV3",
                ["out_ta", "range_t", "of_exit"])
         + node("out", "Identity", ["ys"]))
    return g, W, U


class TestTensorArrayRNN:
    def _reference(self, x, W, U):
        T, B = x.shape[0], x.shape[1]
        h = np.zeros((B, U.shape[0]), np.float32)
        ys = []
        for t in range(T):
            h = np.tanh(x[t] @ W + h @ U)
            ys.append(h)
        return np.stack(ys)

    def test_imports_and_matches_numpy(self, tmp_path):
        from bigdl_tpu.interop.tf_format import load_tf_graph
        rng = np.random.default_rng(0)
        T, B, I, H = 5, 3, 4, 6
        g, W, U = build_dynrnn_graph(T, B, I, H, rng)
        p = str(tmp_path / "dynrnn.pb")
        open(p, "wb").write(g)
        m = load_tf_graph(p, inputs=["x"], outputs=["out"])
        x = rng.normal(0, 1, (T, B, I)).astype(np.float32)
        out = np.asarray(m.forward(x))
        assert out.shape == (T, B, H)
        np.testing.assert_allclose(out, self._reference(x, W, U),
                                   rtol=2e-5, atol=2e-5)

    def test_differentiable_through_tensorarray_loop(self, tmp_path):
        """The bounded loop compiles to lax.scan, so the imported RNN
        TRAINS: gradient wrt the input flows through TensorArray
        read/write."""
        from bigdl_tpu.interop.tf_format import load_tf_graph
        rng = np.random.default_rng(1)
        T, B, I, H = 4, 2, 3, 5
        g, W, U = build_dynrnn_graph(T, B, I, H, rng)
        p = str(tmp_path / "dynrnn2.pb")
        open(p, "wb").write(g)
        m = load_tf_graph(p, inputs=["x"], outputs=["out"])
        x = jnp.asarray(rng.normal(0, 1, (T, B, I)).astype(np.float32))

        def loss(x):
            out, _ = m.apply({}, {}, {"x": x})
            return jnp.sum(out ** 2)

        grad = jax.jit(jax.grad(loss))(x)
        assert grad.shape == x.shape
        # numerical check on one coordinate
        eps = 1e-3
        xp = x.at[1, 0, 2].add(eps)
        xm = x.at[1, 0, 2].add(-eps)
        num = (float(loss(xp)) - float(loss(xm))) / (2 * eps)
        assert abs(num - float(grad[1, 0, 2])) < 5e-2 * max(1, abs(num))


class TestQueueFedTraining:
    def test_e2e_tfrecord_queue_train(self, tmp_path):
        # data: y = x @ [1, -2, 3, 0.5]
        rng = np.random.default_rng(0)
        true_w = np.float32([1.0, -2.0, 3.0, 0.5])
        records = []
        for _ in range(64):
            x = rng.normal(0, 1, 4).astype(np.float32)
            y = np.float32(x @ true_w)
            records.append(np.concatenate([x, [y]]).tobytes())
        rec_path = str(tmp_path / "train.tfrecord")
        tfrecord.write_records(rec_path, records)

        pb = str(tmp_path / "g.pb")
        with open(pb, "wb") as f:
            f.write(build_queue_graph(rec_path))

        from bigdl_tpu import optim
        sess = TFSession(pb, outputs=["loss"])
        assert sess.pipeline is not None
        assert sess.pipeline.batch_size == 8
        losses = sess.train(optim_method=optim.SGD(learning_rate=0.1),
                            epochs=25)
        assert len(losses) == 8 * 25
        assert losses[-1] < losses[0] * 0.05, (losses[0], losses[-1])
        # trained weights approach the generator
        w = np.asarray(sess.graph._params["W"]).reshape(-1)
        np.testing.assert_allclose(w, true_w, atol=0.15)

    def test_cached_const_enqueue(self, tmp_path):
        """Session.scala's 'cached' case: EnqueueMany of constant
        tensors, no reader."""
        xs = np.arange(12, dtype=np.float32).reshape(6, 2)
        g = b""
        g += node("data", "Const", value=attr_tensor(xs))
        g += node("q", "FIFOQueueV2")
        g += node("enq", "QueueEnqueueManyV2", ["q", "data"])
        g += node("n", "Const", value=int_scalar_const(3))
        g += node("dq", "QueueDequeueManyV2", ["q", "n"])
        g += node("two", "Const", value=scalar_const(2.0))
        g += node("out", "Mul", ["dq", "two"])
        pb = str(tmp_path / "cached.pb")
        with open(pb, "wb") as f:
            f.write(g)
        sess = TFSession(pb, outputs=["out"])
        feeds = list(sess.pipeline.batches())
        assert len(feeds) == 2
        out = sess.run({k: v for k, v in feeds[0].items()})
        np.testing.assert_allclose(np.asarray(out), xs[:3] * 2)

    def test_shuffle_queue_reorders(self, tmp_path):
        recs = [np.float32([i]).tobytes() for i in range(32)]
        rec_path = str(tmp_path / "s.tfrecord")
        tfrecord.write_records(rec_path, recs)
        g = b""
        g += node("filenames", "Const", value=string_const([rec_path]))
        g += node("fq", "FIFOQueueV2")
        g += node("fq_enq", "QueueEnqueueManyV2", ["fq", "filenames"])
        g += node("reader", "TFRecordReaderV2")
        g += node("read", "ReaderReadV2", ["reader", "fq"])
        g += node("v", "DecodeRaw", ["read:1"], out_type=attr_type(1))
        g += node("q", "RandomShuffleQueueV2")
        g += node("enq", "QueueEnqueueV2", ["q", "v"])
        g += node("n", "Const", value=int_scalar_const(32))
        g += node("dq", "QueueDequeueManyV2", ["q", "n"])
        g += node("out", "Identity", ["dq"])
        pb = str(tmp_path / "shuf.pb")
        with open(pb, "wb") as f:
            f.write(g)
        sess = TFSession(pb, outputs=["out"])
        batch = next(iter(sess.pipeline.batches(seed=3)))
        vals = batch["dq:0"].reshape(-1)
        assert sorted(vals.tolist()) == list(range(32))
        assert vals.tolist() != list(range(32))  # actually shuffled
