"""Test harness config.

Mirrors the reference's distributed-without-a-cluster test trick
(``TEST/optim/DistriOptimizerSpec.scala:139`` uses ``local[1]`` Spark): we
run every test on a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count=8`` so sharding/collective paths
are exercised without TPU hardware.  MUST be set before jax import.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)

import jax  # noqa: E402

# NOTE: the env var JAX_PLATFORMS is stomped by the axon TPU plugin in this
# image; the config API wins, so force CPU here (must precede device use).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _reset_engine_mesh():
    """Isolate tests from any globally-set Engine mesh."""
    from bigdl_tpu.engine import Engine
    prev = Engine._state.mesh
    yield
    Engine._state.mesh = prev
