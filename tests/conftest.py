"""Test harness config.

Mirrors the reference's distributed-without-a-cluster test trick
(``TEST/optim/DistriOptimizerSpec.scala:139`` uses ``local[1]`` Spark): we
run every test on a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count=8`` so sharding/collective paths
are exercised without TPU hardware.  MUST be set before jax import.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)

# Lockdep opt-in (BIGDL_TPU_LOCKDEP=1): install the lock-order
# sanitizer BEFORE any product module constructs a lock, so every
# tier-1 run doubles as a deadlock hunt.  The module is loaded
# standalone by file path (registered under its canonical name) —
# importing it through the bigdl_tpu package would drag in the whole
# tree and create product locks ahead of the patch.
_LOCKDEP_MOD = None
if os.environ.get("BIGDL_TPU_LOCKDEP", "").lower() in (
        "1", "true", "yes", "on"):
    import importlib.util
    import sys as _sys
    _ld_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..", "bigdl_tpu", "utils", "lockdep.py")
    _spec = importlib.util.spec_from_file_location(
        "bigdl_tpu.utils.lockdep", _ld_path)
    _LOCKDEP_MOD = importlib.util.module_from_spec(_spec)
    _sys.modules["bigdl_tpu.utils.lockdep"] = _LOCKDEP_MOD
    _spec.loader.exec_module(_LOCKDEP_MOD)
    _LOCKDEP_MOD.install(hold_ms=float(
        os.environ.get("BIGDL_TPU_LOCKDEP_HOLD_MS", "200")))

import jax  # noqa: E402

# NOTE: the env var JAX_PLATFORMS is stomped by the axon TPU plugin in this
# image; the config API wins, so force CPU here (must precede device use).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _reset_engine_mesh():
    """Isolate tests from any globally-set Engine mesh."""
    from bigdl_tpu.engine import Engine
    prev = Engine._state.mesh
    yield
    Engine._state.mesh = prev


def pytest_report_header(config):
    if _LOCKDEP_MOD is not None:
        return ["lockdep: lock-order sanitizer INSTALLED "
                "(BIGDL_TPU_LOCKDEP) — cycles fail the session"]
    return []


def pytest_sessionfinish(session, exitstatus):
    """The lockdep gate: a run under BIGDL_TPU_LOCKDEP=1 fails when
    any lock-order cycle was recorded, with both stacks printed."""
    if _LOCKDEP_MOD is None:
        return
    cycles = _LOCKDEP_MOD.cycles()
    edges = len(_LOCKDEP_MOD.graph_edges())
    slow = len(_LOCKDEP_MOD.slow_holds())
    print(f"\nlockdep: {_LOCKDEP_MOD.proxies_allocated()} locks "
          f"instrumented, {edges} order edges, {len(cycles)} cycles, "
          f"{slow} slow holds")
    if cycles:
        for c in cycles:
            print(c.render())
        session.exitstatus = 1
