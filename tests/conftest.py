"""Test harness config.

Mirrors the reference's distributed-without-a-cluster test trick
(``TEST/optim/DistriOptimizerSpec.scala:139`` uses ``local[1]`` Spark): we
run every test on a virtual 8-device CPU mesh via
``--xla_force_host_platform_device_count=8`` so sharding/collective paths
are exercised without TPU hardware.  MUST be set before jax import.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
)

# Lockdep opt-in (BIGDL_TPU_LOCKDEP=1): install the lock-order
# sanitizer BEFORE any product module constructs a lock, so every
# tier-1 run doubles as a deadlock hunt.  The module is loaded
# standalone by file path (registered under its canonical name) —
# importing it through the bigdl_tpu package would drag in the whole
# tree and create product locks ahead of the patch.
_LOCKDEP_MOD = None
if os.environ.get("BIGDL_TPU_LOCKDEP", "").lower() in (
        "1", "true", "yes", "on"):
    import importlib.util
    import sys as _sys
    _ld_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..", "bigdl_tpu", "utils", "lockdep.py")
    _spec = importlib.util.spec_from_file_location(
        "bigdl_tpu.utils.lockdep", _ld_path)
    _LOCKDEP_MOD = importlib.util.module_from_spec(_spec)
    _sys.modules["bigdl_tpu.utils.lockdep"] = _LOCKDEP_MOD
    _spec.loader.exec_module(_LOCKDEP_MOD)
    _LOCKDEP_MOD.install(hold_ms=float(
        os.environ.get("BIGDL_TPU_LOCKDEP_HOLD_MS", "200")))

# Spmdcheck opt-in (BIGDL_TPU_SPMDCHECK=1): the collective-schedule
# sanitizer (runtime twin of graftlint GL4xx).  Unlike lockdep it
# patches nothing — the driver's note sites gate on the recorder — so
# a plain import before jax is enough.  Loaded standalone by file path
# for the same reason as lockdep: importing through the bigdl_tpu
# package would drag in the whole tree here.
_SPMDCHECK_MOD = None
if os.environ.get("BIGDL_TPU_SPMDCHECK", "").lower() in (
        "1", "true", "yes", "on"):
    import importlib.util
    import sys as _sys2
    _sc_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "..", "bigdl_tpu", "utils", "spmdcheck.py")
    if "bigdl_tpu.utils.spmdcheck" in _sys2.modules:
        _SPMDCHECK_MOD = _sys2.modules["bigdl_tpu.utils.spmdcheck"]
    else:
        _sc_spec = importlib.util.spec_from_file_location(
            "bigdl_tpu.utils.spmdcheck", _sc_path)
        _SPMDCHECK_MOD = importlib.util.module_from_spec(_sc_spec)
        _sys2.modules["bigdl_tpu.utils.spmdcheck"] = _SPMDCHECK_MOD
        _sc_spec.loader.exec_module(_SPMDCHECK_MOD)
    _SPMDCHECK_MOD.install()

import jax  # noqa: E402

# NOTE: the env var JAX_PLATFORMS is stomped by the axon TPU plugin in this
# image; the config API wins, so force CPU here (must precede device use).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(autouse=True)
def _reset_engine_mesh():
    """Isolate tests from any globally-set Engine mesh."""
    from bigdl_tpu.engine import Engine
    prev = Engine._state.mesh
    yield
    Engine._state.mesh = prev


def pytest_report_header(config):
    # additive: each sanitizer contributes its own line, so running
    # both (the composition smoke test) reports both
    lines = []
    if _LOCKDEP_MOD is not None:
        lines.append("lockdep: lock-order sanitizer INSTALLED "
                     "(BIGDL_TPU_LOCKDEP) — cycles fail the session")
    if _SPMDCHECK_MOD is not None:
        lines.append("spmdcheck: collective-schedule sanitizer "
                     "INSTALLED (BIGDL_TPU_SPMDCHECK) — divergences "
                     "fail the session")
    return lines


def pytest_sessionfinish(session, exitstatus):
    """The sanitizer gates: a run under BIGDL_TPU_LOCKDEP=1 fails when
    any lock-order cycle was recorded; a run under
    BIGDL_TPU_SPMDCHECK=1 fails when any collective-schedule
    divergence was recorded.  Each gate reports independently — they
    must not clobber one another when both are live."""
    if _LOCKDEP_MOD is not None:
        cycles = _LOCKDEP_MOD.cycles()
        edges = len(_LOCKDEP_MOD.graph_edges())
        slow = len(_LOCKDEP_MOD.slow_holds())
        print(f"\nlockdep: {_LOCKDEP_MOD.proxies_allocated()} locks "
              f"instrumented, {edges} order edges, {len(cycles)} cycles, "
              f"{slow} slow holds")
        if cycles:
            for c in cycles:
                print(c.render())
            session.exitstatus = 1
    if _SPMDCHECK_MOD is not None:
        # intra-run index mismatches only: emulated participants from
        # different tests legitimately record different-LENGTH
        # schedules, so the length finalizer stays off at session scope
        divs = _SPMDCHECK_MOD.divergences()
        print(f"\nspmdcheck: {_SPMDCHECK_MOD.notes_recorded()} "
              f"collective notes, "
              f"{len(_SPMDCHECK_MOD.schedules())} participants, "
              f"{len(divs)} divergences")
        if divs:
            for d in divs:
                print(d.render())
            session.exitstatus = 1
