"""Semantic tests for the table-op / distance / stochastic layer family
(``bigdl_tpu/nn/tensor_extras.py``; reference ``DL/nn/MM.scala`` etc.)."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn

KEY = jax.random.PRNGKey(0)


def _apply(mod, input, training=False, rng=None):
    params, state = mod.init(KEY)
    out, _ = mod.apply(params, state, input, training=training, rng=rng)
    return out, params


def test_mm_mv_dot():
    a = jax.random.normal(KEY, (4, 3, 5))
    b = jax.random.normal(jax.random.fold_in(KEY, 1), (4, 5, 2))
    out, _ = _apply(nn.MM(), (a, b))
    np.testing.assert_allclose(out, jnp.matmul(a, b), rtol=1e-6)
    out, _ = _apply(nn.MM(trans_a=True), (jnp.swapaxes(a, -1, -2), b))
    np.testing.assert_allclose(out, jnp.matmul(a, b), rtol=1e-6)

    v = jax.random.normal(KEY, (4, 5))
    out, _ = _apply(nn.MV(), (a, v))
    np.testing.assert_allclose(out, jnp.einsum("nij,nj->ni", a, v), rtol=1e-5)

    x = jax.random.normal(KEY, (6, 7))
    y = jax.random.normal(jax.random.fold_in(KEY, 2), (6, 7))
    out, _ = _apply(nn.DotProduct(), (x, y))
    np.testing.assert_allclose(out, jnp.sum(x * y, -1), rtol=1e-5)


def test_cross_product_order():
    xs = [jnp.ones((2, 3)) * i for i in (1.0, 2.0, 3.0)]
    out, _ = _apply(nn.CrossProduct(), xs)
    # pairs (1,2),(1,3),(2,3) -> dot = 3*prod
    np.testing.assert_allclose(out[0], [6.0, 9.0, 18.0])


def test_distances():
    x = jnp.array([[3.0, 0.0], [0.0, 4.0]])
    y = jnp.zeros((2, 2))
    out, _ = _apply(nn.PairwiseDistance(2), (x, y))
    np.testing.assert_allclose(out, [3.0, 4.0], rtol=1e-6)

    out, _ = _apply(nn.CosineDistance(), (x, x))
    np.testing.assert_allclose(out, [1.0, 1.0], rtol=1e-5)

    mod = nn.Euclidean(2, 3)
    out, params = _apply(mod, x)
    want = np.linalg.norm(np.asarray(x)[:, None] - np.asarray(
        params["weight"])[None], axis=-1)
    np.testing.assert_allclose(out, want, rtol=1e-5)

    mod = nn.Cosine(2, 3)
    out, params = _apply(mod, x)
    w = np.asarray(params["weight"])
    want = (np.asarray(x) @ w.T) / (
        np.linalg.norm(x, axis=-1, keepdims=True)
        * np.linalg.norm(w, axis=-1))
    np.testing.assert_allclose(out, want, rtol=1e-4)


def test_bilinear():
    mod = nn.Bilinear(3, 4, 2)
    x1 = jax.random.normal(KEY, (5, 3))
    x2 = jax.random.normal(jax.random.fold_in(KEY, 1), (5, 4))
    out, params = _apply(mod, (x1, x2))
    w = np.asarray(params["weight"])
    want = np.einsum("ni,oij,nj->no", x1, w, x2) + np.asarray(params["bias"])
    np.testing.assert_allclose(out, want, rtol=1e-4)


def test_maxout_highway_grads():
    mod = nn.Maxout(4, 3, pool=2)
    x = jax.random.normal(KEY, (5, 4))
    params, state = mod.init(KEY)
    out, _ = mod.apply(params, state, x)
    assert out.shape == (5, 3)
    y = x @ params["weight"].T + params["bias"]
    want = jnp.max(y.reshape(5, 2, 3), axis=1)
    np.testing.assert_allclose(out, want, rtol=1e-5)

    hw = nn.Highway(4)
    params, state = hw.init(KEY)
    out, _ = hw.apply(params, state, x)
    assert out.shape == x.shape
    g = jax.grad(lambda p: jnp.sum(hw.apply(p, {}, x)[0]))(params)
    assert all(jnp.all(jnp.isfinite(v)) for v in jax.tree_util.tree_leaves(g))


def test_mixture_table():
    g = jnp.array([[0.3, 0.7]])
    e1, e2 = jnp.ones((1, 4)), 2 * jnp.ones((1, 4))
    out, _ = _apply(nn.MixtureTable(), (g, (e1, e2)))
    np.testing.assert_allclose(out, 1.7 * jnp.ones((1, 4)), rtol=1e-6)
    # stacked-expert form
    out2, _ = _apply(nn.MixtureTable(), (g, jnp.stack([e1, e2], 1)))
    np.testing.assert_allclose(out2, out, rtol=1e-6)


def test_table_utils():
    x = jnp.arange(12.0).reshape(3, 4)
    out, _ = _apply(nn.Reverse(1), x)
    np.testing.assert_allclose(out, x[:, ::-1])

    out, _ = _apply(nn.Tile(0, 2), x)
    assert out.shape == (6, 4)

    out, _ = _apply(nn.InferReshape((0, -1, 2), batch_mode=False), x)
    assert out.shape == (3, 2, 2)

    a, b = _apply(nn.BifurcateSplitTable(1), x)[0]
    assert a.shape == b.shape == (3, 2)

    out, _ = _apply(nn.NarrowTable(1, 2), (x, x + 1, x + 2))
    assert len(out) == 2
    np.testing.assert_allclose(out[0], x + 1)

    out, _ = _apply(nn.CAveTable(), (x, x + 2))
    np.testing.assert_allclose(out, x + 1)

    out, _ = _apply(nn.MaskedSelect(), (x, x > 5))
    np.testing.assert_allclose(out, jnp.arange(6.0, 12.0))


def test_bottle_maptable():
    inner = nn.Linear(4, 2)
    mod = nn.Bottle(inner, 2)
    x = jax.random.normal(KEY, (3, 5, 4))
    params, state = mod.init(KEY)
    out, _ = mod.apply(params, state, x)
    assert out.shape == (3, 5, 2)
    flat, _ = inner.apply(params, state, x.reshape(15, 4))
    np.testing.assert_allclose(out, flat.reshape(3, 5, 2), rtol=1e-5)

    mt = nn.MapTable(nn.Linear(4, 2))
    params, state = mt.init(KEY)
    outs, _ = mt.apply(params, state, (x[:, 0], x[:, 1]))
    assert len(outs) == 2 and outs[0].shape == (3, 2)


def test_gradient_reversal():
    mod = nn.GradientReversal(the_lambda=2.0)
    x = jnp.array([1.0, 2.0])
    out, _ = _apply(mod, x)
    np.testing.assert_allclose(out, x)
    g = jax.grad(lambda z: jnp.sum(mod.apply({}, {}, z)[0]))(x)
    np.testing.assert_allclose(g, -2.0 * jnp.ones(2))


def test_stochastic_layers():
    x = jnp.ones((256, 8))
    rng = jax.random.PRNGKey(3)
    out, _ = _apply(nn.GaussianDropout(0.5), x, training=True, rng=rng)
    assert abs(float(jnp.mean(out)) - 1.0) < 0.15
    out, _ = _apply(nn.GaussianDropout(0.5), x, training=False)
    np.testing.assert_allclose(out, x)

    out, _ = _apply(nn.GaussianNoise(0.1), x, training=True, rng=rng)
    assert abs(float(jnp.std(out)) - 0.1) < 0.05

    mean, lv = jnp.zeros((512, 4)), jnp.zeros((512, 4))
    out, _ = _apply(nn.GaussianSampler(), (mean, lv), rng=rng)
    assert abs(float(jnp.std(out)) - 1.0) < 0.1


def test_penalty_layers():
    x = jnp.array([[1.0, -2.0], [3.0, -4.0]])
    mod = nn.L1Penalty(0.5)
    out, _ = _apply(mod, x)
    np.testing.assert_allclose(out, x)
    np.testing.assert_allclose(float(mod.penalty(x)), 5.0)

    ar = nn.ActivityRegularization(l1=1.0, l2=1.0)
    np.testing.assert_allclose(float(ar.penalty(x)), 10.0 + 30.0)

    p = jnp.array([[0.5, 0.5]])
    ne = nn.NegativeEntropyPenalty(1.0)
    np.testing.assert_allclose(float(ne.penalty(p)), -0.6931, atol=1e-3)


def test_misc_small():
    x = jnp.array([-1.0, 0.5, 2.0])
    out, _ = _apply(nn.Negative(), x)
    np.testing.assert_allclose(out, -x)
    out, _ = _apply(nn.BinaryThreshold(0.6), x)
    np.testing.assert_allclose(out, [0.0, 0.0, 1.0])
    out, _ = _apply(nn.Add(3), x)
    np.testing.assert_allclose(out, x)  # zero-init bias
    out, _ = _apply(nn.Mul(), x)
    np.testing.assert_allclose(out, x)  # one-init gain


def test_new_activations():
    x = jnp.array([-2.0, -0.3, 0.0, 0.3, 2.0])
    out, _ = _apply(nn.HardShrink(0.5), x)
    np.testing.assert_allclose(out, [-2.0, 0.0, 0.0, 0.0, 2.0])
    out, _ = _apply(nn.SoftShrink(0.5), x)
    np.testing.assert_allclose(out, [-1.5, 0.0, 0.0, 0.0, 1.5])
    out, _ = _apply(nn.LogSigmoid(), x)
    np.testing.assert_allclose(out, jax.nn.log_sigmoid(x), rtol=1e-6)
    out, _ = _apply(nn.SoftMin(), x)
    np.testing.assert_allclose(out, jax.nn.softmax(-x), rtol=1e-6)
    out, _ = _apply(nn.TanhShrink(), x)
    np.testing.assert_allclose(out, x - jnp.tanh(x), rtol=1e-6)
