"""Round-3 TF importer surface: the op sweep (reference
``DL/utils/tf/loaders/`` — VERDICT r2 missing #2), nested while frames
(``DL/nn/Scheduler.scala:104-145`` FrameManager nesting), and the
bounded-loop → ``lax.scan`` rewrite that makes imported loops
trainable."""

import io
import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.ops.registry import OPS, get_op
from bigdl_tpu.interop import load_tf_graph

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from tfgraph_util import node, enter, scalar_const, attr_tensor


def _scalar_shape_attr():
    """AttrValue shape payload for a scalar (empty TensorShapeProto)."""
    from bigdl_tpu.utils import protowire as pw
    return pw.enc_bytes(7, b"")


# ----------------------------------------------------------- op unit tests
class TestNewOps:
    def test_topk(self):
        vals, idx = OPS["TopKV2"]({}, jnp.asarray([[1., 5., 3., 2.]]),
                                  np.int32(2))
        np.testing.assert_array_equal(np.asarray(vals), [[5., 3.]])
        np.testing.assert_array_equal(np.asarray(idx), [[1, 2]])

    def test_in_top_k(self):
        pred = jnp.asarray([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]])
        tgt = jnp.asarray([1, 2])
        out = OPS["InTopK"]({"k": 1}, pred, tgt)
        np.testing.assert_array_equal(np.asarray(out), [True, False])
        out2 = OPS["InTopK"]({"k": 3}, pred, tgt)
        np.testing.assert_array_equal(np.asarray(out2), [True, True])

    def test_split_and_splitv(self):
        x = jnp.arange(12.0).reshape(2, 6)
        parts = OPS["Split"]({"num_split": 3}, np.int32(1), x)
        assert len(parts) == 3 and parts[0].shape == (2, 2)
        np.testing.assert_array_equal(np.asarray(parts[1]),
                                      [[2., 3.], [8., 9.]])
        pv = OPS["SplitV"]({}, x, np.asarray([1, -1]), np.int32(1))
        assert pv[0].shape == (2, 1) and pv[1].shape == (2, 5)

    def test_range_segment_cumsum(self):
        r = OPS["Range"]({}, np.int32(2), np.int32(10), np.int32(3))
        np.testing.assert_array_equal(np.asarray(r), [2, 5, 8])
        s = OPS["SegmentSum"]({}, jnp.asarray([1., 2., 3., 4.]),
                              np.asarray([0, 0, 1, 1]))
        np.testing.assert_allclose(np.asarray(s), [3., 7.])
        c = OPS["Cumsum"]({"exclusive": True}, jnp.asarray([1., 2., 3.]),
                          np.int32(0))
        np.testing.assert_allclose(np.asarray(c), [0., 1., 3.])

    def test_unops_r3(self):
        x = jnp.asarray([0.5, np.nan, np.inf])
        np.testing.assert_array_equal(np.asarray(OPS["IsNan"]({}, x)),
                                      [False, True, False])
        np.testing.assert_array_equal(np.asarray(OPS["IsInf"]({}, x)),
                                      [False, False, True])
        np.testing.assert_allclose(
            np.asarray(OPS["Log1p"]({}, jnp.asarray([0.0, 1.0]))),
            [0.0, np.log(2.0)], rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(OPS["Lgamma"]({}, jnp.asarray([4.0]))),
            [np.log(6.0)], rtol=1e-5)

    def test_lrn_matches_manual(self):
        # TF semantics: alpha NOT divided by window size
        x = np.random.RandomState(0).rand(1, 2, 2, 6).astype(np.float32)
        dr, bias, alpha, beta = 2, 1.0, 0.5, 0.75
        out = np.asarray(OPS["LRN"](
            {"depth_radius": dr, "bias": bias, "alpha": alpha,
             "beta": beta}, jnp.asarray(x)))
        want = np.empty_like(x)
        for c in range(6):
            lo, hi = max(0, c - dr), min(6, c + dr + 1)
            sq = (x[..., lo:hi] ** 2).sum(-1)
            want[..., c] = x[..., c] / (bias + alpha * sq) ** beta
        np.testing.assert_allclose(out, want, rtol=1e-5)

    def test_resize_bilinear_tf1_semantics(self):
        # 2x upscale of [0,1;2,3] with align_corners=False (TF1 default):
        # src = dst*0.5, edge rows/cols clamp
        x = jnp.asarray([[[[0.], [1.]], [[2.], [3.]]]])
        out = np.asarray(OPS["ResizeBilinear"]({}, x, np.asarray([4, 4])))
        np.testing.assert_allclose(out[0, :, :, 0],
                                   [[0.0, 0.5, 1.0, 1.0],
                                    [1.0, 1.5, 2.0, 2.0],
                                    [2.0, 2.5, 3.0, 3.0],
                                    [2.0, 2.5, 3.0, 3.0]], atol=1e-6)
        # align_corners=True: corners map exactly
        out2 = np.asarray(OPS["ResizeBilinear"](
            {"align_corners": True}, x, np.asarray([3, 3])))
        np.testing.assert_allclose(out2[0, :, :, 0],
                                   [[0.0, 0.5, 1.0],
                                    [1.0, 1.5, 2.0],
                                    [2.0, 2.5, 3.0]], atol=1e-6)

    def test_conv3d(self):
        x = jnp.ones((1, 4, 4, 4, 2))
        w = jnp.ones((2, 2, 2, 2, 3))
        out = OPS["Conv3D"]({"strides": [1, 1, 1, 1, 1],
                             "padding": b"VALID"}, x, w)
        assert out.shape == (1, 3, 3, 3, 3)
        np.testing.assert_allclose(np.asarray(out)[0, 0, 0, 0], 16.0)

    def test_decode_raw(self):
        payload = np.asarray([1.5, -2.0], np.float32).tobytes()
        out = OPS["DecodeRaw"]({"out_type": 1}, payload)
        np.testing.assert_allclose(out, [1.5, -2.0])

    def test_decode_jpeg_png(self):
        from PIL import Image
        img = Image.fromarray(
            (np.random.RandomState(0).rand(5, 7, 3) * 255)
            .astype(np.uint8))
        buf = io.BytesIO()
        img.save(buf, format="PNG")
        out = OPS["DecodePng"]({}, buf.getvalue())
        assert out.shape == (5, 7, 3) and out.dtype == np.uint8
        np.testing.assert_array_equal(out, np.asarray(img))
        buf2 = io.BytesIO()
        img.save(buf2, format="JPEG")
        outj = OPS["DecodeJpeg"]({"channels": 1}, buf2.getvalue())
        assert outj.shape == (5, 7, 1)

    def test_parse_example(self):
        from bigdl_tpu.dataset.tfrecord import encode_example
        recs = [encode_example({"x": np.asarray([1.0, 2.0], np.float32),
                                "y": np.asarray([5], np.int64)}),
                encode_example({"x": np.asarray([3.0, 4.0], np.float32),
                                "y": np.asarray([7], np.int64)})]
        serialized = np.asarray(recs, dtype=object)
        x, y = OPS["ParseExample"](
            {"Nsparse": 0, "Ndense": 2, "dense_shapes": [[2], [1]]},
            serialized, np.asarray([b"", b""], dtype=object),
            np.asarray(b"x", dtype=object), np.asarray(b"y", dtype=object))
        np.testing.assert_allclose(x, [[1., 2.], [3., 4.]])
        np.testing.assert_array_equal(y.reshape(-1), [5, 7])


# ------------------------------------------------------- nested while loops
def _nested_loop_graph(tmp_path):
    """outer (i<3): { inner (j<2): acc *= 2 }  => acc *= 2**6."""
    g = (node("acc0", "Placeholder")
         + node("zero", "Const", value=scalar_const(0.0))
         + node("one", "Const", value=scalar_const(1.0))
         + node("two", "Const", value=scalar_const(2.0))
         + node("three", "Const", value=scalar_const(3.0))
         # outer frame
         + enter("i_ent", ["zero"], "outer")
         + enter("acc_ent", ["acc0"], "outer")
         + node("i_mrg", "Merge", ["i_ent", "i_ni"])
         + node("acc_mrg", "Merge", ["acc_ent", "acc_ni"])
         + node("lt", "Less", ["i_mrg", "three"])
         + node("lc", "LoopCond", ["lt"])
         + node("i_sw", "Switch", ["i_mrg", "lc"])
         + node("acc_sw", "Switch", ["acc_mrg", "lc"])
         # inner frame (body of outer)
         + enter("j_ent", ["zero"], "inner")
         + enter("a_ent", ["acc_sw:1"], "inner")
         + node("j_mrg", "Merge", ["j_ent", "j_ni"])
         + node("a_mrg", "Merge", ["a_ent", "a_ni"])
         + node("ltj", "Less", ["j_mrg", "two"])
         + node("lcj", "LoopCond", ["ltj"])
         + node("j_sw", "Switch", ["j_mrg", "lcj"])
         + node("a_sw", "Switch", ["a_mrg", "lcj"])
         + node("j_add", "Add", ["j_sw:1", "one"])
         + node("a_mul", "Mul", ["a_sw:1", "two"])
         + node("j_ni", "NextIteration", ["j_add"])
         + node("a_ni", "NextIteration", ["a_mul"])
         + node("j_exit", "Exit", ["j_sw:0"])
         + node("a_exit", "Exit", ["a_sw:0"])
         # back in outer body
         + node("i_add", "Add", ["i_sw:1", "one"])
         + node("i_ni", "NextIteration", ["i_add"])
         + node("acc_ni", "NextIteration", ["a_exit"])
         + node("i_exit", "Exit", ["i_sw:0"])
         + node("acc_exit", "Exit", ["acc_sw:0"])
         + node("out", "Identity", ["acc_exit"]))
    p = str(tmp_path / "nested.pb")
    open(p, "wb").write(g)
    return p


class TestNestedWhileLoops:
    def test_nested_frames_execute(self, tmp_path):
        m = load_tf_graph(_nested_loop_graph(tmp_path), inputs=["acc0"],
                          outputs=["out"])
        out = m.forward(np.float32(1.5))
        assert float(out) == 1.5 * 64

    def test_nested_under_jit(self, tmp_path):
        m = load_tf_graph(_nested_loop_graph(tmp_path), inputs=["acc0"],
                          outputs=["out"])
        f = jax.jit(lambda a: m.apply({}, {}, {"acc0": a})[0])
        assert float(f(np.float32(2.0))) == 128.0


# -------------------------------------------- bounded loop -> scan rewrite
def _const_init_loop_graph(tmp_path, limit=5.0):
    """while (i < limit): i += 1; acc *= 2 — i starts at Const 0, so the
    trip count is static and the loop compiles to lax.scan."""
    g = (node("acc0", "Placeholder")
         + node("zero", "Const", value=scalar_const(0.0))
         + node("one", "Const", value=scalar_const(1.0))
         + node("two", "Const", value=scalar_const(2.0))
         + node("lim", "Const", value=scalar_const(limit))
         + enter("i_ent", ["zero"], "loop")
         + enter("acc_ent", ["acc0"], "loop")
         + node("i_mrg", "Merge", ["i_ent", "i_ni"])
         + node("acc_mrg", "Merge", ["acc_ent", "acc_ni"])
         + node("lt", "Less", ["i_mrg", "lim"])
         + node("lc", "LoopCond", ["lt"])
         + node("i_sw", "Switch", ["i_mrg", "lc"])
         + node("acc_sw", "Switch", ["acc_mrg", "lc"])
         + node("i_add", "Add", ["i_sw:1", "one"])
         + node("acc_mul", "Mul", ["acc_sw:1", "two"])
         + node("i_ni", "NextIteration", ["i_add"])
         + node("acc_ni", "NextIteration", ["acc_mul"])
         + node("i_exit", "Exit", ["i_sw:0"])
         + node("acc_exit", "Exit", ["acc_sw:0"])
         + node("out", "Identity", ["acc_exit"]))
    p = str(tmp_path / "scanloop.pb")
    open(p, "wb").write(g)
    return p


class TestBoundedLoopScan:
    def test_static_trip_count_detection(self, tmp_path):
        from bigdl_tpu.interop.tf_loops import (extract_frames,
                                                static_trip_count)
        from bigdl_tpu.interop.tf_format import parse_graphdef_binary
        nodes = parse_graphdef_binary(
            open(_const_init_loop_graph(tmp_path), "rb").read())
        frames = extract_frames(nodes)
        by_name = {n["name"]: n for n in nodes}

        def const_eval(nm):
            n = by_name.get(nm)
            if n is not None and n["op"] == "Const":
                return np.asarray(n["attrs"]["value"])
            return None

        assert static_trip_count(frames["loop"], by_name,
                                 const_eval) == 5

    def test_forward_value(self, tmp_path):
        m = load_tf_graph(_const_init_loop_graph(tmp_path),
                          inputs=["acc0"], outputs=["out"])
        assert float(m.forward(np.float32(3.0))) == 96.0

    def test_loop_is_differentiable(self, tmp_path):
        """The point of the scan rewrite: d(acc0 * 2^5)/d(acc0) = 32 —
        a lax.while_loop would raise here."""
        m = load_tf_graph(_const_init_loop_graph(tmp_path),
                          inputs=["acc0"], outputs=["out"])
        grad = jax.grad(lambda a: m.apply({}, {}, {"acc0": a})[0])(
            jnp.float32(1.0))
        assert float(grad) == 32.0

    def test_dynamic_limit_still_works_forward(self, tmp_path):
        """Placeholder-initialized counter: no static trip, while_loop
        fallback must still run forward."""
        g = (node("i0", "Placeholder")
             + node("acc0", "Placeholder")
             + node("one", "Const", value=scalar_const(1.0))
             + node("two", "Const", value=scalar_const(2.0))
             + node("lim", "Const", value=scalar_const(4.0))
             + enter("i_ent", ["i0"], "loop")
             + enter("acc_ent", ["acc0"], "loop")
             + node("i_mrg", "Merge", ["i_ent", "i_ni"])
             + node("acc_mrg", "Merge", ["acc_ent", "acc_ni"])
             + node("lt", "Less", ["i_mrg", "lim"])
             + node("lc", "LoopCond", ["lt"])
             + node("i_sw", "Switch", ["i_mrg", "lc"])
             + node("acc_sw", "Switch", ["acc_mrg", "lc"])
             + node("i_add", "Add", ["i_sw:1", "one"])
             + node("acc_mul", "Mul", ["acc_sw:1", "two"])
             + node("i_ni", "NextIteration", ["i_add"])
             + node("acc_ni", "NextIteration", ["acc_mul"])
             + node("i_exit", "Exit", ["i_sw:0"])
             + node("acc_exit", "Exit", ["acc_sw:0"])
             + node("out", "Identity", ["acc_exit"]))
        p = str(tmp_path / "dyn.pb")
        open(p, "wb").write(g)
        m = load_tf_graph(p, inputs=["i0", "acc0"], outputs=["out"])
        out, _ = m.apply({}, {}, {"i0": np.float32(1.0),
                                  "acc0": np.float32(1.0)})
        assert float(out) == 8.0  # 3 iterations


# ------------------------- e2e: TFRecord + ParseExample + trainable loop
class TestParseExampleTrainingE2E:
    """VERDICT r2 'done' criterion for the importer: import and TRAIN a
    TF graph that uses a loop, fed by ParseExample-parsed TFRecords."""

    def _records(self, tmp_path):
        from bigdl_tpu.dataset.tfrecord import encode_example, \
            write_records
        rng = np.random.RandomState(0)
        # y = 8*x (the loop computes w*x three times; w trains to 2)
        xs = rng.rand(64, 1).astype(np.float32)
        path = str(tmp_path / "train.tfrecord")
        write_records(path, [
            encode_example({"x": x, "y": (8.0 * x).astype(np.float32)})
            for x in xs])
        return path

    def _graph(self, tmp_path):
        """serialized --ParseExample--> x,y ; loop: h = h*w 3 times
        (const trip -> scan -> differentiable); loss = L2(h - y)."""
        g = (node("serialized", "Placeholder")
             + node("names", "Const", value=scalar_const(0.0))
             + node("kx", "Const", value=scalar_const(0.0))
             + node("ky", "Const", value=scalar_const(0.0))
             + node("parse", "ParseExample",
                    ["serialized", "names", "kx", "ky"])
             + node("w", "VariableV2", shape=_scalar_shape_attr())
             + node("zero", "Const", value=scalar_const(0.0))
             + node("one", "Const", value=scalar_const(1.0))
             + node("three", "Const", value=scalar_const(3.0))
             + enter("i_ent", ["zero"], "f")
             + enter("h_ent", ["parse"], "f")
             + enter("w_ent", ["w"], "f")
             + node("i_mrg", "Merge", ["i_ent", "i_ni"])
             + node("h_mrg", "Merge", ["h_ent", "h_ni"])
             + node("lt", "Less", ["i_mrg", "three"])
             + node("lc", "LoopCond", ["lt"])
             + node("i_sw", "Switch", ["i_mrg", "lc"])
             + node("h_sw", "Switch", ["h_mrg", "lc"])
             + node("i_add", "Add", ["i_sw:1", "one"])
             + node("h_mul", "Mul", ["h_sw:1", "w_ent"])
             + node("i_ni", "NextIteration", ["i_add"])
             + node("h_ni", "NextIteration", ["h_mul"])
             + node("i_exit", "Exit", ["i_sw:0"])
             + node("h_exit", "Exit", ["h_sw:0"])
             + node("diff", "Sub", ["h_exit", "parse:1"])
             + node("loss", "L2Loss", ["diff"]))
        p = str(tmp_path / "train.pb")
        open(p, "wb").write(g)
        return p

    def test_import_parse_train(self, tmp_path):
        from bigdl_tpu.dataset.tfrecord import read_records
        rec_path = self._records(tmp_path)
        pb = self._graph(tmp_path)

        # host side: ParseExample over the real TFRecord stream
        parse = OPS["ParseExample"]
        recs = list(read_records(rec_path))
        xs, ys = parse(
            {"Nsparse": 0, "Ndense": 2, "dense_shapes": [[1], [1]]},
            np.asarray(recs, dtype=object),
            np.asarray([b""] * len(recs), dtype=object),
            np.asarray(b"x", dtype=object), np.asarray(b"y", dtype=object))

        # device side: the loop-bearing trainable graph, fed at the
        # ParseExample node's ports
        m = load_tf_graph(pb, inputs=["parse", "parse:1"],
                          outputs=["loss"])
        params, _ = m.init(jax.random.PRNGKey(0))
        params = {"w": jnp.asarray(1.0)}   # start away from the optimum

        @jax.jit
        def step(p, x, y):
            def lf(p):
                out, _ = m.apply(p, {}, {"parse": x, "parse:1": y})
                return out
            l, g = jax.value_and_grad(lf)(p)
            return l, {"w": p["w"] - 3e-4 * g["w"]}

        x = jnp.asarray(xs.reshape(-1))
        yv = jnp.asarray(ys.reshape(-1))
        losses = []
        for i in range(300):
            l, params = step(params, x, yv)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 1e-3
        assert abs(float(params["w"]) - 2.0) < 0.05  # w^3 = 8


class TestReferenceDecodeImageFixture:
    """The reference's committed decode_image_test_case.tfrecord: ONE
    MNIST digit (label 7) encoded as png/jpeg/gif/raw — the cross-format
    oracle for the image-decode ops (reference DecodeImageSpec)."""

    PATH = ("/root/reference/spark/dl/src/test/resources/tf/"
            "decode_image_test_case.tfrecord")

    def _by_format(self):
        from bigdl_tpu.dataset.tfrecord import read_examples
        if not os.path.exists(self.PATH):
            pytest.skip("reference checkout absent")
        return {r["image/format"][0].decode(): r
                for r in read_examples(self.PATH)}

    def test_lossless_formats_agree(self):
        recs = self._by_format()
        raw = OPS["DecodeRaw"]({"out_type": 4},
                               recs["raw"]["image/encoded"][0])
        raw = raw.reshape(28, 28, 1)
        png = OPS["DecodePng"]({"channels": 1},
                               recs["png"]["image/encoded"][0])
        np.testing.assert_array_equal(png, raw)
        # the fixture's GIF holds a DIFFERENT sample (the reference spec
        # decodes each record independently): check decode structure —
        # TF DecodeGif shape (frames, H, W, 3), grayscale palette
        gif = OPS["DecodeGif"]({}, recs["gif"]["image/encoded"][0])
        assert gif.shape == (1, 28, 28, 3) and gif.dtype == np.uint8
        np.testing.assert_array_equal(gif[..., 0], gif[..., 1])
        # format-sniffing DecodeImage dispatches per container
        sniffed = OPS["DecodeImage"]({}, recs["gif"]["image/encoded"][0])
        assert sniffed.shape == (1, 28, 28, 3)
        # expand_animations=False: rank-3 first frame (TF semantics)
        first = OPS["DecodeImage"]({"expand_animations": False},
                                   recs["gif"]["image/encoded"][0])
        assert first.shape == (28, 28, 3)
        # dtype=DT_FLOAT: [0,1] floats like convert_image_dtype
        f = OPS["DecodeImage"]({"dtype": 1},
                               recs["png"]["image/encoded"][0])
        assert f.dtype == np.float32 and 0.0 <= f.min() <= f.max() <= 1.0

    def test_jpeg_decodes_close(self):
        recs = self._by_format()
        raw = OPS["DecodeRaw"]({"out_type": 4},
                               recs["raw"]["image/encoded"][0])
        raw = raw.reshape(28, 28).astype(np.float32)
        jpg = OPS["DecodeJpeg"]({"channels": 1},
                                recs["jpeg"]["image/encoded"][0])
        assert jpg.shape == (28, 28, 1)
        err = np.abs(jpg[:, :, 0].astype(np.float32) - raw).mean()
        assert err < 6.0, err  # lossy but close

    def test_labels_and_sizes(self):
        recs = self._by_format()
        for r in recs.values():
            assert int(r["image/class/label"][0]) == 7
            assert int(r["image/width"][0]) == 28
