"""Pipeline-parallelism tests (beyond-reference capability; SURVEY §2.9
row "Pipeline parallelism: absent in reference").  Runs on the 8-device
CPU mesh from conftest."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.parallel import (GPipe, MicrobatchedSequential,
                                create_mesh, partition_sequential)


class TestPartition:
    def test_balanced_split(self):
        m = nn.Sequential(*[nn.Linear(4, 4) for _ in range(7)])
        stages = partition_sequential(m, 3)
        assert [len(s) for s in stages] == [3, 2, 2]

    def test_invalid_split_raises(self):
        m = nn.Sequential(nn.Linear(4, 4))
        with pytest.raises(ValueError):
            partition_sequential(m, 2)


class TestGPipe:
    def _build(self, pipe=4, data=2):
        mesh = create_mesh(data=data, pipe=pipe)
        stage = nn.Sequential(nn.Linear(12, 12), nn.Tanh())
        gp = GPipe(stage, num_stages=pipe, mesh=mesh)
        params, _ = gp.init(jax.random.PRNGKey(0))
        return gp, params

    def test_matches_sequential_reference(self):
        gp, params = self._build()
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 4, 12))
        out, _ = gp.apply(params, {}, x)
        ref, _ = gp.apply_reference(params, {}, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_grad_matches_reference(self):
        gp, params = self._build(pipe=2, data=4)
        x = jax.random.normal(jax.random.PRNGKey(2), (4, 4, 12))

        def loss_pipe(p):
            o, _ = gp.apply(p, {}, x)
            return jnp.mean(o ** 2)

        def loss_ref(p):
            return jnp.mean(gp.apply_reference(p, {}, x)[0] ** 2)

        g_pipe = jax.grad(loss_pipe)(params)
        g_ref = jax.grad(loss_ref)(params)
        for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_indivisible_microbatches_raise(self):
        gp, params = self._build(pipe=4, data=2)
        x = jax.random.normal(jax.random.PRNGKey(5), (6, 2, 12))
        with pytest.raises(ValueError, match="divide"):
            gp.apply(params, {}, x)

    def test_stateful_stage_bn_running_stats(self):
        """r3: stages may carry state (BN running stats) — VERDICT weak
        #4 'stateless stages only' removed.  Pipelined training output
        AND the updated per-stage stats must match the sequential
        reference (bubble ticks must not pollute the stats)."""
        pipe = 2
        mesh = create_mesh(data=4, pipe=pipe)
        stage = nn.Sequential(nn.Linear(6, 6),
                              nn.BatchNormalization(6), nn.ReLU())
        gp = GPipe(stage, num_stages=pipe, mesh=mesh)
        params, state = gp.init(jax.random.PRNGKey(0))
        assert jax.tree_util.tree_leaves(state), "BN state must exist"
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 8, 6))

        out, new_state = gp.apply(params, state, x, training=True)
        # oracle: sequential microbatch-threaded replay (training-mode BN
        # uses per-microbatch batch stats, so the full-batch
        # apply_reference is NOT the right oracle here; the pipelined
        # schedule processes each stage's microbatches in order)
        st = state
        ref_outs = []
        for m in range(x.shape[0]):
            cur = x[m]
            sts = []
            for s in range(pipe):
                p_s = jax.tree_util.tree_map(lambda a, s=s: a[s], params)
                st_s = jax.tree_util.tree_map(lambda a, s=s: a[s], st)
                cur, ns = gp.stage.apply(p_s, st_s, cur, training=True)
                sts.append(ns)
            st = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *sts)
            ref_outs.append(cur)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(jnp.stack(ref_outs)),
                                   atol=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(new_state),
                        jax.tree_util.tree_leaves(st)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_sharded_params_execute(self):
        # place stage params with the pipe sharding and run under jit
        gp, params = self._build()
        sharded = jax.device_put(params, gp.stage_sharding())
        x = jax.random.normal(jax.random.PRNGKey(3), (4, 2, 12))
        out = jax.jit(lambda p, x: gp.apply(p, {}, x)[0])(sharded, x)
        assert out.shape == (4, 2, 12)
        assert np.isfinite(np.asarray(out)).all()


class TestMicrobatched:
    def test_identical_to_unpipelined(self):
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                              nn.Linear(16, 16), nn.Tanh(),
                              nn.Linear(16, 4))
        stages = partition_sequential(model, 3)
        mb = MicrobatchedSequential(stages, num_microbatches=4)
        params, state = mb.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
        out, _ = mb.apply(params, state, x)

        flat = nn.Sequential(*[m for st in stages for m in st.modules])
        fp = {}
        k = 0
        for i, st in enumerate(stages):
            for j in range(len(st.modules)):
                fp[str(k)] = params[str(i)][str(j)]
                k += 1
        ref, _ = flat.apply(fp, {str(i): {} for i in range(k)}, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)

    def test_indivisible_batch_raises(self):
        mb = MicrobatchedSequential([nn.Identity()], num_microbatches=3)
        p, s = mb.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError):
            mb.apply(p, s, jnp.zeros((8, 2)))
