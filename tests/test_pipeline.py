"""Pipeline-parallelism tests (beyond-reference capability; SURVEY §2.9
row "Pipeline parallelism: absent in reference").  Runs on the 8-device
CPU mesh from conftest."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.parallel import (GPipe, MicrobatchedSequential,
                                create_mesh, partition_sequential)


class TestPartition:
    def test_balanced_split(self):
        m = nn.Sequential(*[nn.Linear(4, 4) for _ in range(7)])
        stages = partition_sequential(m, 3)
        assert [len(s) for s in stages] == [3, 2, 2]

    def test_invalid_split_raises(self):
        m = nn.Sequential(nn.Linear(4, 4))
        with pytest.raises(ValueError):
            partition_sequential(m, 2)


class TestGPipe:
    def _build(self, pipe=4, data=2):
        mesh = create_mesh(data=data, pipe=pipe)
        stage = nn.Sequential(nn.Linear(12, 12), nn.Tanh())
        gp = GPipe(stage, num_stages=pipe, mesh=mesh)
        params, _ = gp.init(jax.random.PRNGKey(0))
        return gp, params

    def test_matches_sequential_reference(self):
        gp, params = self._build()
        x = jax.random.normal(jax.random.PRNGKey(1), (6, 4, 12))
        out, _ = gp.apply(params, {}, x)
        ref = gp.apply_reference(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5)

    def test_grad_matches_reference(self):
        gp, params = self._build(pipe=2, data=4)
        x = jax.random.normal(jax.random.PRNGKey(2), (4, 4, 12))

        def loss_pipe(p):
            o, _ = gp.apply(p, {}, x)
            return jnp.mean(o ** 2)

        def loss_ref(p):
            return jnp.mean(gp.apply_reference(p, x) ** 2)

        g_pipe = jax.grad(loss_pipe)(params)
        g_ref = jax.grad(loss_ref)(params)
        for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-5)

    def test_sharded_params_execute(self):
        # place stage params with the pipe sharding and run under jit
        gp, params = self._build()
        sharded = jax.device_put(params, gp.stage_sharding())
        x = jax.random.normal(jax.random.PRNGKey(3), (4, 2, 12))
        out = jax.jit(lambda p, x: gp.apply(p, {}, x)[0])(sharded, x)
        assert out.shape == (4, 2, 12)
        assert np.isfinite(np.asarray(out)).all()


class TestMicrobatched:
    def test_identical_to_unpipelined(self):
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                              nn.Linear(16, 16), nn.Tanh(),
                              nn.Linear(16, 4))
        stages = partition_sequential(model, 3)
        mb = MicrobatchedSequential(stages, num_microbatches=4)
        params, state = mb.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 8))
        out, _ = mb.apply(params, state, x)

        flat = nn.Sequential(*[m for st in stages for m in st.modules])
        fp = {}
        k = 0
        for i, st in enumerate(stages):
            for j in range(len(st.modules)):
                fp[str(k)] = params[str(i)][str(j)]
                k += 1
        ref, _ = flat.apply(fp, {str(i): {} for i in range(k)}, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)

    def test_indivisible_batch_raises(self):
        mb = MicrobatchedSequential([nn.Identity()], num_microbatches=3)
        p, s = mb.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError):
            mb.apply(p, s, jnp.zeros((8, 2)))
