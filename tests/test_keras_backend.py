"""KerasModelWrapper one-call surface (VERDICT r3 item 8; reference
pyspark/bigdl/keras/backend.py)."""
import json

import numpy as np
import pytest

from bigdl_tpu.keras import KerasModelWrapper, load_model


def model_json():
    return json.dumps({
        "class_name": "Sequential",
        "config": [
            {"class_name": "Dense",
             "config": {"output_dim": 16, "activation": "relu",
                        "batch_input_shape": [None, 4]}},
            {"class_name": "Dense",
             "config": {"output_dim": 2, "activation": "softmax"}},
        ]})


def spiral_data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (n, 4)).astype(np.float32)
    y_ix = (x[:, 0] + x[:, 1] > 0).astype(np.int64)
    y = np.eye(2, dtype=np.float32)[y_ix]
    return x, y, y_ix


class TestKerasModelWrapper:
    def test_one_call_fit_evaluate_predict(self, tmp_path):
        p = tmp_path / "m.json"
        p.write_text(model_json())
        x, y, y_ix = spiral_data()
        m = KerasModelWrapper(str(p), optimizer="adam",
                              loss="categorical_crossentropy",
                              metrics=["accuracy"])
        m.fit(x, y, batch_size=32, nb_epoch=15)
        res = m.evaluate(x, y)
        assert res["Top1Accuracy"] > 0.9, res
        pred = m.predict(x)
        assert pred.shape == (256, 2)
        np.testing.assert_allclose(pred.sum(1), 1.0, rtol=1e-4)
        cls = m.predict_classes(x)
        assert (cls == y_ix).mean() > 0.9

    def test_import_only_then_compile(self, tmp_path):
        p = tmp_path / "m.json"
        p.write_text(model_json())
        m = KerasModelWrapper(str(p))  # no loss: import-only
        with pytest.raises(RuntimeError):
            m.fit(*spiral_data()[:2], nb_epoch=1)
        m.compile("sgd", "categorical_crossentropy")
        m.fit(*spiral_data()[:2], batch_size=64, nb_epoch=1)

    def test_set_weights_then_predict(self, tmp_path):
        p = tmp_path / "m.json"
        p.write_text(model_json())
        rng = np.random.default_rng(1)
        ws = [rng.normal(0, 0.1, (4, 16)).astype(np.float32),
              np.zeros(16, np.float32),
              rng.normal(0, 0.1, (16, 2)).astype(np.float32),
              np.zeros(2, np.float32)]
        m = load_model(str(p)).set_weights(ws)
        x = rng.normal(0, 1, (5, 4)).astype(np.float32)
        got = m.predict(x)
        # numpy reference
        h = np.maximum(x @ ws[0] + ws[1], 0)
        logits = h @ ws[2] + ws[3]
        want = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    def test_hdf5_weights_when_h5py_present(self, tmp_path):
        h5py = pytest.importorskip("h5py")
        p = tmp_path / "m.json"
        p.write_text(model_json())
        rng = np.random.default_rng(2)
        ws = [rng.normal(0, 0.1, (4, 16)).astype(np.float32),
              np.zeros(16, np.float32),
              rng.normal(0, 0.1, (16, 2)).astype(np.float32),
              np.zeros(2, np.float32)]
        h5 = tmp_path / "w.h5"
        with h5py.File(str(h5), "w") as f:
            grp = f.create_group("model_weights")
            grp.attrs["layer_names"] = [b"dense_1", b"dense_2"]
            g1 = grp.create_group("dense_1")
            g1.attrs["weight_names"] = [b"dense_1/W", b"dense_1/b"]
            g1["dense_1/W"] = ws[0]
            g1["dense_1/b"] = ws[1]
            g2 = grp.create_group("dense_2")
            g2.attrs["weight_names"] = [b"dense_2/W", b"dense_2/b"]
            g2["dense_2/W"] = ws[2]
            g2["dense_2/b"] = ws[3]
        m = KerasModelWrapper(str(p), str(h5))
        x = rng.normal(0, 1, (3, 4)).astype(np.float32)
        got = m.predict(x)
        h = np.maximum(x @ ws[0] + ws[1], 0)
        logits = h @ ws[2] + ws[3]
        want = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
