"""Round-5 closures: the last reference trivia (VERDICT r4 missing
#2-4 — FloorMod/BiasAddV1 TF ops, Kv2Tensor feature column,
ChannelScaledNormalizer/RandomResize augmentations) and the r4 advisor
fixes (LookupTableSparse raw-weight mean, ConvLSTMPeephole3D checkpoint
guard, SGD velocity dtype promotion)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu.ops.registry import get_op


class TestLastTFOps:
    def test_floor_mod_sign_follows_divisor(self):
        # floored modulo (TF FloorMod): result carries the DIVISOR's
        # sign — the property that distinguishes it from TruncateMod
        a = jnp.asarray([7.0, -7.0, 7.0, -7.0])
        b = jnp.asarray([3.0, 3.0, -3.0, -3.0])
        out = np.asarray(get_op("FloorMod")({}, a, b))
        np.testing.assert_allclose(out, [1.0, 2.0, -2.0, -1.0])
        got = np.asarray(get_op("FloorMod")(
            {}, jnp.asarray([7, -7], jnp.int32), jnp.asarray(3, jnp.int32)))
        np.testing.assert_array_equal(got, [1, 2])

    def test_bias_add_v1(self):
        x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(3, 4))
        b = jnp.asarray([1.0, 2.0, 3.0, 4.0])
        out = np.asarray(get_op("BiasAddV1")({}, x, b))
        np.testing.assert_allclose(out, np.asarray(x) + np.asarray(b))


class TestKv2Tensor:
    def test_dense(self):
        from bigdl_tpu.dataset import Kv2Tensor
        op = Kv2Tensor()
        out = op(["0:1.5,2:2.0", "1:3.0", ""], fea_len=4)
        want = np.zeros((3, 4), np.float32)
        want[0, 0], want[0, 2], want[1, 1] = 1.5, 2.0, 3.0
        np.testing.assert_allclose(out, want)

    def test_sparse_matches_dense(self):
        from bigdl_tpu.dataset import Kv2Tensor
        col = ["0:1.0,3:4.0", "2:-2.5"]
        dense = Kv2Tensor(trans_type=0)(col, fea_len=5)
        coo = Kv2Tensor(trans_type=1)(col, fea_len=5)
        assert coo.dense_shape == (2, 5)
        np.testing.assert_allclose(np.asarray(coo.to_dense()), dense)

    def test_custom_delimiters_and_range_check(self):
        from bigdl_tpu.dataset import Kv2Tensor
        out = Kv2Tensor(kv_delimiter=";", item_delimiter="=")(
            ["1=2.0;0=1.0"], fea_len=2)
        np.testing.assert_allclose(out, [[1.0, 2.0]])
        with pytest.raises(ValueError):
            Kv2Tensor()(["9:1.0"], fea_len=4)


class TestNewAugmentations:
    def _feature(self, h, w):
        from bigdl_tpu.transform import ImageFeature
        rng = np.random.default_rng(0)
        f = ImageFeature()
        f.image = rng.uniform(0, 255, (h, w, 3)).astype(np.float32)
        return f

    def test_channel_scaled_normalizer(self):
        from bigdl_tpu.transform import ChannelScaledNormalizer
        f = self._feature(4, 5)
        img = f.image.copy()
        out = ChannelScaledNormalizer(10, 20, 30, 0.5).transform(f)
        want = (img - np.asarray([10, 20, 30], np.float32)) * 0.5
        np.testing.assert_allclose(out.image, want, rtol=1e-6)

    def test_random_resize_short_edge_in_range(self):
        from bigdl_tpu.transform import RandomResize
        t = RandomResize(8, 16, seed=3)
        for _ in range(5):
            f = self._feature(20, 30)
            out = t.transform(f)
            h, w = out.image.shape[:2]
            assert 8 <= min(h, w) <= 16
            # aspect ratio preserved (int truncation tolerance)
            assert abs(w / h - 30 / 20) < 0.15

    def test_random_resize_portrait(self):
        from bigdl_tpu.transform import RandomResize
        f = self._feature(40, 10)
        out = RandomResize(12, 12, seed=0).transform(f)
        assert out.image.shape[:2] == (48, 12)


class TestPallasPoolVmemGate:
    def test_supported_gates_large_spatial_blocks(self):
        # jax-0.9 Mosaic rejects blocks over ~400K elements that 0.8
        # compiled (measured on v5e in f32 AND bf16 — the limit is
        # elements, not bytes; see pallas_pool.supported docstring);
        # the gate must route those to the reduce_window fallback
        from bigdl_tpu.ops.pallas_pool import supported
        k, s = (3, 3), (2, 2)
        pads = ((0, 1), (0, 1))
        assert not supported((256, 112, 112, 64), k, s, pads)
        assert not supported((256, 56, 56, 192), k, s, pads)
        assert supported((256, 28, 28, 480), k, s, pads)
        assert supported((256, 14, 14, 832), k, s, pads)
        # structural rejections unchanged
        assert not supported((256, 28, 28, 64), (2, 2), (3, 3), pads)

    def test_fallback_path_still_correct(self):
        import jax
        import jax.numpy as jnp
        from bigdl_tpu.ops.pallas_pool import (
            maxpool_nhwc_with_pallas_bwd, supported)
        rng = np.random.default_rng(0)
        # a gated shape (64*64*256 = 1M elements > 410K): must
        # silently take reduce_window fwd + select-and-scatter bwd
        shape = (2, 64, 64, 192)
        dims, strides = (1, 3, 3, 1), (1, 2, 2, 1)
        pads = ((0, 0), (0, 1), (0, 1), (0, 0))
        assert not supported(shape, (3, 3), (2, 2), (pads[1], pads[2]))
        x = jnp.asarray(rng.normal(0, 1, shape).astype(np.float32))

        def f(x):
            return maxpool_nhwc_with_pallas_bwd(
                x, dims, strides, pads).sum()

        y, g = jax.value_and_grad(f)(x)
        want = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims,
                                     strides, pads)
        np.testing.assert_allclose(float(y), float(want.sum()), rtol=1e-6)
        assert g.shape == x.shape and np.isfinite(np.asarray(g)).all()


class TestScanHoisting:
    """Input-projection hoisting + unroll are exact-math scan
    transformations (Recurrent docstring); every hoist-capable cell
    must match the plain step path bit-for-tolerance."""

    def _no_hoist(self, cell):
        class NoHoist:
            def __init__(self, c):
                self.c = c

            def __getattr__(self, k):
                return getattr(self.c, k)

            def hoist(self, params, xs):
                return None
        return NoHoist(cell)

    @pytest.mark.parametrize("make", [
        lambda R: R.RnnCell(5, 6),
        lambda R: R.LSTM(5, 6),
        lambda R: R.GRU(5, 6),
        lambda R: R.MultiRNNCell([R.LSTM(5, 6), R.GRU(6, 4)]),
    ], ids=["rnn", "lstm", "gru", "stack"])
    @pytest.mark.parametrize("unroll", [1, 4])
    def test_hoisted_matches_plain(self, make, unroll):
        from bigdl_tpu.nn import recurrent as R
        cell = make(R)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(0, 1, (3, 7, 5)).astype(np.float32))
        r = R.Recurrent(cell, unroll=unroll)
        p, s = r.init(jax.random.PRNGKey(0))
        y, _ = r.apply(p, s, x)
        ref = R.Recurrent(self._no_hoist(cell))
        y_ref, _ = ref.apply(p, s, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                                   atol=1e-5)

    def test_duck_typed_cell_without_hoist_api(self):
        # the Cell contract is duck-typed (quantized cells, user cells
        # predating the hoist API provide only step/initial_hidden);
        # Recurrent must not require the new methods
        from bigdl_tpu.nn import recurrent as R

        class MinimalCell:
            hidden_size = 4

            def initial_hidden(self, batch_size):
                return jnp.zeros((batch_size, 4))

            def step(self, params, x_t, h):
                h2 = jnp.tanh(x_t @ params["w"] + h)
                return h2, h2

        r = R.Recurrent(MinimalCell())
        p = {"w": jnp.ones((3, 4)) * 0.1}
        y, _ = r.apply(p, {}, jnp.ones((2, 5, 3)))
        assert y.shape == (2, 5, 4)
        assert np.isfinite(np.asarray(y)).all()

        # and stacked: MultiRNNCell's layer-0 hoist must duck-type too
        class MC(MinimalCell):
            def initial_hidden(self, batch_size):
                return jnp.zeros((batch_size, 4))

        stack = R.Recurrent(R.MultiRNNCell([MC(), R.GRU(4, 3)]))
        g = R.GRU(4, 3)
        gp, _ = g.init(jax.random.PRNGKey(1))
        y2, _ = stack.apply({"0": p, "1": gp}, {}, jnp.ones((2, 5, 3)))
        assert y2.shape == (2, 5, 3)

    def test_grad_flows_through_hoisted_path(self):
        from bigdl_tpu.nn import recurrent as R
        r = R.Recurrent(R.LSTM(5, 6), unroll=2)
        p, s = r.init(jax.random.PRNGKey(0))
        x = jnp.ones((2, 7, 5))

        def loss(p):
            y, _ = r.apply(p, s, x)
            return jnp.sum(y ** 2)

        g = jax.grad(loss)(p)
        assert np.isfinite(np.asarray(g["weight"])).all()
        assert float(jnp.abs(g["weight"]).sum()) > 0


class TestHoistedScanUnderDP:
    def test_ptb_trains_data_parallel_on_mesh(self, devices):
        """The hoisted+unrolled LSTM must compose with GSPMD data
        parallelism (batch-sharded inputs, replicated params)."""
        from functools import partial
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        from bigdl_tpu import nn, optim
        from bigdl_tpu.models.rnn import ptb_model

        mesh = Mesh(np.array(devices), ("data",))
        model = ptb_model(200, 32, 32, 2, scan_unroll=5)
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
        method = optim.SGD(learning_rate=0.1, momentum=0.9)
        p, s = model.init(jax.random.PRNGKey(0))
        os_ = method.init_state(p)
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.integers(0, 200, (32, 12)).astype(np.int32))
        y = jnp.asarray(rng.integers(0, 200, (32, 12)).astype(np.int32))
        data_sh = NamedSharding(mesh, P("data"))
        repl = NamedSharding(mesh, P())
        x, y = jax.device_put(x, data_sh), jax.device_put(y, data_sh)
        p = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, repl), p)
        os_ = jax.tree_util.tree_map(
            lambda a: jax.device_put(a, repl), os_)

        @partial(jax.jit, donate_argnums=(0, 1))
        def step(p, os_, x, y, it):
            def loss_fn(p):
                out, _ = model.apply(p, s, x)
                return crit.apply(out, y)
            loss, g = jax.value_and_grad(loss_fn)(p)
            p, os_ = method.update(g, p, os_, 0.1, it)
            return p, os_, loss

        losses = []
        for i in range(20):
            p, os_, loss = step(p, os_, x, y, i)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        assert np.isfinite(losses).all()


class TestAdvisorFixes:
    def test_convlstm3d_checkpoint_guard(self):
        from bigdl_tpu.nn.recurrent import ConvLSTMPeephole3D
        cell = ConvLSTMPeephole3D(2, 3, spatial=(2, 4, 4))
        old = ConvLSTMPeephole3D(2, 3, spatial=(2, 4, 4),
                                 with_peephole=False)
        params, _ = old.init(jax.random.PRNGKey(0))
        x = jnp.zeros((1, 2, 2, 4, 4))
        hidden = cell.initial_hidden(1)
        with pytest.raises(KeyError, match="with_peephole=False"):
            cell.step(params, x, hidden)

    def test_sgd_velocity_stays_f32_under_bf16_grads(self):
        from bigdl_tpu import optim
        m = optim.SGD(learning_rate=0.1, momentum=0.9)
        params = {"w": jnp.ones((4,), jnp.float32)}
        state = m.init_state(params)
        assert state["velocity"]["w"].dtype == jnp.float32
        grads = {"w": jnp.full((4,), 0.5, jnp.bfloat16)}
        _, state = m.update(grads, params, state, 0.1, 0)
        assert state["velocity"]["w"].dtype == jnp.float32
