"""Autotuner + tuned-config consumption tests (round-11, ISSUE-9).

Covers the acceptance surface:
- ``tuned_configs.json`` schema validation (stale/malformed files
  rejected LOUDLY, whole-file, with the tuned layer skipped);
- the checked-in repo file parses, round-trips, and references only
  knobs that exist on ``Config`` (tier-1 schema gate);
- the full default-resolution precedence chain — explicit setter >
  ``BIGDL_TPU_*`` env > tuned entry for ``workload@backend`` >
  dataclass default — for ``steps_per_dispatch``,
  ``grad_wire_dtype`` and ``kernel_impl``;
- ``Engine.reset()`` drops the cached tuned file (no cross-run leaks);
- successive halving: deterministic given the same measurements
  (tie-break = lexicographically smallest canonical config key), HARD
  window budget with per-rung survivor counts logged, loud refusal
  when the budget cannot rank the grid;
- the end-to-end gate: ``tools.autotune --workload ptb_lstm --smoke``
  writes a valid tuned file and a subsequent ``Optimizer`` run picks
  up the tuned ``steps_per_dispatch`` through the resolution chain
  (dispatch-counted, not hand-checked);
- inertness: tagging a workload with no tuned entry (absent OR empty
  file) is bitwise inert — same loss sequence, same dispatch count;
- ``bench.PRODUCTION_K`` deprecation shim source attribution.
"""

import json
import logging
import math
import os

import numpy as np
import pytest

import bench
import tools.autotune as autotune
from bigdl_tpu import nn, optim
from bigdl_tpu.engine import Engine
from bigdl_tpu.utils import tuned
from bigdl_tpu.utils.config import Config, configure, reset_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolate(monkeypatch, tmp_path):
    """Every test starts with a fresh config, a fresh Engine and the
    tuned layer pointed at an ABSENT file, so the repo's checked-in
    tuned_configs.json (and any ambient env) cannot leak in."""
    monkeypatch.setenv(tuned.ENV_PATH, str(tmp_path / "absent.json"))
    reset_config()
    Engine.reset()
    yield
    reset_config()
    Engine.reset()


def make_entry(workload="ptb_lstm", backend="cpu", best=None, prov=None):
    return {"workload": workload, "backend": backend,
            "best": dict(best if best is not None
                         else {"steps_per_dispatch": 3}),
            "provenance": dict(prov if prov is not None
                               else {"toolchain": {}, "score": 1.0})}


def write_doc(path, entries, version=tuned.SCHEMA_VERSION):
    path.write_text(json.dumps(
        {"schema_version": version, "entries": entries}))
    return path


def use_file(monkeypatch, path):
    """Point the tuned layer at ``path`` and drop every cache."""
    monkeypatch.setenv(tuned.ENV_PATH, str(path))
    Engine.reset()
    reset_config()


# ===========================================================================
class TestSchemaValidation:
    def test_valid_document_roundtrips(self):
        doc = {"schema_version": 1,
               "entries": {"ptb_lstm@cpu": make_entry()}}
        entries = tuned.validate_document(doc)
        assert entries["ptb_lstm@cpu"]["best"]["steps_per_dispatch"] == 3
        assert json.loads(json.dumps(doc)) == doc

    @pytest.mark.parametrize("version", [0, 2, None, "1"])
    def test_wrong_schema_version_rejected(self, version):
        with pytest.raises(tuned.TunedConfigError, match="schema_version"):
            tuned.validate_document(
                {"schema_version": version, "entries": {}})

    @pytest.mark.parametrize("doc", [[], "x", 7, None])
    def test_non_object_top_level_rejected(self, doc):
        with pytest.raises(tuned.TunedConfigError):
            tuned.validate_document(doc)

    def test_unknown_knob_rejected(self):
        doc = {"schema_version": 1, "entries": {"ptb_lstm@cpu": make_entry(
            best={"no_such_knob": 1})}}
        with pytest.raises(tuned.TunedConfigError, match="no_such_knob"):
            tuned.validate_document(doc)

    @pytest.mark.parametrize("best", [
        {"steps_per_dispatch": "8"},     # str into int knob
        {"steps_per_dispatch": True},    # bool must NOT pass as int
        {"grad_wire_dtype": 16},         # int into str knob
    ])
    def test_type_drift_rejected(self, best):
        doc = {"schema_version": 1,
               "entries": {"ptb_lstm@cpu": make_entry(best=best)}}
        with pytest.raises(tuned.TunedConfigError, match="type"):
            tuned.validate_document(doc)

    def test_float_knob_accepts_int(self):
        doc = {"schema_version": 1, "entries": {"s@cpu": make_entry(
            workload="s", best={"serving_batch_timeout_ms": 2})}}
        assert tuned.validate_document(doc)

    def test_key_workload_mismatch_rejected(self):
        doc = {"schema_version": 1,
               "entries": {"other@cpu": make_entry(workload="ptb_lstm")}}
        with pytest.raises(tuned.TunedConfigError, match="key"):
            tuned.validate_document(doc)

    def test_missing_provenance_rejected(self):
        e = make_entry()
        del e["provenance"]
        with pytest.raises(tuned.TunedConfigError, match="provenance"):
            tuned.validate_document(
                {"schema_version": 1, "entries": {"ptb_lstm@cpu": e}})

    def test_empty_best_rejected(self):
        with pytest.raises(tuned.TunedConfigError, match="best"):
            tuned.validate_document(
                {"schema_version": 1,
                 "entries": {"ptb_lstm@cpu": make_entry(best={})}})


# ===========================================================================
class TestCheckedInFile:
    """Tier-1 gate over the ACTUAL checked-in tuned_configs.json."""

    PATH = os.path.join(REPO, "tuned_configs.json")

    def test_checked_in_file_validates_and_roundtrips(self):
        with open(self.PATH, "r", encoding="utf-8") as fh:
            text = fh.read()
        doc = json.loads(text)
        entries = tuned.validate_document(doc)  # knob/type gate inside
        assert entries, "checked-in tuned_configs.json must ship non-empty"
        assert json.loads(json.dumps(doc)) == doc
        cfg_fields = {f.name for f in
                      __import__("dataclasses").fields(Config)}
        for key, e in entries.items():
            assert set(e["best"]) <= cfg_fields, key
            prov = e["provenance"]
            # measurement provenance: auditable or it didn't happen
            assert "toolchain" in prov and "rungs" in prov, key
            assert prov["windows_total"] <= prov["budget"], key

    def test_cpu_baseline_workloads_present(self):
        with open(self.PATH, "r", encoding="utf-8") as fh:
            entries = tuned.validate_document(json.load(fh))
        assert "ptb_lstm@cpu" in entries
        assert "wide_deep@cpu" in entries


# ===========================================================================
class TestInt8GemmWorkload:
    """The quantized-GEMM tuning grid (the int8 speed-path PR)."""

    def test_registered_with_gated_axes(self):
        wl = autotune.WORKLOADS["int8_gemm"]
        assert wl.kind == "kernel"
        knobs = {ax.knob for ax in wl.axes}
        assert knobs == {"int8_activation_mode", "kernel_impl",
                         "int8_block_rows"}
        # every knob must be a real Config field or configure() rejects
        # the winning trial when it's merged back
        cfg_fields = {f.name for f in
                      __import__("dataclasses").fields(Config)}
        assert knobs <= cfg_fields

    def test_cpu_prunes_mosaic_knobs_loudly(self):
        """On a non-TPU host only the activation-mode axis survives
        (both modes are real XLA compute through the bitwise
        fallback); the tile/impl knobs are pruned WITH reasons."""
        wl = autotune.WORKLOADS["int8_gemm"]
        kept, pruned = autotune.prune_axes(wl.axes, "cpu", 1)
        assert [ax.knob for ax in kept] == ["int8_activation_mode"]
        assert set(pruned) == {"kernel_impl", "int8_block_rows"}
        for why in pruned.values():
            assert why  # never silently

    def test_smoke_grid_measures_on_cpu(self):
        r = autotune.tune("int8_gemm", budget=6, smoke=True,
                          dry_run=True)
        assert r["n_configs"] == 2  # weight_only vs dynamic
        assert r["best_config"]["int8_activation_mode"] in (
            "weight_only", "dynamic")
        assert r["score"] > 0

    def test_tuned_block_rows_picked_up_by_kernel_chain(
            self, monkeypatch, tmp_path):
        """int8_matmul's block_rows=None defers to the config chain:
        a tuned int8_gemm@cpu entry must win over the dataclass
        default, and an explicit configure() must beat the tuned
        value."""
        p = write_doc(tmp_path / "t.json", {"int8_gemm@cpu": make_entry(
            workload="int8_gemm", best={"int8_block_rows": 64})})
        use_file(monkeypatch, p)
        v, src = tuned.resolve_default("int8_block_rows",
                                       workload="int8_gemm",
                                       backend="cpu")
        assert (v, src) == (64, "tuned")
        configure(int8_block_rows=128)
        v, src = tuned.resolve_default("int8_block_rows",
                                       workload="int8_gemm",
                                       backend="cpu")
        assert (v, src) == (128, "explicit")


# ===========================================================================
class TestResolutionChain:
    """explicit setter > BIGDL_TPU_* env > tuned entry > dataclass
    default, per knob (the documented order, utils/tuned docstring)."""

    CASES = [
        ("steps_per_dispatch", 3, "BIGDL_TPU_STEPS_PER_DISPATCH",
         "5", 5, 7),
        ("grad_wire_dtype", "bf16", "BIGDL_TPU_GRAD_WIRE_DTYPE",
         "f16", "f16", "f32"),
        ("kernel_impl", "xla", "BIGDL_TPU_KERNEL_IMPL",
         "pallas", "pallas", "xla"),
    ]

    @pytest.mark.parametrize("knob,tv,env,envs,envv,expl", CASES)
    def test_chain(self, monkeypatch, tmp_path, knob, tv, env, envs,
                   envv, expl):
        default = getattr(Config(), knob)
        p = write_doc(tmp_path / "t.json",
                      {"ptb_lstm@cpu": make_entry(best={knob: tv})})
        use_file(monkeypatch, p)
        # 1) no tag: dataclass default
        assert tuned.resolve_default(knob) == (default, "default")
        # 2) tagged: tuned beats default
        assert tuned.resolve_default(knob, workload="ptb_lstm") == \
            (tv, "tuned")
        # 3) env beats tuned even when tagged
        monkeypatch.setenv(env, envs)
        reset_config()
        assert tuned.resolve_default(knob, workload="ptb_lstm") == \
            (envv, "env")
        # 4) explicit configure() beats env
        configure(**{knob: expl})
        assert tuned.resolve_default(knob, workload="ptb_lstm") == \
            (expl, "explicit")

    def test_engine_steps_per_dispatch_chain(self, monkeypatch, tmp_path):
        p = write_doc(tmp_path / "t.json", {"ptb_lstm@cpu": make_entry(
            best={"steps_per_dispatch": 3})})
        use_file(monkeypatch, p)
        assert Engine.steps_per_dispatch() == 1
        assert Engine.steps_per_dispatch(workload="ptb_lstm") == 3
        # process-wide tag works where the call site carries none
        Engine.set_workload("ptb_lstm")
        assert Engine.steps_per_dispatch() == 3
        Engine.set_workload(None)
        # the explicit Engine setter tops everything
        Engine.set_steps_per_dispatch(9)
        assert Engine.steps_per_dispatch(workload="ptb_lstm") == 9

    def test_engine_kernel_impl_chain(self, monkeypatch, tmp_path):
        p = write_doc(tmp_path / "t.json", {"ptb_lstm@cpu": make_entry(
            best={"kernel_impl": "xla"})})
        use_file(monkeypatch, p)
        assert Engine.kernel_impl() == "auto"
        assert Engine.kernel_impl(workload="ptb_lstm") == "xla"
        Engine.set_kernel_impl("pallas")
        assert Engine.kernel_impl(workload="ptb_lstm") == "pallas"

    def test_backend_keying_isolates_tuned_values(self, monkeypatch,
                                                  tmp_path):
        """A tpu-tuned entry must never leak onto a cpu run."""
        p = write_doc(tmp_path / "t.json", {"ptb_lstm@tpu": make_entry(
            backend="tpu", best={"steps_per_dispatch": 16})})
        use_file(monkeypatch, p)
        assert Engine.steps_per_dispatch(workload="ptb_lstm") == 1

    def test_serving_defaults_pick_up_tuned_entry(self, monkeypatch,
                                                  tmp_path):
        p = write_doc(tmp_path / "t.json", {"serving_mlp@cpu": make_entry(
            workload="serving_mlp",
            best={"serving_max_batch_size": 16,
                  "serving_batch_timeout_ms": 1.5,
                  "serving_row_buckets": "top"})})
        use_file(monkeypatch, p)
        d = Engine.serving_defaults("serving_mlp")
        assert d["max_batch_size"] == 16
        assert d["batch_timeout_ms"] == 1.5
        assert d["row_buckets"] == "top"
        # untagged service sees plain config defaults
        d0 = Engine.serving_defaults()
        assert d0["max_batch_size"] == 32
        assert d0["row_buckets"] == ""

    def test_activation_memory_explicit_none_beats_tuned(
            self, monkeypatch, tmp_path):
        """set_activation_memory(None) is the documented INERT policy,
        not 'unset': it must override a tuned/env value exactly like
        'none' does (only a never-called setter lets the default chain
        fill the knob)."""
        p = write_doc(tmp_path / "t.json", {"ptb_lstm@cpu": make_entry(
            best={"activation_memory": "dots"})})
        use_file(monkeypatch, p)

        def opt():
            model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
            return optim.LocalOptimizer(
                model, None, nn.ClassNLLCriterion()).set_workload(
                    "ptb_lstm")

        # setter never called: tuned policy applies
        assert opt()._resolved_activation_memory() == "dots"
        # explicit None forces the inert policy over the tuned entry
        assert opt().set_activation_memory(
            None)._resolved_activation_memory() == "none"
        # ... and over an env value too
        monkeypatch.setenv("BIGDL_TPU_ACTIVATION_MEMORY", "full")
        reset_config()
        assert opt()._resolved_activation_memory() == "full"
        assert opt().set_activation_memory(
            None)._resolved_activation_memory() == "none"


# ===========================================================================
class TestFailureContract:
    def test_absent_file_is_silent_and_inert(self, caplog):
        with caplog.at_level(logging.ERROR, logger="bigdl_tpu.tuned"):
            v, src = tuned.resolve_default("steps_per_dispatch",
                                           workload="ptb_lstm")
        assert (v, src) == (1, "default")
        assert caplog.records == []

    def test_empty_file_is_silent_and_inert(self, monkeypatch, tmp_path,
                                            caplog):
        p = tmp_path / "empty.json"
        p.write_text("")
        use_file(monkeypatch, p)
        with caplog.at_level(logging.ERROR, logger="bigdl_tpu.tuned"):
            assert tuned.resolve_default(
                "steps_per_dispatch", workload="ptb_lstm") == \
                (1, "default")
        assert caplog.records == []

    @pytest.mark.parametrize("text", [
        "{not json",
        '{"schema_version": 99, "entries": {}}',
        '{"entries": {}}',
    ])
    def test_damaged_file_rejected_loudly_layer_skipped(
            self, monkeypatch, tmp_path, caplog, text):
        p = tmp_path / "bad.json"
        p.write_text(text)
        use_file(monkeypatch, p)
        with caplog.at_level(logging.ERROR, logger="bigdl_tpu.tuned"):
            v, src = tuned.resolve_default("steps_per_dispatch",
                                           workload="ptb_lstm")
        assert (v, src) == (1, "default")
        assert len(caplog.records) == 1  # ONE loud rejection
        assert str(p) in caplog.records[0].getMessage()

    def test_one_bad_entry_poisons_whole_file(self, monkeypatch,
                                              tmp_path, caplog):
        """Partial trust is no trust: a good entry in a file with one
        bad knob must NOT be applied."""
        p = write_doc(tmp_path / "mixed.json", {
            "ptb_lstm@cpu": make_entry(best={"steps_per_dispatch": 4}),
            "wide_deep@cpu": make_entry(workload="wide_deep",
                                        best={"bogus_knob": 1}),
        })
        use_file(monkeypatch, p)
        with caplog.at_level(logging.ERROR, logger="bigdl_tpu.tuned"):
            v, src = tuned.resolve_default("steps_per_dispatch",
                                           workload="ptb_lstm")
        assert (v, src) == (1, "default")
        assert len(caplog.records) == 1


# ===========================================================================
class TestEngineResetClearsCache:
    def test_reset_forgets_cached_tuned_file(self, monkeypatch, tmp_path):
        """The ISSUE-9 regression gate: a prior workload's tuned
        defaults must not leak across Engine.reset() boundaries."""
        p = write_doc(tmp_path / "t.json", {"ptb_lstm@cpu": make_entry(
            best={"steps_per_dispatch": 3})})
        use_file(monkeypatch, p)
        assert Engine.steps_per_dispatch(workload="ptb_lstm") == 3
        write_doc(p, {"ptb_lstm@cpu": make_entry(
            best={"steps_per_dispatch": 4})})
        # cached: the rewrite is invisible until a reset
        assert Engine.steps_per_dispatch(workload="ptb_lstm") == 3
        Engine.reset()
        assert Engine.steps_per_dispatch(workload="ptb_lstm") == 4

    def test_reset_cache_alone_reloads(self, monkeypatch, tmp_path):
        p = write_doc(tmp_path / "t.json", {"ptb_lstm@cpu": make_entry(
            best={"steps_per_dispatch": 3})})
        use_file(monkeypatch, p)
        assert tuned.lookup("ptb_lstm", "steps_per_dispatch") == 3
        p.unlink()
        tuned.reset_cache()
        assert tuned.lookup("ptb_lstm", "steps_per_dispatch") is None


# ===========================================================================
class TestProductionKShim:
    def test_tuned_entry_wins_with_source(self, monkeypatch, tmp_path):
        p = write_doc(tmp_path / "t.json", {"ptb_lstm@cpu": make_entry(
            best={"steps_per_dispatch": 5})})
        use_file(monkeypatch, p)
        assert bench.PRODUCTION_K["ptb_lstm"] == 5
        assert bench.PRODUCTION_K.source("ptb_lstm") == \
            (5, "tuned_configs.json")

    def test_hand_dict_fallback(self):
        # fixture points at an absent file: every workload falls back
        assert bench.PRODUCTION_K["ptb_lstm"] == 8
        assert bench.PRODUCTION_K.source("wide_deep") == (8, "hand")
        assert bench.PRODUCTION_K.source("resnet50") == (1, "hand")

    def test_unknown_workload_still_raises(self):
        with pytest.raises(KeyError):
            bench.PRODUCTION_K["nope"]


# ===========================================================================
class TestSuccessiveHalving:
    """Pure search-driver semantics via injected measurements — no jax
    in the loop."""

    @staticmethod
    def grid(n):
        return [{"steps_per_dispatch": 2 ** i} for i in range(n)]

    def test_plan_rungs_spends_budget_back_to_front(self):
        # ladder [8,4,2,1]; minimal 15; leftover flows to late rungs
        assert autotune.plan_rungs(8, 24, eta=2, full_windows=4) == \
            [(8, 1), (4, 1), (2, 4), (1, 4)]
        assert autotune.plan_rungs(2, 8, eta=2, full_windows=4) == \
            [(2, 2), (1, 4)]

    def test_plan_refuses_unrankable_budget(self):
        with pytest.raises(ValueError, match="budget"):
            autotune.plan_rungs(8, 14)  # minimal is 15

    def test_budget_is_hard_and_rungs_logged(self):
        calls = []

        def measure(cfg, windows, rung):
            calls.append(windows)
            return [100.0 + cfg["steps_per_dispatch"]] * windows

        budget = 24
        res = autotune.successive_halving(self.grid(8), measure, budget)
        assert res["windows_total"] == sum(calls) <= budget
        assert res["budget"] == budget
        assert [r["trials"] for r in res["rungs"]] == [8, 4, 2, 1]
        assert [r["survivors"] for r in res["rungs"]] == [4, 2, 1, 1]
        assert sum(r["windows_used"] for r in res["rungs"]) == \
            res["windows_total"]

    def test_deterministic_given_same_measurements(self):
        def measure(cfg, windows, rung):
            # deterministic but config-dependent; rung-independent
            base = 100.0 + (cfg["steps_per_dispatch"] * 7919) % 13
            return [base + 0.01 * w for w in range(windows)]

        a = autotune.successive_halving(self.grid(8), measure, 24)
        b = autotune.successive_halving(self.grid(8), measure, 24)
        assert a == b

    def test_best_config_wins(self):
        def measure(cfg, windows, rung):
            return [float(cfg["steps_per_dispatch"])] * windows

        res = autotune.successive_halving(self.grid(5), measure, 16)
        assert res["best_config"] == {"steps_per_dispatch": 16}
        scores = [e["score"] for e in res["leaderboard"]]
        assert scores == sorted(scores, reverse=True)

    def test_exact_tie_breaks_to_smallest_canonical_key(self):
        def measure(cfg, windows, rung):
            return [42.0] * windows

        trials = [{"b": 2}, {"a": 1}, {"c": 3}]
        res = autotune.successive_halving(trials, measure, 12)
        assert res["best_config"] == {"a": 1}
        assert autotune.config_key(res["best_config"]) == \
            min(autotune.config_key(t) for t in trials)

    def test_steady_filter_excludes_outlier_windows(self):
        steady, excluded = autotune.steady_filter([100, 101, 99, 50])
        assert excluded == 1 and 50 not in steady
        # short sample lists pass through untouched
        assert autotune.steady_filter([100, 50]) == ([100, 50], 0)

    def test_steady_filter_is_the_shared_bench_filter(self):
        """One implementation (bench.steady_windows) backs both the
        autotuner and scaling_child, so exclusion accountings stay
        comparable; a uniformly-unsteady trial scores on the reference
        with EVERY window counted excluded — never a silent fall-back
        to the raw set."""
        import bench
        samples = [100.0, 101.0, 99.0, 50.0]
        kept_b, excl_b, _ = bench.steady_windows(samples, min_samples=4)
        assert autotune.steady_filter(samples) == (kept_b, excl_b)
        # nothing within ±15% of the reference: ref scored, all excluded
        unsteady = [100.0, 50.0, 200.0, 10.0]
        steady, excluded = autotune.steady_filter(unsteady)
        assert excluded == len(unsteady)
        assert steady == [bench.steady_windows(unsteady,
                                               min_samples=4)[2]]

    def test_axis_pruning_is_recorded_not_silent(self):
        kept, pruned = autotune.prune_axes(
            autotune._TRAINING_AXES, backend="cpu", n_devices=1)
        assert {ax.knob for ax in kept} == \
            {"steps_per_dispatch", "activation_memory"}
        assert set(pruned) == {"kernel_impl", "grad_wire_dtype",
                               "grad_bucket_bytes"}
        assert all(pruned.values())  # every prune carries its reason
        kept_tpu, pruned_tpu = autotune.prune_axes(
            autotune._TRAINING_AXES, backend="tpu", n_devices=8)
        assert pruned_tpu == {}

    def test_grid_build_order_deterministic(self):
        axes = (autotune.Axis("a", (1, 2)), autotune.Axis("b", ("x",)))
        assert autotune.build_grid(axes) == [
            {"a": 1, "b": "x"}, {"a": 2, "b": "x"}]


# ===========================================================================
class RecordingSummary:
    def __init__(self):
        self.losses = []

    def add_train_step(self, step, loss, lr, throughput):
        self.losses.append(loss)

    def add_scalar(self, *a):
        pass

    def trigger_for(self, name):
        return None


def tiny_run(iters=6, workload=None, k=None):
    from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(0, 1, (16,)).astype(np.float32),
                      np.int32(rng.integers(0, 4)))
               for _ in range(64)]
    model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(),
                          nn.Linear(16, 4), nn.LogSoftMax())
    rec = RecordingSummary()
    opt = (optim.LocalOptimizer(model,
                                DataSet.array(samples)
                                >> SampleToMiniBatch(16),
                                nn.ClassNLLCriterion())
           .set_optim_method(optim.SGD(learning_rate=0.1))
           .set_seed(7)
           .set_train_summary(rec)
           .set_end_when(optim.max_iteration(iters)))
    if workload is not None:
        opt.set_workload(workload)
    if k is not None:
        opt.set_steps_per_dispatch(k)
    opt.optimize()
    return np.asarray(rec.losses), opt


# ===========================================================================
class TestInertness:
    """Enabling the tuned-config layer with an absent or empty file is
    provably inert (the established bitwise gate pattern)."""

    def test_workload_tag_with_absent_file_bitwise_inert(self):
        base_losses, base_opt = tiny_run()
        tag_losses, tag_opt = tiny_run(workload="no_such_workload")
        np.testing.assert_array_equal(base_losses, tag_losses)
        assert base_opt._dispatch_count == tag_opt._dispatch_count

    def test_workload_tag_with_empty_file_bitwise_inert(
            self, monkeypatch, tmp_path):
        base_losses, base_opt = tiny_run()
        p = tmp_path / "empty.json"
        p.write_text("")
        use_file(monkeypatch, p)
        tag_losses, tag_opt = tiny_run(workload="ptb_lstm")
        np.testing.assert_array_equal(base_losses, tag_losses)
        assert base_opt._dispatch_count == tag_opt._dispatch_count


# ===========================================================================
class TestEndToEnd:
    """The ISSUE-9 acceptance gate: the CLI completes on CPU, writes a
    valid file, and a subsequent Optimizer run picks the tuned K up
    through the resolution chain — proven by dispatch count."""

    def test_autotune_cli_to_optimizer_pickup(self, monkeypatch,
                                              tmp_path, capsys):
        out = tmp_path / "tuned.json"
        rc = autotune.main(["--workload", "ptb_lstm", "--smoke",
                            "--budget", "6", "--out", str(out)])
        assert rc == 0
        printed = json.loads(capsys.readouterr().out.strip())
        assert printed["windows_total"] <= printed["budget"] == 6
        assert [r["survivors"] for r in printed["rungs"]][-1] == 1
        with open(out, "r", encoding="utf-8") as fh:
            entries = tuned.validate_document(json.load(fh))
        k = entries["ptb_lstm@cpu"]["best"]["steps_per_dispatch"]
        assert k in (1, 2)  # the smoke grid
        # consumption: a fresh process state + the tuned file
        use_file(monkeypatch, out)
        assert Engine.steps_per_dispatch(workload="ptb_lstm") == k
        iters = 6
        _, opt = tiny_run(iters=iters, workload="ptb_lstm")
        assert opt._dispatch_count == math.ceil(iters / k)
        # and an untagged run keeps the dataclass default K=1
        _, opt0 = tiny_run(iters=iters)
        assert opt0._dispatch_count == iters

    def test_merge_preserves_other_entries(self, tmp_path):
        out = write_doc(tmp_path / "t.json", {"wide_deep@cpu": make_entry(
            workload="wide_deep", best={"steps_per_dispatch": 4})})
        result = {"best_config": {"steps_per_dispatch": 2}}
        autotune.write_tuned(str(out), "ptb_lstm", "cpu", result,
                             {"toolchain": {}})
        with open(out, "r", encoding="utf-8") as fh:
            entries = tuned.validate_document(json.load(fh))
        assert set(entries) == {"wide_deep@cpu", "ptb_lstm@cpu"}

    def test_write_refuses_to_extend_damaged_file(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text('{"schema_version": 99, "entries": {}}')
        with pytest.raises(tuned.TunedConfigError):
            autotune.write_tuned(str(bad), "ptb_lstm", "cpu",
                                 {"best_config": {"steps_per_dispatch": 2}},
                                 {"toolchain": {}})

    def test_unknown_workload_exits_loudly(self):
        with pytest.raises(SystemExit, match="unknown workload"):
            autotune.tune("no_such_workload", budget=4)

    def test_smoke_refuses_default_out_path(self, tmp_path):
        """A smoke winner (tiny models, tiny grid) must never replace
        a production-tuned entry in the checked-in file: --smoke
        without an explicit --out or --dry-run is refused BEFORE any
        budget is spent."""
        with pytest.raises(SystemExit, match="smoke"):
            autotune.tune("ptb_lstm", budget=6, smoke=True)
        # an explicit out path (the CLI gate test) and dry-run both
        # stay legal — only the default checked-in path is protected
        res = autotune.tune("ptb_lstm", budget=6, smoke=True,
                            dry_run=True,
                            measure=lambda t, w, r: [1.0] * w)
        assert res["smoke"] and "out" not in res


# ===========================================================================
class TestMeasureActivationMemory:
    """The bench._measure remat knob the autotuner trials sweep."""

    def _xy(self):
        import jax.numpy as jnp
        rng = np.random.default_rng(0)
        return (jnp.asarray(rng.normal(0, 1, (8, 16))
                            .astype(np.float32)),
                jnp.asarray(rng.integers(0, 4, (8,)).astype(np.int32)))

    def _model(self):
        return nn.Sequential(nn.Linear(16, 16), nn.ReLU(),
                             nn.Linear(16, 4), nn.LogSoftMax())

    def test_invalid_policy_rejected(self):
        x, y = self._xy()
        with pytest.raises(ValueError, match="activation_memory"):
            bench._measure(self._model(), 8, 1, 1, x=x, y=y,
                           criterion=nn.ClassNLLCriterion(),
                           activation_memory="bf16")

    def test_dots_policy_measures(self):
        x, y = self._xy()
        samples, ca, _ = bench._measure(
            self._model(), 8, 1, 2, x=x, y=y,
            criterion=nn.ClassNLLCriterion(),
            activation_memory="dots")
        assert len(samples) == 1 and samples[0] > 0


# ===========================================================================
class TestServingKnobs:
    """parse_row_buckets spec grammar + the tuned serving path."""

    def test_spec_grammar(self):
        from bigdl_tpu.serving.service import parse_row_buckets
        assert parse_row_buckets("", 32) == (1, 2, 4, 8, 16, 32)
        assert parse_row_buckets("pow2", 32) == (1, 2, 4, 8, 16, 32)
        assert parse_row_buckets("top", 32) == (32,)
        assert parse_row_buckets("8,16,32", 32) == (8, 16, 32)

    @pytest.mark.parametrize("spec", ["8,x", "16,8", "8,8,16", "0,8",
                                      "4,8"])
    def test_bad_specs_rejected(self, spec):
        from bigdl_tpu.serving.service import parse_row_buckets
        with pytest.raises(ValueError):
            parse_row_buckets(spec, 32)

    def test_explicit_tuple_validated_through_same_grammar(self):
        from bigdl_tpu.serving.service import parse_row_buckets
        with pytest.raises(ValueError):
            parse_row_buckets("16,8", 8)


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
