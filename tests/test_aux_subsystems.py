"""Aux subsystems: LBFGS+LineSearch, per-layer profiling, unified config,
TF control-flow (Switch/Merge) import.

Reference analogs: ``DL/optim/LBFGS.scala``+``LineSearch.scala``,
``AbstractModule.getTimes`` (``AbstractModule.scala:254-287``),
the ``bigdl.*`` property soup (``Engine.scala:45-47``), and the
DynamicGraph ``Scheduler`` (``nn/Scheduler.scala:104-145``).
"""

import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tfgraph_util import attr_tensor, node, scalar_const, shape_const  # noqa: E501
from bigdl_tpu import nn, optim


class TestLBFGS:
    def test_minimize_rosenbrock(self):
        def rosen(p):
            x, y = p["x"], p["y"]
            return (1 - x) ** 2 + 100.0 * (y - x * x) ** 2

        feval = jax.jit(jax.value_and_grad(rosen))
        p0 = {"x": jnp.asarray(-1.2), "y": jnp.asarray(1.0)}
        p, loss, it = optim.LBFGS(history=10).minimize(feval, p0,
                                                       max_iter=100)
        assert loss < 1e-8
        np.testing.assert_allclose(float(p["x"]), 1.0, atol=1e-3)

    def test_update_contract_under_jit(self):
        A = jnp.asarray(np.diag([1.0, 10.0, 100.0]))

        def q(p):
            return 0.5 * p["w"] @ A @ p["w"]

        lb = optim.LBFGS(history=5)
        params = {"w": jnp.asarray([1.0, 1.0, 1.0])}
        st = lb.init_state(params)
        vg = jax.value_and_grad(q)
        upd = jax.jit(lb.update)
        for i in range(50):
            _, g = vg(params)
            params, st = upd(g, params, st, 0.5, i)
        assert float(q(params)) < 1e-6

    def test_trains_via_optimizer(self):
        # full-batch logistic regression through the normal Optimizer API
        rng = np.random.RandomState(0)
        x = rng.randn(128, 4).astype(np.float32)
        w_true = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
        y = (x @ w_true > 0).astype(np.int32)
        from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.sample import Sample
        samples = [Sample(x[i], y[i]) for i in range(128)]
        model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
        opt = (optim.LocalOptimizer(
                   model, DataSet.array(samples) >> SampleToMiniBatch(128),
                   nn.ClassNLLCriterion())
               .set_optim_method(optim.LBFGS(learning_rate=0.5))
               .set_end_when(optim.max_epoch(30)))
        opt.optimize()
        model.training = False
        acc = (np.argmax(np.asarray(model.forward(x)), -1) == y).mean()
        assert acc > 0.95, acc


class TestProfiling:
    def test_get_times_per_layer(self):
        from bigdl_tpu.utils.profiling import format_times, get_times
        m = nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                          nn.Linear(128, 8), nn.LogSoftMax())
        m.initialize()
        x = jnp.ones((16, 64))
        times = get_times(m, x, repeats=2)
        names = [t.name for t in times]
        # one row per leaf (execution order) + total
        assert sum("Linear" in n for n in names) == 2
        assert all(t.forward_s >= 0 for t in times)
        table = format_times(times)
        assert "fwd(ms)" in table and "Linear" in table

    def test_profile_step_writes_trace(self, tmp_path):
        from bigdl_tpu.utils.profiling import profile_step
        f = jax.jit(lambda x: jnp.sum(x * x))
        out = profile_step(f, jnp.ones((128, 128)),
                           log_dir=str(tmp_path), steps=2)
        assert np.isfinite(float(out))
        # a trace directory appeared
        found = any("plugins" in root or f
                    for root, _, f in os.walk(tmp_path))
        assert found


class TestConfig:
    def test_env_overlay_and_configure(self, monkeypatch):
        from bigdl_tpu.utils import config as C
        C.reset_config()
        monkeypatch.setenv("BIGDL_TPU_FAILURE_RETRY_TIMES", "7")
        monkeypatch.setenv("BIGDL_TPU_COMPUTE_DTYPE", "bfloat16")
        cfg = C.get_config()
        assert cfg.failure_retry_times == 7
        assert cfg.compute_dtype == "bfloat16"
        C.configure(loader_workers=12)
        assert C.get_config().loader_workers == 12
        with pytest.raises(AttributeError):
            C.configure(nonsense=1)
        C.reset_config()

    def test_engine_reads_config_default(self):
        from bigdl_tpu.utils import config as C
        C.reset_config()
        from bigdl_tpu.engine import _EngineState
        assert _EngineState().failure_retry_times == \
            C.get_config().failure_retry_times


class TestControlFlowImport:
    def _cond_graph(self, tmp_path):
        from bigdl_tpu.utils import protowire as pw



        g = (node("x", "Placeholder")
             + node("pred", "Placeholder")
             + node("sw", "Switch", ["x", "pred"])
             + node("two", "Const", value=scalar_const(2.0))
             + node("ten", "Const", value=scalar_const(10.0))
             + node("tb", "Mul", ["sw:1", "two"])
             + node("fb", "Add", ["sw:0", "ten"])
             + node("merged", "Merge", ["fb", "tb"])
             + node("out", "Identity", ["merged"]))
        p = str(tmp_path / "cond.pb")
        open(p, "wb").write(g)
        return p

    def test_cond_selects_by_predicate(self, tmp_path):
        from bigdl_tpu.interop import load_tf_graph
        m = load_tf_graph(self._cond_graph(tmp_path),
                          inputs=["x", "pred"], outputs=["out"])
        x = np.array([1.0, 2.0], np.float32)
        t, _ = m.apply({}, {}, {"x": x, "pred": np.array(True)})
        f, _ = m.apply({}, {}, {"x": x, "pred": np.array(False)})
        np.testing.assert_allclose(np.asarray(t), x * 2)
        np.testing.assert_allclose(np.asarray(f), x + 10)

    def test_cond_with_traced_predicate_under_jit(self, tmp_path):
        from bigdl_tpu.interop import load_tf_graph
        m = load_tf_graph(self._cond_graph(tmp_path),
                          inputs=["x", "pred"], outputs=["out"])
        x = np.array([3.0], np.float32)
        fn = jax.jit(lambda x, p: m.apply({}, {},
                                          {"x": x, "pred": p})[0])
        np.testing.assert_allclose(np.asarray(fn(x, True)), x * 2)
        np.testing.assert_allclose(np.asarray(fn(x, False)), x + 10)

    def test_malformed_loop_frame_rejected(self, tmp_path):
        # a lone Enter with no LoopCond is not a valid while frame; the
        # loader (which now reconstructs real loops) rejects it up front
        from bigdl_tpu.interop import load_tf_graph
        from bigdl_tpu.utils import protowire as pw
        g = (pw.enc_bytes(1, pw.enc_str(1, "x")
                          + pw.enc_str(2, "Placeholder"))
             + pw.enc_bytes(1, pw.enc_str(1, "e") + pw.enc_str(2, "Enter")
                            + pw.enc_str(3, "x")))
        p = str(tmp_path / "loop.pb")
        open(p, "wb").write(g)
        with pytest.raises(NotImplementedError, match="LoopCond"):
            load_tf_graph(p, inputs=["x"], outputs=["e"])


class TestAuxReviewFixes:
    """Regressions for the round-2 aux review findings."""

    def test_lbfgs_survives_rejected_first_pair(self):
        # first (s, y) pair violates curvature (crafted gradient flip);
        # the optimizer must keep moving (used to freeze forever)
        lb = optim.LBFGS(history=4, learning_rate=0.1)
        params = {"w": jnp.asarray([1.0, -1.0, 2.0])}
        st = lb.init_state(params)
        grads = [jnp.asarray([2.0, 2.0, 2.0]),    # step 0
                 jnp.asarray([4.0, 4.0, 4.0]),    # s.y < 0 vs step 0 dir
                 jnp.asarray([1.0, 1.0, 1.0]),
                 jnp.asarray([0.5, 0.5, 0.5])]
        prev = params["w"]
        for i, g in enumerate(grads):
            params, st = lb.update({"w": g}, params, st, 0.1, i)
        assert not np.allclose(np.asarray(params["w"]),
                               np.asarray(prev)), "LBFGS froze"
        assert np.isfinite(np.asarray(params["w"])).all()

    def test_lbfgs_minimize_no_unevaluated_step(self):
        # a badly scaled objective where curvature keeps failing must not
        # commit an unevaluated exploding step
        def f(p):
            return jnp.sum(jnp.abs(p["w"]) ** 1.5)

        feval = jax.value_and_grad(f)
        p0 = {"w": jnp.asarray([2.0, -3.0])}
        p, loss, _ = optim.LBFGS().minimize(feval, p0, max_iter=20,
                                            max_ls=4)
        assert np.isfinite(loss)
        assert loss <= float(f(p0)) + 1e-9

    def test_imported_random_inits_differ_per_node(self, tmp_path):
        from bigdl_tpu.interop import load_tf_graph
        from bigdl_tpu.utils import protowire as pw



        g = b""
        for name in ("v1", "v2"):
            g += node(f"{name}/shape", "Const", value=shape_const([4, 4]))
            g += node(f"{name}/init", "TruncatedNormal",
                      [f"{name}/shape"])
            g += node(name, "VariableV2")
            g += node(f"{name}/assign", "Assign", [name, f"{name}/init"])
        g += node("out", "Add", ["v1", "v2"])
        p = str(tmp_path / "g.pb")
        open(p, "wb").write(g)
        m = load_tf_graph(p, inputs=[], outputs=["out"])
        v1, v2 = np.asarray(m._var_init["v1"]), np.asarray(m._var_init["v2"])
        assert v1.shape == (4, 4)
        assert not np.allclose(v1, v2), "same-shape inits byte-identical"

    def test_dilated_conv2d_attr_respected(self):
        from bigdl_tpu.ops import get_op
        x = np.random.RandomState(0).randn(1, 8, 8, 1).astype(np.float32)
        w = np.random.RandomState(1).randn(3, 3, 1, 1).astype(np.float32)
        conv = get_op("Conv2D")
        base = conv({"strides": [1, 1, 1, 1], "padding": b"VALID"}, x, w)
        dil = conv({"strides": [1, 1, 1, 1], "padding": b"VALID",
                    "dilations": [1, 2, 2, 1]}, x, w)
        assert base.shape == (1, 6, 6, 1)
        assert dil.shape == (1, 4, 4, 1)  # effective kernel 5x5

    def test_convert_cli_rejects_tf_to_bigdl_before_load(self, tmp_path):
        from bigdl_tpu.interop.convert_model import main as convert
        with pytest.raises(SystemExit):
            convert(["--from", "tensorflow", "--to", "bigdl",
                     "--input", str(tmp_path / "missing.pb"),
                     "--output", str(tmp_path / "x.bigdl"),
                     "--inputs", "a", "--outputs", "b"])
