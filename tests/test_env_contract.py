"""Toolchain-drift guard (VERDICT r4 item 7).

Round 3→4 showed the environment can change under the repo between
rounds (jax 0.8→0.9 recompiled identical source to +6.4 GB/step and
nothing noticed in-round), and a harness regression (a silently
swallowed cost-analysis failure) shipped a BENCH capture with half the
deliverable missing.  These tests make both failure modes loud:

- the jax version floor and the shard_map API shape this repo depends
  on (``from jax import shard_map`` + ``check_vma=``) are asserted, so
  the next upgrade fails CI instead of silently changing semantics;
- the real accelerator's presence is asserted (subprocess probe — this
  suite itself pins CPU by design, ``conftest.py``);
- ``bench.py --resnet-only --smoke`` must emit a JSON with EVERY key
  the round deliverable needs, including the roofline fields whose
  silent loss was r4's headline integrity failure.
"""

import functools
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _clean_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)       # no CPU-mesh device-count leak
    env.pop("JAX_PLATFORMS", None)   # children choose the real platform
    return env


def test_jax_version_floor():
    import jax
    import jaxlib
    ver = tuple(int(p) for p in jax.__version__.split(".")[:2])
    assert ver >= (0, 9), (
        f"jax {jax.__version__} < 0.9: bench numbers and the shard_map "
        f"API contract were calibrated under 0.9 — recalibrate before "
        f"trusting a BENCH capture from this environment")
    assert jaxlib.__version__.split(".")[:2] == \
        jax.__version__.split(".")[:2], "jax/jaxlib version skew"


def test_shard_map_api_shape():
    # the repo-wide import path and kwarg (parallel/pipeline.py,
    # bench.py collective child): jax>=0.8 renamed check_rep→check_vma
    from jax import shard_map
    import inspect
    params = inspect.signature(shard_map).parameters
    assert "check_vma" in params, list(params)
    assert "mesh" in params and "in_specs" in params \
        and "out_specs" in params


@functools.lru_cache(maxsize=1)
def _probe_platform():
    r = subprocess.run(
        [sys.executable, "-c",
         "import jax; d = jax.devices()[0]; "
         "print(d.platform, getattr(d, 'device_kind', '?'))"],
        capture_output=True, text=True, timeout=180, env=_clean_env())
    assert r.returncode == 0, r.stderr[-1000:]
    return r.stdout.strip().split()[0] if r.stdout.strip() else "?"


def test_real_accelerator_present():
    """The driver's bench runs on the real chip; if the tunnel is gone,
    every throughput number silently becomes a CPU number.  Probe in a
    subprocess (this process is CPU-pinned by conftest)."""
    platform = _probe_platform()
    if platform != "tpu":
        pytest.skip(f"no TPU attached (platform={platform}) — bench "
                    f"numbers from this machine are not chip numbers")


# every key a BENCH_r* capture is contractually required to carry;
# `bottleneck`/`mfu` may be replaced by cost_analysis_error — but that
# substitution must be LOUD (asserted below), never a silent drop
_SMOKE_KEYS = {"metric", "value", "unit", "vs_baseline", "best_window",
               "spread", "toolchain", "timing_path", "config"}
_SPREAD_KEYS = {"median", "min", "max", "rel_spread", "windows"}
_TOOLCHAIN_KEYS = {"jax", "jaxlib", "platform", "device_kind"}


def test_bench_smoke_emits_full_contract():
    """1-window/4-iter smoke run of the real bench entry (on the real
    chip when attached).  A field-dropping harness regression fails
    HERE instead of shipping inside a round's BENCH capture."""
    if _probe_platform() != "tpu":
        pytest.skip("no TPU attached — the b256 ResNet smoke step is "
                    "impractical on this host's CPU; the contract is "
                    "only meaningful for chip captures")
    try:
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py"),
             "--resnet-only", "--smoke"],
            capture_output=True, text=True, timeout=900,
            env=_clean_env())
    except subprocess.TimeoutExpired:
        raise AssertionError(
            "bench --smoke exceeded 900s on the chip — the harness or "
            "the tunnel regressed")
    assert r.returncode == 0, r.stderr[-2000:]
    line = r.stdout.strip().splitlines()[-1]
    out = json.loads(line)

    missing = _SMOKE_KEYS - out.keys()
    assert not missing, f"bench smoke JSON lost keys: {sorted(missing)}"
    assert _SPREAD_KEYS <= out["spread"].keys()
    assert _TOOLCHAIN_KEYS <= out["toolchain"].keys()

    if "cost_analysis_error" in out:
        # the loud-failure path: allowed by the schema, but it IS a
        # contract failure for a round capture — surface the message
        raise AssertionError(
            f"cost analysis failed (loudly, as designed): "
            f"{out['cost_analysis_error']}")
    assert out["timing_path"] == "aot"
    assert {"mfu", "bottleneck"} <= out.keys()
    assert {"kind", "xla_flops_G", "xla_bytes_GB", "t_mxu_floor_ms",
            "t_hbm_floor_ms", "t_measured_ms",
            "hbm_floor_fraction"} <= out["bottleneck"].keys()
    assert out["value"] > 0 and out["best_window"] >= out["value"]
