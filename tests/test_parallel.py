"""Parallelism tests on the virtual 8-device CPU mesh: ring attention
(sequence parallelism), tensor parallelism, combined mesh training."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu import nn, optim, parallel
from bigdl_tpu.nn.attention import dot_product_attention
from bigdl_tpu.parallel import create_mesh, ring_attention, build_param_specs


def rng(i=0):
    return jax.random.PRNGKey(i)


class TestAttention:
    def test_mha_shapes(self):
        m = nn.MultiHeadAttention(32, 4)
        p, s = m.init(rng(0))
        y, _ = m.apply(p, s, jnp.ones((2, 10, 32)))
        assert y.shape == (2, 10, 32)

    def test_causal_mask_blocks_future(self):
        q = k = v = jax.random.normal(rng(0), (1, 1, 6, 8))
        full = dot_product_attention(q, k, v, causal=True)
        # truncating the future must not change causal outputs
        trunc = dot_product_attention(q[:, :, :3], k[:, :, :3], v[:, :, :3],
                                      causal=True)
        np.testing.assert_allclose(full[:, :, :3], trunc, rtol=1e-5,
                                   atol=1e-6)

    def test_layernorm(self):
        ln = nn.LayerNorm(16).initialize(0)
        y = ln.forward(jax.random.normal(rng(1), (4, 16)) * 5 + 3)
        np.testing.assert_allclose(jnp.mean(y, -1), 0.0, atol=1e-5)
        np.testing.assert_allclose(jnp.std(y, -1), 1.0, atol=1e-2)

    def test_cross_attention(self):
        m = nn.MultiHeadAttention(16, 2)
        p, s = m.init(rng(0))
        q = jnp.ones((2, 5, 16))
        kv = jnp.ones((2, 9, 16))
        y, _ = m.apply(p, s, (q, kv))
        assert y.shape == (2, 5, 16)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, causal, devices):
        mesh = create_mesh(data=1, seq=8)
        B, H, T, D = 2, 4, 64, 16
        q = jax.random.normal(rng(0), (B, H, T, D))
        k = jax.random.normal(rng(1), (B, H, T, D))
        v = jax.random.normal(rng(2), (B, H, T, D))
        ref = dot_product_attention(q, k, v, causal=causal)
        out = ring_attention(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_with_data_and_seq_axes(self, devices):
        mesh = create_mesh(data=2, seq=4)
        B, H, T, D = 4, 2, 32, 8
        q = jax.random.normal(rng(0), (B, H, T, D))
        k = jax.random.normal(rng(1), (B, H, T, D))
        v = jax.random.normal(rng(2), (B, H, T, D))
        ref = dot_product_attention(q, k, v, causal=True)
        out = ring_attention(q, k, v, mesh, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_grad_flows(self, devices):
        mesh = create_mesh(data=1, seq=8)
        B, H, T, D = 1, 2, 32, 8
        q = jax.random.normal(rng(0), (B, H, T, D))

        def loss(q):
            return jnp.sum(ring_attention(q, q, q, mesh, causal=True) ** 2)

        g = jax.grad(loss)(q)
        assert np.isfinite(np.asarray(g).sum())


class TestTensorParallel:
    def test_param_specs_built(self):
        from bigdl_tpu.models.transformer import transformer_lm
        model = transformer_lm(vocab_size=64, embed_dim=32, num_heads=4,
                               num_layers=1, max_len=32, shard=True)
        p, s = model.init(rng(0))
        specs = build_param_specs(model, p)
        assert jax.tree_util.tree_structure(
            specs, is_leaf=lambda x: isinstance(x, P)) == \
            jax.tree_util.tree_structure(p)
        flat = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P))
        sharded = [sp for sp in flat if sp != P()]
        assert len(sharded) >= 8  # qkv/wo + mlp col/row (+biases)

    def test_tp_forward_matches_replicated(self, devices):
        """TP-sharded execution must be numerically ≈ the single-device
        forward (GSPMD inserts the collectives)."""
        mesh = create_mesh(data=2, model=4)
        lin1 = nn.Linear(16, 32, shard="column")
        lin2 = nn.Linear(32, 8, shard="row")
        model = nn.Sequential().add(lin1).add(nn.ReLU()).add(lin2)
        p, s = model.init(rng(0))
        x = jax.random.normal(rng(1), (8, 16))
        ref, _ = model.apply(p, s, x)

        specs = build_param_specs(model, p)
        p_sh = jax.tree_util.tree_map(
            lambda a, sp: jax.device_put(a, NamedSharding(mesh, sp)),
            p, specs, is_leaf=lambda x: isinstance(x, (P, jnp.ndarray)))
        x_sh = jax.device_put(x, NamedSharding(mesh, P("data")))

        @jax.jit
        def fwd(p, x):
            y, _ = model.apply(p, s, x)
            return y

        out = fwd(p_sh, x_sh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5)

    def test_distri_optimizer_with_tp(self, devices):
        """dp×tp training: loss decreases on an 2x4 mesh."""
        from bigdl_tpu.dataset import MiniBatch

        class Batches:
            def __init__(self):
                self.r = np.random.default_rng(0)
                self.w = self.r.normal(0, 1, (16, 4)).astype(np.float32)

            def size(self):
                return 512

            def shuffle(self):
                pass

            def data(self, train):
                def gen():
                    while True:
                        x = self.r.normal(0, 1, (32, 16)).astype(np.float32)
                        y = (x @ self.w).argmax(-1).astype(np.int32)
                        yield MiniBatch(x, y)
                return gen()

        mesh = create_mesh(data=2, model=4)
        model = (nn.Sequential()
                 .add(nn.Linear(16, 64, shard="column"))
                 .add(nn.ReLU())
                 .add(nn.Linear(64, 4, shard="row"))
                 .add(nn.LogSoftMax()))
        # build specs against a throwaway init
        p0, _ = model.init(rng(0))
        specs = build_param_specs(model, p0)
        opt = (optim.DistriOptimizer(model, Batches(), nn.ClassNLLCriterion(),
                                     mesh=mesh, param_specs=specs)
               .set_optim_method(optim.Adam(5e-3))
               .set_end_when(optim.max_iteration(40)))
        opt.optimize()
        assert opt.state["loss"] < 0.9, opt.state["loss"]


class TestTransformerLM:
    def test_forward_and_train_step(self):
        from bigdl_tpu.models.transformer import transformer_lm
        model = transformer_lm(vocab_size=50, embed_dim=32, num_heads=4,
                               num_layers=2, max_len=16)
        p, s = model.init(rng(0))
        toks = jnp.zeros((2, 12), jnp.int32)
        y, _ = model.apply(p, s, toks)
        assert y.shape == (2, 12, 50)
        # rows are log-probs
        np.testing.assert_allclose(jnp.sum(jnp.exp(y[0, 0])), 1.0, rtol=1e-4)


class TestReviewRegressions:
    def test_specs_traverse_wrappers(self):
        """shard annotations survive TimeDistributed/Recurrent nesting."""
        model = (nn.Sequential()
                 .add(nn.TimeDistributed(nn.Linear(8, 16, shard="column")))
                 .add(nn.Recurrent(nn.GRU(16, 8))))
        p, _ = model.init(rng(0))
        specs = build_param_specs(model, p)
        assert specs["0"]["weight"] == P("model", None)
        assert specs["1"]["w_gates"] == P()

    def test_dryrun_multichip_6_devices(self, devices):
        """Non-power-of-two device counts must work (dp=3 fallback)."""
        import __graft_entry__ as ge
        ge.dryrun_multichip(6)
