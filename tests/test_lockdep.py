"""lockdep — the runtime half of the deadlock story (ISSUE 15).

Layout:
- THE POSITIVE GATE: a real 2-lock cycle across 2 threads is detected
  at acquire time and the report names BOTH conflicting stacks;
- negatives: consistent order, reentrant RLocks, Condition wait/notify
  round-trips and same-class lock pairs record no cycle;
- the slow-hold (blocking-under-lock) wall-clock check;
- THE INERTNESS GATE: lockdep off allocates NO wrapper (bitwise
  factory identity, zero proxies) and the driver loop is bitwise
  identical with ``maybe_install()`` called under the off config —
  the FaultInjector empty-plan discipline, applied to locks.

When the whole suite runs under ``BIGDL_TPU_LOCKDEP=1`` (the conftest
opt-in) the sanitizer is session-installed and its graph must stay
cycle-free — so the tests here that deliberately MANUFACTURE a cycle
(or uninstall/reset the global state) skip themselves rather than
poison the session gate; the session run still executes the negative
accounting tests, which is the point of the opt-in.
"""

import os
import threading
import time

import numpy as np
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.transformer import Sample, SampleToMiniBatch
from bigdl_tpu.utils import lockdep
from bigdl_tpu.utils.config import configure, reset_config

_SESSION_LOCKDEP = os.environ.get("BIGDL_TPU_LOCKDEP", "").lower() in (
    "1", "true", "yes", "on")

needs_isolation = pytest.mark.skipif(
    _SESSION_LOCKDEP,
    reason="session-wide lockdep is installed (BIGDL_TPU_LOCKDEP=1); "
           "this test manufactures cycles / resets global state and "
           "would poison the session's zero-cycle gate")


@pytest.fixture
def sandbox():
    """Fresh install for one test, fully torn down after."""
    assert not lockdep.installed()
    lockdep.install(hold_ms=0)
    lockdep.reset()
    try:
        yield lockdep
    finally:
        lockdep.uninstall()
        lockdep.reset()


def _run_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(10.0)
    assert not t.is_alive()


# ===========================================================================
@needs_isolation
class TestCycleDetection:
    def test_two_lock_cycle_across_two_threads_names_both_stacks(
            self, sandbox):
        """THE ISSUE-15 acceptance gate: t1 takes A then B, t2 takes B
        then A — no actual deadlock occurs (the threads run
        sequentially), but the order graph must report the inversion
        at acquire time, naming both sides' stacks."""
        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def order_ab_worker():
            with lock_a:
                with lock_b:
                    pass

        def order_ba_worker():
            with lock_b:
                with lock_a:
                    pass

        _run_thread(order_ab_worker)
        assert lockdep.cycles() == []          # one order alone is fine
        _run_thread(order_ba_worker)
        cycles = lockdep.cycles()
        assert len(cycles) == 1
        report = cycles[0].render()
        # the report names BOTH conflicting acquisition stacks: the
        # acquiring side (t2's frame) and the recorded edge (t1's
        # frames, held + acquired)
        assert "order_ba_worker" in report
        assert "order_ab_worker" in report
        assert "held at" in report and "acquired at" in report
        # and both lock allocation sites (this file)
        assert report.count("test_lockdep.py") >= 3

    def test_cycle_reported_once_per_site_pair(self, sandbox):
        # separate lines: same-line allocations share one site and
        # form ONE lock class (the family semantics, tested below)
        lock_a = threading.Lock()
        lock_b = threading.Lock()

        def ab():
            with lock_a:
                with lock_b:
                    pass

        def ba():
            with lock_b:
                with lock_a:
                    pass

        _run_thread(ab)
        for _ in range(3):
            _run_thread(ba)
        assert len(lockdep.cycles()) == 1       # no cascade

    def test_three_lock_cycle_through_the_graph(self, sandbox):
        """A -> B, B -> C, then C -> A: the cycle closes through a
        PATH, not a direct edge — the graph search, not pairwise
        bookkeeping, finds it."""
        a = threading.Lock()
        b = threading.Lock()
        c = threading.Lock()

        def t1():
            with a:
                with b:
                    pass

        def t2():
            with b:
                with c:
                    pass

        def t3():
            with c:
                with a:
                    pass

        _run_thread(t1)
        _run_thread(t2)
        assert lockdep.cycles() == []
        _run_thread(t3)
        cycles = lockdep.cycles()
        assert len(cycles) == 1
        assert len(cycles[0].path) == 3        # c -> a -> b(=c's blocker)


# ===========================================================================
@needs_isolation
class TestNoFalsePositives:
    def test_consistent_order_from_many_threads_is_clean(self, sandbox):
        a = threading.Lock()
        b = threading.Lock()

        def worker():
            for _ in range(20):
                with a:
                    with b:
                        pass

        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10.0)
        assert lockdep.cycles() == []
        assert (next(iter(lockdep.graph_edges().values()))) >= 4

    def test_acyclic_chain_plus_independent_lock_is_clean(self, sandbox):
        """ISSUE-17 regression guard: a strict A -> B -> C hierarchy
        exercised from several threads, plus an independent lock D
        taken under all three, builds a 3+ edge DAG and must stay
        cycle-free — ``check_clean`` passes.  (The positive twin is
        TestCycleDetection; this pins the no-false-positive side so a
        graph-search change cannot start reporting hierarchies.)"""
        a = threading.Lock()
        b = threading.Lock()
        c = threading.Lock()
        d = threading.Lock()

        def chain_worker():
            for _ in range(10):
                with a:
                    with b:
                        with c:
                            with d:
                                pass

        ts = [threading.Thread(target=chain_worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10.0)
        for t in ts:
            assert not t.is_alive()
        assert lockdep.cycles() == []
        assert len(lockdep.graph_edges()) >= 3   # a->b, b->c, c->d at least
        lockdep.check_clean()                    # no raise

    def test_rlock_reentrancy_records_no_self_edge(self, sandbox):
        r = threading.RLock()
        with r:
            with r:
                pass
        assert lockdep.cycles() == []
        assert lockdep.graph_edges() == {}

    def test_condition_wait_notify_round_trip_is_clean(self, sandbox):
        """Condition() rides the patched RLock factory; wait() releases
        through ``_release_save`` and re-acquires through
        ``_acquire_restore`` — the held-stack accounting must survive
        the round trip without phantom holds or edges."""
        cond = threading.Condition()
        box = []

        def consumer():
            with cond:
                while not box:
                    cond.wait(5.0)
                # still holds cond here: nesting another lock is a
                # legitimate edge, not a phantom
                with threading.Lock():
                    pass

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.05)
        with cond:
            box.append(1)
            cond.notify_all()
        t.join(10.0)
        assert not t.is_alive()
        assert lockdep.cycles() == []

    def test_same_class_lock_pairs_are_not_edges(self, sandbox):
        """Two instances from ONE allocation site (a lock family, e.g.
        per-replica death locks) nested in both orders must not
        report — with site-keyed classes the direction is ambiguous,
        and same-object re-takes are GL202's static domain."""
        family = [threading.Lock() for _ in range(2)]

        def fwd():
            with family[0]:
                with family[1]:
                    pass

        def rev():
            with family[1]:
                with family[0]:
                    pass

        _run_thread(fwd)
        _run_thread(rev)
        assert lockdep.cycles() == []

    def test_queue_and_futures_machinery_is_clean(self, sandbox):
        import queue
        from concurrent.futures import ThreadPoolExecutor
        q = queue.Queue(maxsize=4)
        with ThreadPoolExecutor(2) as ex:
            futs = [ex.submit(q.put, i) for i in range(4)]
            for f in futs:
                f.result(5.0)
        assert q.qsize() == 4
        assert lockdep.cycles() == []


# ===========================================================================
@needs_isolation
class TestSlowHold:
    def test_hold_past_threshold_recorded_with_acquire_stack(self):
        assert not lockdep.installed()
        lockdep.install(hold_ms=20.0)
        lockdep.reset()
        try:
            lk = threading.Lock()

            def slow_holder():
                with lk:
                    time.sleep(0.06)

            _run_thread(slow_holder)
            holds = lockdep.slow_holds()
            assert len(holds) == 1
            assert holds[0].held_s >= 0.02
            assert "slow_holder" in holds[0].render()
            assert lockdep.cycles() == []      # advisory, not a cycle
        finally:
            lockdep.uninstall()
            lockdep.reset()

    def test_threshold_zero_disables_the_check(self, sandbox):
        lk = threading.Lock()
        with lk:
            time.sleep(0.03)
        assert lockdep.slow_holds() == []


# ===========================================================================
class RecordingSummary:
    def __init__(self):
        self.losses = []

    def add_train_step(self, step, loss, lr, throughput):
        self.losses.append(loss)

    def add_scalar(self, *a):
        pass

    def trigger_for(self, name):
        return None


def tiny_run(iters=6, k=1):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(0, 1, (16,)).astype(np.float32),
                      np.int32(rng.integers(0, 4)))
               for _ in range(64)]
    model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(),
                          nn.Linear(16, 4), nn.LogSoftMax())
    rec = RecordingSummary()
    opt = (optim.LocalOptimizer(model,
                                DataSet.array(samples)
                                >> SampleToMiniBatch(16),
                                nn.ClassNLLCriterion())
           .set_optim_method(optim.SGD(learning_rate=0.1))
           .set_seed(7)
           .set_train_summary(rec)
           .set_steps_per_dispatch(k)
           .set_end_when(optim.max_iteration(iters)))
    opt.optimize()
    return np.asarray(rec.losses), opt


# ===========================================================================
class TestInertness:
    """The ISSUE-15 acceptance gate: lockdep OFF is bitwise — no
    wrapper object exists, the stdlib factories are untouched, and the
    driver loop is unchanged (loss sequence + dispatch count)."""

    @needs_isolation
    def test_off_state_is_structurally_inert(self):
        assert threading.Lock is lockdep._ORIG_LOCK
        assert threading.RLock is lockdep._ORIG_RLOCK
        before = lockdep.proxies_allocated()
        # the config gate declines without touching anything
        configure(lockdep=False)
        try:
            assert lockdep.maybe_install() is False
        finally:
            reset_config()
        assert not lockdep.installed()
        assert threading.Lock is lockdep._ORIG_LOCK
        lk = threading.Lock()
        assert type(lk) is not lockdep._LockProxy
        assert lockdep.proxies_allocated() == before  # NOTHING allocated

    @needs_isolation
    @pytest.mark.parametrize("k", [1, 4])
    def test_driver_bitwise_with_maybe_install_under_off_config(self, k):
        before = lockdep.proxies_allocated()
        base_l, base_o = tiny_run(iters=6, k=k)
        configure(lockdep=False)
        try:
            assert lockdep.maybe_install() is False
            off_l, off_o = tiny_run(iters=6, k=k)
        finally:
            reset_config()
        np.testing.assert_array_equal(base_l, off_l)
        assert base_o._dispatch_count == off_o._dispatch_count
        assert lockdep.proxies_allocated() == before
        assert threading.Lock is lockdep._ORIG_LOCK

    def test_maybe_install_honors_config_on(self):
        """With lockdep configured ON, maybe_install patches (and in a
        session-lockdep run, finds it already installed)."""
        was = lockdep.installed()
        configure(lockdep=True)
        try:
            assert lockdep.maybe_install() is True
            assert lockdep.installed()
            assert threading.Lock is lockdep._lock_factory
        finally:
            reset_config()
            if not was:
                lockdep.uninstall()
                lockdep.reset()
        assert lockdep.installed() == was

    @needs_isolation
    def test_driver_runs_green_under_lockdep(self):
        """The sanitizer ON must not perturb semantics either: same
        losses as the uninstrumented run (locks guard host plumbing,
        not math), zero cycles from the driver plane."""
        base_l, _ = tiny_run(iters=4)
        lockdep.install(hold_ms=0)
        lockdep.reset()
        try:
            on_l, _ = tiny_run(iters=4)
        finally:
            lockdep.uninstall()
            lockdep.reset()
        np.testing.assert_array_equal(base_l, on_l)
        assert lockdep.cycles() == []


# ===========================================================================
class TestLifecycle:
    @needs_isolation
    def test_install_uninstall_idempotent(self):
        lockdep.install(hold_ms=0)
        lockdep.install(hold_ms=0)     # no double-patch
        assert threading.Lock is lockdep._lock_factory
        lockdep.uninstall()
        lockdep.uninstall()
        assert threading.Lock is lockdep._ORIG_LOCK
        lockdep.reset()

    @needs_isolation
    def test_existing_proxies_survive_uninstall(self):
        lockdep.install(hold_ms=0)
        lk = threading.Lock()
        lockdep.uninstall()
        with lk:                        # still a working lock
            assert lk.locked()
        assert not lk.locked()
        lockdep.reset()

    def test_check_clean_raises_with_report(self):
        if _SESSION_LOCKDEP:
            pytest.skip("would poison the session graph")
        lockdep.install(hold_ms=0)
        lockdep.reset()
        try:
            a = threading.Lock()
            b = threading.Lock()

            def ab():
                with a:
                    with b:
                        pass

            def ba():
                with b:
                    with a:
                        pass

            _run_thread(ab)
            _run_thread(ba)
            with pytest.raises(lockdep.LockOrderError,
                               match="lock-order cycle"):
                lockdep.check_clean()
        finally:
            lockdep.uninstall()
            lockdep.reset()
        lockdep.check_clean()           # clean state passes
