"""Round-3 small closures (VERDICT r2 Next #10 + Weak #8/#9):
NormalizeScale, module DenseToSparse, block-compressed SequenceFiles,
trigger-gated parameter histograms, padding buckets vs recompilation."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu import nn


class TestNormalizeScale:
    def test_l2_normalize_then_scale(self):
        # SSD conv4_3 idiom: per-channel scale init 20
        m = nn.NormalizeScale(p=2.0, scale=20.0, size=(1, 4, 1, 1))
        p, _ = m.init(jax.random.PRNGKey(0))
        np.testing.assert_allclose(np.asarray(p["weight"]), 20.0)
        x = np.random.RandomState(0).rand(2, 4, 3, 3).astype(np.float32)
        out, _ = m.apply(p, {}, jnp.asarray(x))
        norm = np.sqrt((x * x).sum(axis=1, keepdims=True))
        np.testing.assert_allclose(np.asarray(out),
                                   20.0 * x / (norm + 1e-10), rtol=1e-5)

    def test_scale_is_trainable(self):
        m = nn.NormalizeScale(scale=2.0, size=(1, 3, 1, 1))
        p, _ = m.init(jax.random.PRNGKey(0))
        x = jnp.ones((1, 3, 2, 2))
        g = jax.grad(lambda p: jnp.sum(m.apply(p, {}, x)[0] ** 2))(p)
        assert float(jnp.sum(jnp.abs(g["weight"]))) > 0


class TestDenseToSparse:
    def test_bags_match_host_helper(self):
        from bigdl_tpu.nn.sparse import dense_to_bags
        dense = np.zeros((3, 10), np.float32)
        dense[0, [2, 7]] = [1.5, -2.0]
        dense[1, [0]] = [3.0]
        m = nn.DenseToSparse(bag_size=2)
        (ids, weights), _ = m.apply({}, {}, jnp.asarray(dense))
        ref_ids, ref_w = dense_to_bags(dense, bag_size=2)
        # same (id, weight) multiset per row (order may differ)
        for r in range(3):
            got = {(int(i), float(w))
                   for i, w in zip(np.asarray(ids[r]),
                                   np.asarray(weights[r])) if i >= 0}
            want = {(int(i), float(w))
                    for i, w in zip(ref_ids[r], ref_w[r]) if i >= 0}
            assert got == want, (r, got, want)

    def test_feeds_lookup_table_sparse(self):
        m = nn.Sequential(nn.DenseToSparse(bag_size=3),
                          nn.LookupTableSparse(10, 4, combiner="sum"))
        m.initialize(0)
        dense = np.zeros((2, 10), np.float32)
        dense[0, 1] = 1.0
        dense[1, [2, 5]] = [1.0, 1.0]
        out = m.forward(jnp.asarray(dense))
        assert np.asarray(out).shape == (2, 4)
        assert np.isfinite(np.asarray(out)).all()


class TestBlockCompressedSeqFile:
    def test_roundtrip(self, tmp_path):
        from bigdl_tpu.dataset.seqfile import read_seqfile, write_seqfile
        recs = [(f"key{i}".encode(), os.urandom(50 + i * 13))
                for i in range(23)]
        path = str(tmp_path / "block.seq")
        from bigdl_tpu.dataset.seqfile import BYTES_WRITABLE
        write_seqfile(path, recs, val_cls=BYTES_WRITABLE,
                      sync_interval=7, block_compressed=True)
        got = list(read_seqfile(path))
        assert got == recs

    def test_header_flags(self, tmp_path):
        from bigdl_tpu.dataset.seqfile import write_seqfile
        path = str(tmp_path / "b.seq")
        write_seqfile(path, [(b"k", b"v")], block_compressed=True)
        raw = open(path, "rb").read()
        assert raw[:4] == b"SEQ\x06"
        # compressed + blockCompressed flags precede the codec string
        assert b"DefaultCodec" in raw


class TestParameterHistograms:
    def test_trigger_gated_dump(self, tmp_path):
        from bigdl_tpu import optim
        from bigdl_tpu.utils.summary import TrainSummary
        from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.sample import Sample

        rng = np.random.RandomState(0)
        samples = [Sample(rng.rand(8).astype(np.float32),
                          np.int32(rng.randint(0, 2)))
                   for _ in range(64)]
        model = nn.Sequential(nn.Linear(8, 2), nn.LogSoftMax())
        summary = TrainSummary(str(tmp_path), "run")
        summary.set_summary_trigger("Parameters",
                                    optim.several_iteration(2))
        opt = (optim.DistriOptimizer(
                  model, DataSet.array(samples) >> SampleToMiniBatch(16),
                  nn.ClassNLLCriterion())
               .set_optim_method(optim.SGD(learning_rate=0.1))
               .set_end_when(optim.max_iteration(4))
               .set_train_summary(summary))
        opt.optimize()
        summary.close()
        run_dir = str(tmp_path / "run" / "train")
        files = [f for f in os.listdir(run_dir) if "tfevents" in f]
        assert files, "no event file written"
        data = open(os.path.join(run_dir, files[0]), "rb").read()
        assert b"Parameters/" in data, "no parameter histograms in events"


class TestPerLayerRegularizers:
    """Reference ``DL/optim/Regularizer.scala``: per-layer L1L2 applied
    in accGradParameters — here via the loss, same gradient."""

    def test_gradient_matches_reference_formula(self):
        from bigdl_tpu.nn.regularizers import regularization_loss
        m = nn.Sequential(
            nn.Linear(4, 3, w_regularizer=nn.L2Regularizer(0.1),
                      b_regularizer=nn.L1Regularizer(0.05)),
            nn.ReLU(),
            nn.Linear(3, 2))           # no regularizer on this one
        m.initialize(0)
        p = m._params
        g = jax.grad(lambda p: regularization_loss(m, p))(p)
        w = np.asarray(p["0"]["weight"])
        b = np.asarray(p["0"]["bias"])
        np.testing.assert_allclose(np.asarray(g["0"]["weight"]), 0.1 * w,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g["0"]["bias"]),
                                   0.05 * np.sign(b), rtol=1e-6)
        assert float(jnp.sum(jnp.abs(g["2"]["weight"]))) == 0.0

    def test_optimizer_applies_penalty(self):
        from bigdl_tpu import optim
        from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
        from bigdl_tpu.dataset.sample import Sample
        rng = np.random.RandomState(0)
        samples = [Sample(rng.rand(6).astype(np.float32),
                          np.float32(rng.rand()))
                   for _ in range(64)]

        def train(reg):
            model = nn.Sequential(
                nn.Linear(6, 8, w_regularizer=reg), nn.ReLU(),
                nn.Linear(8, 1))
            opt = (optim.LocalOptimizer(
                      model, DataSet.array(samples) >> SampleToMiniBatch(16),
                      nn.MSECriterion())
                   .set_optim_method(optim.SGD(learning_rate=0.1))
                   .set_end_when(optim.max_epoch(8)))
            trained = opt.optimize()
            return float(jnp.sum(trained._params["0"]["weight"] ** 2))

        # strong L2 on layer 0 must shrink its weights vs no regularizer
        assert train(nn.L2Regularizer(1.0)) < 0.5 * train(None)

    def test_bigdl_checkpoint_persists_regularizers(self, tmp_path):
        """r3 review: save/load must not silently drop the penalties
        (reference ModuleSerializer persists wRegularizer/bRegularizer)."""
        from bigdl_tpu.interop import save_bigdl_module, load_bigdl_module
        from bigdl_tpu.nn.regularizers import has_regularizers
        m = nn.Sequential(
            nn.Linear(4, 3, w_regularizer=nn.L2Regularizer(0.25),
                      b_regularizer=nn.L1Regularizer(0.125)),
            nn.SpatialConvolution(1, 1, 1, 1,
                                  w_regularizer=nn.L1L2Regularizer(
                                      0.5, 0.75)))
        m.initialize(0)
        path = str(tmp_path / "reg.bigdl")
        save_bigdl_module(m, path)
        m2 = load_bigdl_module(path)
        assert has_regularizers(m2)
        lin, conv = m2.modules
        assert (lin.w_regularizer.l1, lin.w_regularizer.l2) == (0.0, 0.25)
        assert (lin.b_regularizer.l1, lin.b_regularizer.l2) == (0.125, 0.0)
        assert (conv.w_regularizer.l1,
                conv.w_regularizer.l2) == (0.5, 0.75)


class TestPaddingBuckets:
    def test_bucketed_padding_bounds_compiles(self):
        """Weak #8 regression: variable-length batches with bucketed
        padding produce at most len(buckets) distinct shapes (= XLA
        compiles), where per-batch max padding would give one per
        length."""
        from bigdl_tpu.dataset.sample import (PaddingParam, Sample,
                                              batch_samples)
        rng = np.random.RandomState(0)
        param = PaddingParam(padding_value=0.0, buckets=[8, 16, 32])
        traces = []

        @jax.jit
        def step(xb):
            traces.append(xb.shape)  # records per-TRACE, not per-call
            return jnp.sum(xb * xb)

        shapes = set()
        for _ in range(12):
            lens = rng.randint(3, 30, size=4)
            samples = [Sample(rng.rand(l, 5).astype(np.float32),
                              np.int32(0)) for l in lens]
            mb = batch_samples(samples, feature_padding=param)
            shapes.add(mb.input.shape)
            step(jnp.asarray(mb.input))
        assert len(shapes) <= 3, shapes
        assert len(traces) <= 3, f"{len(traces)} recompiles"

    def test_oversized_sequence_raises(self):
        from bigdl_tpu.dataset.sample import (PaddingParam, Sample,
                                              batch_samples)
        param = PaddingParam(buckets=[4])
        samples = [Sample(np.zeros((9, 2), np.float32), np.int32(0)),
                   Sample(np.zeros((2, 2), np.float32), np.int32(0))]
        with pytest.raises(ValueError, match="bucket"):
            batch_samples(samples, feature_padding=param)


class TestImageFrameRead:
    """Reference ``ImageFrame.read`` / ``DLImageReader``: folder →
    LocalImageFrame, with the one-subdir-per-class label convention."""

    def _write_imgs(self, root, layout):
        from PIL import Image
        rng = np.random.RandomState(0)
        for rel in layout:
            p = os.path.join(str(root), rel)
            os.makedirs(os.path.dirname(p), exist_ok=True)
            Image.fromarray(rng.randint(0, 255, (6, 8, 3), dtype=np.uint8)
                            ).save(p)

    def test_flat_folder(self, tmp_path):
        from bigdl_tpu.transform.vision import ImageFrame
        self._write_imgs(tmp_path, ["b.png", "a.jpg"])
        open(str(tmp_path / "readme.txt"), "w").write("not an image")
        frame = ImageFrame.read(str(tmp_path))
        assert [f["uri"] for f in frame.features] == ["a.jpg", "b.png"]
        assert frame.features[0].image.shape == (6, 8, 3)

    def test_labeled_subdirs(self, tmp_path):
        from bigdl_tpu.transform.vision import ImageFrame
        self._write_imgs(tmp_path, ["cat/x.png", "cat/y.png", "dog/z.png"])
        frame = ImageFrame.read(str(tmp_path), with_label=True)
        labels = [int(f.label) for f in frame.features]
        assert labels == [0, 0, 1]
        samples = frame.to_samples()
        assert samples[2].label == 1
