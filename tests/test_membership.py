"""Elastic training — membership epochs + resize-on-preemption
(ISSUE 16).

The load-bearing gates:

- **Headline e2e**: train at world 4 on the virtual CPU mesh, inject a
  shrink to 2 mid-epoch via the fault plan, resume, regrow to 4 —
  ``membership_epoch`` == 3, zero steps lost, zero aborted runs, and
  bitwise-equal to an uninterrupted same-seed world-4 reference
  *wherever the replay boundary makes that well-defined*: the entire
  world-4 prefix including the boundary snapshot the shrink resumed
  from is compared bitwise, the elastic trajectory itself is bitwise
  run-to-run repeatable, and the cross-world remainder is pinned to a
  tight tolerance.  Full-trajectory cross-world bitwise equality is
  NOT well-defined on this backend: XLA CPU's batch-dimension
  contraction in the backward matmuls (``dW = x^T @ dy``) picks
  shape-dependent kernels/accumulation orders, so a (2, 16) per-chip
  shard and a (1, 16) one diverge by ~1 ULP per step even with
  identical rows, f32 wire, and exact power-of-two psum trees
  (measured: 300/300 grad mismatches between local batch 2 and 4 of
  *identical* rows; the psum/reshard/restore layers were each checked
  bitwise-exact in isolation).
- **Inertness**: with no fault plan no ``ClusterMembership`` object
  exists and training is bitwise-identical run-to-run (K ∈ {1, 4}).
- **Unit layers**: monotonic epochs over prefix rosters, membership
  fault clauses (one-shot by default — an elastic restore rewinds the
  step counter), ZeRO-1 state resharding, elastic-compat schema diffs,
  the ``latest_valid()`` GC pin, and scale-aware fast-forward.
"""

import os
import tempfile
import threading

import numpy as np
import pytest

import jax
from jax.sharding import Mesh

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.dataset.prefetch import fast_forward_records
from bigdl_tpu.checkpoint.manager import CheckpointManager
from bigdl_tpu.checkpoint.snapshot import load_snapshot
from bigdl_tpu.checkpoint.schema import (SchemaMismatchError, build_schema,
                                         diff_schemas, elastic_compatible,
                                         validate_schema)
from bigdl_tpu.parallel import grad_sync
from bigdl_tpu.resilience import (ClusterMembership, FaultInjector,
                                  MembershipChanged, MembershipEpoch,
                                  parse_fault_plan)
from bigdl_tpu.telemetry.registry import MetricRegistry
from bigdl_tpu.utils.config import configure, reset_config

_SESSION_LOCKDEP = os.environ.get("BIGDL_TPU_LOCKDEP", "").lower() in (
    "1", "true", "yes", "on")


# ===========================================================================
class TestClusterMembership:
    def test_initial_epoch_freezes_full_pool(self):
        m = ClusterMembership(("a", "b", "c", "d"))
        cur = m.current()
        assert (m.epoch(), cur.world, cur.reason) == (1, 4, "initial")
        assert cur.devices == ("a", "b", "c", "d")
        assert m.pool_size() == 4

    def test_resize_opens_monotonic_epochs_with_prefix_rosters(self):
        m = ClusterMembership(("a", "b", "c", "d"))
        e2 = m.request_resize(2)
        assert (e2.epoch, e2.world, e2.graceful) == (2, 2, True)
        assert e2.devices == ("a", "b")         # lowest-indexed survive
        e3 = m.request_resize(4)
        assert (e3.epoch, e3.world) == (3, 4)
        assert e3.devices == ("a", "b", "c", "d")  # tail re-admitted
        assert [e.epoch for e in m.history()] == [1, 2, 3]

    def test_same_size_resize_is_not_epoch_churn(self):
        m = ClusterMembership(("a", "b"))
        assert m.request_resize(2).epoch == 1
        assert m.epoch() == 1

    def test_resize_outside_pool_refused(self):
        m = ClusterMembership(("a", "b"))
        with pytest.raises(ValueError, match="outside"):
            m.request_resize(3)
        with pytest.raises(ValueError, match="outside"):
            m.request_resize(0)

    def test_host_loss_graceful_default_half(self):
        m = ClusterMembership(tuple(range(8)))
        ep = m.signal_host_loss()
        assert (ep.world, ep.reason, ep.graceful) == (4, "host_loss", True)

    def test_device_loss_abrupt_default_minus_one(self):
        m = ClusterMembership(tuple(range(4)))
        ep = m.signal_device_loss()
        assert (ep.world, ep.reason, ep.graceful) == \
            (3, "device_loss", False)

    def test_changed_since_is_the_replay_boundary_predicate(self):
        m = ClusterMembership(("a", "b", "c", "d"))
        assert m.changed_since(1) is None
        m.request_resize(2)
        assert m.changed_since(1).epoch == 2
        assert m.changed_since(2) is None

    def test_epoch_gauge_emitted(self):
        reg = MetricRegistry()
        m = ClusterMembership(("a", "b", "c", "d"), registry=reg)
        m.request_resize(2)
        m.request_resize(4)
        assert reg.snapshot()["gauges"][
            "resilience/membership_epoch"] == 3

    def test_signals_race_safely(self):
        m = ClusterMembership(tuple(range(8)))
        stop = threading.Event()

        def churn():
            while not stop.is_set():
                m.request_resize(2)
                m.request_resize(8)

        ts = [threading.Thread(target=churn) for _ in range(4)]
        for t in ts:
            t.start()
        for _ in range(200):
            m.epoch()
        stop.set()
        for t in ts:
            t.join()
        hist = m.history()
        assert [e.epoch for e in hist] == list(range(1, len(hist) + 1))
        assert all(h.world in (2, 8) for h in hist)

    def test_empty_pool_refused(self):
        with pytest.raises(ValueError, match=">= 1"):
            ClusterMembership(())


# ===========================================================================
class TestMembershipFaultClauses:
    def test_parse_resize_clause(self):
        (c,) = parse_fault_plan("resize@at=5,to=2")
        assert (c.kind, c.at, c.to, c.where) == ("resize", 5, 2, "driver")

    def test_membership_clauses_are_one_shot_by_default(self):
        # an elastic restore REWINDS the step counter, so a budget-less
        # at= clause would re-fire on every replay crossing
        for plan in ("resize@at=5,to=2", "host_loss@at=5",
                     "device_loss@at=5"):
            (c,) = parse_fault_plan(plan)
            assert c.count == 1, plan
        (c,) = parse_fault_plan("device_loss@at=5,count=3")
        assert c.count == 3  # explicit budget still wins

    def test_resize_requires_target_world(self):
        with pytest.raises(ValueError, match="to="):
            parse_fault_plan("resize@at=5")

    def test_to_rejected_on_non_membership_kinds(self):
        with pytest.raises(ValueError, match="membership"):
            parse_fault_plan("corrupt_batch@at=1,to=2")

    def test_membership_events_fire_once_at_site(self):
        fi = FaultInjector("resize@at=3,to=2;host_loss@at=7", seed=1)
        assert fi.has_membership_kinds()
        assert fi.membership_events(2) == []
        fired = fi.membership_events(3)
        assert [c.kind for c in fired] == ["resize"]
        assert fi.membership_events(3) == []   # budget spent
        assert [c.kind for c in fi.membership_events(7)] == ["host_loss"]

    def test_plans_without_membership_kinds_report_none(self):
        fi = FaultInjector("corrupt_batch@at=1", seed=1)
        assert not fi.has_membership_kinds()
        assert fi.membership_events(1) == []


# ===========================================================================
def _tiny_params(rng, n=290):
    # deliberately NOT a multiple of any world size: padding matters
    return {"w": rng.normal(0, 1, (n,)).astype(np.float32),
            "b": rng.normal(0, 1, (7,)).astype(np.float32)}


class TestReshardState:
    def test_round_trip_preserves_content_across_world_sizes(self):
        rng = np.random.default_rng(0)
        params = _tiny_params(rng)
        p4 = grad_sync.build_plan(params, 4, 1 << 20)
        p2 = grad_sync.build_plan(params, 2, 1 << 20)
        assert grad_sync.bucket_content_sizes(p4) == \
            grad_sync.bucket_content_sizes(p2)
        state4 = grad_sync.init_state(p4, params, optim.Adam())
        # scribble non-trivial values so content equality is meaningful
        state4 = jax.tree_util.tree_map(
            lambda a: np.asarray(a) + np.arange(a.size,
                                                dtype=np.float32), state4)
        state2 = grad_sync.reshard_state(p2, state4)
        content = grad_sync.bucket_content_sizes(p2)
        for s4, s2, c in zip(state4["master"], state2["master"], content):
            np.testing.assert_array_equal(np.asarray(s4)[:c],
                                          np.asarray(s2)[:c])
            assert s2.shape == (p2.bucket_sizes[
                state2["master"].index(s2)],)
            assert (np.asarray(s2)[c:] == 0).all()   # fresh zero padding
        # the elementwise inner state reshards identically
        for k in ("m", "v"):
            for s4, s2, c in zip(state4["opt"][k], state2["opt"][k],
                                 content):
                np.testing.assert_array_equal(np.asarray(s4)[:c],
                                              np.asarray(s2)[:c])

    def test_same_world_reshard_is_identity(self):
        rng = np.random.default_rng(1)
        params = _tiny_params(rng)
        plan = grad_sync.build_plan(params, 4, 1 << 20)
        state = grad_sync.init_state(plan, params, optim.SGD(momentum=0.9))
        out = grad_sync.reshard_state(plan, state)
        for a, b in zip(state["master"], out["master"]):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_bucket_count_drift_refused(self):
        rng = np.random.default_rng(2)
        params = _tiny_params(rng)
        small = grad_sync.build_plan(params, 2, 256)   # many buckets
        big = grad_sync.build_plan(params, 2, 1 << 20)  # one bucket
        state = grad_sync.init_state(small, params, optim.SGD())
        with pytest.raises(ValueError, match="not just the world size"):
            grad_sync.reshard_state(big, state)

    def test_non_grad_sync_layout_refused(self):
        plan = grad_sync.build_plan(
            _tiny_params(np.random.default_rng(3)), 2, 1 << 20)
        with pytest.raises(ValueError, match="no bucket index"):
            grad_sync.reshard_state(
                plan, {"master": {"w": np.zeros(4, np.float32)}})


# ===========================================================================
class TestElasticSchema:
    def _schemas(self):
        rng = np.random.default_rng(0)
        params = _tiny_params(rng)
        p4 = grad_sync.build_plan(params, 4, 1 << 20)
        p2 = grad_sync.build_plan(params, 2, 1 << 20)
        mk = lambda p: build_schema(  # noqa: E731
            params, grad_sync=True, bucket_sizes=p.bucket_sizes,
            wire_dtype="float32", n_shard=p.n_shard, optim_method="SGD",
            bucket_content=grad_sync.bucket_content_sizes(p))
        return mk(p4), mk(p2)

    def test_strict_mode_still_refuses_world_drift(self):
        s4, s2 = self._schemas()
        assert diff_schemas(s4, s2) != []
        with pytest.raises(SchemaMismatchError, match="elastically"):
            validate_schema(s4, s2)

    def test_elastic_mode_tolerates_world_and_padding_drift(self):
        s4, s2 = self._schemas()
        assert diff_schemas(s4, s2, elastic=True) == []
        validate_schema(s4, s2, elastic=True)   # no raise
        ok, lines = elastic_compatible(s4, s2)
        assert ok and lines == []

    def test_elastic_mode_keeps_logical_identity_strict(self):
        s4, s2 = self._schemas()
        drifted = {**s2, "grad_sync": dict(s2["grad_sync"],
                                           wire_dtype="bfloat16")}
        ok, lines = elastic_compatible(s4, drifted)
        assert not ok and any("wire_dtype" in ln for ln in lines)
        with pytest.raises(SchemaMismatchError, match="elastic resume"):
            validate_schema(s4, drifted, elastic=True)

    def test_elastic_mode_compares_bucket_content_when_present(self):
        s4, s2 = self._schemas()
        drifted = {**s2, "grad_sync": dict(
            s2["grad_sync"],
            bucket_content=[c + 1 for c in
                            s2["grad_sync"]["bucket_content"]])}
        ok, lines = elastic_compatible(s4, drifted)
        assert not ok and any("bucket_content" in ln for ln in lines)

    def test_pre_elastic_snapshot_skips_content_check(self):
        s4, s2 = self._schemas()
        legacy = {**s4, "grad_sync": {
            k: v for k, v in s4["grad_sync"].items()
            if k != "bucket_content"}}
        ok, lines = elastic_compatible(legacy, s2)
        assert ok, lines

    def test_legacy_schema_less_snapshot_is_compatible_with_caveat(self):
        _, s2 = self._schemas()
        ok, lines = elastic_compatible(None, s2)
        assert ok and any("legacy" in ln for ln in lines)


# ===========================================================================
class TestSnapshotPin:
    def _save(self, mgr, step):
        mgr.save(step, {"w": np.full((4,), float(step), np.float32)},
                 sync=True)

    def test_latest_valid_pins_against_keep_last_gc(self):
        with tempfile.TemporaryDirectory() as d:
            mgr = CheckpointManager(d, keep_last=1, async_save=False)
            self._save(mgr, 1)
            pinned = mgr.latest_valid()
            assert pinned == mgr.path_for(1)
            # retention turns over while the restore is mid-read: the
            # pinned snapshot must survive the ring
            self._save(mgr, 2)
            self._save(mgr, 3)
            assert os.path.exists(pinned)
            assert mgr.steps() == [1, 3]
            mgr.unpin()
            self._save(mgr, 4)
            assert not os.path.exists(pinned)
            assert mgr.steps() == [4]

    def test_restore_releases_pin_on_success_path_via_restore_into(self):
        class _Opt:  # the minimal restore_into surface
            class _M:
                _params = None
                _state = None
            model = _M()
            _resume_opt_state = None
            _resume_schema = None
            dataset = None

            def set_state(self, s):
                pass

            def set_seed(self, s):
                pass

        with tempfile.TemporaryDirectory() as d:
            mgr = CheckpointManager(d, keep_last=1, async_save=False)
            self._save(mgr, 1)
            path = mgr.latest_valid()
            mgr.restore_into(_Opt(), path, verified=True)
            # pin released after application → GC may collect
            self._save(mgr, 2)
            self._save(mgr, 3)
            assert not os.path.exists(mgr.path_for(1))

    def test_failed_restore_releases_pin(self):
        with tempfile.TemporaryDirectory() as d:
            mgr = CheckpointManager(d, keep_last=1, async_save=False)
            self._save(mgr, 1)
            path = mgr.latest_valid()
            os.unlink(path)   # the failure restore() trips over
            with pytest.raises(Exception):
                mgr.restore(path, verified=True)
            # the raise path released the pin — retention is not wedged
            self._save(mgr, 2)
            self._save(mgr, 3)
            assert mgr.steps() == [3]

    def test_unpin_is_idempotent(self):
        with tempfile.TemporaryDirectory() as d:
            mgr = CheckpointManager(d, keep_last=1, async_save=False)
            mgr.unpin()
            mgr.unpin()


# ===========================================================================
class TestScaleAwareFastForward:
    def _batches(self, n, size=4):
        class B:
            def __init__(self, s):
                self._s = s

            def size(self):
                return self._s

        return iter([B(size) for _ in range(n)])

    def test_exact_skip(self):
        assert fast_forward_records(self._batches(5), 12) == 12

    def test_zero_skip_touches_nothing(self):
        it = self._batches(1)
        assert fast_forward_records(it, 0) == 0
        assert next(it).size() == 4   # untouched

    def test_misaligned_boundary_is_loud(self):
        with pytest.raises(ValueError, match="batch boundaries"):
            fast_forward_records(self._batches(5), 10)

    def test_exhausted_epoch_is_loud(self):
        with pytest.raises(ValueError, match="exhausted"):
            fast_forward_records(self._batches(2), 12)

    def test_records_counter_must_divide_by_scale(self):
        # ISSUE-16 satellite: the PR-7 fast-forward assumed a constant
        # P — a records counter written at another process count must
        # refuse loudly, not silently mis-position the dataset
        model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
        opt = optim.LocalOptimizer(
            model, DataSet.array(
                [Sample(np.zeros(4, np.float32), np.int32(0))])
            >> SampleToMiniBatch(1), nn.ClassNLLCriterion())
        opt._records_scale = lambda: 2
        state = {"records_processed_this_epoch": 3}
        with pytest.raises(ValueError, match="records scale"):
            opt._fast_forward(self._batches(4, 1), state)


# ===========================================================================
class RecordingSummary:
    def __init__(self):
        self.losses = []

    def add_train_step(self, step, loss, lr, throughput):
        self.losses.append(loss)

    def add_scalar(self, *a):
        pass

    def trigger_for(self, name):
        return None


class SyncEveryStepSummary(RecordingSummary):
    """A per-iteration ``Parameters`` trigger makes EVERY block a sync
    (replay) boundary, so membership detection decouples from the
    checkpoint cadence — without it the driver only reaches a loop top
    (where detection runs) on checkpoint-trigger boundaries, which by
    construction always just committed a snapshot (steps lost == 0)."""

    def trigger_for(self, name):
        if name == "Parameters":
            return optim.several_iteration(1)
        return None

    def add_histogram(self, *a):
        pass


def grouped_samples(n_groups=16, group=4, din=16, nclass=4, seed=0):
    """Batches of IDENTICAL rows (varying across steps): every chip
    contributes the same per-shard value, so the 1/n-prescaled psum
    is exact for power-of-two worlds and the forward pass is
    world-size-invariant bitwise.  The backward batch-dim contraction
    still is not (see the module docstring) — identical rows just pin
    the residual cross-world drift to kernel-level ULPs (~1e-8 on
    params over this run) instead of data-dependent noise."""
    rng = np.random.default_rng(seed)
    samples = []
    for _ in range(n_groups):
        row = rng.normal(0, 1, (din,)).astype(np.float32)
        lbl = np.int32(rng.integers(0, nclass))
        samples.extend(Sample(row.copy(), lbl) for _ in range(group))
    return samples


def elastic_run(plan=None, ckpt=None, iters=8, k=1, world=4,
                ckpt_every=1, seed=7, keep_last=None,
                summary_cls=RecordingSummary):
    if plan is not None:
        configure(fault_plan=plan)
    try:
        mesh = Mesh(np.array(jax.devices()[:world]), ("data",))
        model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(),
                              nn.Linear(16, 4), nn.LogSoftMax())
        rec = summary_cls()
        opt = (optim.DistriOptimizer(model,
                                     DataSet.array(grouped_samples())
                                     >> SampleToMiniBatch(4),
                                     nn.ClassNLLCriterion(), mesh=mesh,
                                     grad_wire_dtype="f32")
               .set_optim_method(optim.SGD(learning_rate=0.1))
               .set_seed(seed)
               .set_train_summary(rec)
               .set_steps_per_dispatch(k)
               .set_end_when(optim.max_iteration(iters)))
        if ckpt is not None:
            opt.set_checkpoint(ckpt,
                               optim.several_iteration(ckpt_every),
                               keep_last=keep_last)
        opt.optimize()
        return np.asarray(rec.losses), opt, model
    finally:
        if plan is not None:
            reset_config()


def params_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestElasticEndToEnd:
    """The ISSUE-16 headline gate."""

    def test_shrink_regrow_bitwise_equals_uninterrupted_reference(self):
        plan = "resize@at=2,to=2;resize@at=5,to=4"
        with tempfile.TemporaryDirectory() as dref, \
                tempfile.TemporaryDirectory() as dela, \
                tempfile.TemporaryDirectory() as dela2:
            ref_l, ref_o, ref_m = elastic_run(ckpt=dref, keep_last=100)
            ela_l, ela_o, ela_m = elastic_run(plan=plan, ckpt=dela,
                                              keep_last=100)
            # zero aborted runs: both optimize() calls returned; the
            # elastic one crossed epochs 1 → 2 (world 2) → 3 (world 4)
            m = ela_o._membership
            assert m is not None and m.epoch() == 3
            assert [e.world for e in m.history()] == [4, 2, 4]
            snap = ela_o.metrics.registry.snapshot()
            assert snap["gauges"]["resilience/membership_epoch"] == 3
            # graceful resizes replay the in-flight block + snapshot at
            # the boundary: nothing is lost, both resumes were measured
            assert snap["counters"][
                "resilience/steps_lost_to_resize"] == 0
            assert snap["histograms"][
                "resilience/resize_downtime_s"]["count"] == 2
            # bitwise where the replay boundary makes it well-defined:
            # the at=2 clause opens the epoch inside the block running
            # step 3, the graceful suspend replays it and snapshots at
            # neval == 3 — so losses 0..2 and the model.3 snapshot the
            # world-2 resume restored from are all world-4 work and
            # must match the reference exactly
            boundary = 3
            np.testing.assert_array_equal(ref_l[:boundary],
                                          ela_l[:boundary])
            ref_blob = load_snapshot(os.path.join(
                dref, f"model.{boundary}"))
            ela_blob = load_snapshot(os.path.join(
                dela, f"model.{boundary}"))
            params_equal(ref_blob["params"], ela_blob["params"])
            # the elastic trajectory itself is deterministic: a second
            # same-seed shrink/regrow run is bitwise-identical end to
            # end (same losses, same final params)
            ela2_l, _, ela2_m = elastic_run(plan=plan, ckpt=dela2)
            np.testing.assert_array_equal(ela_l, ela2_l)
            params_equal(ela_m._params, ela2_m._params)
        # across the world-2 segment bitwise is not well-defined (see
        # module docstring) — pin the whole trajectory to kernel-ULP
        # tolerance instead: the measured drift is ~1e-7 on losses and
        # ~1.5e-8 on params, so 1e-5 catches any real resume bug
        # (wrong snapshot, dropped step, bad reshard) by orders of
        # magnitude
        np.testing.assert_allclose(ref_l, ela_l, rtol=0, atol=1e-5)
        for x, y in zip(jax.tree_util.tree_leaves(ref_m._params),
                        jax.tree_util.tree_leaves(ela_m._params)):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       rtol=0, atol=1e-5)
        assert ref_o._membership is None   # the reference stayed inert

    def test_abrupt_device_loss_resumes_from_latest_valid(self):
        # device_loss abandons whatever is in flight: with sync
        # boundaries every step (SyncEveryStepSummary) the at=4 signal
        # is detected at neval 5, where the every-4 trigger has only
        # committed model.4 — the resume restores that and pays step 5
        # again, counted in steps_lost_to_resize, never aborted
        with tempfile.TemporaryDirectory() as d:
            losses, opt, _ = elastic_run(
                plan="device_loss@at=4,to=2", ckpt=d, ckpt_every=4,
                iters=6, summary_cls=SyncEveryStepSummary)
        m = opt._membership
        assert m is not None and m.epoch() == 2
        assert m.current().world == 2 and not m.current().graceful
        snap = opt.metrics.registry.snapshot()
        assert snap["counters"]["resilience/steps_lost_to_resize"] == 1
        assert int(opt.state["neval"]) == 6
        assert np.isfinite(np.asarray(losses, np.float64)).all()

    def test_elastic_without_checkpoint_refused_loudly(self):
        with pytest.raises(ValueError, match="set_checkpoint"):
            elastic_run(plan="resize@at=2,to=2")

    def test_membership_plan_on_local_optimizer_refused_loudly(self):
        configure(fault_plan="resize@at=2,to=2")
        try:
            model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
            opt = optim.LocalOptimizer(
                model, DataSet.array(
                    [Sample(np.zeros(4, np.float32), np.int32(0))
                     for _ in range(8)])
                >> SampleToMiniBatch(2), nn.ClassNLLCriterion()) \
                .set_end_when(optim.max_iteration(2))
            with pytest.raises(ValueError, match="LocalOptimizer"):
                opt.optimize()
        finally:
            reset_config()

    def test_explicit_set_elastic_resize_without_fault_plan(self):
        # the operator-request path: no injector at all — an external
        # request_resize on the armed membership drives the same cycle.
        # The resize lands BEFORE the first step, so the driver
        # snapshots the initial state, restores it, and runs every step
        # at world 2 — making a plain uninterrupted world-2 run the
        # bitwise-exact reference (no cross-world segment at all)
        with tempfile.TemporaryDirectory() as dref, \
                tempfile.TemporaryDirectory() as dela:
            ref_l, _, ref_m = elastic_run(ckpt=dref, iters=6, world=2)

            mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
            model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(),
                                  nn.Linear(16, 4), nn.LogSoftMax())
            rec = RecordingSummary()
            opt = (optim.DistriOptimizer(model,
                                         DataSet.array(grouped_samples())
                                         >> SampleToMiniBatch(4),
                                         nn.ClassNLLCriterion(),
                                         mesh=mesh, grad_wire_dtype="f32")
                   .set_optim_method(optim.SGD(learning_rate=0.1))
                   .set_seed(7)
                   .set_train_summary(rec)
                   .set_end_when(optim.max_iteration(6)))
            opt.set_checkpoint(dela, optim.several_iteration(1))
            opt.set_elastic()
            assert opt._membership.epoch() == 1
            # a second set_elastic must NOT reset the epoch ledger
            opt.set_elastic()
            assert opt._membership.epoch() == 1
            opt._membership.request_resize(2)   # before the run: the
            opt.optimize()                      # driver detects at once
        assert opt._membership.epoch() == 2
        np.testing.assert_array_equal(ref_l, np.asarray(rec.losses))
        params_equal(ref_m._params, model._params)


# ===========================================================================
class TestElasticInertness:
    """Fault plan absent ⇒ provably inert (acceptance gate)."""

    @pytest.mark.parametrize("k", [1, 4])
    def test_no_plan_no_membership_and_bitwise_repeatable(self, k):
        assert FaultInjector.from_config() is None
        a_l, a_o, a_m = elastic_run(k=k)
        b_l, b_o, b_m = elastic_run(k=k)
        assert a_o._membership is None and b_o._membership is None
        assert a_o._fault_injector is None
        np.testing.assert_array_equal(a_l, b_l)
        params_equal(a_m._params, b_m._params)
        snap = a_o.metrics.registry.snapshot()
        assert "resilience/membership_epoch" not in snap["gauges"]

    def test_non_membership_plan_does_not_arm_membership(self):
        losses, opt, _ = elastic_run(plan="dispatch_delay@ms=0.1,count=1")
        assert opt._fault_injector is not None
        assert opt._membership is None


# ===========================================================================
class TestCkptInspectSchema:
    """ISSUE-16 satellite: ``tools.ckpt_inspect --schema`` — the
    operator-facing elastic audit (world size, ZeRO bucket layout,
    per-snapshot elastic verdict, exit 0/1)."""

    def _save(self, mgr, step, schema):
        mgr.save(step, {"w": np.full((8,), float(step), np.float32)},
                 schema=schema, sync=True)

    def _schemas(self):
        params = {"w": np.zeros((8,), np.float32)}
        mk = lambda **kw: build_schema(params, grad_sync=True,
                                       optim_method="SGD", **kw)
        return (
            mk(bucket_sizes=[12], wire_dtype="f32", n_shard=4,
               bucket_content=[10]),
            mk(bucket_sizes=[10], wire_dtype="f32", n_shard=2,
               bucket_content=[10]),
            mk(bucket_sizes=[10], wire_dtype="bf16", n_shard=2,
               bucket_content=[10]),
        )

    def test_mixed_world_directory_is_resumable_exit_zero(self, capsys):
        from tools.ckpt_inspect import main
        s_w4, s_w2, _ = self._schemas()
        with tempfile.TemporaryDirectory() as d:
            mgr = CheckpointManager(d, async_save=False)
            self._save(mgr, 2, s_w4)   # written at world 4
            self._save(mgr, 4, s_w2)   # written at world 2 post-shrink
            assert main([d, "--schema"]) == 0
        out = capsys.readouterr().out
        assert "world 4" in out and "world 2" in out
        assert "buckets [12] (content [10] unpadded)" in out
        assert "elastic: elastic-resumable" in out
        assert "elastic: reference" in out
        assert "elastic verdict: RESUMABLE" in out

    def test_wire_dtype_drift_is_incompatible_exit_one(self, capsys):
        from tools.ckpt_inspect import main
        s_w4, s_w2, s_bad_wire = self._schemas()
        with tempfile.TemporaryDirectory() as d:
            mgr = CheckpointManager(d, async_save=False)
            self._save(mgr, 2, s_w4)
            self._save(mgr, 4, s_bad_wire)  # newest: bf16 wire
            assert main([d, "--schema"]) == 1
        out = capsys.readouterr().out
        # world drift alone would be fine — the wire dtype is logical
        # model identity and must fail the audit loudly
        assert "elastic: INCOMPATIBLE" in out
        assert "wire_dtype" in out
        assert "elastic verdict: INCOMPATIBLE" in out

    def test_json_audit_from_real_elastic_run(self, capsys):
        import json as _json
        from tools.ckpt_inspect import main
        with tempfile.TemporaryDirectory() as d:
            elastic_run(plan="resize@at=2,to=2;resize@at=5,to=4",
                        ckpt=d, keep_last=100)
            assert main([d, "--schema", "--json"]) == 0
        rep = _json.loads(capsys.readouterr().out)
        audit = rep["elastic"]
        assert audit["compatible"] is True
        verdicts = {v["verdict"] for v in audit["verdicts"]}
        # snapshots from both world sizes are present, so at least one
        # row resumed elastically rather than being schema-identical
        assert "elastic-resumable" in verdicts
        assert audit["reference"] == rep["latest_valid"]
        worlds = {(r["schema"]["grad_sync"] or {}).get("n_shard")
                  for r in rep["snapshots"]}
        assert worlds == {4, 2}


# ===========================================================================
@pytest.mark.skipif(_SESSION_LOCKDEP, reason="session-wide lockdep is "
                    "installed (BIGDL_TPU_LOCKDEP=1); in-test install "
                    "would double-patch")
class TestElasticUnderLockdep:
    """ISSUE-16 satellite: the elastic suites double as a deadlock hunt
    — the membership lock, the checkpoint pin lock, and the writer
    thread interleave across a full shrink/regrow cycle with the
    sanitizer on, and must record zero lock-order cycles (the whole
    file re-runs under the conftest opt-in when BIGDL_TPU_LOCKDEP=1)."""

    def test_shrink_regrow_cycle_is_lock_order_clean(self):
        from bigdl_tpu.utils import lockdep
        lockdep.install(hold_ms=0)
        lockdep.reset()
        try:
            with tempfile.TemporaryDirectory() as d:
                _, opt, _ = elastic_run(
                    plan="resize@at=2,to=2;resize@at=5,to=4", ckpt=d)
            assert opt._membership.epoch() == 3
            assert lockdep.cycles() == []
        finally:
            lockdep.uninstall()
            lockdep.reset()


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
