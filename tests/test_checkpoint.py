"""bigdl_tpu.checkpoint — async fault-tolerant checkpointing tests.

The ISSUE-7 acceptance surface:
- snapshot format: atomic commit, CRC32c manifest, data-only npz,
  read-manifest/verify without loading arrays;
- discovery: corrupt/torn snapshots are SKIPPED, never loaded;
- retention: keep_last ring + keep_every pins;
- THE CRASH/RESUME GATE: train N steps straight vs train-with-kill +
  resume → bitwise-identical loss sequences and final params, K∈{1,4},
  grad_sync on/off — in-process (fresh-object resume and the
  DistriOptimizer retry loop) plus REAL subprocess fault injection
  (SIGKILL mid-epoch, SIGTERM preemption → final snapshot + clean
  exit);
- async inertness: checkpointing on adds zero dispatches and the loss
  sequence stays bitwise identical;
- schema validation: grad_sync flips / bucket-plan drift /
  architecture drift fail loudly with a diff;
- shim back-compat + the now-real non-overwrite path;
- tools/ckpt_inspect.py CLI.
"""

import os
import re
import signal
import subprocess
import sys
import time
import zipfile

import jax
import numpy as np
import pytest

import ckpt_train_child as child_mod
from bigdl_tpu import nn, optim
from bigdl_tpu.checkpoint import (AsyncSnapshotWriter, CheckpointManager,
                                  PreemptionHandler, SchemaMismatchError,
                                  SnapshotError, build_schema,
                                  load_snapshot, read_manifest,
                                  verify_snapshot, write_snapshot)
from bigdl_tpu.dataset.dataset import (DistributedDataSet, LocalDataSet,
                                       TransformedDataSet)
from bigdl_tpu.optim.optimizer import LocalOptimizer

HERE = os.path.dirname(os.path.abspath(__file__))
CHILD = os.path.join(HERE, "ckpt_train_child.py")


# ---------------------------------------------------------------- helpers
class Rec:
    """TrainSummary stand-in capturing the per-iteration replay."""

    def __init__(self):
        self.rows = []  # (step, loss)

    def add_train_step(self, step, loss, lr, throughput):
        self.rows.append((step, loss))

    def add_scalar(self, tag, value, step):
        pass

    def trigger_for(self, name):
        return None

    @property
    def losses(self):
        return np.array([l for _, l in self.rows])

    @property
    def steps(self):
        return [s for s, _ in self.rows]

    def by_step(self):
        """step → loss, LAST occurrence winning (a crashed-then-retried
        run replays some iterations; the retried values are the ones
        that produced the final params)."""
        return dict(self.rows)


def build_opt(ckpt_dir=None, iters=16, k=4, every=3, grad_sync=None,
              distri=False, rec=None, **distri_kw):
    cls = optim.DistriOptimizer if distri else LocalOptimizer
    kw = dict(distri_kw)
    if distri and grad_sync is not None:
        kw["grad_sync"] = grad_sync
    opt = (cls(child_mod.mlp(), child_mod.pipeline(),
               nn.ClassNLLCriterion(), **kw)
           .set_optim_method(optim.Adam(1e-3))
           .set_steps_per_dispatch(k)
           .set_seed(7)
           .set_end_when(optim.max_iteration(iters)))
    if rec is not None:
        opt.set_train_summary(rec)
    if ckpt_dir:
        opt.set_checkpoint(ckpt_dir, optim.several_iteration(every))
    return opt


def reference_run(iters=16, k=4, every=3, grad_sync=None, distri=False,
                  **distri_kw):
    rec = Rec()
    opt = build_opt(iters=iters, k=k, grad_sync=grad_sync, distri=distri,
                    rec=rec, **distri_kw)
    # same trigger cadence as the checkpointed runs (it shapes block
    # planning — a firing iteration always ends a block) but no path,
    # so the reference shares the EXACT scan partitioning and the
    # bitwise comparison isolates the save/resume machinery
    opt.checkpoint_trigger = optim.several_iteration(every)
    opt.optimize()
    return rec, opt


def assert_params_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def flaky_lr(opt, crash_at):
    """Raise once on the ``crash_at``-th host LR computation — the
    fault-injection shape test_training already uses."""
    real = opt.optim_method.current_lr
    calls = {"n": 0}

    def lr(it, ep, metric=None):
        calls["n"] += 1
        if calls["n"] == crash_at:
            raise RuntimeError("injected mid-epoch failure")
        return real(it, ep, metric)

    opt.optim_method.current_lr = lr


# ========================================================== snapshot layer
class TestSnapshotFormat:
    def test_roundtrip_manifest_and_schema_hash(self, tmp_path):
        params = {"layer": {"w": np.arange(6, dtype=np.float32)
                            .reshape(2, 3)},
                  "pair": (np.zeros(2), [np.ones(3), 5]),
                  "bf": jax.numpy.arange(4, dtype=jax.numpy.bfloat16)}
        schema = build_schema(params, optim_method="Adam")
        f = write_snapshot(str(tmp_path / "model.3"), params=params,
                           opt_state={"m": np.ones(3), "step": 7},
                           driver_state={"neval": 3, "epoch": 1,
                                         "loss": 0.5},
                           run_state={"seed": 7,
                                      "dataset_position":
                                          {"shuffle_epoch": 1}},
                           step=3, schema=schema)
        m = read_manifest(f)
        assert m["format"] == "bigdl_tpu-snapshot" and m["version"] == 3
        assert m["step"] == 3 and m["epoch"] == 1
        assert len(m["schema_hash"]) == 12
        assert m["total_bytes"] == sum(e["nbytes"] for e in m["arrays"])
        ok, detail = verify_snapshot(f)
        assert ok, detail
        blob = load_snapshot(f)
        assert blob["params"]["bf"].dtype == jax.numpy.bfloat16
        assert isinstance(blob["params"]["pair"], tuple)
        assert blob["run"]["dataset_position"] == {"shuffle_epoch": 1}
        assert blob["manifest"]["schema"]["optim_method"] == "Adam"
        # data-only: plain zip, loads with pickle OFF
        assert zipfile.is_zipfile(f)
        with np.load(f, allow_pickle=False) as z:
            assert "__manifest__" in z.files

    def test_atomic_commit_leaves_no_tmp(self, tmp_path):
        f = write_snapshot(str(tmp_path / "model.1"),
                           params={"w": np.ones(8)}, step=1)
        assert os.path.exists(f)
        assert not os.path.exists(f + ".tmp")

    def test_overwrite_false_raises(self, tmp_path):
        f = str(tmp_path / "model.2")
        write_snapshot(f, params={"w": np.ones(2)}, step=2)
        with pytest.raises(FileExistsError, match="overWriteCheckpoint"):
            write_snapshot(f, params={"w": np.zeros(2)}, step=2,
                           overwrite=False)
        # overwrite=True replaces
        write_snapshot(f, params={"w": np.zeros(2)}, step=2)
        assert float(np.asarray(load_snapshot(f)["params"]["w"]).sum()) \
            == 0.0


def _corrupt_array_byte(path, member="a0.npy"):
    """Flip one byte inside a member's DATA region (the .npy payload is
    located via its magic + header length, so the flip lands in payload
    bytes, not in zip/npy framing)."""
    zi = zipfile.ZipFile(path).getinfo(member)
    raw = bytearray(open(path, "rb").read())
    pos = raw.find(b"\x93NUMPY", zi.header_offset)
    assert pos != -1
    hlen = int.from_bytes(raw[pos + 8:pos + 10], "little")
    raw[pos + 10 + hlen + 2] ^= 0x01
    open(path, "wb").write(bytes(raw))


class TestIntegrityAndDiscovery:
    def _write(self, d, step, fill=1.0):
        return write_snapshot(os.path.join(d, f"model.{step}"),
                              params={"w": np.full(64, fill, np.float32)},
                              step=step)

    def test_bit_flip_detected_skipped_never_loaded(self, tmp_path):
        d = str(tmp_path)
        self._write(d, 2)
        bad = self._write(d, 4)
        _corrupt_array_byte(bad)
        ok, detail = verify_snapshot(bad)
        assert not ok and "crc" in detail.lower()
        with pytest.raises(SnapshotError, match="refusing to load"):
            load_snapshot(bad)
        mgr = CheckpointManager(d)
        assert mgr.latest_valid() == os.path.join(d, "model.2")

    def test_meta_member_corruption_detected_and_skipped(self, tmp_path):
        """A bit-flip in the __meta__ skeleton (not an array) must fail
        verification exactly like array corruption — otherwise the
        latest-VALID fallback would hand np.load a corrupt file and the
        retry loop would crash instead of falling back."""
        d = str(tmp_path)
        good = self._write(d, 2)
        bad = self._write(d, 6)
        _corrupt_array_byte(bad, member="__meta__.npy")
        ok, detail = verify_snapshot(bad)
        assert not ok, detail
        with pytest.raises(SnapshotError):
            load_snapshot(bad)
        assert CheckpointManager(d).latest_valid() == good

    def test_torn_write_skipped(self, tmp_path):
        d = str(tmp_path)
        good = self._write(d, 3)
        raw = open(good, "rb").read()
        open(os.path.join(d, "model.9"), "wb").write(raw[:len(raw) // 2])
        ok, detail = verify_snapshot(os.path.join(d, "model.9"))
        assert not ok
        assert CheckpointManager(d).latest_valid() == good

    def test_foreign_and_garbage_files_ignored(self, tmp_path):
        d = str(tmp_path)
        good = self._write(d, 1)
        open(os.path.join(d, "model.zzz"), "w").write("not a step")
        np.savez(os.path.join(d, "model.5"), foreign=np.ones(3))
        os.replace(os.path.join(d, "model.5.npz"),
                   os.path.join(d, "model.5"))
        mgr = CheckpointManager(d)
        assert mgr.latest_valid() == good

    def test_legacy_v2_without_manifest_still_loads(self, tmp_path):
        import json
        from bigdl_tpu.checkpoint.snapshot import encode_tree
        arrays = []
        sk = {"version": 2, "params": encode_tree({"w": np.ones(2)},
                                                  arrays),
              "model_state": None, "opt_state": None,
              "driver_state": {"neval": 4}}
        path = str(tmp_path / "model.4")
        with open(path, "wb") as f:
            np.savez(f, __meta__=np.frombuffer(
                json.dumps(sk).encode(), dtype=np.uint8),
                **{f"a{i}": a for i, a in enumerate(arrays)})
        ok, detail = verify_snapshot(path)
        assert ok and "legacy" in detail
        blob = load_snapshot(path)
        assert blob["driver_state"]["neval"] == 4
        assert blob["manifest"] is None
        assert CheckpointManager(str(tmp_path)).latest_valid() == path


class TestManagerRetentionAndWriter:
    def _save(self, mgr, step):
        mgr.save(step, {"w": np.full(4, step, np.float32)},
                 driver_state={"neval": step}, sync=True)

    def test_keep_last_ring(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last=2,
                                async_save=False)
        for s in (1, 2, 3, 4, 5):
            self._save(mgr, s)
        assert mgr.steps() == [4, 5]

    def test_keep_every_pins_sparse_archive(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last=2, keep_every=3,
                                async_save=False)
        for s in range(1, 9):
            self._save(mgr, s)
        assert mgr.steps() == [3, 6, 7, 8]

    def test_async_commits_in_order_and_drains(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), keep_last=10)
        for s in (1, 2, 3):
            mgr.save(s, {"w": np.full(4, s, np.float32)},
                     driver_state={"neval": s})
        mgr.wait()
        assert mgr.steps() == [1, 2, 3]
        blob = mgr.restore()
        assert blob["driver_state"]["neval"] == 3

    def test_writer_error_surfaces_on_drain(self):
        w = AsyncSnapshotWriter()

        def boom():
            raise OSError("disk full")

        w.submit(boom)
        with pytest.raises(RuntimeError, match="NOT durably saved"):
            w.drain()

    def test_writer_bounded_backpressure(self):
        import threading
        gate = threading.Event()
        w = AsyncSnapshotWriter(capacity=1)
        w.submit(gate.wait)  # occupies the worker
        w.submit(lambda: None)  # fills the queue
        t0 = time.perf_counter()
        t = threading.Thread(target=lambda: w.submit(lambda: None))
        t.start()
        t.join(timeout=0.2)
        assert t.is_alive()  # third submit blocks — bounded
        gate.set()
        t.join(timeout=5.0)
        assert not t.is_alive()
        w.close()
        assert time.perf_counter() - t0 < 10


# ===================================================== dataset positioning
class TestDatasetPosition:
    def test_local_dataset_epoch_keyed_restore(self):
        a = LocalDataSet(list(range(20)), seed=3)
        for _ in range(4):
            a.shuffle()
        b = LocalDataSet(list(range(20)), seed=3)
        b.restore_position(a.position_state())
        assert list(b._indexes) == list(a._indexes)
        assert sorted(b._indexes) == list(range(20))  # a permutation

    def test_epoch_zero_is_insertion_order(self):
        a = LocalDataSet(list(range(5)), seed=1)
        a.restore_position({"shuffle_epoch": 0})
        assert list(a._indexes) == [0, 1, 2, 3, 4]

    def test_transformed_dataset_delegates(self):
        from bigdl_tpu.dataset.transformer import Transformer

        class Ident(Transformer):
            def __call__(self, it):
                return it

        base = LocalDataSet(list(range(8)), seed=2)
        ds = TransformedDataSet(base, Ident())
        ds.shuffle()
        st = ds.position_state()
        assert st == {"shuffle_epoch": 1}
        ds.restore_position({"shuffle_epoch": 0})
        assert base._epoch == 0

    def test_distributed_dataset_restore(self):
        a = DistributedDataSet(list(range(16)), seed=3, process_index=0,
                               process_count=2)
        a.shuffle(), a.shuffle()
        b = DistributedDataSet(list(range(16)), seed=3, process_index=0,
                               process_count=2)
        b.restore_position(a.position_state())
        assert np.array_equal(a._global_indexes, b._global_indexes)


# ================================================= THE CRASH/RESUME GATES
class TestResumeBitwiseInProcess:
    """Emulated kill (exception mid-epoch) + fresh-object resume must be
    bitwise-identical to the uninterrupted run — K∈{1,4}, grad_sync
    on/off.  The subprocess class below repeats this with REAL kills."""

    def _splice_check(self, ref_rec, ref_opt, crashed, resumed_rec,
                      resumed_opt, iters):
        ref = ref_rec.by_step()
        got = dict(crashed.by_step())
        got.update(resumed_rec.by_step())
        assert sorted(got) == list(range(1, iters + 1))
        for s in got:
            assert got[s] == ref[s], (s, got[s], ref[s])
        assert_params_equal(ref_opt.model._params,
                            resumed_opt.model._params)

    @pytest.mark.parametrize("k", [1, 4])
    def test_local_kill_and_fresh_resume(self, k, tmp_path):
        iters = 16  # 10-step epochs: the crash AND the resume are
        ref_rec, ref_opt = reference_run(iters=iters, k=k)  # mid-epoch
        d = str(tmp_path / f"ck{k}")
        crashed = Rec()
        opt = build_opt(d, iters=iters, k=k, rec=crashed)
        flaky_lr(opt, crash_at=9)
        with pytest.raises(RuntimeError, match="injected"):
            opt.optimize()
        resumed = Rec()
        opt2 = build_opt(d, iters=iters, k=k, rec=resumed)
        assert opt2.resume()
        opt2.optimize()
        assert resumed.steps[0] > 1  # really resumed, not restarted
        self._splice_check(ref_rec, ref_opt, crashed, resumed, opt2,
                           iters)

    @pytest.mark.parametrize("k,grad_sync", [(1, True), (4, True),
                                             (4, False)])
    def test_distri_retry_loop_resumes_bitwise(self, k, grad_sync,
                                               tmp_path, devices):
        """The DistriOptimizer failure-retry loop (now manager-backed:
        latest-VALID discovery + full-state restore incl. the ZeRO-1
        masters and shuffle position) must land on the uninterrupted
        trajectory bitwise."""
        iters = 12
        ref_rec, ref_opt = reference_run(iters=iters, k=k, distri=True,
                                         grad_sync=grad_sync)
        rec = Rec()
        opt = build_opt(str(tmp_path / "ck"), iters=iters, k=k,
                        distri=True, grad_sync=grad_sync, rec=rec)
        flaky_lr(opt, crash_at=8)
        opt.optimize()  # crashes mid-epoch, retries from model.6
        assert opt.state["neval"] == iters
        ref = ref_rec.by_step()
        got = rec.by_step()
        assert sorted(got) == list(range(1, iters + 1))
        for s in got:
            assert got[s] == ref[s], (s, got[s], ref[s])
        assert_params_equal(ref_opt.model._params, opt.model._params)

    def test_retry_skips_corrupt_latest_snapshot(self, tmp_path,
                                                 devices):
        """Crash → corrupt the newest snapshot → retry must fall back
        to the previous VALID one and still finish on the reference
        trajectory (resuming from an earlier step recomputes the same
        values bitwise)."""
        iters = 12
        _, ref_opt = reference_run(iters=iters, k=4, distri=True)
        d = str(tmp_path / "ck")
        opt = build_opt(d, iters=iters, k=4, distri=True)
        real_impl = opt._optimize_impl
        calls = {"n": 0}

        def impl():
            calls["n"] += 1
            if calls["n"] == 2:
                # between crash and retry: newest snapshot goes bad
                mgr = opt._checkpoint_manager()
                _corrupt_array_byte(mgr.path_for(max(mgr.steps())))
            return real_impl()

        opt._optimize_impl = impl
        flaky_lr(opt, crash_at=8)
        opt.optimize()
        assert opt.state["neval"] == iters
        assert_params_equal(ref_opt.model._params, opt.model._params)

    def test_resume_from_epoch_boundary_snapshot(self, tmp_path):
        """A snapshot taken at the epoch-rollover iteration (neval=10,
        records reset to 0, shuffle already advanced) must resume with
        the epoch-1 permutation and zero fast-forward — the rollover/
        checkpoint ordering inside _replay_block is what this pins."""
        iters = 16
        ref_rec, ref_opt = reference_run(iters=iters, k=4, every=5)
        d = str(tmp_path / "ck")
        crashed = Rec()
        opt = build_opt(d, iters=iters, k=4, every=5, rec=crashed)
        flaky_lr(opt, crash_at=12)
        with pytest.raises(RuntimeError):
            opt.optimize()
        resumed = Rec()
        opt2 = build_opt(d, iters=iters, k=4, every=5, rec=resumed)
        assert opt2.resume()
        assert opt2.state["neval"] == 10
        assert opt2.state["records_processed_this_epoch"] == 0
        assert opt2.state["epoch"] == 1
        opt2.optimize()
        self._splice_check(ref_rec, ref_opt, crashed, resumed, opt2,
                           iters)

    def test_resume_crosses_epoch_boundary_with_restored_shuffle(
            self, tmp_path):
        """Kill in epoch 0, resume, run through the epoch-1 shuffle:
        the restored run must re-derive the SAME epoch-1 permutation
        (epoch-keyed shuffle) — any drift shows up as a loss
        mismatch."""
        iters = 25  # crosses shuffles at 10 and 20
        ref_rec, ref_opt = reference_run(iters=iters, k=4)
        d = str(tmp_path / "ck")
        crashed = Rec()
        opt = build_opt(d, iters=iters, k=4, rec=crashed)
        flaky_lr(opt, crash_at=8)
        with pytest.raises(RuntimeError):
            opt.optimize()
        resumed = Rec()
        opt2 = build_opt(d, iters=iters, k=4, rec=resumed)
        assert opt2.resume()
        opt2.optimize()
        self._splice_check(ref_rec, ref_opt, crashed, resumed, opt2,
                           iters)


def _wait_for_step(losses_path, step, proc, timeout=90):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if os.path.exists(losses_path):
            lines = open(losses_path).read().splitlines()
            if lines and int(lines[-1].split()[0]) >= step:
                return
        if proc.poll() is not None:
            raise AssertionError(
                "child exited before reaching step "
                f"{step}:\n{proc.stderr.read().decode()[-2000:]}")
        time.sleep(0.05)
    proc.kill()
    raise AssertionError(f"child never reached step {step}")


def _wait_for_commit(ckpt_dir, proc, timeout=90):
    """Wait until at least one snapshot has COMMITTED (a ``model.N``
    file, not a ``.tmp``).  The async writer trails the driver loop, so
    'the loss log passed step 8' does not imply 'model.3 is on disk' —
    killing in that gap leaves the resume child nothing valid and the
    test flakes on writer-thread scheduling instead of testing the
    resume path (a latent race surfaced by the obs-plane PR's timing
    shifts)."""
    t0 = time.time()
    while time.time() - t0 < timeout:
        if os.path.isdir(ckpt_dir) and any(
                re.fullmatch(r"model\.\d+", f)
                for f in os.listdir(ckpt_dir)):
            return
        if proc.poll() is not None:
            return  # a finished child drained its writer — committed
        time.sleep(0.02)
    proc.kill()
    raise AssertionError("no snapshot ever committed")


def _parse_losses(path):
    out = {}
    for line in open(path).read().splitlines():
        s, l = line.split()
        out[int(s)] = float(l)
    return out


def _run_child(args, wait=True):
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(HERE) + os.pathsep \
        + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, CHILD] + args, cwd=os.path.dirname(HERE),
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    if not wait:
        return proc
    out, err = proc.communicate(timeout=300)
    assert proc.returncode == 0, err.decode()[-2000:]
    return out.decode()


class TestSubprocessFaultInjection:
    """REAL kills: a child process training with checkpointing is
    SIGKILLed mid-epoch (or SIGTERM-preempted) and a second child
    resumes — the spliced loss sequence and the final params must equal
    the uninterrupted reference bitwise.  Kept lean (one reference per
    config, children share the tiny-MLP recipe) to stay well under the
    ~30s budget."""

    def _reference(self, iters, k, every=3):
        rec, opt = reference_run(iters=iters, k=k, every=every)
        return rec.by_step(), opt

    def _check_against_reference(self, ref, ref_opt, losses_a, losses_b,
                                 params_npz, iters):
        a, b = _parse_losses(losses_a), _parse_losses(losses_b)
        assert min(b) > 1 and max(b) == iters  # resumed, not restarted
        combined = dict(a)
        combined.update(b)
        assert sorted(combined) == list(range(1, iters + 1))
        for s, l in combined.items():
            assert l == ref[s], (s, l, ref[s])
        with np.load(params_npz) as z:
            got = [z[f"p{i}"] for i in range(len(z.files))]
        for x, y in zip(jax.tree_util.tree_leaves(ref_opt.model._params),
                        got):
            np.testing.assert_array_equal(np.asarray(x), y)

    @pytest.mark.parametrize("k", [1, 4])
    def test_sigkill_mid_epoch_resumes_bitwise(self, k, tmp_path):
        iters = 16
        ref, ref_opt = self._reference(iters, k)
        d = str(tmp_path / "ck")
        la, lb = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")
        pout = str(tmp_path / "params.npz")
        proc = _run_child(["--dir", d, "--losses", la, "--iters",
                           str(iters), "--k", str(k)], wait=False)
        try:
            _wait_for_step(la, 8, proc)  # past model.6, mid-epoch
            _wait_for_commit(d, proc)  # ... and ≥1 snapshot ON DISK
        finally:
            proc.kill()
        proc.wait(timeout=30)
        _run_child(["--dir", d, "--losses", lb, "--iters", str(iters),
                    "--k", str(k), "--resume", "--params-out", pout])
        self._check_against_reference(ref, ref_opt, la, lb, pout, iters)

    def test_sigterm_preemption_final_snapshot_then_resume(self,
                                                           tmp_path):
        """SIGTERM → the child finishes the in-flight block, writes a
        final snapshot, exits 0 (clean preemption); the resume child
        continues to a bitwise-identical end state."""
        # long enough that SIGTERM lands while the driver loop is live
        # (a finished run uninstalls the handler and would die with
        # the default action — that would be a -15 exit, caught below)
        iters, k = 150, 4
        ref, ref_opt = self._reference(iters, k, every=1000)
        d = str(tmp_path / "ck")
        la, lb = str(tmp_path / "a.txt"), str(tmp_path / "b.txt")
        pout = str(tmp_path / "params.npz")
        proc = _run_child(["--dir", d, "--losses", la, "--iters",
                           str(iters), "--k", str(k), "--preemption",
                           # sparse trigger: the final snapshot is the
                           # preemption path's own work, not a trigger's
                           "--every", "1000"], wait=False)
        _wait_for_step(la, 5, proc)
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=60)
        assert proc.returncode == 0, err.decode()[-2000:]
        assert b"PREEMPTED" in out, out
        n_final = int(out.split()[-1])
        snaps = CheckpointManager(d).steps()
        assert snaps == [n_final]  # exactly the preemption snapshot
        _run_child(["--dir", d, "--losses", lb, "--iters", str(iters),
                    "--k", str(k), "--resume", "--params-out", pout])
        self._check_against_reference(ref, ref_opt, la, lb, pout, iters)


class TestPreemptionInProcess:
    def test_request_finishes_block_snapshots_and_exits_cleanly(
            self, tmp_path):
        d = str(tmp_path / "ck")
        rec = Rec()
        opt = build_opt(d, iters=50, k=4, every=100, rec=rec) \
            .set_preemption_handling()
        orig = rec.add_train_step

        def hook(step, loss, lr, thr):
            orig(step, loss, lr, thr)
            if step == 7:
                opt._preemption.request()

        rec.add_train_step = hook
        opt.optimize()
        assert opt.state.get("preempted") is True
        n = opt.state["neval"]
        assert 7 <= n < 50  # stopped at the next block boundary
        assert rec.steps == list(range(1, n + 1))  # in-flight replayed
        mgr = CheckpointManager(d)
        assert mgr.steps() == [n]
        blob = mgr.restore()
        assert blob["driver_state"]["neval"] == n
        assert "preempted" not in blob["driver_state"]

    def test_preempted_flag_cleared_on_next_run(self, tmp_path):
        """A later optimize() on the same optimizer must not report a
        phantom preemption — nor bake one into its checkpoints'
        driver_state."""
        d = str(tmp_path / "ck")
        rec = Rec()
        opt = build_opt(d, iters=50, k=4, every=100, rec=rec) \
            .set_preemption_handling()
        orig = rec.add_train_step

        def hook(step, loss, lr, thr):
            orig(step, loss, lr, thr)
            if step == 7 and not opt.state.get("preempted"):
                opt._preemption.request()

        rec.add_train_step = hook
        opt.optimize()
        assert opt.state.get("preempted") is True
        opt.optimize()  # continue in-process to completion
        assert opt.state["neval"] == 50
        assert "preempted" not in opt.state
        blob = CheckpointManager(d).restore()
        assert "preempted" not in blob["driver_state"]

    def test_no_redundant_final_snapshot_when_trigger_just_fired(
            self, tmp_path):
        """Preemption landing on an iteration a trigger checkpoint just
        covered must not write (or collide on) a second model.<N> —
        even with over_write_checkpoint(False)."""
        d = str(tmp_path / "ck")
        rec = Rec()
        opt = build_opt(d, iters=50, k=4, every=4, rec=rec) \
            .set_preemption_handling().over_write_checkpoint(False)
        orig = rec.add_train_step

        def hook(step, loss, lr, thr):
            orig(step, loss, lr, thr)
            if step == 4:
                opt._preemption.request()

        rec.add_train_step = hook
        opt.optimize()  # must NOT raise FileExistsError
        assert opt.state.get("preempted") is True
        assert opt.state["neval"] == 4
        assert CheckpointManager(d).steps() == [4]

    def test_set_checkpoint_reconfigure_stops_old_writer(self, tmp_path):
        opt = build_opt(str(tmp_path / "a"), iters=4, k=4, every=2)
        opt.optimize()
        old = opt._ckpt_manager
        thread = old._writer._thread
        assert thread is not None and thread.is_alive()
        opt.set_checkpoint(str(tmp_path / "b"),
                           optim.several_iteration(2))
        assert not thread.is_alive()  # no stranded daemon per reconfig
        assert opt._ckpt_manager is None

    def test_handler_installs_and_restores_signal_handlers(self):
        prev = signal.getsignal(signal.SIGTERM)
        with PreemptionHandler() as h:
            assert h.installed
            assert signal.getsignal(signal.SIGTERM) == h._on_signal
            os.kill(os.getpid(), signal.SIGTERM)
            for _ in range(100):
                if h.triggered:
                    break
                time.sleep(0.01)
            assert h.triggered and h.signum == signal.SIGTERM
        assert signal.getsignal(signal.SIGTERM) == prev


# ======================================================== async inertness
class TestAsyncInertness:
    def test_checkpointing_adds_zero_dispatches_and_keeps_loss_bitwise(
            self, monkeypatch, tmp_path):
        """The counting-wrapper gate: checkpointing enabled (async)
        must not change the dispatch count and the loss sequence stays
        bitwise identical — the save path never touches the device
        beyond the replay-boundary D2H capture.

        Minimal pair: the checkpoint TRIGGER legitimately shapes block
        planning (a firing iteration always ends a block), so the
        baseline run keeps the SAME trigger wired for probing but no
        checkpoint path — the only delta between the runs is the save
        path itself."""
        calls = {"n": 0}
        orig = LocalOptimizer._build_block_fn

        def counting(self, grad_fn, kk):
            fn = orig(self, grad_fn, kk)

            def wrapped(*a, **kw):
                calls["n"] += 1
                return fn(*a, **kw)

            return wrapped

        monkeypatch.setattr(LocalOptimizer, "_build_block_fn", counting)
        runs = {}
        for mode in ("off", "on"):
            calls["n"] = 0
            rec = Rec()
            opt = build_opt(str(tmp_path / "ck") if mode == "on"
                            else None, iters=16, k=4, every=3, rec=rec)
            if mode == "off":
                # same probe cadence, no path → no saves
                opt.checkpoint_trigger = optim.several_iteration(3)
            opt.optimize()
            runs[mode] = (rec, calls["n"])
        (rec_off, n_off), (rec_on, n_on) = runs["off"], runs["on"]
        assert n_on == n_off
        np.testing.assert_array_equal(rec_off.losses, rec_on.losses)
        assert CheckpointManager(str(tmp_path / "ck")).steps()  # saved

    def test_metrics_and_telemetry_span_recorded(self, tmp_path):
        opt = build_opt(str(tmp_path / "ck"), iters=8, k=4)
        opt.set_telemetry(True)
        opt.optimize()
        snap = opt.telemetry_snapshot()
        hists = snap["histograms"]
        assert hists["checkpoint/driver_stall_s"]["count"] == 2
        assert hists["checkpoint/save_s"]["count"] == 2
        assert snap["counters"]["checkpoint/snapshots_committed"] == 2
        assert snap["counters"]["checkpoint/bytes_written"] > 0
        assert 0.0 <= snap["gauges"]["checkpoint/stall_fraction"] < 1.0
        names = [e[1] for e in opt._telemetry.tracer.events()]
        assert "checkpoint" in names

    def test_async_driver_stall_much_smaller_than_write(self, tmp_path):
        """The point of async: the driver-side stall per snapshot is a
        fraction of the full serialize+CRC+fsync the writer thread
        pays.  (The bench rider records the production-sized numbers;
        this just pins the ordering so a regression that moves the
        write back inline fails loudly.)"""
        opt = build_opt(str(tmp_path / "ck"), iters=12, k=4, every=2)
        opt.optimize()
        reg = opt.metrics.registry
        drv = reg.get("checkpoint/driver_stall_s")
        save = reg.get("checkpoint/save_s")
        assert drv.count == save.count >= 5
        assert drv.mean < save.mean, (drv.mean, save.mean)


# ======================================================= schema validation
class TestSchemaValidation:
    def _train_distri(self, d, devices, **kw):
        opt = build_opt(d, iters=4, k=4, every=2, distri=True, **kw)
        opt.optimize()
        return opt

    def test_grad_sync_flip_fails_with_diff(self, tmp_path, devices):
        d = str(tmp_path / "ck")
        self._train_distri(d, devices, grad_sync=True)
        opt2 = build_opt(d, iters=8, k=4, distri=True, grad_sync=False)
        opt2.failure_retry_times = 0
        assert opt2.resume()
        with pytest.raises(SchemaMismatchError) as ei:
            opt2.optimize()
        msg = str(ei.value)
        assert "grad_sync.enabled" in msg and "snapshot: True" in msg
        assert "matching grad_sync" in msg

    def test_bucket_plan_drift_fails_with_diff(self, tmp_path, devices):
        d = str(tmp_path / "ck")
        self._train_distri(d, devices, grad_sync=True,
                           grad_bucket_bytes=4 << 20)
        opt2 = build_opt(d, iters=8, k=4, distri=True, grad_sync=True,
                         grad_bucket_bytes=64 * 4)  # forces many buckets
        opt2.failure_retry_times = 0
        assert opt2.resume()
        with pytest.raises(SchemaMismatchError) as ei:
            opt2.optimize()
        msg = str(ei.value)
        assert "grad_sync.bucket_sizes" in msg
        assert "bucket plan drifted" in msg

    def test_architecture_drift_refused_at_resume(self, tmp_path):
        """A drifted model must be refused BEFORE the snapshot's params
        overwrite it (afterwards the drift would be invisible — the
        restored params ARE the old architecture); the diff names the
        mismatched leaf shapes."""
        d = str(tmp_path / "ck")
        build_opt(d, iters=4, k=4, every=2).optimize()
        opt2 = (LocalOptimizer(
            nn.Sequential().add(nn.Reshape((784,)))
            .add(nn.Linear(784, 16)).add(nn.ReLU())  # 32 → 16
            .add(nn.Linear(16, 10)).add(nn.LogSoftMax()),
            child_mod.pipeline(), nn.ClassNLLCriterion())
            .set_optim_method(optim.Adam(1e-3))
            .set_end_when(optim.max_iteration(8))
            .set_checkpoint(d, optim.several_iteration(3)))
        with pytest.raises(SchemaMismatchError) as ei:
            opt2.resume()
        msg = str(ei.value)
        assert "params" in msg and "(32, 784)" in msg \
            and "(16, 784)" in msg
        assert "architecture changed" in msg
        assert opt2.model._params is None  # model untouched

    def test_matching_schema_validates_silently(self, tmp_path):
        d = str(tmp_path / "ck")
        build_opt(d, iters=4, k=4, every=2).optimize()
        opt2 = build_opt(d, iters=8, k=4)
        assert opt2.resume()
        opt2.optimize()  # no raise
        assert opt2.state["neval"] == 8


# ================================================== shim + non-overwrite
class TestShimAndNonOverwrite:
    def test_shim_signatures_and_wire_unchanged(self, tmp_path):
        from bigdl_tpu.utils import checkpoint as ckpt
        f = ckpt.save_checkpoint(str(tmp_path / "ck"),
                                 {"w": np.arange(4, dtype=np.float32)},
                                 opt_state={"step": 3},
                                 driver_state={"neval": 3}, neval=3)
        assert f.endswith("model.3")
        blob = ckpt.load_checkpoint(f)
        assert sorted(blob) == ["driver_state", "model_state",
                                "opt_state", "params"]
        assert blob["opt_state"]["step"] == 3
        assert ckpt.latest_checkpoint(str(tmp_path / "ck")) == f

    def test_shim_latest_checkpoint_skips_corrupt(self, tmp_path):
        from bigdl_tpu.utils import checkpoint as ckpt
        d = str(tmp_path / "ck")
        f2 = ckpt.save_checkpoint(d, {"w": np.ones(64)}, neval=2)
        f4 = ckpt.save_checkpoint(d, {"w": np.ones(64)}, neval=4)
        _corrupt_array_byte(f4)
        assert ckpt.latest_checkpoint(d) == f2

    def test_versioned_non_overwrite_path_is_real(self, tmp_path):
        """The reference's unset overWriteCheckpoint: a second run into
        the same directory must refuse to clobber an existing
        model.<neval> — and over_write_checkpoint() re-allows it."""
        d = str(tmp_path / "ck")
        build_opt(d, iters=4, k=4, every=2).optimize()  # model.2/.4
        opt2 = build_opt(d, iters=4, k=4, every=2) \
            .over_write_checkpoint(False)
        with pytest.raises(FileExistsError,
                           match="overWriteCheckpoint"):
            opt2.optimize()
        opt3 = build_opt(d, iters=4, k=4, every=2) \
            .over_write_checkpoint()  # no-arg call = legacy behavior
        opt3.optimize()
        assert opt3.state["neval"] == 4

    def test_config_fields_exist(self):
        from bigdl_tpu.utils.config import Config
        c = Config()
        assert (c.checkpoint_keep_last, c.checkpoint_keep_every,
                c.checkpoint_async) == (5, 0, True)


# =============================================================== inspect
class TestCkptInspectCLI:
    def _fixture_dir(self, tmp_path):
        d = str(tmp_path / "ck")
        opt = build_opt(d, iters=4, k=4, every=2)
        opt.optimize()
        return d

    def test_ok_directory_exit_zero(self, tmp_path, capsys):
        from tools.ckpt_inspect import main
        d = self._fixture_dir(tmp_path)
        assert main([d]) == 0
        out = capsys.readouterr().out
        assert "step 4" in out and "checksum ok" in out
        assert "grad_sync off" in out
        assert f"latest valid: {os.path.join(d, 'model.4')}" in out

    def test_corrupt_snapshot_exit_one(self, tmp_path, capsys):
        from tools.ckpt_inspect import main
        d = self._fixture_dir(tmp_path)
        _corrupt_array_byte(os.path.join(d, "model.4"))
        assert main([d]) == 1
        out = capsys.readouterr().out
        assert "[corrupt]" in out
        assert f"latest valid: {os.path.join(d, 'model.2')}" in out

    def test_json_schema_and_no_verify(self, tmp_path, capsys):
        import json
        from tools.ckpt_inspect import main
        d = self._fixture_dir(tmp_path)
        assert main([d, "--json", "--no-verify"]) == 0
        rep = json.loads(capsys.readouterr().out)
        assert rep["corrupt"] == 0
        rows = rep["snapshots"]
        assert [r["step"] for r in rows] == [2, 4]
        assert all(r["checksum"] == "unverified" for r in rows)
        assert rows[0]["schema_hash"] == rows[1]["schema_hash"]
        assert rows[0]["param_leaves"] == 4

    def test_missing_path_exit_two(self, tmp_path, capsys):
        from tools.ckpt_inspect import main
        assert main([str(tmp_path / "nope")]) == 2


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
