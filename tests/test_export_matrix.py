"""Export matrix: Caffe persister, Torch7 module export/import, and the
full ConvertModel CLI (reference ``DL/utils/caffe/CaffePersister.scala``,
``DL/utils/ConvertModel.scala:24-46``) — VERDICT r2 missing #4."""

import os

import numpy as np
import pytest

from bigdl_tpu import nn

REF_CAFFE = "/root/reference/spark/dl/src/test/resources/caffe"


def _cnn():
    m = nn.Sequential(
        nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1, name="conv1"),
        nn.SpatialBatchNormalization(4, name="bn1"),
        nn.ReLU(name="relu1"),
        nn.SpatialMaxPooling(2, 2, 2, 2, ceil_mode=True, name="pool1"),
        nn.Flatten(name="flat"),
        nn.Linear(4 * 4 * 4, 5, name="fc"),
        nn.SoftMax(name="prob"),
        name="TestNet")
    m.initialize(3)
    # non-trivial BN stats so parity actually checks them
    import jax.numpy as jnp
    m._state["1"]["running_mean"] = jnp.asarray([0.1, -0.2, 0.3, 0.0])
    m._state["1"]["running_var"] = jnp.asarray([1.5, 0.7, 1.0, 2.0])
    return m


class TestCaffePersister:
    def test_roundtrip_forward_parity(self, tmp_path):
        from bigdl_tpu.interop import save_caffe, load_caffe_model
        m = _cnn()
        m.evaluate()
        x = np.random.RandomState(0).rand(2, 3, 8, 8).astype(np.float32)
        want = np.asarray(m.forward(x))

        proto = str(tmp_path / "net.prototxt")
        model = str(tmp_path / "net.caffemodel")
        save_caffe(m, proto, model, input_shapes=[[1, 3, 8, 8]])
        m2 = load_caffe_model(proto, model)
        m2.evaluate()
        got = np.asarray(m2.forward(x))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_prototxt_is_reference_shaped(self, tmp_path):
        """The emitted prototxt parses with the same textproto parser the
        importer applies to genuine Caffe files."""
        from bigdl_tpu.interop import save_caffe
        from bigdl_tpu.interop.caffe_format import _parse_prototxt
        m = _cnn()
        proto = str(tmp_path / "net.prototxt")
        save_caffe(m, proto, str(tmp_path / "net.caffemodel"),
                   input_shapes=[[1, 3, 8, 8]])
        net = _parse_prototxt(open(proto).read())
        types = [l["type"] for l in net["layers"]]
        assert types == ["Convolution", "BatchNorm", "Scale", "ReLU",
                         "Pooling", "Flatten", "InnerProduct", "Softmax"]
        # chained bottoms/tops
        for prev, cur in zip(net["layers"], net["layers"][1:]):
            assert cur["bottom"] == prev["top"]

    @pytest.mark.skipif(not os.path.isdir(REF_CAFFE),
                        reason="reference checkout absent")
    def test_reference_fixture_reexport(self, tmp_path):
        """Import the reference's committed caffemodel, re-export, and
        re-import: forward must agree (the CaffePersisterSpec analog)."""
        from bigdl_tpu.interop import load_caffe_model, save_caffe
        m = load_caffe_model(
            os.path.join(REF_CAFFE, "test_persist.prototxt"),
            os.path.join(REF_CAFFE, "test_persist.caffemodel"))
        m.evaluate()
        x = np.random.RandomState(1).rand(1, 3, 5, 5).astype(np.float32)
        want = np.asarray(m.forward(x))
        proto = str(tmp_path / "re.prototxt")
        model = str(tmp_path / "re.caffemodel")
        save_caffe(m, proto, model, input_shapes=[[1, 3, 5, 5]])
        m2 = load_caffe_model(proto, model)
        m2.evaluate()
        np.testing.assert_allclose(np.asarray(m2.forward(x)), want,
                                   rtol=1e-5, atol=1e-6)


class TestTorchModuleExport:
    def test_roundtrip_forward_parity(self, tmp_path):
        from bigdl_tpu.interop import save_torch_module, load_torch_module
        m = _cnn()
        m.evaluate()
        x = np.random.RandomState(2).rand(2, 3, 8, 8).astype(np.float32)
        want = np.asarray(m.forward(x))
        path = str(tmp_path / "net.t7")
        save_torch_module(m, path)
        m2 = load_torch_module(path)
        m2.evaluate()
        np.testing.assert_allclose(np.asarray(m2.forward(x)), want,
                                   rtol=1e-5, atol=1e-6)

    def test_t7_tree_has_torch_classes(self, tmp_path):
        from bigdl_tpu.interop import save_torch_module, load_t7
        m = _cnn()
        path = str(tmp_path / "net.t7")
        save_torch_module(m, path)
        tree = load_t7(path)
        assert tree["_torch_class"] == "nn.Sequential"
        classes = [c["_torch_class"] for c in tree["fields"]["modules"]]
        assert classes == ["nn.SpatialConvolution",
                           "nn.SpatialBatchNormalization", "nn.ReLU",
                           "nn.SpatialMaxPooling", "nn.View", "nn.Linear",
                           "nn.SoftMax"]
        conv = tree["fields"]["modules"][0]["fields"]
        assert conv["weight"].shape == (4, 3, 3, 3)
        assert conv["gradWeight"].shape == (4, 3, 3, 3)


class TestBigDLGraphSerialization:
    """nn.Graph <-> BigDL protobuf StaticGraph scheme (reference
    ``Graph.scala:563`` GraphSerializable) — graphs previously could not
    be saved in the native checkpoint format at all."""

    def test_branchy_graph_roundtrip(self, tmp_path):
        from bigdl_tpu.interop import save_bigdl_module, load_bigdl_module
        from bigdl_tpu.nn.graph import Graph, Input
        inp = Input()
        h = nn.Linear(6, 8, name="fc1")(inp)
        A = nn.ReLU(name="act_a")(h)
        b = nn.Tanh(name="act_b")(h)
        out = nn.CAddTable(name="add")([A, b])
        g = Graph([inp], [out], name="branchy")
        g.initialize(7)
        g.evaluate()
        x = np.random.RandomState(6).rand(4, 6).astype(np.float32)
        want = np.asarray(g.forward(x))

        path = str(tmp_path / "g.bigdl")
        save_bigdl_module(g, path)
        g2 = load_bigdl_module(path)
        g2.evaluate()
        np.testing.assert_allclose(np.asarray(g2.forward(x)), want,
                                   rtol=1e-5)

    def test_shared_layer_graph_roundtrip_stays_tied(self, tmp_path):
        import jax
        from bigdl_tpu.interop import save_bigdl_module, load_bigdl_module
        from bigdl_tpu.nn.graph import Graph, Input
        inp = Input()
        shared = nn.Linear(5, 5, name="tied")
        h1 = shared(inp)
        h2 = shared(h1)          # same instance called twice -> tied
        g = Graph([inp], [h2], name="tied_graph")
        g.initialize(11)
        g.evaluate()
        x = np.random.RandomState(7).rand(2, 5).astype(np.float32)
        want = np.asarray(g.forward(x))

        path = str(tmp_path / "tied.bigdl")
        save_bigdl_module(g, path)
        g2 = load_bigdl_module(path)
        g2.evaluate()
        np.testing.assert_allclose(np.asarray(g2.forward(x)), want,
                                   rtol=1e-5)
        # still ONE param bundle after the roundtrip (weights tied)
        assert len(jax.tree_util.tree_leaves(g2._params)) == 2

    def test_shared_layer_distinct_occurrences_wire_correctly(self,
                                                              tmp_path):
        """Regression (r3 review): consumers of a NON-final occurrence of
        a shared layer must not be rewired to the last occurrence."""
        from bigdl_tpu.interop import save_bigdl_module, load_bigdl_module
        from bigdl_tpu.nn.graph import Graph, Input
        inp = Input()
        shared = nn.Linear(5, 5, name="tied")
        h1 = shared(inp)
        h2 = shared(h1)
        out = nn.CAddTable(name="add")([h1, h2])   # h1 used AND h2 used
        g = Graph([inp], [out], name="occ_graph")
        g.initialize(13)
        g.evaluate()
        x = np.random.RandomState(8).rand(2, 5).astype(np.float32)
        want = np.asarray(g.forward(x))
        path = str(tmp_path / "occ.bigdl")
        save_bigdl_module(g, path)
        g2 = load_bigdl_module(path)
        g2.evaluate()
        np.testing.assert_allclose(np.asarray(g2.forward(x)), want,
                                   rtol=1e-5)


class TestConvertModelCLI:
    def _mlp(self):
        m = nn.Sequential(nn.Linear(6, 4, name="fc1"), nn.ReLU(),
                          nn.Linear(4, 2, name="fc2"), name="MLP")
        m.initialize(5)
        return m

    def test_bigdl_to_torch_to_bigdl(self, tmp_path):
        from bigdl_tpu.interop import save_bigdl_module, load_bigdl_module
        from bigdl_tpu.interop.convert_model import main
        m = self._mlp()
        m.evaluate()
        x = np.random.RandomState(3).rand(3, 6).astype(np.float32)
        want = np.asarray(m.forward(x))
        src = str(tmp_path / "m.bigdl")
        t7 = str(tmp_path / "m.t7")
        back = str(tmp_path / "back.bigdl")
        save_bigdl_module(m, src)
        main(["--from", "bigdl", "--input", src, "--to", "torch",
              "--output", t7])
        main(["--from", "torch", "--input", t7, "--to", "bigdl",
              "--output", back])
        m2 = load_bigdl_module(back)
        m2.evaluate()
        np.testing.assert_allclose(np.asarray(m2.forward(x)), want,
                                   rtol=1e-5)

    def test_quantize_round_trip(self, tmp_path):
        """--quantize through the kernel-backed int8 GEMM path: the
        128-multiple dims make the panel eligible for the pallas
        kernel; the saved model reloads as quantized twins with a
        byte-exact int8 panel (values -127..127 are lossless through
        the f32 tensor wire format), so the loaded forward is bitwise
        the in-memory quantized forward."""
        import jax.numpy as jnp

        from bigdl_tpu.interop import save_bigdl_module, load_bigdl_module
        from bigdl_tpu.interop.convert_model import main
        from bigdl_tpu.nn.quantized import QuantizedLinear, quantize
        m = nn.Sequential(nn.Linear(128, 128, name="fc1"), nn.ReLU(),
                          nn.Linear(128, 2, name="fc2"), name="QMLP")
        m.initialize(7)
        m.evaluate()
        x = np.random.RandomState(3).rand(4, 128).astype(np.float32)
        want = np.asarray(m.forward(x))
        src = str(tmp_path / "m.bigdl")
        dst = str(tmp_path / "q.bigdl")
        save_bigdl_module(m, src)
        main(["--from", "bigdl", "--input", src, "--to", "bigdl",
              "--output", dst, "--quantize"])
        q = load_bigdl_module(dst)
        q.evaluate()
        got = np.asarray(q.forward(x))
        # int8 weight error bound (the CLI's own parity gate already
        # enforced 0.05 before saving)
        assert np.max(np.abs(got - want)) / np.max(np.abs(want)) < 0.05
        assert isinstance(q.modules[0], QuantizedLinear)
        assert q.modules[0].weight_q.dtype == jnp.int8
        assert q.modules[0].mode == "weight_only"
        qm = quantize(m)
        qm.evaluate()
        np.testing.assert_array_equal(np.asarray(qm.forward(x)), got)

    def test_quantize_conv_round_trip_dynamic(self, tmp_path):
        from bigdl_tpu.interop import save_bigdl_module, load_bigdl_module
        from bigdl_tpu.interop.convert_model import main
        from bigdl_tpu.nn.quantized import QuantizedSpatialConvolution
        c = nn.Sequential(
            nn.SpatialConvolution(3, 8, 3, 3, pad_w=1, pad_h=1,
                                  name="c1"),
            nn.ReLU(), name="QCNN")
        c.initialize(5)
        c.evaluate()
        x = np.random.RandomState(4).rand(2, 3, 8, 8).astype(np.float32)
        want = np.asarray(c.forward(x))
        src = str(tmp_path / "c.bigdl")
        dst = str(tmp_path / "qc.bigdl")
        save_bigdl_module(c, src)
        main(["--from", "bigdl", "--input", src, "--to", "bigdl",
              "--output", dst, "--quantize", "--quantize-mode",
              "dynamic"])
        qc = load_bigdl_module(dst)
        qc.evaluate()
        got = np.asarray(qc.forward(x))
        assert np.max(np.abs(got - want)) / np.max(np.abs(want)) < 0.05
        assert isinstance(qc.modules[0], QuantizedSpatialConvolution)
        assert qc.modules[0].mode == "dynamic"  # mode survives the file

    def test_quantize_parity_gate_aborts_before_save(self, tmp_path):
        """The forward-parity check refuses to write the output when
        the quantized model misses the tolerance (any model has
        nonzero int8 error, so a near-zero tolerance must trip it)."""
        from bigdl_tpu.interop import save_bigdl_module
        from bigdl_tpu.interop.convert_model import main
        m = self._mlp()
        src = str(tmp_path / "m.bigdl")
        dst = str(tmp_path / "q.bigdl")
        save_bigdl_module(m, src)
        with pytest.raises(SystemExit, match="parity check FAILED"):
            main(["--from", "bigdl", "--input", src, "--to", "bigdl",
                  "--output", dst, "--quantize",
                  "--quantize-tolerance", "1e-9"])
        assert not os.path.exists(dst)  # nothing was saved

    def test_bigdl_to_caffe(self, tmp_path):
        from bigdl_tpu.interop import save_bigdl_module, load_caffe_model
        from bigdl_tpu.interop.convert_model import main
        m = _cnn()
        m.evaluate()
        x = np.random.RandomState(4).rand(1, 3, 8, 8).astype(np.float32)
        want = np.asarray(m.forward(x))
        src = str(tmp_path / "m.bigdl")
        save_bigdl_module(m, src)
        out = str(tmp_path / "m.caffemodel")
        main(["--from", "bigdl", "--input", src, "--to", "caffe",
              "--output", out])
        m2 = load_caffe_model(out + ".prototxt", out)
        m2.evaluate()
        np.testing.assert_allclose(np.asarray(m2.forward(x)), want,
                                   rtol=1e-5, atol=1e-6)

    @pytest.mark.skipif(not os.path.isdir(REF_CAFFE),
                        reason="reference checkout absent")
    def test_caffe_to_bigdl(self, tmp_path):
        from bigdl_tpu.interop import load_bigdl_module
        from bigdl_tpu.interop.convert_model import main
        out = str(tmp_path / "m.bigdl")
        main(["--from", "caffe",
              "--prototxt", os.path.join(REF_CAFFE,
                                         "test_persist.prototxt"),
              "--input", os.path.join(REF_CAFFE,
                                      "test_persist.caffemodel"),
              "--to", "bigdl", "--output", out])
        m = load_bigdl_module(out)
        m.evaluate()
        y = m.forward(np.random.RandomState(5)
                      .rand(1, 3, 5, 5).astype(np.float32))
        assert np.asarray(y).shape[-1] == 2
