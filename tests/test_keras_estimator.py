"""Keras-style topology + Estimator facade tests.

Mirrors the reference's Keras test strategy (``TEST/keras/`` — 91 specs
compare behaviors, ``pyspark/test/bigdl/test_simple_integration.py`` runs
small end-to-end fits) at the scale of the CPU mesh harness.
"""

import numpy as np
import pytest

from bigdl_tpu import keras, nn, optim
from bigdl_tpu.estimator import NNClassifier, NNEstimator
from bigdl_tpu.keras import (
    Activation, Convolution2D, Dense, Dropout, Flatten, LSTM,
    MaxPooling2D, Reshape, Sequential,
)


def _blobs(n=256, d=8, classes=3, seed=0):
    """Linearly separable gaussian blobs."""
    rng = np.random.RandomState(seed)
    centers = rng.randn(classes, d) * 4
    y = rng.randint(0, classes, size=n)
    x = centers[y] + rng.randn(n, d)
    return x.astype(np.float32), y.astype(np.int32)


class TestKerasLayers:
    def test_dense_shape_inference(self):
        m = Sequential()
        m.add(Dense(32, activation="relu", input_shape=(16,)))
        m.add(Dense(4))
        assert m.output_shape == (None, 4)

    def test_conv_stack_shape_inference(self):
        m = Sequential([
            Convolution2D(6, 5, 5, input_shape=(1, 28, 28),
                          activation="tanh"),
            MaxPooling2D(),
            Flatten(),
            Dense(10, activation="softmax"),
        ])
        # 28 -> conv5 valid -> 24 -> pool2 -> 12; 6*12*12 = 864 flattened
        assert m.output_shape == (None, 10)
        core = m.core_module()
        out = core.forward(np.zeros((2, 1, 28, 28), np.float32))
        assert out.shape == (2, 10)

    def test_lstm_return_sequences(self):
        m = Sequential([LSTM(7, return_sequences=True,
                             input_shape=(5, 3))])
        assert m.output_shape == (None, 5, 7)
        m2 = Sequential([LSTM(7, input_shape=(5, 3))])
        assert m2.output_shape == (None, 7)

    def test_unknown_activation_raises(self):
        with pytest.raises(ValueError):
            Sequential([Dense(4, activation="nope", input_shape=(3,))]).build()

    def test_first_layer_needs_input_shape(self):
        with pytest.raises(ValueError):
            Sequential().add(Dense(4))


class TestKerasFit:
    def test_compile_fit_evaluate_predict(self):
        x, y = _blobs()
        m = Sequential([
            Dense(16, activation="relu", input_shape=(8,)),
            Dense(3, activation="softmax"),
        ])
        m.compile(optimizer=optim.SGD(learning_rate=0.1),
                  loss="categorical_crossentropy", metrics=["accuracy"])
        m.fit(x, y, batch_size=32, nb_epoch=8)
        scores = m.evaluate(x, y)
        acc = scores["Top1Accuracy"]
        assert acc > 0.9, scores
        preds = m.predict_classes(x[:64])
        assert (preds == y[:64]).mean() > 0.85

    def test_kld_maps_to_probability_criterion(self):
        # ADVICE r2: Keras "kld" takes probability inputs ->
        # KullbackLeiblerDivergenceCriterion, NOT DistKLDivCriterion
        # (log-prob inputs)
        from bigdl_tpu.keras.topology import _LOSSES
        assert _LOSSES["kld"] is nn.KullbackLeiblerDivergenceCriterion
        assert _LOSSES["kullback_leibler_divergence"] \
            is nn.KullbackLeiblerDivergenceCriterion

    def test_fit_with_validation(self):
        x, y = _blobs(128)
        m = Sequential([Dense(3, activation="softmax",
                              input_shape=(8,))])
        m.compile("sgd", "categorical_crossentropy", ["accuracy"])
        m.fit(x, y, batch_size=32, nb_epoch=2, validation_data=(x, y))

    def test_model_wrapping_core_module(self):
        x, y = _blobs(128)
        core = nn.Sequential(nn.Linear(8, 3), nn.LogSoftMax())
        m = keras.Model(core)
        # core ends in LogSoftMax -> pass a criterion object for log-probs
        m.compile(optim.SGD(learning_rate=0.1),
                  nn.ClassNLLCriterion(), ["accuracy"])
        m.fit(x, y, batch_size=32, nb_epoch=6)
        assert m.evaluate(x, y)["Top1Accuracy"] > 0.9


class TestEstimator:
    def test_classifier_fit_transform(self):
        x, y = _blobs()
        model = nn.Sequential(nn.Linear(8, 3), nn.LogSoftMax())
        clf = NNClassifier(model, batch_size=32, max_epoch=8,
                           optim_method=optim.SGD(learning_rate=0.1))
        fitted = clf.fit(x, y)
        preds = fitted.transform(x)
        assert preds.shape == (len(x),)
        assert (preds == y).mean() > 0.9

    def test_estimator_regression(self):
        rng = np.random.RandomState(0)
        x = rng.randn(256, 4).astype(np.float32)
        w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
        y = x @ w
        est = NNEstimator(nn.Linear(4, 1), nn.MSECriterion(),
                          batch_size=32, max_epoch=20,
                          optim_method=optim.SGD(learning_rate=0.05))
        fitted = est.fit(x, y)
        pred = fitted.transform(x)
        assert np.mean((pred - y) ** 2) < 0.05


class TestReviewFixes:
    """Regressions for the round-2 code-review findings."""

    def test_same_padding_even_kernel(self):
        # Keras 'same': out = ceil(in / stride); symmetric k//2 padding
        # would give 29 for a 2x2 kernel on 28 — must be 28
        m = Sequential([Convolution2D(4, 2, 2, border_mode="same",
                                      input_shape=(3, 28, 28))])
        assert m.output_shape == (None, 4, 28, 28)
        m2 = Sequential([Convolution2D(4, 3, 3, border_mode="same",
                                       subsample=(2, 2),
                                       input_shape=(3, 28, 28))])
        assert m2.output_shape == (None, 4, 14, 14)

    def test_same_pooling_shape_and_values(self):
        m = Sequential([MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                                     border_mode="same",
                                     input_shape=(1, 5, 5))])
        assert m.output_shape == (None, 1, 3, 3)
        # average 'same' must exclude padded cells from the count
        from bigdl_tpu.keras import AveragePooling2D
        ma = Sequential([AveragePooling2D(pool_size=(2, 2), strides=(2, 2),
                                          border_mode="same",
                                          input_shape=(1, 3, 3))])
        x = np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3)
        out = ma.core_module().forward(x)
        # bottom-right window covers only cell (2,2)=8 -> avg 8, not 8/4
        np.testing.assert_allclose(np.asarray(out)[0, 0, 1, 1], 8.0)

    def test_cropping_full_extent_gives_empty(self):
        from bigdl_tpu.nn import Cropping2D
        out = Cropping2D((0, 4), (0, 0)).forward(
            np.zeros((1, 2, 4, 5), np.float32))
        assert out.shape == (1, 2, 0, 5)

    def test_categorical_crossentropy_one_hot(self):
        from bigdl_tpu import nn as _nn
        import jax.numpy as jnp
        probs = jnp.array([[0.7, 0.2, 0.1], [0.1, 0.8, 0.1]])
        onehot = jnp.array([[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]])
        ints = jnp.array([0, 1])
        c = _nn.CategoricalCrossEntropy()
        expected = -(np.log(0.7) + np.log(0.8)) / 2
        np.testing.assert_allclose(c.forward(probs, onehot), expected,
                                   rtol=1e-5)
        np.testing.assert_allclose(c.forward(probs, ints), expected,
                                   rtol=1e-5)

    def test_smooth_l1_rejects_two_tuple(self):
        from bigdl_tpu import nn as _nn
        import jax.numpy as jnp
        c = _nn.SmoothL1CriterionWithWeights()
        with pytest.raises(ValueError):
            c.forward(jnp.zeros((1, 2)), (jnp.zeros((1, 2)),
                                          jnp.ones((1, 2))))


class TestKerasBreadthWrappers:
    def test_mixed_stack_shapes_and_forward(self):
        from bigdl_tpu import keras as K
        m = K.Sequential([
            K.Convolution2D(4, 3, 3, input_shape=(2, 8, 8),
                            activation="relu"),
            K.UpSampling2D(),
            K.Cropping2D(((1, 1), (1, 1))),
            K.Permute((2, 3, 1)),
            K.Flatten(),
            K.MaxoutDense(6),
            K.Highway(),
            K.RepeatVector(3),
            K.GlobalAveragePooling1D(),
            K.Dense(2),
        ])
        assert m.output_shape == (None, 2)
        out = m.core_module().forward(np.zeros((2, 2, 8, 8), np.float32))
        assert out.shape == (2, 2)
        assert np.isfinite(np.asarray(out)).all()

    def test_1d_pooling_and_padding(self):
        from bigdl_tpu import keras as K
        m = K.Sequential([
            K.ZeroPadding1D(2, input_shape=(6, 3)),
            K.Convolution1D(5, 3, activation="tanh"),
            K.MaxPooling1D(2),
            K.GlobalMaxPooling1D(),
        ])
        assert m.output_shape == (None, 5)

    def test_separable_conv(self):
        from bigdl_tpu import keras as K
        m = K.Sequential([K.SeparableConvolution2D(
            8, 3, 3, input_shape=(4, 9, 9))])
        out_shape = m.output_shape
        assert out_shape[1] == 8

    def test_merge_modes(self):
        from bigdl_tpu import keras as K
        for mode, expect in (("sum", 3.0), ("mul", 2.0), ("max", 2.0)):
            merged = K.Merge(mode=mode).build((4,))
            out = merged.forward((np.full((2, 4), 1.0, np.float32),
                                  np.full((2, 4), 2.0, np.float32)))
            np.testing.assert_allclose(np.asarray(out), expect)


class TestBreadthReviewFixes:
    def test_separable_tf_ordering_rejected(self):
        from bigdl_tpu import keras as K
        with pytest.raises(NotImplementedError, match="dim_ordering"):
            K.Sequential([K.SeparableConvolution2D(
                8, 3, 3, dim_ordering="tf",
                input_shape=(9, 9, 4))]).build()

    def test_highway_activation_respected(self):
        from bigdl_tpu import keras as K
        import jax.numpy as jnp
        hw = K.Highway(activation="relu").build((6,))
        # g(relu) never outputs negatives in the transform branch;
        # compare against default-tanh build on a strongly negative input
        hw_tanh = K.Highway().build((6,))
        assert hw.activation is not hw_tanh.activation

    def test_merge_in_sequential_raises(self):
        from bigdl_tpu import keras as K
        m = K.Sequential([K.InputLayer(input_shape=(4,)),
                          K.Merge(mode="sum")])
        with pytest.raises(TypeError, match="Sequential"):
            _ = m.output_shape
