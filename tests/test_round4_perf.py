"""Round-4 perf work: pallas maxpool backward (interpret mode), phase
maxpool, bf16 stochastic-rounded optimizer state."""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.optim.optim_method import _stochastic_round


def rng(i):
    return jax.random.PRNGKey(i)


class TestPhaseMaxPool:
    CASES = [
        dict(k=3, s=2, p=1, fmt="NCHW", shape=(2, 3, 13, 17)),
        dict(k=3, s=2, p=0, fmt="NHWC", shape=(2, 14, 14, 5)),
        dict(k=3, s=1, p=1, fmt="NHWC", shape=(2, 9, 9, 4)),
        dict(k=2, s=2, p=0, fmt="NCHW", shape=(1, 2, 8, 8)),
        dict(k=5, s=3, p=2, fmt="NHWC", shape=(1, 20, 21, 2), ceil=True),
    ]

    @pytest.mark.parametrize("case", CASES)
    def test_matches_reduce_window(self, case):
        x = jnp.asarray(np.random.default_rng(0).normal(
            0, 1, case["shape"]).astype(np.float32))
        mk = lambda impl: nn.SpatialMaxPooling(
            case["k"], case["k"], case["s"], case["s"], case["p"],
            case["p"], ceil_mode=case.get("ceil", False),
            format=case["fmt"], impl=impl)
        y_ph, _ = mk("phase").apply({}, {}, x)
        y_rw, _ = mk("reduce_window").apply({}, {}, x)
        np.testing.assert_array_equal(np.asarray(y_ph), np.asarray(y_rw))


class TestPallasPoolBwd:
    """First-match parity vs XLA select-and-scatter, via pallas
    interpret mode (runs on CPU; the compiled path is exercised on the
    real chip by bench.py)."""

    CASES = [
        ((2, 16, 16, 64), (3, 3), (2, 2), ((0, 1), (0, 1))),
        ((1, 8, 8, 128), (3, 3), (1, 1), ((1, 1), (1, 1))),
        ((1, 12, 12, 8), (2, 2), (2, 2), ((0, 0), (0, 0))),
        ((1, 14, 14, 160), (3, 3), (2, 2), ((1, 1), (1, 1))),  # C pad
    ]

    @pytest.mark.parametrize("shape,kernel,stride,hw_pads", CASES)
    def test_first_match_parity(self, shape, kernel, stride, hw_pads,
                                monkeypatch):
        from bigdl_tpu.ops import pallas_pool
        from jax.experimental import pallas as pl
        import functools

        orig = pl.pallas_call
        monkeypatch.setattr(pallas_pool.pl, "pallas_call",
                            functools.partial(orig, interpret=True))
        # integer values force exact ties → first-match order matters
        x = jnp.asarray(np.random.default_rng(0).integers(
            -4, 5, shape).astype(np.float32))
        dims = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = ((0, 0),) + hw_pads + ((0, 0),)
        w = jnp.cos(jnp.arange(np.prod([
            shape[0],
            (shape[1] + sum(hw_pads[0]) - kernel[0]) // stride[0] + 1,
            (shape[2] + sum(hw_pads[1]) - kernel[1]) // stride[1] + 1,
            shape[3]])))

        def loss_ref(x):
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pads)
            return jnp.sum(y * w.reshape(y.shape))

        def loss_pl(x):
            y = pallas_pool.maxpool_nhwc_with_pallas_bwd(
                x, dims, strides, pads)
            return jnp.sum(y * w.reshape(y.shape))

        g_ref = jax.grad(loss_ref)(x)
        g_pl = jax.grad(loss_pl)(x)
        np.testing.assert_allclose(np.asarray(g_ref), np.asarray(g_pl),
                                   atol=1e-5)

    def test_unsupported_falls_back(self):
        from bigdl_tpu.ops.pallas_pool import supported
        assert not supported((1, 13, 13, 4), (3, 3), (2, 2),
                             ((0, 0), (0, 0)))  # H % sh != 0
        assert supported((1, 14, 14, 4), (3, 3), (2, 2), ((1, 1), (1, 1)))


class TestBf16OptimizerState:
    def test_stochastic_round_unbiased(self):
        x = jnp.asarray(np.float32([1.0001, -0.33333, 3.14159e-3]))
        rs = np.stack([
            np.asarray(_stochastic_round(x, jnp.bfloat16, rng(i)).astype(
                jnp.float32)) for i in range(2000)])
        ulp = np.abs(np.asarray(x)) * 0.0078125
        assert (np.abs(rs.mean(0) - np.asarray(x)) < 0.05 * ulp).all()

    def test_sgd_bf16_velocity_trains(self):
        m = optim.SGD(learning_rate=0.5, momentum=0.9,
                      state_dtype=jnp.bfloat16)
        p = {"w": jnp.asarray([2.0, -3.0])}
        s = m.init_state(p)
        assert s["velocity"]["w"].dtype == jnp.bfloat16
        for it in range(50):
            g = {"w": p["w"]}  # grad of 0.5*||w||^2
            p, s = m.update(g, p, s, 0.1, it)
        assert float(jnp.abs(p["w"]).max()) < 0.5  # converges toward 0

    def test_sgd_default_stays_f32(self):
        m = optim.SGD(learning_rate=0.1, momentum=0.9)
        s = m.init_state({"w": jnp.zeros((3,))})
        assert s["velocity"]["w"].dtype == jnp.float32
