"""Optim-method / schedule / trigger unit tests (reference ``TEST/optim/``:
``SGDSpec``, ``AdamSpec``, …)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import optim


def rosenbrock_like():
    """Simple quadratic: f(x) = sum((x - 3)^2); min at 3."""
    target = 3.0

    def grad(params):
        return jax.tree_util.tree_map(lambda p: 2 * (p - target), params)

    return grad, target


@pytest.mark.parametrize("method,steps,lr_tol", [
    (optim.SGD(learning_rate=0.1), 100, 1e-3),
    (optim.SGD(learning_rate=0.05, momentum=0.9), 150, 1e-2),
    (optim.SGD(learning_rate=0.05, momentum=0.9, dampening=0.0,
               nesterov=True), 150, 1e-2),
    (optim.Adam(learning_rate=0.3), 200, 1e-2),
    (optim.Adagrad(learning_rate=1.0), 300, 1e-2),
    (optim.Adadelta(decay_rate=0.9), 2000, 0.5),
    (optim.Adamax(learning_rate=0.5), 200, 1e-2),
    (optim.RMSprop(learning_rate=0.1), 300, 1e-2),
])
def test_methods_converge_on_quadratic(method, steps, lr_tol):
    grad_fn, target = rosenbrock_like()
    params = {"w": jnp.array([0.0, 1.0]), "b": jnp.array([5.0])}
    state = method.init_state(params)
    for t in range(steps):
        g = grad_fn(params)
        params, state = method.update(g, params, state, method.learning_rate, t)
    for leaf in jax.tree_util.tree_leaves(params):
        np.testing.assert_allclose(leaf, target, atol=lr_tol * 10)


def test_ftrl_sparsifies():
    m = optim.Ftrl(learning_rate=0.5, l1_regularization_strength=2.0)
    params = {"w": jnp.array([0.05, -0.02])}  # tiny weights, strong l1
    state = m.init_state(params)
    for t in range(50):
        g = {"w": 0.1 * params["w"]}  # weak pull
        params, state = m.update(g, params, state, m.learning_rate, t)
    np.testing.assert_allclose(params["w"], 0.0, atol=1e-6)


def test_weight_decay_shrinks():
    m = optim.SGD(learning_rate=0.1, weight_decay=0.5)
    params = {"w": jnp.array([2.0])}
    state = m.init_state(params)
    params, _ = m.update({"w": jnp.array([0.0])}, params, state, 0.1, 0)
    assert float(params["w"][0]) < 2.0


class TestSchedules:
    def test_step(self):
        s = optim.Step(10, 0.5)
        assert s(1.0, 0, 0) == 1.0
        assert s(1.0, 10, 0) == 0.5
        assert s(1.0, 25, 0) == 0.25

    def test_multistep(self):
        s = optim.MultiStep([5, 15], 0.1)
        assert s(1.0, 4, 0) == 1.0
        np.testing.assert_allclose(s(1.0, 5, 0), 0.1)
        np.testing.assert_allclose(s(1.0, 15, 0), 0.01)

    def test_poly(self):
        s = optim.Poly(0.5, 100)
        assert s(1.0, 0, 0) == 1.0
        np.testing.assert_allclose(s(1.0, 75, 0), 0.5)
        assert s(1.0, 100, 0) == 0.0

    def test_warmup_then_sequential(self):
        # ResNet recipe: warmup 5 iters 0.1->0.6, then poly
        seq = optim.SequentialSchedule(optim.Warmup(0.1, 5),
                                       optim.Poly(2.0, 100))
        np.testing.assert_allclose(seq(0.1, 0, 0), 0.1)
        np.testing.assert_allclose(seq(0.1, 5, 0), 0.1)  # poly iter 0 of base
        assert seq(0.1, 4, 0) > seq(0.1, 0, 0)

    def test_epoch_schedule_regimes(self):
        s = optim.EpochSchedule([(0, 2, 1e-2), (3, 6, 1e-3), (7, 100, 1e-4)])
        assert s(1.0, 0, 1) == 1e-2
        assert s(1.0, 0, 5) == 1e-3
        assert s(1.0, 0, 50) == 1e-4

    def test_plateau_drops_on_stall(self):
        s = optim.Plateau(factor=0.1, patience=2, mode="min")
        lrs = [s(1.0, i, 0, metric=5.0) for i in range(5)]
        # i=0 sets best; i=1,2 stall -> drop; i=3,4 stall -> second drop
        assert lrs[0] == 1.0
        assert lrs[2] == pytest.approx(0.1)
        assert lrs[4] == pytest.approx(0.01)
        # improvement resets the wait counter
        s2 = optim.Plateau(factor=0.1, patience=2, mode="min")
        vals = [5.0, 4.0, 3.0, 2.0, 1.0]
        lrs2 = [s2(1.0, i, 0, metric=v) for i, v in enumerate(vals)]
        assert all(lr == 1.0 for lr in lrs2)

    def test_default_decay(self):
        s = optim.Default(0.1)
        np.testing.assert_allclose(s(1.0, 10, 0), 1.0 / 2.0)


class TestTriggers:
    def test_max_epoch_and_iteration(self):
        assert optim.max_epoch(5)({"epoch": 5})
        assert not optim.max_epoch(5)({"epoch": 4})
        assert optim.max_iteration(10)({"neval": 10})

    def test_every_epoch_and_several_iteration(self):
        assert optim.every_epoch()({"epoch_finished": True})
        assert not optim.every_epoch()({"epoch_finished": False})
        t = optim.several_iteration(3)
        assert [t({"neval": i}) for i in range(1, 7)] == \
            [False, False, True, False, False, True]

    def test_composition(self):
        t = optim.max_epoch(2).or_(optim.min_loss(0.1))
        assert t({"epoch": 0, "loss": 0.05})
        assert t({"epoch": 2, "loss": 9.0})
        assert not t({"epoch": 1, "loss": 1.0})


class TestValidationMethods:
    def test_top1(self):
        out = jnp.array([[0.9, 0.1], [0.2, 0.8], [0.6, 0.4]])
        target = jnp.array([0, 1, 1])
        r = optim.Top1Accuracy()(out, target)
        np.testing.assert_allclose(r.result, 2 / 3)

    def test_top5(self):
        out = jax.nn.one_hot(jnp.array([3, 9]), 10) * 5.0
        # target 3 in top5 trivially; target 0 for second row is not top-1
        r = optim.Top5Accuracy()(out, jnp.array([3, 0]))
        assert r.result >= 0.5

    def test_result_associative(self):
        a = optim.ValidationResult(3, 4)
        b = optim.ValidationResult(1, 4)
        np.testing.assert_allclose((a + b).result, 0.5)

    def test_hit_ratio_ndcg(self):
        # positive score highest -> hit, ndcg=1
        out = jnp.array([[5.0] + [1.0] * 20])
        assert optim.HitRatio(10)(out, None).result == 1.0
        np.testing.assert_allclose(optim.NDCG(10)(out, None).result, 1.0)

    def test_clip_global_norm(self):
        g = {"a": jnp.array([3.0]), "b": jnp.array([4.0])}
        clipped = optim.clip_by_global_norm(g, 1.0)
        np.testing.assert_allclose(float(optim.global_norm(clipped)), 1.0,
                                   rtol=1e-5)
