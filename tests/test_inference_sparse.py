"""Inference path + sparse/recommender tests (reference:
``PredictorSpec``, ``EvaluatorSpec``, ``SparseLinearSpec``,
``LookupTableSparseSpec``)."""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import models, nn, optim
from bigdl_tpu.dataset import (
    DataSet, MiniBatch, Sample, SampleToMiniBatch,
)
from bigdl_tpu.nn.sparse import dense_to_bags
from bigdl_tpu.optim.predictor import Evaluator, PredictionService, Predictor


def rng(i=0):
    return jax.random.PRNGKey(i)


def make_model():
    return (nn.Sequential()
            .add(nn.Linear(4, 16)).add(nn.ReLU())
            .add(nn.Linear(16, 3)).add(nn.LogSoftMax())).initialize(0)


class TestPredictor:
    def test_predict_samples(self):
        model = make_model()
        samples = [Sample(np.ones((4,), np.float32)) for _ in range(10)]
        out = model.predict(samples, batch_size=4)
        assert out.shape == (10, 3)

    def test_predict_class(self):
        model = make_model()
        samples = [Sample(np.ones((4,), np.float32)) for _ in range(5)]
        cls = model.predict_class(samples)
        assert cls.shape == (5,)
        assert set(np.unique(cls)) <= {0, 1, 2}

    def test_predict_dataset(self):
        model = make_model()
        samples = [Sample(np.full((4,), i, np.float32)) for i in range(8)]
        ds = DataSet.array(samples) >> SampleToMiniBatch(
            4, drop_remainder=False)
        out = Predictor(model).predict(ds)
        assert out.shape == (8, 3)

    def test_predict_consistent_across_batch_sizes(self):
        model = make_model()
        samples = [Sample(np.random.default_rng(i).normal(
            0, 1, (4,)).astype(np.float32)) for i in range(7)]
        a = Predictor(model, batch_size=3).predict(samples)
        b = Predictor(model, batch_size=7).predict(samples)
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


class TestEvaluator:
    def test_evaluate_metrics(self):
        model = make_model()
        xs = np.random.default_rng(0).normal(0, 1, (32, 4)).astype(np.float32)
        preds = model.predict([Sample(x) for x in xs]).argmax(-1)
        samples = [Sample(x, np.int32(p)) for x, p in zip(xs, preds)]
        ds = DataSet.array(samples) >> SampleToMiniBatch(8)
        res = model.evaluate_on(ds, [optim.Top1Accuracy(), optim.Loss()])
        assert res["Top1Accuracy"].result == 1.0  # labels = own predictions
        assert np.isfinite(res["Loss"].result)


class TestPredictionService:
    def test_odd_sizes_and_chunking(self):
        model = make_model()
        svc = PredictionService(model, batch_size=4)
        out1 = svc.predict(np.ones((1, 4), np.float32))
        out9 = svc.predict(np.ones((9, 4), np.float32))
        assert out1.shape == (1, 3) and out9.shape == (9, 3)
        np.testing.assert_allclose(out9[0], out1[0], rtol=1e-6)

    def test_concurrent_callers(self):
        model = make_model()
        svc = PredictionService(model, batch_size=8)
        errs = []

        def worker(seed):
            try:
                x = np.random.default_rng(seed).normal(
                    0, 1, (5, 4)).astype(np.float32)
                for _ in range(5):
                    out = svc.predict(x)
                    assert out.shape == (5, 3)
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert svc.request_count == 20


class TestSparse:
    def test_dense_to_bags_roundtrip(self):
        dense = np.zeros((2, 10), np.float32)
        dense[0, [1, 5]] = [2.0, 3.0]
        dense[1, 9] = 1.5
        ids, w = dense_to_bags(dense)
        assert ids.shape == w.shape == (2, 2)
        assert set(ids[0]) == {1, 5}
        assert ids[1, 1] == -1 and w[1, 1] == 0.0

    def test_sparse_linear_matches_dense(self):
        lin = nn.SparseLinear(10, 3).initialize(0)
        dense = np.zeros((4, 10), np.float32)
        r = np.random.default_rng(0)
        for i in range(4):
            cols = r.choice(10, 3, replace=False)
            dense[i, cols] = r.normal(0, 1, 3)
        ids, w = dense_to_bags(dense)
        y_sparse = lin.forward((jnp.asarray(ids), jnp.asarray(w)))
        W = lin._params["weight"]  # (in, out)
        expected = dense @ np.asarray(W) + np.asarray(lin._params["bias"])
        np.testing.assert_allclose(np.asarray(y_sparse), expected, rtol=1e-5,
                                   atol=1e-6)

    # tf.nn.embedding_lookup_sparse semantics (BigDL LookupTableSparse
    # mirrors them): mean = sum(w*e)/sum(|w|), sqrtn = sum(w*e)/sqrt(sum w²)
    @pytest.mark.parametrize("combiner,expected", [
        ("sum", 3.0), ("mean", 1.0), ("sqrtn", 3.0 / np.sqrt(5))])
    def test_lookup_table_sparse_combiners(self, combiner, expected):
        lt = nn.LookupTableSparse(5, 1, combiner=combiner)
        lt._params = {"weight": jnp.ones((5, 1))}
        lt._state = {}
        ids = jnp.array([[0, 1, -1]])
        w = jnp.array([[1.0, 2.0, 0.0]])
        y = lt.forward((ids, w))
        np.testing.assert_allclose(float(y[0, 0]), expected, rtol=1e-5)

    def test_sparse_join_table(self):
        j = nn.SparseJoinTable([10, 20])
        ids = j.forward(((jnp.array([[1, -1]]), jnp.array([[1.0, 0.0]])),
                         (jnp.array([[3, 5]]), jnp.array([[2.0, 1.0]]))))
        np.testing.assert_array_equal(np.asarray(ids[0]),
                                      [[1, -1, 13, 15]])


class TestRecommenderModels:
    def test_ncf_learns_preferences(self):
        """NCF fits a small synthetic preference matrix."""
        U, I = 20, 15
        r = np.random.default_rng(0)
        u_emb = r.normal(0, 1, (U, 4))
        i_emb = r.normal(0, 1, (I, 4))
        labels = ((u_emb @ i_emb.T) > 0).astype(np.float32)

        model = models.NeuralCF(U, I, embed_dim=8, mlp_dims=(16, 8))
        p, s = model.init(rng(0))
        users, items = np.meshgrid(np.arange(U), np.arange(I),
                                   indexing="ij")
        users = jnp.asarray(users.ravel())
        items = jnp.asarray(items.ravel())
        y = jnp.asarray(labels.ravel())[:, None]
        crit = nn.BCECriterion()
        method = optim.Adam(learning_rate=0.02)
        ostate = method.init_state(p)

        @jax.jit
        def step(p, ostate, it):
            def loss(p):
                out, _ = model.apply(p, s, (users, items), training=True)
                return crit.apply(out, y)
            l, g = jax.value_and_grad(loss)(p)
            p, ostate = method.update(g, p, ostate, method.learning_rate, it)
            return p, ostate, l

        for it in range(200):
            p, ostate, l = step(p, ostate, it)
        out, _ = model.apply(p, s, (users, items))
        acc = float(jnp.mean((out[:, 0] > 0.5) == (y[:, 0] > 0.5)))
        assert acc > 0.9, acc

    def test_wide_and_deep_forward_and_grad(self):
        model = models.WideAndDeep(wide_dim=100,
                                   deep_field_counts=[10, 20],
                                   dense_dim=3, embed_dim=4)
        p, s = model.init(rng(0))
        N = 8
        wide_ids = jnp.asarray(
            np.random.default_rng(0).integers(0, 100, (N, 5)))
        wide_w = jnp.ones((N, 5))
        deep_ids = jnp.asarray(
            np.random.default_rng(1).integers(0, 10, (N, 2)))
        dense = jnp.ones((N, 3))
        out, _ = model.apply(p, s, ((wide_ids, wide_w), deep_ids, dense))
        assert out.shape == (N, 1)
        assert bool(jnp.all((out >= 0) & (out <= 1)))

        def loss(p):
            o, _ = model.apply(p, s, ((wide_ids, wide_w), deep_ids, dense))
            return jnp.mean((o - 1.0) ** 2)

        g = jax.grad(loss)(p)
        total = sum(float(jnp.sum(jnp.abs(l)))
                    for l in jax.tree_util.tree_leaves(g))
        assert np.isfinite(total) and total > 0
