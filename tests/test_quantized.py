"""int8 post-training quantization tests (reference
``TEST/.../QuantizationSpec`` + ``quantized/LinearSpec``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.nn.quantized import (QuantizedLinear,
                                    QuantizedSpatialConvolution, quantize)


def test_quantized_linear_close_to_f32():
    m = nn.Linear(32, 16)
    m.initialize(rng=0)
    x = np.random.RandomState(0).randn(8, 32).astype(np.float32)
    ref = np.asarray(m.forward(x))
    q = QuantizedLinear.from_linear(m, m._params)
    out = np.asarray(q.forward(x))
    # int8 symmetric per-channel: relative error bounded by ~2/127
    rel = np.abs(out - ref) / (np.abs(ref).max() + 1e-6)
    assert rel.max() < 0.03, rel.max()
    assert q.weight_q.dtype == jnp.int8


def test_quantized_conv_close_to_f32():
    m = nn.SpatialConvolution(3, 8, 3, 3, pad_w=1, pad_h=1)
    m.initialize(rng=1)
    x = np.random.RandomState(1).randn(2, 3, 8, 8).astype(np.float32)
    ref = np.asarray(m.forward(x))
    q = QuantizedSpatialConvolution.from_conv(m, m._params)
    out = np.asarray(q.forward(x))
    rel = np.abs(out - ref) / (np.abs(ref).max() + 1e-6)
    assert rel.max() < 0.03, rel.max()


def test_grouped_conv_quantization():
    m = nn.SpatialConvolution(4, 8, 3, 3, n_group=2)
    m.initialize(rng=2)
    x = np.random.RandomState(2).randn(1, 4, 6, 6).astype(np.float32)
    ref = np.asarray(m.forward(x))
    q = QuantizedSpatialConvolution.from_conv(m, m._params)
    rel = np.abs(np.asarray(q.forward(x)) - ref) / (np.abs(ref).max() + 1e-6)
    assert rel.max() < 0.03


def test_quantize_tree_preserves_structure_and_accuracy():
    rng = np.random.RandomState(0)
    centers = rng.randn(4, 16) * 3
    y = rng.randint(0, 4, 512)
    x = (centers[y] + rng.randn(512, 16)).astype(np.float32)

    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(),
                          nn.Linear(64, 4), nn.LogSoftMax())
    from bigdl_tpu import optim
    from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample
    samples = [Sample(x[i], np.int32(y[i])) for i in range(512)]
    (optim.LocalOptimizer(model,
                          DataSet.array(samples) >> SampleToMiniBatch(64),
                          nn.ClassNLLCriterion())
     .set_optim_method(optim.Adam(learning_rate=0.01))
     .set_end_when(optim.max_epoch(10))).optimize()

    model.training = False
    f32_acc = (np.argmax(np.asarray(model.forward(x)), -1) == y).mean()
    q = quantize(model)
    q_acc = (np.argmax(np.asarray(q.forward(x)), -1) == y).mean()
    # VERDICT acceptance: within 1% of f32 accuracy
    assert f32_acc > 0.95
    assert q_acc >= f32_acc - 0.01, (f32_acc, q_acc)
    # original untouched; quantized leaves are int8
    assert isinstance(model.modules[0], nn.Linear)
    assert isinstance(q.modules[0], QuantizedLinear)
    # quantized model runs under jit
    out = jax.jit(lambda xx: q.apply(q._params, q._state, xx,
                                     training=False)[0])(jnp.asarray(x[:8]))
    assert np.isfinite(np.asarray(out)).all()


def test_int32_accumulation_exact():
    # tiny ints roundtrip exactly through the int8 path (no f32 rounding):
    # weights/activations already on the int8 grid
    # rows whose values land exactly on the per-channel int8 grid
    w = np.array([[1.0, -1.0], [2.0, -2.0]], np.float32)
    m = nn.Linear(2, 2, with_bias=False)
    m.initialize()
    m._params = {"weight": jnp.asarray(w)}
    q = QuantizedLinear.from_linear(m, m._params)
    x = np.array([[127.0, -127.0]], np.float32)
    out = np.asarray(q.forward(x))
    np.testing.assert_allclose(out, x @ w.T, rtol=1e-6)


# --------------------------------------------------- recurrent (r3)
# reference Quantization.quantize also converts recurrent cells
# ("Linear/SpatialConvolution/gru etc", SURVEY §2.2)
def test_quantized_lstm_close_to_f32():
    from bigdl_tpu.nn.quantized import QuantizedLSTM, quantize
    from bigdl_tpu.nn.recurrent import LSTM, Recurrent
    rng = np.random.RandomState(0)
    model = nn.Sequential(Recurrent(LSTM(6, 8)))
    model.initialize(0)
    x = jnp.asarray(rng.rand(3, 7, 6).astype(np.float32))
    ref = np.asarray(model.forward(x))
    q = quantize(model)
    assert isinstance(q.modules[0].cell, QuantizedLSTM)
    out = np.asarray(q.forward(x))
    assert out.shape == ref.shape
    # int8 gates: small relative error, same dynamics
    assert np.max(np.abs(out - ref)) < 0.06, np.max(np.abs(out - ref))


def test_quantized_gru_and_rnn_cells():
    from bigdl_tpu.nn.quantized import (QuantizedGRU, QuantizedRnnCell,
                                        quantize)
    from bigdl_tpu.nn.recurrent import GRU, Recurrent, RnnCell
    rng = np.random.RandomState(1)
    for cell, qcls in ((GRU(5, 6), QuantizedGRU),
                       (RnnCell(5, 6), QuantizedRnnCell)):
        model = nn.Sequential(Recurrent(cell))
        model.initialize(2)
        x = jnp.asarray(rng.rand(2, 5, 5).astype(np.float32))
        ref = np.asarray(model.forward(x))
        q = quantize(model)
        assert isinstance(q.modules[0].cell, qcls)
        out = np.asarray(q.forward(x))
        assert np.max(np.abs(out - ref)) < 0.08, np.max(np.abs(out - ref))


def test_quantized_bi_recurrent():
    from bigdl_tpu.nn.quantized import QuantizedLSTM, quantize
    from bigdl_tpu.nn.recurrent import LSTM
    rng = np.random.RandomState(2)
    model = nn.Sequential(nn.BiRecurrent(LSTM(4, 5)))
    model.initialize(3)
    x = jnp.asarray(rng.rand(2, 6, 4).astype(np.float32))
    ref = np.asarray(model.forward(x))
    q = quantize(model)
    bi = q.modules[0]
    assert isinstance(bi.fwd.cell, QuantizedLSTM)
    assert isinstance(bi.bwd.cell, QuantizedLSTM)
    out = jax.jit(lambda xx: q.apply(q._params, q._state, xx,
                                     training=False)[0])(x)
    assert np.max(np.abs(np.asarray(out) - ref)) < 0.08


# --------------------------------------------- activation modes (this PR)
# bounds per mode: dynamic adds per-tensor activation rounding on top of
# the weight rounding, so its band is wider; saturating gate activations
# keep the recurrent dynamics close either way
_RECURRENT_MODE_TOL = {"weight_only": 0.06, "dynamic": 0.10}


@pytest.mark.parametrize("mode", ["weight_only", "dynamic"])
def test_recurrent_parity_both_modes(mode):
    from bigdl_tpu.nn.quantized import quantize
    from bigdl_tpu.nn.recurrent import GRU, LSTM, Recurrent
    rng = np.random.RandomState(4)
    for cell_fn in (lambda: LSTM(6, 8), lambda: GRU(6, 8)):
        model = nn.Sequential(Recurrent(cell_fn()))
        model.initialize(0)
        x = jnp.asarray(rng.rand(3, 7, 6).astype(np.float32))
        ref = np.asarray(model.forward(x))
        q = quantize(model, mode=mode)
        assert q.modules[0].cell.mode == mode
        err = np.max(np.abs(np.asarray(q.forward(x)) - ref))
        assert err < _RECURRENT_MODE_TOL[mode], (mode, err)


def test_quantize_stamps_mode_on_every_leaf():
    from bigdl_tpu.nn.quantized import quantize
    from bigdl_tpu.nn.recurrent import LSTM, Recurrent
    model = nn.Sequential(
        nn.Linear(8, 16), nn.ReLU(),
        nn.SpatialConvolution(1, 2, 3, 3),
        Recurrent(LSTM(4, 4)))
    model.initialize(1)
    q = quantize(model, mode="dynamic")
    stamped = [m.mode for m in (q.modules[0], q.modules[2],
                                q.modules[3].cell)]
    assert stamped == ["dynamic"] * 3


def test_quantize_is_idempotent():
    """A second quantize() pass must keep already-quantized leaves
    as-is — same objects' buffers, bitwise-identical forward — instead
    of re-quantizing the int8 grid (which would compound rounding)."""
    from bigdl_tpu.nn.quantized import quantize
    rng = np.random.RandomState(5)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                          nn.Linear(32, 4))
    model.initialize(0)
    model.training = False
    x = rng.randn(4, 16).astype(np.float32)
    q1 = quantize(model)
    y1 = np.asarray(q1.forward(x))
    q2 = quantize(q1)
    assert isinstance(q2.modules[0], QuantizedLinear)
    np.testing.assert_array_equal(
        np.asarray(q2.modules[0].weight_q), np.asarray(q1.modules[0].weight_q))
    np.testing.assert_array_equal(np.asarray(q2.forward(x)), y1)
