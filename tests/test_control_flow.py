"""User-facing control-flow modules (VERDICT r3 item 6): loop/cond/
switch-merge graphs built via the nn API — NOT the TF importer — that
execute and TRAIN (reference Scheduler.scala:104-145)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn, optim


def rng(i):
    return jax.random.PRNGKey(i)


class TestWhile:
    def test_unbounded_while_matches_python(self):
        # carry = (i, x); while i < 5: x = 2x + 1, i += 1
        body = nn.Lambda(lambda c: (c[0] + 1, 2.0 * c[1] + 1.0))
        w = nn.While(lambda c: c[0] < 5, body)
        p, s = w.init(rng(0))
        out, _ = w.apply(p, s, (jnp.asarray(0), jnp.asarray(1.0)))
        x = 1.0
        for _ in range(5):
            x = 2 * x + 1
        assert float(out[1]) == x and int(out[0]) == 5

    def test_bounded_while_masks_after_exit(self):
        body = nn.Lambda(lambda c: (c[0] + 1, c[1] * 2.0))
        w = nn.While(lambda c: c[0] < 3, body, max_trip_count=10)
        p, s = w.init(rng(0))
        out, _ = w.apply(p, s, (jnp.asarray(0), jnp.asarray(1.0)))
        assert int(out[0]) == 3 and float(out[1]) == 8.0  # not 2**10

    def test_loop_graph_trains(self):
        """The verdict's 'Done' case: a loop graph built via the nn
        API trains through the bounded While."""
        steps = 4

        class Step(nn.Module):
            def __init__(self):
                super().__init__("Step")
                self.lin = nn.Linear(6, 6)

            def spec_children(self):
                return {"lin": self.lin}

            def init(self, r):
                p, s = self.lin.init(r)
                return {"lin": p}, {"lin": s}

            def apply(self, params, state, c, *, training=False, rng=None):
                i, h = c
                y, _ = self.lin.apply(params["lin"], state["lin"], h)
                return (i + 1, jnp.tanh(y)), state

        loop = nn.While(lambda c: c[0] < steps, Step(),
                        max_trip_count=8)
        inp = nn.Input()
        looped = loop(inp)
        head = nn.Lambda(lambda c: c[1])(looped)
        out = nn.Linear(6, 2)(head)
        model = nn.DynamicGraph([inp], [nn.LogSoftMax()(out)])

        p, st = model.init(rng(0))
        method = optim.Adam(learning_rate=0.01)
        os_ = method.init_state(p)
        crit = nn.ClassNLLCriterion()
        data_rng = np.random.default_rng(0)
        x = data_rng.normal(0, 1, (64, 6)).astype(np.float32)
        y = (x.sum(1) > 0).astype(np.int32)
        x, y = jnp.asarray(x), jnp.asarray(y)

        @jax.jit
        def step(p, os_, it):
            def loss_fn(p):
                outv, _ = model.apply(
                    p, st, (jnp.zeros((), jnp.int32) + 0, x))
                return crit.apply(outv, y)
            loss, g = jax.value_and_grad(loss_fn)(p)
            p, os_ = method.update(g, p, os_, 0.01, it)
            return p, os_, loss

        losses = []
        for it in range(80):
            p, os_, loss = step(p, os_, it)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    def test_cond_module_as_predicate(self):
        pred = nn.Lambda(lambda c: c[0] < 2)
        body = nn.Lambda(lambda c: (c[0] + 1, c[1] + 10.0))
        w = nn.While(pred, body)
        p, s = w.init(rng(0))
        out, _ = w.apply(p, s, (jnp.asarray(0), jnp.asarray(0.0)))
        assert float(out[1]) == 20.0


class TestWhileRobustness:
    def test_dead_iterations_do_not_poison_gradients(self):
        """A body that diverges past the exit point must not leak
        inf/NaN into gradients: dead iterations are SKIPPED, not
        masked."""
        body = nn.Lambda(lambda c: (c[0] + 1, c[1] * 50.0))
        w = nn.While(lambda c: c[0] < 3, body, max_trip_count=60)
        p, s = w.init(rng(0))

        def loss_fn(x):
            out, _ = w.apply(p, s, (jnp.asarray(0), x))
            return out[1]

        g = jax.grad(loss_fn)(jnp.asarray(1.0))
        assert np.isfinite(float(g))
        assert float(g) == 50.0 ** 3

    def test_dropout_inside_while_body(self):
        body = nn.Sequential().add(nn.Dropout(0.5)) \
            .add(nn.Lambda(lambda x: x))
        carry_body = nn.Lambda(lambda c: c)  # wrap: carry = (i, x)

        class B(nn.Module):
            def __init__(self):
                super().__init__("B")
                self.inner = body

            def spec_children(self):
                return {"inner": self.inner}

            def init(self, r):
                p, s = self.inner.init(r)
                return {"inner": p}, {"inner": s}

            def apply(self, params, state, c, *, training=False,
                      rng=None):
                i, x = c
                y, _ = self.inner.apply(params["inner"], state["inner"],
                                        x, training=training, rng=rng)
                return (i + 1, y), state

        w = nn.While(lambda c: c[0] < 2, B(), max_trip_count=4)
        p, s = w.init(rng(0))
        out, _ = w.apply(p, s, (jnp.asarray(0), jnp.ones((8,))),
                         training=True, rng=rng(1))
        assert out[1].shape == (8,)  # no "needs an rng" error


class TestCond:
    def test_branch_selection(self):
        c = nn.Cond(lambda x: jnp.sum(x) > 0,
                    nn.Lambda(lambda x: x * 2.0),
                    nn.Lambda(lambda x: x - 1.0))
        p, s = c.init(rng(0))
        out, _ = c.apply(p, s, jnp.asarray([1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(out), [2.0, 4.0])
        out, _ = c.apply(p, s, jnp.asarray([-1.0, -2.0]))
        np.testing.assert_allclose(np.asarray(out), [-2.0, -3.0])

    def test_cond_trains_both_branches(self):
        model = nn.Cond(lambda x: jnp.mean(x) > 0,
                        nn.Linear(4, 3), nn.Linear(4, 3))
        p, st = model.init(rng(0))

        def loss_fn(p, x):
            out, _ = model.apply(p, st, x)
            return jnp.sum(out ** 2)

        xpos = jnp.ones((4,))
        g = jax.grad(loss_fn)(p, xpos)
        # taken branch gets gradient, untaken gets zeros
        assert float(jnp.abs(g["true"]["weight"]).sum()) > 0
        assert float(jnp.abs(g["false"]["weight"]).sum()) == 0


class TestSwitchMerge:
    def test_piecewise_graph(self):
        """Hand-built Switch/Merge graph: relu-like piecewise select,
        the reference's port semantics compiled to a select."""
        data = nn.Input()
        pred = nn.Input()
        sw = nn.Switch()
        ports = sw((data, pred))
        f_br = nn.Lambda(lambda t: t[0] * 0.1)(ports)   # port 0: false
        t_br = nn.Lambda(lambda t: t[1])(ports)         # port 1: true
        merged = nn.Merge()((f_br, t_br, pred))
        g = nn.DynamicGraph([data, pred], [merged])
        p, s = g.init(rng(0))
        x = jnp.asarray([-2.0, 3.0])
        out_t, _ = g.apply(p, s, (x, jnp.asarray(True)))
        out_f, _ = g.apply(p, s, (x, jnp.asarray(False)))
        np.testing.assert_allclose(np.asarray(out_t), [-2.0, 3.0])
        np.testing.assert_allclose(np.asarray(out_f), [-0.2, 0.3],
                                   rtol=1e-6)
