"""Sharded serving + continuous-batching decode (ISSUE 20).

The load-bearing gates:

- **Decode correctness**: the KV-cache prefill is BITWISE-equal to the
  full-context ``model.apply`` (same shapes ⇒ same XLA reduction
  order), and every incremental decode step is tight-allclose
  (rtol=1e-5, atol=1e-6) to a full-context forward over the grown
  sequence — the PR-16 cross-shape numerics precedent: the step's
  attention GEMMs run at Tq=1 vs the reference's Tq=T, so reduction
  order differs while greedy argmax tokens stay EXACTLY equal.
  Covered at every step, including mid-batch admission and
  slot-reuse-after-EOS.
- **Continuous batching, proven by accounting**: a sequence submitted
  while another is mid-decode joins the RUNNING batch —
  ``A.admit_step <= B.admit_step < A.finish_step`` on the
  ``DecodeResult`` step counters (dispatch accounting, never timing).
- **Sharded replicas**: a ``ShardedReplicaSet`` slot owns an N-device
  mesh slice with ``param_specs``-declared NamedShardings; it serves
  through the unchanged ``FrontendServer`` submit() contract.
- **Wire generate route (both cores)**: chunked-ndjson token streams
  arrive in order and equal the per-request full-context reference;
  zero dropped requests through one ``HotCutover`` over a
  ``deploy(service=)`` decode backend.
- **Chunked request bodies (both cores)**: ``Transfer-Encoding:
  chunked`` POSTs are de-chunked incrementally by the shared
  ``ChunkedDecoder``; malformed framing answers 400, the body cap
  413, TE+CL smuggling 400, unknown codings 501.

Tiny models throughout; the serving-scale numbers live in
``bench.py --serving``, not tier-1.
"""

import http.client
import json
import socket
import threading
import time
from io import BytesIO

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.frontend import FrontendServer, HotCutover
from bigdl_tpu.frontend.http1 import (ChunkedDecoder, ProtocolError,
                                      RequestParser, read_chunked_body)
from bigdl_tpu.models.transformer import (init_kv_cache, kv_cache_spec,
                                          transformer_lm,
                                          transformer_lm_decode_step,
                                          transformer_lm_prefill)
from bigdl_tpu.serving import (DeadlineExceeded, DecodeService,
                               InferenceService, ModelRegistry,
                               RequestSpecError, ServiceClosed,
                               ServiceOverloaded, ShardedReplicaSet)

VOCAB = 64


@pytest.fixture(scope="module")
def lm():
    return transformer_lm(vocab_size=VOCAB, embed_dim=32, num_heads=4,
                          num_layers=2, max_len=64).initialize(0)


def greedy_ref(model, prompt, max_new, eos_id=None, max_seq_len=64):
    """Per-request full-context greedy reference: re-run the WHOLE
    grown sequence through ``model.apply`` for every next token —
    exactly what the KV-cache path must reproduce."""
    toks = [int(t) for t in prompt]
    max_new = min(int(max_new), max_seq_len - len(toks))
    out = []
    for _ in range(max_new):
        lp, _ = model.apply(model._params, model._state,
                            np.asarray([toks], np.int32),
                            training=False)
        nxt = int(np.asarray(lp)[0, -1].argmax())
        out.append(nxt)
        if eos_id is not None and nxt == eos_id:
            break
        toks.append(nxt)
        if len(toks) >= max_seq_len:
            break
    return out


def wait_until(pred, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() >= deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.005)


# ===========================================================================
# decode-path numerics — pure functions, no threads (satellite 3)
# ===========================================================================
class TestDecodeNumerics:
    def test_prefill_bitwise_equals_full_context(self, lm):
        """Prefill runs the same (S, T) shapes as the full-context
        apply, so XLA's reduction order matches and equality is
        BITWISE — the strongest half of the correctness gate."""
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, VOCAB, (1, 12)).astype(np.int32)
        ref, _ = lm.apply(lm._params, lm._state, prompt, training=False)
        lp, k, v = transformer_lm_prefill(lm, lm._params,
                                          jnp.asarray(prompt))
        np.testing.assert_array_equal(np.asarray(lp), np.asarray(ref))
        shape, _ = kv_cache_spec(lm, 1, 12)
        assert k.shape == shape and v.shape == shape

    def test_incremental_steps_allclose_full_context_every_step(
            self, lm):
        """Every decode step's logits vs a full-context forward over
        the grown sequence: tight-allclose (rtol=1e-5, atol=1e-6 —
        measured ≲5e-7; NOT bitwise because the step attends Tq=1
        against the cache while the reference runs Tq=T, so the
        attention GEMM reduction order differs), and greedy argmax
        tokens EXACTLY equal."""
        rng = np.random.default_rng(1)
        prompt = rng.integers(0, VOCAB, (1, 9)).astype(np.int32)
        lp, kp, vp = transformer_lm_prefill(lm, lm._params,
                                            jnp.asarray(prompt))
        k, v = init_kv_cache(lm, 1, 64)
        k = jax.lax.dynamic_update_slice(k, kp, (0, 0, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(v, vp, (0, 0, 0, 0, 0))
        toks = list(prompt[0])
        last = int(np.asarray(lp)[0, -1].argmax())
        lengths = np.array([9], np.int32)
        for _ in range(8):
            toks.append(last)
            lp1, k, v = transformer_lm_decode_step(
                lm, lm._params, jnp.asarray([last], jnp.int32),
                jnp.asarray(lengths), k, v)
            lengths[0] += 1
            ref, _ = lm.apply(lm._params, lm._state,
                              np.asarray([toks], np.int32),
                              training=False)
            got = np.asarray(lp1)[0]
            want = np.asarray(ref)[0, -1]
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
            assert int(got.argmax()) == int(want.argmax())
            last = int(got.argmax())

    def test_mid_batch_admission_numerics(self, lm):
        """Admitting B into slot 1 while A is mid-decode in slot 0 must
        not perturb either sequence: after the splice, EVERY further
        step matches both sequences' own full-context references."""
        rng = np.random.default_rng(2)
        pa = rng.integers(0, VOCAB, (7,)).astype(np.int32)
        pb = rng.integers(0, VOCAB, (4,)).astype(np.int32)
        k, v = init_kv_cache(lm, 2, 64)
        # prefill A into slot 0, step it alone three times
        lp, kp, vp = transformer_lm_prefill(lm, lm._params,
                                            jnp.asarray(pa[None, :]))
        k = jax.lax.dynamic_update_slice(k, kp, (0, 0, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(v, vp, (0, 0, 0, 0, 0))
        toks_a = list(pa)
        last = np.zeros((2,), np.int32)
        lengths = np.array([7, 0], np.int32)
        last[0] = int(np.asarray(lp)[0, -1].argmax())
        for _ in range(3):
            toks_a.append(int(last[0]))
            lp1, k, v = transformer_lm_decode_step(
                lm, lm._params, jnp.asarray(last),
                jnp.asarray(lengths), k, v)
            lengths[0] += 1
            last[0] = int(np.asarray(lp1)[0].argmax())
        # mid-batch: splice B's prefill into slot 1
        lpb, kb, vb = transformer_lm_prefill(lm, lm._params,
                                             jnp.asarray(pb[None, :]))
        k = jax.lax.dynamic_update_slice(k, kb, (0, 1, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(v, vb, (0, 1, 0, 0, 0))
        toks_b = list(pb)
        lengths[1] = 4
        last[1] = int(np.asarray(lpb)[0, -1].argmax())
        for _ in range(4):
            toks_a.append(int(last[0]))
            toks_b.append(int(last[1]))
            lp1, k, v = transformer_lm_decode_step(
                lm, lm._params, jnp.asarray(last),
                jnp.asarray(lengths), k, v)
            lengths += 1
            lph = np.asarray(lp1)
            for slot, toks in ((0, toks_a), (1, toks_b)):
                ref, _ = lm.apply(lm._params, lm._state,
                                  np.asarray([toks], np.int32),
                                  training=False)
                want = np.asarray(ref)[0, -1]
                np.testing.assert_allclose(lph[slot], want,
                                           rtol=1e-5, atol=1e-6)
                assert int(lph[slot].argmax()) == int(want.argmax())
                last[slot] = int(lph[slot].argmax())

    def test_slot_reuse_overwrites_stale_cache(self, lm):
        """Re-prefilling a slot after a finished sequence must fully
        mask the previous occupant: the new sequence decodes exactly
        as if the cache had been zeroed (stale positions past the new
        length are never attended)."""
        rng = np.random.default_rng(3)
        pa = rng.integers(0, VOCAB, (11,)).astype(np.int32)
        pb = rng.integers(0, VOCAB, (5,)).astype(np.int32)
        k, v = init_kv_cache(lm, 1, 64)
        _, kp, vp = transformer_lm_prefill(lm, lm._params,
                                           jnp.asarray(pa[None, :]))
        k = jax.lax.dynamic_update_slice(k, kp, (0, 0, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(v, vp, (0, 0, 0, 0, 0))
        # slot reclaimed; B (shorter!) takes it — A's tail positions
        # 5..10 still hold A's K/V
        lpb, kb, vb = transformer_lm_prefill(lm, lm._params,
                                             jnp.asarray(pb[None, :]))
        k = jax.lax.dynamic_update_slice(k, kb, (0, 0, 0, 0, 0))
        v = jax.lax.dynamic_update_slice(v, vb, (0, 0, 0, 0, 0))
        toks = list(pb)
        last = int(np.asarray(lpb)[0, -1].argmax())
        lengths = np.array([5], np.int32)
        for _ in range(6):
            toks.append(last)
            lp1, k, v = transformer_lm_decode_step(
                lm, lm._params, jnp.asarray([last], jnp.int32),
                jnp.asarray(lengths), k, v)
            lengths[0] += 1
            ref, _ = lm.apply(lm._params, lm._state,
                              np.asarray([toks], np.int32),
                              training=False)
            got = np.asarray(lp1)[0]
            want = np.asarray(ref)[0, -1]
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
            assert int(got.argmax()) == int(want.argmax())
            last = int(got.argmax())


# ===========================================================================
# DecodeService — the continuous-batching scheduler
# ===========================================================================
class TestDecodeService:
    def test_single_request_equals_reference(self, lm):
        with DecodeService(lm, slots=2, max_seq_len=48,
                           max_prompt_len=8, prefill_buckets="top",
                           name="d1") as dec:
            prompt = [5, 9, 3]
            res = dec.generate(prompt, max_new_tokens=6)
        ref = greedy_ref(lm, prompt, 6, max_seq_len=48)
        assert list(res.tokens) == ref
        assert res.finish_reason == "length"
        assert res.prompt_len == 3 and res.prefill_bucket >= 3
        assert res.admit_step <= res.finish_step

    def test_concurrent_mixed_lengths_equal_reference(self, lm):
        """The acceptance shape: staged concurrent requests of
        DIFFERENT lengths all resolve token-for-token equal to their
        own full-context references — zero drops."""
        rng = np.random.default_rng(7)
        prompts = [rng.integers(0, VOCAB, (n,)).tolist()
                   for n in (2, 5, 9, 14, 3, 7)]
        with DecodeService(lm, slots=3, max_seq_len=48,
                           max_prompt_len=16, prefill_buckets="top",
                           name="dmix") as dec:
            futs = [dec.submit(p, max_new_tokens=4 + i % 3)
                    for i, p in enumerate(prompts)]
            results = [f.result(timeout=120) for f in futs]
        for i, (p, res) in enumerate(zip(prompts, results)):
            ref = greedy_ref(lm, p, 4 + i % 3, max_seq_len=48)
            assert list(res.tokens) == ref, f"request {i}"
        occupied = {r.slot for r in results}
        assert occupied <= set(range(3))

    def test_mid_batch_admission_by_step_accounting(self, lm):
        """THE continuous-batching gate, by dispatch accounting rather
        than timing: B is submitted from inside A's on_token callback
        (so A is demonstrably mid-decode), and B's result must show it
        joined A's RUNNING batch — ``A.admit_step <= B.admit_step <
        A.finish_step`` — while both stay token-correct."""
        fut_b = []
        dec = DecodeService(lm, slots=2, max_seq_len=48,
                            max_prompt_len=8, prefill_buckets="top",
                            name="dmid")

        def on_token(index, token):
            if index == 2 and not fut_b:
                fut_b.append(dec.submit([11, 2], max_new_tokens=3))

        try:
            fut_a = dec.submit([5, 9, 3, 1], max_new_tokens=12,
                               on_token=on_token)
            res_a = fut_a.result(timeout=120)
            assert fut_b, "on_token never fired at index 2"
            res_b = fut_b[0].result(timeout=120)
        finally:
            dec.stop()
        assert list(res_a.tokens) == greedy_ref(lm, [5, 9, 3, 1], 12,
                                                max_seq_len=48)
        assert list(res_b.tokens) == greedy_ref(lm, [11, 2], 3,
                                                max_seq_len=48)
        assert res_a.admit_step <= res_b.admit_step < res_a.finish_step
        assert res_a.slot != res_b.slot  # genuinely concurrent slots

    def test_on_token_streams_every_token_in_order(self, lm):
        seen = []
        with DecodeService(lm, slots=1, max_seq_len=48,
                           max_prompt_len=8, prefill_buckets="top",
                           name="dstr") as dec:
            res = dec.generate([5, 9, 3], max_new_tokens=5,
                               on_token=lambda i, t: seen.append((i, t)))
        assert [i for i, _ in seen] == list(range(len(res.tokens)))
        assert [t for _, t in seen] == list(res.tokens)

    def test_slot_reuse_after_eos(self, lm):
        """EOS mid-generation reclaims the slot THAT step and the next
        queued sequence takes it; the reused slot decodes its new
        occupant exactly (stale cache fully masked)."""
        ref = greedy_ref(lm, [5, 9, 3], 10, max_seq_len=48)
        # an eos that fires MID-generation: the first token whose first
        # occurrence in the reference stream is at index >= 1
        eos = next(t for i, t in enumerate(ref)
                   if ref.index(t) == i and i >= 1)
        k = ref.index(eos)
        ref_eos = greedy_ref(lm, [5, 9, 3], 10, eos_id=eos,
                             max_seq_len=48)
        assert ref_eos == ref[:k + 1] and len(ref_eos) >= 2
        with DecodeService(lm, slots=1, max_seq_len=48, eos_id=eos,
                           max_prompt_len=8, prefill_buckets="top",
                           name="deos") as dec:
            fut_a = dec.submit([5, 9, 3], max_new_tokens=10)
            fut_b = dec.submit([7, 1, 4, 2], max_new_tokens=4)
            res_a = fut_a.result(timeout=120)
            res_b = fut_b.result(timeout=120)
        assert res_a.finish_reason == "eos"
        assert list(res_a.tokens) == ref_eos
        assert res_b.slot == res_a.slot  # slots=1 ⇒ the SAME slot
        assert res_b.admit_step >= res_a.finish_step
        assert list(res_b.tokens) == greedy_ref(
            lm, [7, 1, 4, 2], 4, eos_id=eos, max_seq_len=48)
        st = dec.stats()["decode"]
        assert st["slots_reclaimed"] >= 2
        assert st["admissions"] == 2

    def test_request_spec_taxonomy(self, lm):
        with DecodeService(lm, slots=1, max_seq_len=32,
                           max_prompt_len=8, prefill_buckets="top",
                           name="dspec") as dec:
            with pytest.raises(RequestSpecError):
                dec.submit([[1, 2], [3, 4]])  # 2-D
            with pytest.raises(RequestSpecError):
                dec.submit([])  # empty
            with pytest.raises(RequestSpecError):
                dec.submit([1.5, 2.5])  # float tokens
            with pytest.raises(RequestSpecError):
                dec.submit(list(range(40)))  # > max_prompt_len
            with pytest.raises(RequestSpecError):
                dec.submit([1, 2], max_new_tokens=0)

    def test_expired_deadline_settles_deadline_exceeded(self, lm):
        with DecodeService(lm, slots=1, max_seq_len=16,
                           max_prompt_len=4, prefill_buckets="top",
                           name="ddl") as dec:
            fut = dec.submit([1, 2, 3],
                             deadline=time.monotonic() - 0.001)
            with pytest.raises(DeadlineExceeded):
                fut.result(timeout=60)

    def test_overload_sheds_with_service_overloaded(self, lm):
        dec = DecodeService(lm, slots=1, max_seq_len=16,
                            max_prompt_len=4, prefill_buckets="top",
                            queue_capacity=2, name="dover",
                            start=False)  # never drains: queue fills
        try:
            dec.submit([1, 2])
            dec.submit([3, 4])
            with pytest.raises(ServiceOverloaded):
                dec.submit([5, 6])
        finally:
            dec.stop(drain=False)

    def test_stop_then_submit_service_closed(self, lm):
        dec = DecodeService(lm, slots=1, max_seq_len=16,
                            max_prompt_len=4, prefill_buckets="top",
                            name="dcl")
        dec.stop()
        with pytest.raises(ServiceClosed):
            dec.submit([1, 2])

    def test_nondrain_stop_cancels_backlog_and_active(self, lm):
        """Deterministically parked: A's on_token blocks the scheduler
        thread mid-admission, so B is still queued and A still active
        when the non-drain stop lands — A fails, B is cancelled, both
        with ServiceClosed."""
        dec = DecodeService(lm, slots=1, max_seq_len=16,
                            max_prompt_len=4, prefill_buckets="top",
                            name="dnd")
        entered, release = threading.Event(), threading.Event()

        def park(index, token):
            entered.set()
            release.wait(30)

        try:
            fut_a = dec.submit([1, 2], max_new_tokens=8, on_token=park)
            assert entered.wait(30)
            fut_b = dec.submit([3, 4])
            dec.stop(drain=False, timeout=0.01)  # returns immediately
            release.set()
            with pytest.raises(ServiceClosed):
                fut_a.result(timeout=60)
            with pytest.raises(ServiceClosed):
                fut_b.result(timeout=60)
        finally:
            release.set()
            dec.stop(drain=False)

    def test_zero_steady_state_retrace(self, lm):
        """The GL106 discipline at serving runtime: after construction
        warms every bucket + the step executable, NO request shape may
        trace again."""
        with DecodeService(lm, slots=2, max_seq_len=48,
                           max_prompt_len=16, prefill_buckets="pow2@4",
                           name="dtrace") as dec:
            warm = dec._trace_count
            assert warm > 0
            for n in (1, 3, 4, 7, 12):
                dec.generate(list(range(1, n + 1)), max_new_tokens=3)
            assert dec._trace_count == warm

    def test_kv_budget_is_a_hard_cap(self, lm):
        shape, dtype = kv_cache_spec(lm, 1, 32)
        per_slot_mb = (2 * int(np.prod(shape))
                       * jnp.dtype(dtype).itemsize) / (1 << 20)
        dec = DecodeService(lm, slots=8, max_seq_len=32,
                            max_prompt_len=4, prefill_buckets="top",
                            kv_budget_mb=per_slot_mb * 2.5,
                            name="dkv", start=False)
        assert dec.slots == 2  # 8 requested, budget affords 2
        assert dec.kv_bytes <= per_slot_mb * 2.5 * (1 << 20)
        dec.stop(drain=False)
        with pytest.raises(ValueError):
            DecodeService(lm, slots=1, max_seq_len=32,
                          max_prompt_len=4, prefill_buckets="top",
                          kv_budget_mb=per_slot_mb * 0.4, start=False)

    def test_stats_schema(self, lm):
        with DecodeService(lm, slots=2, max_seq_len=32,
                           max_prompt_len=4, prefill_buckets="top",
                           name="dst") as dec:
            dec.generate([1, 2, 3], max_new_tokens=4)
            st = dec.stats()
        d = st["decode"]
        assert d["slots"] == 2 and d["active"] == 0
        assert d["steps"] >= 3 and d["tokens_generated"] >= 4
        assert d["admissions"] == 1 and d["slots_reclaimed"] == 1
        assert 0.0 < d["step_occupancy"] <= 1.0
        assert d["kv_bytes"] > 0 and d["prefill_buckets"]
        assert st["requests_completed"] == 1

    def test_scheduler_crash_settles_inflight_futures(self, lm):
        # a crashed scheduler must fail every live future with the
        # crash (not park callers forever) and refuse new submits
        dec = DecodeService(lm, slots=2, max_seq_len=16,
                            max_prompt_len=4, prefill_buckets="top",
                            name="crash")
        try:
            dec._step_exec = _raise_injected
            fut = dec.submit([5, 9, 3], max_new_tokens=4)
            with pytest.raises(RuntimeError, match="injected step"):
                fut.result(timeout=30)
            wait_until(lambda: not dec.alive)
            with pytest.raises(ServiceClosed):
                dec.submit([1, 2])
        finally:
            dec.stop(drain=False, timeout=5)


def _raise_injected(*a, **kw):
    raise RuntimeError("injected step failure")


# ===========================================================================
# ShardedReplicaSet — mesh-slice replicas (tentpole part a)
# ===========================================================================
def make_mlp(din=16, dout=4, shard=False):
    return nn.Sequential(
        nn.Linear(din, 32, shard="column" if shard else None),
        nn.ReLU(),
        nn.Linear(32, dout, shard="row" if shard else None),
        nn.SoftMax()).initialize(0)


SPEC16 = ((16,), np.float32)


class TestShardedReplicaSet:
    def test_validation(self, devices):
        model = make_mlp()
        with pytest.raises(ValueError):
            ShardedReplicaSet(model, devices_per_replica=0)
        with pytest.raises(ValueError):
            ShardedReplicaSet(model, devices_per_replica=16)  # > 8 devs
        with pytest.raises(ValueError):
            ShardedReplicaSet(model, devices_per_replica=4,
                              mesh_axes={"bogus": 4})
        with pytest.raises(ValueError):
            ShardedReplicaSet(model, devices_per_replica=4,
                              mesh_axes={"model": 2})  # 2 != 4

    def test_params_land_with_declared_shardings(self, devices):
        """The tentpole's placement contract: a replica's params carry
        the module-declared NamedShardings over ITS mesh slice —
        column weight split P('model', None), row weight
        P(None, 'model'), non-opt-ins replicated."""
        from jax.sharding import PartitionSpec as P
        model = make_mlp(shard=True)
        rs = ShardedReplicaSet(model, devices_per_replica=4,
                               input_spec=SPEC16, start=False)
        try:
            assert rs.n_replicas == 2  # 8 devices / 4 per slice
            for ix in range(2):
                svc = rs._replicas[ix]
                mesh = rs.replica_mesh(ix)
                assert mesh.shape["model"] == 4
                w0 = svc.params["0"]["weight"]  # column Linear
                assert w0.sharding.spec == P("model", None)
                w2 = svc.params["2"]["weight"]  # row Linear
                assert w2.sharding.spec == P(None, "model")
                assert set(w0.sharding.mesh.devices.flat) == \
                    set(mesh.devices.flat)
            # the two slices own DISJOINT device groups
            d0 = set(rs.replica_mesh(0).devices.flat)
            d1 = set(rs.replica_mesh(1).devices.flat)
            assert d0.isdisjoint(d1)
        finally:
            rs.stop()

    def test_sharded_predict_equals_single_device(self, devices):
        model = make_mlp(shard=True)
        ref_model = make_mlp(shard=False)  # same init seed ⇒ same params
        rs = ShardedReplicaSet(model, devices_per_replica=4,
                               input_spec=SPEC16)
        try:
            x = np.random.default_rng(0).normal(
                0, 1, (6, 16)).astype(np.float32)
            got = np.asarray(rs.predict(x))
            ref, _ = ref_model.apply(ref_model._params,
                                     ref_model._state, x,
                                     training=False)
            np.testing.assert_allclose(got, np.asarray(ref),
                                       rtol=1e-5, atol=1e-6)
        finally:
            rs.stop()

    def test_serves_through_unchanged_frontend(self, devices):
        """Zero frontend changes: add_backend sees the submit()-shaped
        ReplicaSet contract and the wire path just works at mesh-slice
        granularity."""
        model = make_mlp(shard=True)
        rs = ShardedReplicaSet(model, devices_per_replica=2,
                               n_replicas=2, input_spec=SPEC16)
        reg = ModelRegistry()
        fe = FrontendServer(reg, port=0)
        fe.add_backend("shmlp", rs)
        fe.start()
        try:
            x = np.random.default_rng(1).normal(
                0, 1, (3, 16)).astype(np.float32)
            conn = http.client.HTTPConnection("127.0.0.1", fe.port,
                                              timeout=60)
            conn.request("POST", "/v1/models/shmlp/predict",
                         body=json.dumps({"inputs": x.tolist()}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = resp.read()
            conn.close()
            assert resp.status == 200, body
            got = np.asarray(json.loads(body)["outputs"], np.float32)
            ref = np.asarray(rs.predict(x))
            np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-7)
        finally:
            fe.stop()
            rs.stop()

    def test_elastic_resize_keeps_mesh_granularity(self, devices):
        model = make_mlp(shard=True)
        rs = ShardedReplicaSet(model, devices_per_replica=2,
                               n_replicas=1, input_spec=SPEC16)
        try:
            rs.set_replica_count(3)  # > 8//2 groups? no: 3 <= 4 groups
            assert rs.n_replicas == 3
            for ix in range(3):
                assert rs.replica_mesh(ix).shape["model"] == 2
            x = np.random.default_rng(2).normal(
                0, 1, (4, 16)).astype(np.float32)
            got = np.asarray(rs.predict(x))
            assert got.shape == (4, 4)
            st = rs.stats()
            assert len(st["replicas"]) == 3
        finally:
            rs.stop()

    def test_sharded_decode_service_equals_reference(self, lm, devices):
        """DecodeService(mesh=) — sharded big-model decode: params laid
        out by param_specs over a 4-device mesh, tokens still EXACTLY
        the unsharded greedy reference."""
        from bigdl_tpu.parallel.mesh import create_mesh
        sh = transformer_lm(vocab_size=VOCAB, embed_dim=32, num_heads=4,
                            num_layers=2, max_len=64,
                            shard=True).initialize(0)
        mesh = create_mesh(model=4, devices=jax.local_devices()[:4])
        with DecodeService(sh, slots=2, max_seq_len=16, mesh=mesh,
                           max_prompt_len=4, prefill_buckets="top",
                           name="dsh") as dec:
            res = dec.generate([5, 9, 3], max_new_tokens=4)
        # same init seed ⇒ same params ⇒ same greedy tokens as the
        # unsharded fixture model
        assert list(res.tokens) == greedy_ref(lm, [5, 9, 3], 4,
                                              max_seq_len=16)


# ===========================================================================
# wire generate route — both connection cores
# ===========================================================================
def post(port, path, body, headers=None, timeout=120):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def parse_stream(body: bytes):
    """ndjson token stream → (ordered token list, done trailer)."""
    lines = [json.loads(ln) for ln in body.splitlines()]
    assert lines, "empty stream"
    done = lines[-1]
    toks = lines[:-1]
    assert [t["index"] for t in toks] == list(range(len(toks)))
    return [t["token"] for t in toks], done


@pytest.fixture(scope="module")
def genstack(lm):
    reg = ModelRegistry()
    dec = DecodeService(lm, slots=3, max_seq_len=48, queue_capacity=64,
                        max_prompt_len=16, prefill_buckets="top",
                        name="lm")
    reg.deploy("lm", service=dec)
    clf = make_mlp()
    reg.deploy("clf", clf, input_spec=SPEC16, max_batch_size=8,
               batch_timeout_ms=2.0)
    yield reg, lm
    reg.stop_all()


@pytest.fixture(scope="module", params=["eventloop", "threaded"])
def genwire(request, genstack):
    reg, lm = genstack
    fe = FrontendServer(reg, port=0, core=request.param)
    fe.start()
    yield fe, reg, lm
    fe.stop()


class TestGenerateWire:
    def test_stream_ordered_and_equal_reference(self, genwire):
        fe, _reg, lm = genwire
        status, hdrs, body = post(
            fe.port, "/v1/models/lm/generate",
            json.dumps({"prompt": [5, 9, 3],
                        "max_new_tokens": 6}).encode())
        assert status == 200, body
        assert hdrs["Content-Type"] == "application/x-ndjson"
        assert hdrs.get("X-Trace-Id")
        streamed, done = parse_stream(body)
        ref = greedy_ref(lm, [5, 9, 3], 6, max_seq_len=48)
        assert done["done"] is True and done["finish_reason"] == "length"
        assert done["tokens"] == streamed == ref
        assert done["n"] == len(ref)

    def test_concurrent_mixed_lengths_zero_drops(self, genwire):
        """The wire acceptance gate: staged concurrent decode requests
        of different lengths all stream in order and equal their own
        references — zero dropped requests."""
        fe, _reg, lm = genwire
        rng = np.random.default_rng(9)
        prompts = [rng.integers(0, VOCAB, (n,)).tolist()
                   for n in (2, 6, 11, 4, 8, 3)]
        results = [None] * len(prompts)

        def client(i):
            results[i] = post(
                fe.port, "/v1/models/lm/generate",
                json.dumps({"prompt": prompts[i],
                            "max_new_tokens": 3 + i % 4}).encode())

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for i, p in enumerate(prompts):
            status, _h, body = results[i]
            assert status == 200, (i, body)
            streamed, done = parse_stream(body)
            ref = greedy_ref(lm, p, 3 + i % 4, max_seq_len=48)
            assert streamed == ref == done["tokens"], f"request {i}"

    def test_generate_on_predict_backend_400(self, genwire):
        fe, _reg, _lm = genwire
        status, _h, body = post(
            fe.port, "/v1/models/clf/generate",
            json.dumps({"prompt": [1, 2]}).encode())
        assert status == 400
        assert b"not a decode backend" in body

    def test_predict_on_decode_backend_400(self, genwire):
        fe, _reg, _lm = genwire
        status, _h, _body = post(
            fe.port, "/v1/models/lm/predict",
            json.dumps({"inputs": [[1.0, 2.0]]}).encode())
        assert status == 400

    def test_generate_body_taxonomy_400(self, genwire):
        fe, _reg, _lm = genwire
        for payload in (b"not json", b'{"inputs": [1]}',
                        b'{"prompt": []}', b'{"prompt": [[1, 2]]}',
                        b'{"prompt": [1], "max_new_tokens": 0}'):
            status, _h, _b = post(fe.port, "/v1/models/lm/generate",
                                  payload)
            assert status == 400, payload

    def test_unknown_model_404(self, genwire):
        fe, _reg, _lm = genwire
        status, _h, _b = post(fe.port, "/v1/models/nope/generate",
                              json.dumps({"prompt": [1]}).encode())
        assert status == 404

    def test_wire_deadline_while_queued_504(self, genwire):
        """A prompt still queued past its wire deadline answers 504 —
        the pre-stream path, so the REAL status goes out (no 200
        header committed).  Staged with a never-started service so
        expiry is deterministic."""
        fe, reg, lm = genwire
        parked = DecodeService(lm, slots=1, max_seq_len=16,
                               max_prompt_len=4, prefill_buckets="top",
                               name="parked", start=False)
        reg.deploy("parked", service=parked)
        try:
            status, _h, body = post(
                fe.port, "/v1/models/parked/generate",
                json.dumps({"prompt": [1, 2]}).encode(),
                headers={"X-Deadline-Ms": "120"})
            assert status == 504, body
        finally:
            reg.undeploy("parked", drain=False)

    def test_hot_cutover_zero_drops_under_generate_load(self, genwire):
        """One HotCutover over a deploy(service=) decode backend while
        12 concurrent generate clients stream: every request answers
        200 with reference-equal tokens (zero drops), the wire drains,
        and the outgoing service is stopped."""
        fe, _reg, lm = genwire
        reg2 = ModelRegistry()
        reg2.deploy("cut", service=DecodeService(
            lm, slots=3, max_seq_len=32, queue_capacity=64,
            max_prompt_len=8, prefill_buckets="top", name="cut-v1"))
        fe2 = FrontendServer(reg2, port=0, core=fe.core)
        fe2.start()
        cut = HotCutover(reg2, fe2)
        n = 12
        results = [None] * n
        rng = np.random.default_rng(13)
        prompts = [rng.integers(0, VOCAB, (2 + i % 5,)).tolist()
                   for i in range(n)]
        barrier = threading.Barrier(n + 1)

        def client(i):
            barrier.wait()
            time.sleep(0.01 * i)  # staged: spans the cutover window
            results[i] = post(
                fe2.port, "/v1/models/cut/generate",
                json.dumps({"prompt": prompts[i],
                            "max_new_tokens": 4}).encode())

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n)]
        for t in threads:
            t.start()
        barrier.wait()
        old = reg2.get("cut", reg2.latest_version("cut"))
        report = cut.deploy("cut", service=DecodeService(
            lm, slots=3, max_seq_len=32, queue_capacity=64,
            max_prompt_len=8, prefill_buckets="top", name="cut-v2"))
        for t in threads:
            t.join()
        try:
            assert report["old_undeployed"] is True
            assert report["wire_drained"] is True
            assert not old.alive  # outgoing service actually stopped
            for i in range(n):
                status, _h, body = results[i]
                assert status == 200, (i, body)
                streamed, done = parse_stream(body)
                ref = greedy_ref(lm, prompts[i], 4, max_seq_len=32)
                assert streamed == ref == done["tokens"], f"client {i}"
        finally:
            fe2.stop()
            reg2.stop_all()


# ===========================================================================
# chunked request bodies — shared decoder + both cores (satellite 1)
# ===========================================================================
def chunk_body(payload: bytes, sizes):
    """Encode ``payload`` as chunked transfer coding, cut at ``sizes``
    (any remainder becomes a final chunk)."""
    pieces, off = [], 0
    for n in sizes:
        pieces.append(payload[off:off + n])
        off += n
    pieces.append(payload[off:])
    out = b"".join(f"{len(p):x}\r\n".encode() + p + b"\r\n"
                   for p in pieces if p)
    return out + b"0\r\n\r\n"


def chunked_req(path, payload: bytes, sizes, extra=None):
    head = (f"POST {path} HTTP/1.1\r\n"
            "Host: t\r\n"
            "Content-Type: application/json\r\n"
            "Transfer-Encoding: chunked\r\n"
            + "".join(f"{k}: {v}\r\n" for k, v in (extra or {}).items())
            + "\r\n")
    return head.encode("latin-1") + chunk_body(payload, sizes)


class TestChunkedDecoder:
    def test_byte_at_a_time_roundtrip(self):
        payload = b'{"hello": "world", "n": 12345}'
        wire = chunk_body(payload, [3, 7, 1, 11])
        dec = ChunkedDecoder(1 << 20)
        for i in range(len(wire)):
            dec.feed(wire[i:i + 1])
            body = dec.poll()
            if body is not None:
                assert i == len(wire) - 1  # only the LAST byte completes
                assert body == payload
                break
        else:
            pytest.fail("decoder never completed")
        assert dec.residual() == b""

    def test_chunk_extensions_discarded(self):
        dec = ChunkedDecoder(1 << 20)
        dec.feed(b"5;ext=foo\r\nhello\r\n0\r\n\r\n")
        assert dec.poll() == b"hello"

    def test_trailer_fields_discarded(self):
        dec = ChunkedDecoder(1 << 20)
        dec.feed(b"2\r\nhi\r\n0\r\nX-Check: abc\r\nX-More: d\r\n\r\n")
        assert dec.poll() == b"hi"

    def test_residual_preserves_pipelined_bytes(self):
        dec = ChunkedDecoder(1 << 20)
        dec.feed(b"2\r\nok\r\n0\r\n\r\nGET / HTTP/1.1\r\n")
        assert dec.poll() == b"ok"
        assert dec.residual() == b"GET / HTTP/1.1\r\n"

    def test_malformed_size_line_400(self):
        dec = ChunkedDecoder(1 << 20)
        dec.feed(b"ZZZ\r\n")
        with pytest.raises(ProtocolError) as ei:
            dec.poll()
        assert ei.value.status == 400

    def test_missing_chunk_terminator_400(self):
        dec = ChunkedDecoder(1 << 20)
        dec.feed(b"2\r\nhiXX0\r\n\r\n")  # XX where CRLF belongs
        with pytest.raises(ProtocolError) as ei:
            dec.poll()
        assert ei.value.status == 400

    def test_body_cap_413(self):
        dec = ChunkedDecoder(16)
        dec.feed(b"20\r\n" + b"a" * 32 + b"\r\n0\r\n\r\n")
        with pytest.raises(ProtocolError) as ei:
            dec.poll()
        assert ei.value.status == 413

    def test_read_chunked_body_blocking_driver(self):
        payload = b"x" * 100
        rfile = BytesIO(chunk_body(payload, [40, 40]))
        assert read_chunked_body(rfile) == payload

    def test_read_chunked_body_truncated_400(self):
        rfile = BytesIO(b"10\r\nonly-seven")  # stream ends mid-chunk
        with pytest.raises(ProtocolError) as ei:
            read_chunked_body(rfile)
        assert ei.value.status == 400

    def test_read_chunked_body_cap_413(self):
        rfile = BytesIO(chunk_body(b"y" * 64, [64]))
        with pytest.raises(ProtocolError) as ei:
            read_chunked_body(rfile, max_body=16)
        assert ei.value.status == 413


class TestChunkedRequestParser:
    def test_chunked_request_end_to_end(self):
        payload = json.dumps({"inputs": [[1.0, 2.0]]}).encode()
        raw = chunked_req("/v1/models/clf/predict", payload, [5, 9])
        p = RequestParser()
        for i in range(len(raw)):
            p.feed(raw[i:i + 1])
            req = p.poll()
            if req is not None:
                assert i == len(raw) - 1
                assert req.body == payload
                return
        pytest.fail("parser never produced the request")

    def test_chunked_then_pipelined_keepalive_not_misframed(self):
        payload = b'{"a": 1}'
        raw = chunked_req("/a", payload, [4]) + \
            b"GET /b HTTP/1.1\r\nHost: t\r\n\r\n"
        p = RequestParser()
        p.feed(raw)
        ra = p.poll()
        assert ra is not None and ra.body == payload
        rb = p.poll()
        assert rb is not None and rb.target == "/b"

    def test_te_plus_content_length_400(self):
        p = RequestParser()
        p.feed(b"POST / HTTP/1.1\r\nContent-Length: 3\r\n"
               b"Transfer-Encoding: chunked\r\n\r\n")
        with pytest.raises(ProtocolError) as ei:
            p.poll()
        assert ei.value.status == 400  # request-smuggling refusal

    def test_unknown_transfer_coding_501(self):
        p = RequestParser()
        p.feed(b"POST / HTTP/1.1\r\nTransfer-Encoding: gzip\r\n\r\n")
        with pytest.raises(ProtocolError) as ei:
            p.poll()
        assert ei.value.status == 501

    def test_parser_max_body_cap_413(self):
        p = RequestParser(max_body=16)
        p.feed(b"POST / HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
               + chunk_body(b"z" * 64, [64]))
        with pytest.raises(ProtocolError) as ei:
            p.poll()
        assert ei.value.status == 413


def post_chunked(port, path, payload: bytes, piece=7, timeout=120):
    """POST ``payload`` with ``Transfer-Encoding: chunked`` (http.client
    encodes each yielded piece as one chunk)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(
            "POST", path,
            body=(payload[i:i + piece]
                  for i in range(0, len(payload), piece)),
            headers={"Content-Type": "application/json",
                     "Transfer-Encoding": "chunked"},
            encode_chunked=True)
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


class TestChunkedWireBothCores:
    """Chunked POST bodies over live sockets against BOTH cores."""

    def _raw_status(self, fe, raw, timeout=60.0):
        """Send raw bytes, return the response status line's code."""
        s = socket.create_connection(("127.0.0.1", fe.port),
                                     timeout=timeout)
        try:
            s.sendall(raw)
            s.settimeout(timeout)
            buf = b""
            while b"\r\n" not in buf:
                d = s.recv(4096)
                if not d:
                    break
                buf += d
            assert buf, "connection closed with no response"
            return int(buf.split(b" ", 2)[1])
        finally:
            s.close()

    def test_chunked_predict_equals_reference(self, genwire):
        fe, reg, _lm = genwire
        x = np.random.default_rng(3).normal(
            0, 1, (2, 16)).astype(np.float32)
        payload = json.dumps({"inputs": x.tolist()}).encode()
        status, _h, body = post_chunked(
            fe.port, "/v1/models/clf/predict", payload, piece=11)
        assert status == 200, body
        svc = reg.get("clf", reg.latest_version("clf"))
        got = np.asarray(json.loads(body)["outputs"], np.float32)
        ref = svc.predict(x)
        np.testing.assert_allclose(got, np.asarray(ref),
                                   rtol=1e-6, atol=1e-7)

    def test_chunked_generate_streams_tokens(self, genwire):
        fe, _reg, lm = genwire
        payload = json.dumps({"prompt": [5, 9, 3],
                              "max_new_tokens": 4}).encode()
        status, _h, body = post_chunked(
            fe.port, "/v1/models/lm/generate", payload, piece=5)
        assert status == 200, body
        streamed, done = parse_stream(body)
        ref = greedy_ref(lm, [5, 9, 3], 4, max_seq_len=48)
        assert streamed == ref == done["tokens"]

    def test_raw_socket_chunked_with_extension_and_trailer(
            self, genwire):
        """Hand-built framing the stdlib client never produces: chunk
        extensions and trailer fields must be discarded on the wire
        path too."""
        fe, _reg, lm = genwire
        payload = json.dumps({"prompt": [5, 9, 3],
                              "max_new_tokens": 2}).encode()
        head = (b"POST /v1/models/lm/generate HTTP/1.1\r\n"
                b"Host: t\r\nContent-Type: application/json\r\n"
                b"Connection: close\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n")
        mid = len(payload) // 2
        raw = (head
               + f"{mid:x};ext=1\r\n".encode() + payload[:mid] + b"\r\n"
               + f"{len(payload) - mid:x}\r\n".encode()
               + payload[mid:] + b"\r\n"
               + b"0\r\nX-Trailer: ignored\r\n\r\n")
        s = socket.create_connection(("127.0.0.1", fe.port), timeout=60)
        try:
            s.sendall(raw)
            s.settimeout(60)
            buf = b""
            while True:
                d = s.recv(65536)
                if not d:
                    break
                buf += d
        finally:
            s.close()
        assert b" 200 " in buf.split(b"\r\n", 1)[0]
        ref = greedy_ref(lm, [5, 9, 3], 2, max_seq_len=48)
        done = json.loads([ln for ln in buf.splitlines()
                           if b'"done"' in ln][-1])
        assert done["tokens"] == ref

    def test_malformed_chunk_framing_400(self, genwire):
        fe, _reg, _lm = genwire
        head = (b"POST /v1/models/clf/predict HTTP/1.1\r\n"
                b"Host: t\r\nContent-Type: application/json\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n")
        assert self._raw_status(fe, head + b"NOTHEX\r\n") == 400

    def test_te_plus_cl_smuggling_refused_400(self, genwire):
        fe, _reg, _lm = genwire
        raw = (b"POST /v1/models/clf/predict HTTP/1.1\r\n"
               b"Host: t\r\nContent-Type: application/json\r\n"
               b"Content-Length: 5\r\n"
               b"Transfer-Encoding: chunked\r\n\r\n"
               b"0\r\n\r\n")
        assert self._raw_status(fe, raw) == 400

    def test_unknown_coding_501(self, genwire):
        fe, _reg, _lm = genwire
        raw = (b"POST /v1/models/clf/predict HTTP/1.1\r\n"
               b"Host: t\r\nContent-Type: application/json\r\n"
               b"Transfer-Encoding: gzip\r\n\r\nxxxx")
        assert self._raw_status(fe, raw) == 501


# ===========================================================================
# event-loop shard CPU pinning (satellite 2)
# ===========================================================================
class TestPinCpus:
    def test_config_env_knob(self, monkeypatch):
        from bigdl_tpu.utils.config import Config
        monkeypatch.setenv("BIGDL_TPU_FRONTEND_PIN_CPUS", "1")
        assert Config.from_env().frontend_pin_cpus is True
        monkeypatch.delenv("BIGDL_TPU_FRONTEND_PIN_CPUS")
        assert Config.from_env().frontend_pin_cpus is False

    @pytest.mark.skipif(not hasattr(__import__("os"),
                                    "sched_setaffinity"),
                        reason="no sched_setaffinity on this platform")
    def test_each_loop_pins_to_one_cpu(self, monkeypatch):
        import os
        calls = []
        monkeypatch.setattr(
            os, "sched_setaffinity",
            lambda pid, mask: calls.append((pid, set(mask))))
        reg = ModelRegistry()
        fe = FrontendServer(reg, port=0, core="eventloop", shards=2,
                            pin_cpus=True)
        fe.start()
        try:
            wait_until(lambda: len(calls) >= 2, what="loops pinned")
            avail = sorted(os.sched_getaffinity(0))
            for pid, mask in calls:
                assert pid == 0  # calling thread, per Linux semantics
                assert len(mask) == 1 and mask <= set(avail)
            # loop i → cpu i mod count ⇒ two shards pin DIFFERENT cpus
            # when more than one cpu is available
            if len(avail) > 1:
                assert calls[0][1] != calls[1][1]
        finally:
            fe.stop()

    def test_pinning_inert_when_unsupported(self, monkeypatch):
        """The knob is best-effort by contract: a platform that
        refuses affinity calls must not break serving."""
        import os

        def refuse(pid, mask):
            raise OSError("not permitted")

        monkeypatch.setattr(os, "sched_setaffinity", refuse)
        reg = ModelRegistry()
        reg.deploy("clf", make_mlp(), input_spec=SPEC16,
                   max_batch_size=8, batch_timeout_ms=2.0)
        fe = FrontendServer(reg, port=0, core="eventloop",
                            pin_cpus=True)
        fe.start()
        try:
            x = np.random.default_rng(4).normal(
                0, 1, (2, 16)).astype(np.float32)
            status, _h, body = post(
                fe.port, "/v1/models/clf/predict",
                json.dumps({"inputs": x.tolist()}).encode())
            assert status == 200, body
        finally:
            fe.stop()
            reg.stop_all()

    def test_default_is_unpinned(self, monkeypatch):
        import os
        if not hasattr(os, "sched_setaffinity"):
            pytest.skip("no affinity API")
        calls = []
        monkeypatch.setattr(
            os, "sched_setaffinity",
            lambda pid, mask: calls.append((pid, set(mask))))
        reg = ModelRegistry()
        fe = FrontendServer(reg, port=0, core="eventloop")
        fe.start()
        try:
            time.sleep(0.05)
            assert calls == []  # pin_cpus defaults off
        finally:
            fe.stop()


# ===========================================================================
# registry deploy(service=) contract
# ===========================================================================
class TestDeployService:
    def test_mutually_exclusive_with_model_kwargs(self, lm):
        reg = ModelRegistry()
        dec = DecodeService(lm, slots=1, max_seq_len=16,
                            max_prompt_len=4, prefill_buckets="top",
                            start=False)
        try:
            with pytest.raises(ValueError):
                reg.deploy("x", lm, service=dec)
            with pytest.raises(ValueError):
                reg.deploy("x", service=dec, max_batch_size=4)
            reg.deploy("x", service=dec)
            assert reg.get("x", reg.latest_version("x")) is dec
        finally:
            reg.stop_all()

    def test_undeploy_stops_prebuilt_service(self, lm):
        reg = ModelRegistry()
        dec = DecodeService(lm, slots=1, max_seq_len=16,
                            max_prompt_len=4, prefill_buckets="top")
        reg.deploy("y", service=dec)
        reg.undeploy("y", drain=True)
        assert not dec.alive
        with pytest.raises(ServiceClosed):
            dec.submit([1, 2])


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
