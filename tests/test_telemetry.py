"""Telemetry subsystem tests (ISSUE-6 acceptance surface).

- tracer: span nesting/ordering, Chrome-trace JSON schema, disabled
  no-op path, bounded capacity;
- registry: counters/gauges/histograms, thread safety under concurrent
  submit, snapshot schema;
- Metrics back-compat: the ``summary()`` string format is unchanged by
  the registry rebase;
- serving: per-row-bucket latency reservoirs in ``stats()``;
- watchdogs: recompile positive (seeded shape-churn jit loop) and
  negative (AOT-warmed serving path), stall detector semantics, memory
  watermark degrades silently off-TPU;
- THE INERTNESS GATE: with telemetry enabled, the per-step loss
  sequence is BITWISE identical and the dispatch count equal to
  telemetry-off, for K ∈ {1, 4};
- trace_report: fixture-driven summary (phase shares sum to ~1,
  self-time attribution, watchdog events) and CLI exit codes.
"""

import json
import math
import os
import threading

import jax
import numpy as np
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import DataSet, SampleToMiniBatch
from bigdl_tpu.dataset import image, mnist
from bigdl_tpu.optim.optimizer import LocalOptimizer
from bigdl_tpu.telemetry import (MemoryWatermark, MetricRegistry,
                                 RecompileWatchdog, Reservoir,
                                 StallDetector, Tracer, jit_cache_size)
from bigdl_tpu.telemetry.tracer import NULL_SPAN
from bigdl_tpu.utils.metrics import Metrics
from tools import trace_report

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


# ==========================================================================
# tracer
# ==========================================================================
class TestTracer:
    def test_span_nesting_and_ordering(self):
        t = Tracer()
        with t.span("outer", cat="replay"):
            with t.span("inner", cat="trigger"):
                pass
            with t.span("inner2", cat="trigger"):
                pass
        evs = t.events()  # (ph, name, cat, t0_ns, dur_ns, tid, args)
        names = [e[1] for e in evs]
        # spans are recorded at EXIT: children land before their parent
        assert names == ["inner", "inner2", "outer"]
        by = {e[1]: e for e in evs}
        out0, outd = by["outer"][3], by["outer"][4]
        for child in ("inner", "inner2"):
            c0, cd = by[child][3], by[child][4]
            assert c0 >= out0
            assert c0 + cd <= out0 + outd  # nested inside the parent
        # siblings are ordered
        assert by["inner"][3] + by["inner"][4] <= by["inner2"][3]

    def test_chrome_trace_schema(self, tmp_path):
        t = Tracer()
        with t.span("dispatch", cat="dispatch", k=4):
            pass
        t.instant("recompile", key="x")
        t.record("block_inflight", 1000, 5000, cat="pipeline",
                 track="device", steps=2)
        path = t.dump(str(tmp_path / "trace.json"))
        data = json.load(open(path))
        assert set(data) == {"traceEvents", "displayTimeUnit", "otherData"}
        evs = data["traceEvents"]
        metas = [e for e in evs if e["ph"] == "M"]
        assert any(e["name"] == "process_name" for e in metas)
        # the virtual device track is NAMED in the thread metadata
        assert any(e["name"] == "thread_name"
                   and e["args"]["name"] == "device" for e in metas)
        xs = [e for e in evs if e["ph"] == "X"]
        assert len(xs) == 2
        for e in xs:
            assert {"name", "ph", "pid", "tid", "ts", "dur"} <= set(e)
            assert isinstance(e["tid"], int)
        inst = [e for e in evs if e["ph"] == "i"]
        assert len(inst) == 1 and inst[0]["args"] == {"key": "x"}
        # µs conversion: the explicit-endpoint span is 4000ns = 4µs
        inflight = next(e for e in xs if e["name"] == "block_inflight")
        assert inflight["ts"] == 1.0 and inflight["dur"] == 4.0

    def test_disabled_tracer_is_a_shared_noop(self):
        t = Tracer(enabled=False)
        s1 = t.span("a", cat="stage")
        s2 = t.span("b", cat="stage", k=3)
        assert s1 is s2 is NULL_SPAN  # zero allocation on the off path
        with s1:
            pass
        t.instant("x")
        t.record("y", 0, 10)
        assert t.events() == []

    def test_capacity_bound_drops_and_counts(self):
        t = Tracer(capacity=2)
        for i in range(5):
            with t.span(f"s{i}"):
                pass
        assert len(t.events()) == 2
        assert t.dropped_events == 3
        assert t.to_chrome_trace()["otherData"]["dropped_events"] == 3

    def test_span_cost_micro_bound(self):
        """Backs the README overhead budget: a span must cost
        microseconds, not milliseconds — 10k spans under 0.5s is a
        50µs/span ceiling, ~100× above the measured cost but far below
        anything that could move a 3-5ms training step by 2%."""
        import time as _time
        t = Tracer(capacity=20_000)
        t0 = _time.perf_counter()
        for _ in range(10_000):
            with t.span("s", cat="dispatch"):
                pass
        assert _time.perf_counter() - t0 < 0.5
        assert len(t.events()) == 10_000

    def test_phase_totals(self):
        t = Tracer()
        t.record("a", 0, 10_000_000, cat="stage")
        t.record("b", 0, 30_000_000, cat="stage")
        t.record("c", 0, 5_000_000, cat="dispatch")
        totals = t.phase_totals()
        assert totals["stage"] == pytest.approx(0.04)
        assert totals["dispatch"] == pytest.approx(0.005)


# ==========================================================================
# registry
# ==========================================================================
class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(4)
        reg.gauge("g").set(2.5)
        h = reg.histogram("h")
        for v in (1.0, 2.0, 3.0):
            h.observe(v)
        assert reg.counter("c").value == 5
        assert reg.gauge("g").value == 2.5
        assert h.count == 3 and h.sum == 6.0 and h.mean == 2.0
        assert h.snapshot()["min"] == 1.0 and h.snapshot()["max"] == 3.0
        snap = reg.snapshot()
        json.dumps(snap)  # JSON-able
        assert snap["counters"]["c"] == 5
        assert snap["gauges"]["g"] == 2.5
        assert snap["histograms"]["h"]["p50"] == 2.0

    def test_type_conflict_raises(self):
        reg = MetricRegistry()
        reg.counter("x")
        with pytest.raises(TypeError):
            reg.gauge("x")

    def test_thread_safety_under_concurrent_submit(self):
        reg = MetricRegistry()
        N, T = 2000, 8
        start = threading.Barrier(T)

        def worker():
            start.wait()
            for i in range(N):
                # get-or-create races on the same names by design
                reg.counter("shared/count").inc()
                reg.histogram("shared/lat").observe(i)
                reg.gauge("shared/g").set(i)

        threads = [threading.Thread(target=worker) for _ in range(T)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.counter("shared/count").value == N * T
        h = reg.histogram("shared/lat")
        assert h.count == N * T
        assert h.sum == pytest.approx(T * N * (N - 1) / 2)

    def test_reservoir_percentile_contract(self):
        # the serving LatencyReservoir alias must keep its semantics
        from bigdl_tpu.serving import LatencyReservoir
        assert LatencyReservoir is Reservoir
        r = Reservoir(capacity=64)
        for v in range(1, 101):  # window keeps the most recent 64
            r.record(v / 1000.0)
        p = r.percentiles()
        assert set(p) == {"p50", "p95", "p99", "mean", "max"}
        assert p["p50"] <= p["p95"] <= p["p99"] <= p["max"] == 0.1
        assert r.count == 100


# ==========================================================================
# Metrics veneer back-compat
# ==========================================================================
class TestMetricsBackCompat:
    def test_summary_format_unchanged(self):
        m = Metrics()
        m.add("computing", 0.5)
        m.add("computing", 1.5)
        m.add("data", 0.25)
        assert m.summary() == (
            "computing: sum=2.0000 mean=1.0000 n=2\n"
            "data: sum=0.2500 mean=0.2500 n=1")
        assert m.value("computing") == 2.0
        assert m.mean("computing") == 1.0
        assert m.value("absent") == 0.0 and m.mean("absent") == 0.0
        m.reset()
        assert m.summary() == ""

    def test_time_context_manager(self):
        m = Metrics()
        with m.time("phase"):
            pass
        assert m.value("phase") > 0.0
        assert m.registry.histogram("phase").count == 1

    def test_shared_registry(self):
        reg = MetricRegistry()
        m = Metrics(registry=reg)
        m.add("x", 1.0)
        assert reg.histogram("x").count == 1

    def test_reset_clears_only_owned_names_on_shared_registry(self):
        """reset() must not wipe the watchdog metrics sharing the
        registry — a blanket registry.reset() would orphan the counter
        objects the watchdogs cache, silently losing every later
        increment from the snapshot."""
        reg = MetricRegistry()
        counter = reg.counter("telemetry/recompiles")  # watchdog-cached
        reg.gauge("driver/device_wait_fraction").set(0.5)
        m = Metrics(registry=reg)
        m.add("data", 1.0)
        m.reset()
        assert m.summary() == ""
        # foreign metrics survive, and the cached counter object is
        # STILL the registered one (no orphaning)
        assert reg.get("telemetry/recompiles") is counter
        counter.inc()
        assert reg.snapshot()["counters"]["telemetry/recompiles"] == 1
        assert reg.gauge("driver/device_wait_fraction").value == 0.5


# ==========================================================================
# serving: per-bucket latency reservoirs
# ==========================================================================
class TestServingPerBucketLatency:
    def test_snapshot_keys_by_bucket(self):
        from bigdl_tpu.serving.metrics import ServingMetrics
        sm = ServingMetrics()
        sm.record_done(1, 0.001, bucket=1)
        sm.record_done(4, 0.004, bucket=4)
        sm.record_done(3, 0.005, bucket=4)
        snap = sm.snapshot()
        assert set(snap["latency_ms_by_bucket"]) == {1, 4}
        assert snap["latency_ms_by_bucket"][1]["p50"] == 1.0
        # global window still sees every completion
        assert snap["latency_ms"]["max"] == 5.0

    def test_inference_service_stats_expose_buckets(self):
        from bigdl_tpu.serving import InferenceService
        model = nn.Sequential(nn.Linear(4, 3), nn.SoftMax())
        model.initialize(rng=0)
        svc = InferenceService(model, input_spec=((4,), np.float32),
                               max_batch_size=2, batch_timeout_ms=0.0,
                               name="bucketed")
        try:
            svc.predict(np.zeros((1, 4), np.float32))
            svc.predict(np.zeros((2, 4), np.float32))
            stats = svc.stats()
            by = stats["latency_ms_by_bucket"]
            assert by is not None and set(by) <= {1, 2}
            assert 1 in by and 2 in by
            for pct in by.values():
                assert {"p50", "p95", "p99"} <= set(pct)
        finally:
            svc.stop()


# ==========================================================================
# watchdogs
# ==========================================================================
class TestRecompileWatchdog:
    def test_flags_shape_churn_loop(self):
        reg, tr = MetricRegistry(), Tracer()
        wd = RecompileWatchdog(reg, tr)
        f = jax.jit(lambda x: x * 2)
        for n in (1, 2, 3, 4):  # seeded shape churn: retrace per shape
            f(np.zeros((n,), np.float32))
            wd.observe("step", jit_cache_size(f))
        assert wd.recompile_count == 3  # first compile is the baseline
        assert not wd.silent
        assert reg.counter("telemetry/recompiles").value == 3
        assert sum(1 for e in tr.events() if e[1] == "recompile") == 3

    def test_silent_on_aot_warmed_serving_path(self):
        from bigdl_tpu.serving import InferenceService
        model = nn.Sequential(nn.Linear(4, 3), nn.SoftMax())
        model.initialize(rng=0)
        svc = InferenceService(model, input_spec=((4,), np.float32),
                               max_batch_size=4, batch_timeout_ms=0.0,
                               name="warmed")
        wd = RecompileWatchdog()
        try:
            wd.observe("svc", svc.compile_count)  # post-warmup baseline
            rng = np.random.default_rng(0)
            for n in (1, 2, 3, 4, 1, 3):  # mixed sizes hit warm buckets
                svc.predict(rng.normal(0, 1, (n, 4)).astype(np.float32))
                assert not wd.observe("svc", svc.compile_count)
        finally:
            svc.stop()
        assert wd.silent and wd.recompile_count == 0

    def test_none_cache_size_is_noop(self):
        wd = RecompileWatchdog()
        assert wd.observe("k", None) is False
        assert jit_cache_size(lambda x: x) is None  # not a jit wrapper


class TestStallDetector:
    def test_starvation_flagged_and_fractions_sum(self):
        reg = MetricRegistry()
        det = StallDetector(reg, warm_blocks=0)
        # healthy pipelined block: device wait absorbs nearly everything
        det.record_block(stage_s=0.01, dispatch_s=0.001, wait_s=0.2,
                         replay_s=0.002)
        assert det.starvation_count == 0
        # starved block: staging dominates, device wait ~zero
        for _ in range(3):
            det.record_block(stage_s=0.2, dispatch_s=0.001, wait_s=0.001,
                             replay_s=0.001)
        assert det.starvation_count == 3
        fr = det.fractions()
        assert sum(fr.values()) == pytest.approx(1.0)
        assert reg.gauge("driver/host_stage_fraction").value == \
            pytest.approx(fr["stage"])

    def test_dispatch_sync_stall_flagged_but_not_for_compiles(self):
        reg = MetricRegistry()
        det = StallDetector(reg, warm_blocks=0, dispatch_stall_ms=50.0)
        det.record_block(0.0, 0.2, 0.0, 0.0, first_compile=True)
        assert det.sync_stall_count == 0  # planned compile, not a stall
        det.record_block(0.0, 0.2, 0.0, 0.0)
        assert det.sync_stall_count == 1

    def test_warm_blocks_withhold_verdicts(self):
        det = StallDetector(MetricRegistry(), warm_blocks=2)
        for _ in range(2):
            det.record_block(0.5, 0.2, 0.0, 0.0)
        assert det.starvation_count == 0 and det.sync_stall_count == 0


class TestMemoryWatermark:
    def test_degrades_silently_without_backend_stats(self):
        reg = MetricRegistry()
        mw = MemoryWatermark(reg)

        class NoStats:
            def memory_stats(self):
                return None

        assert mw.observe(NoStats()) is None
        assert mw.available is False
        assert reg.names() == []

    def test_gauges_when_stats_present(self):
        reg = MetricRegistry()
        mw = MemoryWatermark(reg)

        class WithStats:
            def memory_stats(self):
                return {"bytes_in_use": 1024, "peak_bytes_in_use": 4096}

        assert mw.observe(WithStats())["bytes_in_use"] == 1024
        assert mw.available is True
        assert reg.gauge("device/bytes_in_use").value == 1024
        assert reg.gauge("device/peak_bytes_in_use").value == 4096


# ==========================================================================
# the inertness gate + end-to-end trace
# ==========================================================================
def mnist_pipeline(n, batch, seed=0):
    imgs, labels = mnist.synthetic_mnist(n, seed=seed)
    samples = mnist.to_samples(imgs, labels)
    ds = (DataSet.array(samples)
          >> image.BytesToGreyImg()
          >> image.GreyImgNormalizer(mnist.TRAIN_MEAN, mnist.TRAIN_STD))
    return ds >> SampleToMiniBatch(batch)


def small_mlp():
    return (nn.Sequential()
            .add(nn.Reshape((784,)))
            .add(nn.Linear(784, 32)).add(nn.ReLU())
            .add(nn.Linear(32, 10)).add(nn.LogSoftMax()))


class RecordingSummary:
    def __init__(self):
        self.rows = []
        self.scalars = []

    def add_train_step(self, step, loss, lr, throughput):
        self.rows.append((step, loss, lr))

    def add_scalar(self, tag, value, step):
        self.scalars.append((tag, value, step))

    def trigger_for(self, name):
        return None

    @property
    def losses(self):
        return np.array([l for _, l, _ in self.rows])


def run_counted(k, telemetry, trace_path=None, iters=11, n=256, batch=32):
    """One small training run with a dispatch-counting wrapper around
    the REAL block fns (the test_fused_step budget discipline)."""
    calls = {"n": 0}
    rec = RecordingSummary()
    opt = (LocalOptimizer(small_mlp(), mnist_pipeline(n, batch),
                          nn.ClassNLLCriterion())
           .set_optim_method(optim.Adam(1e-3))
           .set_train_summary(rec)
           .set_steps_per_dispatch(k)
           .set_end_when(optim.max_iteration(iters)))
    opt.set_telemetry(telemetry, trace_path=trace_path)
    orig = opt._build_block_fn

    def counting_build(grad_fn, kk):
        fn = orig(grad_fn, kk)

        def wrapped(*a, **kw):
            calls["n"] += 1
            return fn(*a, **kw)

        # expose the real jit underneath so the recompile watchdog's
        # cache-size probe still sees it through the wrapper
        wrapped._cache_size = getattr(fn, "_cache_size", None)
        return wrapped

    opt._build_block_fn = counting_build
    opt.optimize()
    return rec, opt, calls["n"]


class TestTelemetryInert:
    @pytest.mark.parametrize("k", [1, 4])
    def test_bitwise_identical_loss_and_dispatch_count(self, k, tmp_path):
        """THE acceptance gate: telemetry on changes NOTHING observable
        about training — per-step losses bitwise equal, same number of
        jit dispatches — while still emitting a valid trace.

        Round 2 extends the gate to the full new surface: with the
        admin plane off, the flight recorder off and request tracing
        off (all defaults), the run allocates NO admin server, NO
        flight recorder, NO request contexts and ZERO extra threads —
        and the loss sequence/dispatch count remain the off-path
        numbers."""
        from bigdl_tpu.telemetry import admin as admin_mod
        from bigdl_tpu.telemetry import flight as flight_mod
        threads_before = {t.ident for t in threading.enumerate()}
        rec_off, opt_off, n_off = run_counted(k, telemetry=False)
        # the new observability surface stayed entirely un-allocated
        assert admin_mod.current() is None
        assert flight_mod.current() is None
        assert opt_off._flight is None
        surviving = [t for t in threading.enumerate()
                     if t.ident not in threads_before and t.is_alive()]
        assert not [t for t in surviving
                    if t.name == "bigdl-tpu-admin"], surviving
        # zero extra threads: whatever transient helpers ran (stager
        # producer), nothing new outlives the run
        assert not surviving, surviving
        trace = str(tmp_path / f"trace_k{k}.json")
        rec_on, opt_on, n_on = run_counted(k, telemetry=True,
                                           trace_path=trace)
        np.testing.assert_array_equal(rec_off.losses, rec_on.losses)
        assert n_off == n_on
        assert opt_off._dispatch_count == opt_on._dispatch_count
        budget = math.ceil(11 / k) + 2
        assert n_on <= budget
        # telemetry-off leaves no telemetry state behind
        assert opt_off.telemetry_snapshot() is None
        assert opt_on.telemetry_snapshot() is not None
        # ... and the enabled run produced a trace the reporter can
        # summarize with phase shares that close to ~1
        report = trace_report.summarize(trace_report.load_trace(trace))
        assert report["span_count"] > 0
        assert sum(report["phase_share"].values()) == pytest.approx(
            1.0, abs=0.02)
        for cat in ("stage", "dispatch", "device_wait", "replay"):
            assert cat in report["phase_seconds"], report["phase_seconds"]

    def test_no_steady_state_recompiles_in_driver(self, tmp_path):
        """The fused driver's block fns compile once per block length —
        the recompile watchdog must stay silent across a multi-epoch
        run (the negative control for the runtime GL106 gate)."""
        _, opt, _ = run_counted(4, telemetry=True,
                                trace_path=str(tmp_path / "t.json"),
                                iters=16)
        snap = opt.telemetry_snapshot()
        assert snap["watchdogs"]["recompile_events"] == []
        assert snap["watchdogs"]["blocks_observed"] > 0

    def test_gauges_mirrored_into_train_summary(self, tmp_path):
        rec, opt, _ = run_counted(4, telemetry=True,
                                  trace_path=str(tmp_path / "t.json"))
        tags = {t for t, _, _ in rec.scalars}
        assert "Telemetry/driver/device_wait_fraction" in tags
        assert "Telemetry/driver/host_stage_fraction" in tags

    def test_off_run_writes_no_trace(self, tmp_path):
        trace = str(tmp_path / "never.json")
        rec, opt, _ = run_counted(1, telemetry=False, trace_path=trace)
        assert not os.path.exists(trace)

    def test_set_telemetry_false_actually_disables_on_reuse(self,
                                                            tmp_path):
        """Toggling off between runs on the SAME optimizer must drop
        the stale DriverTelemetry — _tel_span reads self._telemetry, so
        a leftover bundle would keep recording through an 'off' run."""
        rec = RecordingSummary()
        opt = (LocalOptimizer(small_mlp(), mnist_pipeline(128, 32),
                              nn.ClassNLLCriterion())
               .set_optim_method(optim.Adam(1e-3))
               .set_train_summary(rec)
               .set_telemetry(True,
                              trace_path=str(tmp_path / "t.json"))
               .set_end_when(optim.max_iteration(3)))
        opt.optimize()
        tel_first = opt._telemetry
        assert tel_first is not None
        events_after_on = len(tel_first.tracer.events())
        assert events_after_on > 0
        opt.set_telemetry(False)
        opt.set_end_when(optim.max_iteration(6))
        opt.optimize()
        assert opt._telemetry is None
        assert opt.telemetry_snapshot() is None
        # the old bundle stopped recording too
        assert len(tel_first.tracer.events()) == events_after_on


class TestConfigSurface:
    def test_config_fields_exist(self):
        from bigdl_tpu.utils.config import Config
        cfg = Config()
        assert cfg.telemetry_enabled is False
        assert cfg.telemetry_trace_path == ""
        assert cfg.telemetry_trace_capacity == 200_000
        # round 2 (admin plane / flight recorder / request tracing):
        # every new knob defaults to the provably-inert state
        assert cfg.admin_port == 0
        assert cfg.request_tracing is False
        assert cfg.flight_recorder_path == ""
        assert cfg.flight_recorder_capacity == 4096

    def test_round2_env_knobs(self, monkeypatch):
        from bigdl_tpu.utils.config import Config
        monkeypatch.setenv("BIGDL_TPU_ADMIN_PORT", "9187")
        monkeypatch.setenv("BIGDL_TPU_REQUEST_TRACING", "1")
        monkeypatch.setenv("BIGDL_TPU_FLIGHT_RECORDER_PATH",
                           "/tmp/fl.jsonl")
        cfg = Config.from_env()
        assert cfg.admin_port == 9187
        assert cfg.request_tracing is True
        assert cfg.flight_recorder_path == "/tmp/fl.jsonl"

    def test_env_alias(self, monkeypatch):
        from bigdl_tpu.utils.config import Config
        monkeypatch.setenv("BIGDL_TPU_TELEMETRY", "1")
        assert Config.from_env().telemetry_enabled is True
        # the explicit long form wins over the alias
        monkeypatch.setenv("BIGDL_TPU_TELEMETRY_ENABLED", "0")
        assert Config.from_env().telemetry_enabled is False

    def test_set_telemetry_builder(self):
        opt = LocalOptimizer(small_mlp(), mnist_pipeline(64, 32),
                             nn.ClassNLLCriterion())
        assert opt.telemetry_enabled is None  # resolve from config
        assert opt.set_telemetry(True, "x.json") is opt
        assert opt.telemetry_enabled is True
        assert opt.telemetry_trace_path == "x.json"


# ==========================================================================
# trace_report (fixture-driven)
# ==========================================================================
class TestTraceReport:
    FIXTURE = os.path.join(FIXTURES, "trace_pipeline.json")

    def test_fixture_summary_exact(self):
        report = trace_report.summarize(
            trace_report.load_trace(self.FIXTURE))
        assert report["wall_s"] == pytest.approx(1.0)
        share = report["phase_share"]
        # hand-built fixture: stage .2, dispatch .1, wait .5, replay .1
        # with a nested 40ms trigger span (self-time split), other .1;
        # the device-track pipeline span must NOT count
        assert share == {"stage": 0.2, "dispatch": 0.1,
                         "device_wait": 0.5, "replay": 0.06,
                         "trigger": 0.04, "other": 0.1}
        assert sum(share.values()) == pytest.approx(1.0)
        assert report["stall"]["device_wait_fraction"] == 0.5
        assert report["watchdog_events"] == {"recompile": 2,
                                             "stager_starvation": 1}
        assert len(report["recompile_events"]) == 2
        top = report["top_spans"]
        assert top[0]["name"] == "device_wait"
        assert top[0]["total_ms"] == 500.0

    def test_cli_exit_codes(self, tmp_path, capsys):
        assert trace_report.main([self.FIXTURE]) == 0
        out = capsys.readouterr().out
        assert "phase share" in out and "device_wait" in out
        assert trace_report.main([self.FIXTURE, "--json"]) == 0
        json.loads(capsys.readouterr().out)  # valid JSON mode
        missing = str(tmp_path / "nope.json")
        assert trace_report.main([missing]) == 2
        bad = tmp_path / "bad.json"
        bad.write_text("{}")
        assert trace_report.main([str(bad)]) == 2

    def test_bare_event_list_accepted(self, tmp_path):
        events = json.load(open(self.FIXTURE))["traceEvents"]
        p = tmp_path / "bare.json"
        p.write_text(json.dumps(events))
        report = trace_report.summarize(trace_report.load_trace(str(p)))
        assert report["wall_s"] == pytest.approx(1.0)


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
