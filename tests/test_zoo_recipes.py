"""Zoo training-recipe smoke tests (reference: ``models/*/Train*.scala``
are exercised by ``TEST/models`` + integration specs; here each recipe
main runs a tiny synthetic config on the CPU mesh and must reach a sane
loss)."""

import os
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, script), "--cpu", *args],
        capture_output=True, text=True, timeout=520, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    return r.stdout


def _final_metric(out: str, key: str = "loss") -> float:
    for line in out.splitlines():
        if line.startswith("final:"):
            return float(line.split(f"{key}=")[1].split()[0])
    raise AssertionError(f"no final line in:\n{out}")


def _final_loss(out: str) -> float:
    return _final_metric(out, "loss")


def test_resnet_cifar_recipe():
    # augmentation draws are sample-keyed (utils/imgops.sample_key), so
    # this run is bit-deterministic: 2 epochs land at loss ~1.27 —
    # a real learning signal, not a threshold race (VERDICT r2 weak#2)
    out = _run("examples/resnet/train_cifar10.py", "-e", "2",
               "--synthetic-n", "512", "-b", "64")
    assert _final_loss(out) < 2.0


def test_vgg_recipe():
    out = _run("examples/vgg/train.py", "-e", "1",
               "--synthetic-n", "128", "-b", "64")
    assert _final_loss(out) < 2.5


def test_rnn_recipe():
    out = _run("examples/rnn/train.py", "-e", "2")
    # random Zipf corpus entropy is ~<ln 51; Adam should be well under
    assert _final_loss(out) < 3.6


def test_inception_recipe():
    out = _run("examples/inception/train.py", "--max-iteration", "4",
               "--synthetic-n", "32", "-b", "8", "--classes", "8")
    assert np.isfinite(_final_loss(out))


def test_imagenet_recipe_smoke():
    # image size must stay 224: ResNet-50's final 7x7 avg pool collapses
    # to zero-dim maps on smaller inputs (structurally invalid)
    out = _run("examples/resnet/train_imagenet.py", "-e", "1",
               "--synthetic-n", "48", "-b", "16", "--classes", "8",
               "--warmup-epochs", "0", "--max-lr", "0.01")
    assert np.isfinite(_final_loss(out))


def test_textclassification_recipe():
    out = _run("examples/textclassification/train.py", "-e", "4")
    assert _final_metric(out, "train_acc") > 0.9, out


def test_udfpredictor_service():
    out = _run("examples/udfpredictor/serve.py", "--requests", "16",
               "--threads", "4")
    assert "served 16 requests" in out


def test_autoencoder_recipe():
    out = _run("examples/autoencoder/train.py", "-e", "3",
               "--synthetic-n", "1024")
    assert _final_metric(out, "recon_mse") < 0.05, out


def test_wide_deep_recipe():
    out = _run("examples/recommender/train_wide_deep.py", "-e", "4")
    assert _final_metric(out, "train_acc") > 0.65, out


# ---------------------------------------------------- r3 examples sweep
def test_lenet_local_recipe():
    out = _run("examples/lenetLocal/train.py", "-e", "1",
               "--synthetic-n", "512", "-b", "64")
    assert np.isfinite(_final_loss(out))
    assert "top1=" in out


def test_imageclassification_recipe():
    out = _run("examples/imageclassification/predict.py",
               "--batch-size", "8", "--classes", "4")
    assert "predicted=16" in out


def test_mlpipeline_recipe():
    out = _run("examples/mlpipeline/train_classifier.py", "-e", "15")
    assert _final_metric(out, "train_acc") > 0.9, out
    assert _final_metric(out, "lenet_acc") > 0.9, out
    assert _final_metric(out, "mse") < 0.01, out


def test_tensorflow_train_imported_recipe():
    out = _run("examples/tensorflow/train_imported.py", "-e", "4")
    assert "reload parity: OK" in out
    assert _final_metric(out, "train_acc") > 0.9, out


def test_languagemodel_recipe():
    out = _run("examples/languagemodel/train_ptb.py", "-e", "1",
               "--vocab", "100", "--hidden", "32")
    # synthetic Zipf corpus entropy is well under ln(100)
    assert _final_loss(out) < 4.0, out


def test_loadmodel_validator_recipe():
    out = _run("examples/loadmodel/validate.py")
    assert "formats=bigdl,torch,caffe" in out
    assert _final_metric(out, "top1") > 0.5, out


def test_dlframes_transfer_learning_recipe():
    out = _run("examples/dlframes/transfer_learning.py", "-e", "8")
    assert _final_metric(out, "train_acc") > 0.9, out
