"""Regression tests for tools/byte_audit.py HLO operand parsing.

Canned HLO snippets (tests/fixtures/hlo_*.txt) in the real
``compiled.as_text()`` style — operand shapes printed inline, metadata
attributes after the operand list — pin down two historical parsing
bugs around tuple-shaped results:

1. a consumer of a tuple-shaped value printed with its tuple type
   (``while((s32[], f32[...]{1,0}) %tuple)``) lost every operand ref
   after the type's internal ``)`` — split(")")[0] cut inside it, so
   the while was charged no operand read at all;
2. async ``*-done`` ops reference the ``*-start``'s (operand, result)
   tuple directly (no get-tuple-element), and were charged the FULL
   tuple instead of the aliased result element — double-counting every
   collective's bytes.

get-tuple-element-mediated consumers must always resolve the ELEMENT
size, never the producing tuple's total.
"""

import os

from tools.byte_audit import (_operand_text, audit, collective_wire_bytes,
                              copy_audit, diff_audit, shape_bytes)

FIX = os.path.join(os.path.dirname(__file__), "fixtures")

F32 = 4
BIG = 128 * 256 * F32          # f32[128,256]
AR = 1024 * 1024 * F32         # f32[1024,1024]


def _load(name):
    with open(os.path.join(FIX, name)) as fh:
        return fh.read()


class TestShapeBytes:
    def test_single(self):
        assert shape_bytes("f32[128,256]{1,0}") == BIG
        assert shape_bytes("s32[]") == 4
        assert shape_bytes("bf16[64]") == 128

    def test_tuple_sums_elements(self):
        assert shape_bytes("(s32[], f32[128,256]{1,0})") == 4 + BIG


class TestOperandText:
    def test_flat(self):
        line = "x = f32[8]{0} add(f32[8]{0} %a, f32[8]{0} %b), meta={}"
        start = line.index("add(") + 4
        assert _operand_text(line, start) == "f32[8]{0} %a, f32[8]{0} %b"

    def test_tuple_typed_operand_not_truncated(self):
        line = ("%w = (s32[], f32[8]{0}) while((s32[], f32[8]{0}) "
                "%tuple), condition=%c, body=%b")
        start = line.index("while(") + 6
        assert "%tuple" in _operand_text(line, start)
        assert "condition" not in _operand_text(line, start)


class TestWhileGteFixture:
    def test_while_reads_its_tuple_operand(self):
        by_op, _ = audit(_load("hlo_while_gte.txt"), top=10)
        # write (4 + BIG) + read of %tuple (4 + BIG): the operand ref
        # used to be lost to the printed tuple type's inner paren
        assert by_op["while"] == 2 * (4 + BIG)

    def test_gte_consumer_charged_element_not_tuple(self):
        by_op, _ = audit(_load("hlo_while_gte.txt"), top=10)
        # add = out + gte element + parameter, all f32[128,256]
        assert by_op["add"] == 3 * BIG

    def test_nested_computations_excluded(self):
        by_op, _ = audit(_load("hlo_while_gte.txt"), top=10)
        # %multiply.9 lives in the while body, not the entry
        assert "multiply" not in by_op

    def test_bookkeeping_ops_carry_no_traffic(self):
        by_op, _ = audit(_load("hlo_while_gte.txt"), top=10)
        for op in ("get-tuple-element", "tuple", "parameter"):
            assert op not in by_op

    def test_top_instructions_sorted(self):
        _, instrs = audit(_load("hlo_while_gte.txt"), top=10)
        sizes = [b for b, _, _, _ in instrs]
        assert sizes == sorted(sizes, reverse=True)
        assert sizes[0] == 3 * BIG  # the root add outranks the while


class TestCollectiveWireBytes:
    """Per-collective wire-byte attribution (round-7, the grad_sync
    wire-format audit).  Twin canned HLOs — identical program, one f32
    wire, one bf16 — pin the headline invariant: a bf16 wire halves
    every collective's payload.  The fixtures deliberately put the
    reduce-scatter/all-gather inside a while (scan) body: the fused
    K-step driver compiles them there, and an entry-only walk would
    read zero."""

    MB = 1048576  # one f32[1048576] = 4 MiB payload

    def test_f32_kinds_and_payloads(self):
        cw = collective_wire_bytes(_load("hlo_wire_f32.txt"))
        # reduce-scatter charged its OPERAND (full pre-scatter vector)
        assert cw["reduce-scatter"] == 4 * self.MB
        # async all-gather-start charged the largest in-flight element
        # (the gathered result), not the (operand, result) tuple sum;
        # the fixture carries start+done PAIRS, so these exact equalities
        # also pin that -done ops are never charged a second time
        assert cw["all-gather"] == 4 * self.MB
        assert cw["all-reduce"] == 4 * self.MB
        assert cw["total"] == 12 * self.MB

    def test_bf16_wire_halves_collective_bytes(self):
        f32 = collective_wire_bytes(_load("hlo_wire_f32.txt"))
        bf16 = collective_wire_bytes(_load("hlo_wire_bf16.txt"))
        for kind in ("reduce-scatter", "all-gather", "all-reduce",
                     "total"):
            assert bf16[kind] * 2 == f32[kind], kind

    def test_no_collectives_reads_zero(self):
        cw = collective_wire_bytes(_load("hlo_while_gte.txt"))
        assert cw == {"total": 0}

    def test_legacy_async_fixture_consistent(self):
        # the PR-2 async fixture: one all-reduce-start/done pair on a
        # f32[1024,1024] — payload is the single aliased buffer
        cw = collective_wire_bytes(_load("hlo_async_done.txt"))
        assert cw["all-reduce"] == AR
        assert cw["total"] == AR


class TestDiffAudit:
    """--diff (round-10): per-op-kind bytes delta between two HLO
    dumps.  Regression-tested on the existing wire fixtures, plus the
    ISSUE-8 acceptance gate: the canned fused PTB-LSTM / Wide&Deep step
    programs show STRICTLY lower bytes than their XLA baselines, with
    the baseline op kinds gone and one custom-call in their place."""

    def test_wire_fixture_diff_matches_audit_totals(self):
        d = diff_audit(_load("hlo_wire_f32.txt"), _load("hlo_wire_bf16.txt"))
        a_by, _ = audit(_load("hlo_wire_f32.txt"), top=5)
        b_by, _ = audit(_load("hlo_wire_bf16.txt"), top=5)
        assert d["total_a"] == sum(a_by.values())
        assert d["total_b"] == sum(b_by.values())
        assert d["total_delta"] == d["total_b"] - d["total_a"]
        # the wire payload table rides along and shows the bf16 halving
        assert d["wire_b"]["total"] * 2 == d["wire_a"]["total"]
        # per_op rows are sorted by |delta| descending
        deltas = [abs(r[3]) for r in d["per_op"]]
        assert deltas == sorted(deltas, reverse=True)

    def test_ptb_cell_fused_strictly_lower(self):
        d = diff_audit(_load("hlo_ptb_cell_xla.txt"),
                       _load("hlo_ptb_cell_fused.txt"))
        assert d["total_b"] < d["total_a"]  # the acceptance bar
        per = {k: (a, b) for k, a, b, _ in d["per_op"]}
        # the gate-chain op kinds vanish; one custom-call replaces them
        for kind in ("dot", "slice", "logistic", "tanh", "multiply"):
            assert per[kind][1] == 0, kind
        assert per["custom-call"][0] == 0 and per["custom-call"][1] > 0

    def test_wd_bag_fused_strictly_lower(self):
        d = diff_audit(_load("hlo_wd_bag_xla.txt"),
                       _load("hlo_wd_bag_fused.txt"))
        assert d["total_b"] < d["total_a"]
        per = {k: (a, b) for k, a, b, _ in d["per_op"]}
        # no materialized (nnz, D) intermediate: gather/multiply/scatter
        # all gone in the fused program
        for kind in ("gather", "multiply", "scatter", "broadcast"):
            assert per[kind][1] == 0, kind
        assert per["custom-call"][1] > 0
        # the dominant saving is the (nnz, D) round-trips: delta at
        # least the two multiply operands' worth
        assert d["total_a"] - d["total_b"] > 2 * 65536 * 16 * F32

    def test_int8_gemm_weight_panel_strictly_lower(self):
        """The int8 speed-path acceptance bar: the quantized GEMM's
        step program moves strictly fewer bytes than the f32 linear,
        and the saving is dominated by the weight panel (s8[256,256]
        = 64 KiB vs f32[256,256] = 256 KiB; the f32 scale/bias rows it
        adds are 2 KiB)."""
        d = diff_audit(_load("hlo_int8_gemm_f32.txt"),
                       _load("hlo_int8_gemm_pallas.txt"))
        assert d["total_b"] < d["total_a"]  # strictly lower, the gate
        panel_f32 = 256 * 256 * F32
        panel_s8 = 256 * 256
        extra_rows = 2 * 256 * F32  # (1,256) scale + (1,256) bias
        # the f32 baseline also pays the broadcast bias materialization
        # the fused epilogue removes; the panel saving alone must be
        # visible net of the added scale/bias reads
        assert d["total_a"] - d["total_b"] >= \
            (panel_f32 - panel_s8) - extra_rows
        per = {k: (a, b) for k, a, b, _ in d["per_op"]}
        assert per["dot"][1] == 0 and per["broadcast"][1] == 0
        assert per["custom-call"][0] == 0 and per["custom-call"][1] > 0


class TestCopyAudit:
    """--audit-copies (round-10 donation/aliasing audit)."""

    def test_finds_entry_copy_above_threshold(self):
        # hlo_while_gte carries one f32[128,256] entry copy (131072 B)
        found = copy_audit(_load("hlo_while_gte.txt"), min_bytes=65536)
        assert [name for _, name, _ in found] == ["copy.1"]
        assert found[0][0] == BIG

    def test_threshold_filters_small_copies(self):
        assert copy_audit(_load("hlo_while_gte.txt"),
                          min_bytes=BIG + 1) == []

    def test_nested_computation_copies_excluded(self):
        # only ENTRY copies are donation-relevant; fused/while bodies
        # never materialize
        found = copy_audit(_load("hlo_wire_f32.txt"), min_bytes=1)
        assert found == []


class TestAsyncDoneFixture:
    def test_done_charges_aliased_element_not_full_tuple(self):
        by_op, _ = audit(_load("hlo_async_done.txt"), top=10)
        # out + ONE aliased element — not out + 2-element tuple
        assert by_op["all-reduce-done"] == 2 * AR

    def test_start_still_counts_tuple_write(self):
        by_op, _ = audit(_load("hlo_async_done.txt"), top=10)
        # write (2 elements) + read of %p0
        assert by_op["all-reduce-start"] == 3 * AR

    def test_gte_off_start_resolves_element(self):
        by_op, _ = audit(_load("hlo_async_done.txt"), top=10)
        # add = out + done result + gte element
        assert by_op["add"] == 3 * AR
