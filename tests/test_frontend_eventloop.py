"""C100K wire plane — event-loop front end core (ISSUE 19).

The load-bearing gates:

- **Incremental parser**: ``frontend/http1.RequestParser`` driven
  byte-at-a-time — slow-loris request lines, split headers, and
  truncated bodies park the CONNECTION (``None``), never mis-frame the
  next keep-alive request, and malformed heads poison the parser with
  the right status (400/431/505).
- **Slow-loris robustness on the wire**: a byte-dribbled request on
  one socket must not block service for other clients — asserted
  against BOTH cores (``core="eventloop"`` and ``core="threaded"``),
  since the threaded core is the transition fallback.
- **Reaper + cap**: past ``frontend_max_connections`` new accepts are
  refused cheaply (counted), idle sockets are closed after
  ``frontend_idle_timeout_s`` (counted), and an idle flood below the
  cap never starves active requests.
- **SO_REUSEPORT sharding**: multi-loop (``shards=2``) and
  multi-server (``reuse_port=True`` on a shared port) fan-in both
  serve every request; gracefully skipped where the platform lacks
  ``SO_REUSEPORT``.

Everything here runs tiny models and sub-second timeouts — the 10k
connection number lives in ``bench.py --serving``, not tier-1.
"""

import http.client
import json
import socket
import threading
import time

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.frontend import FrontendServer
from bigdl_tpu.frontend.http1 import (CHUNK_TRAILER, ProtocolError,
                                      RequestParser, encode_chunk,
                                      render_head)
from bigdl_tpu.serving import ModelRegistry


def make_model(din=16, dout=4):
    return nn.Sequential(nn.Linear(din, 32), nn.ReLU(),
                         nn.Linear(32, dout), nn.SoftMax()).initialize(0)


SPEC16 = ((16,), np.float32)


def post(port, path, body, headers=None, timeout=60):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=body,
                     headers={"Content-Type": "application/json",
                              **(headers or {})})
        resp = conn.getresponse()
        return resp.status, dict(resp.getheaders()), resp.read()
    finally:
        conn.close()


def wait_until(pred, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() >= deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.005)


def req_bytes(path, obj, extra=None, version="HTTP/1.1"):
    """Serialize one POST request for raw-socket tests."""
    body = json.dumps(obj).encode()
    head = (f"POST {path} {version}\r\n"
            "Host: t\r\n"
            "Content-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\n"
            + "".join(f"{k}: {v}\r\n" for k, v in (extra or {}).items())
            + "\r\n")
    return head.encode("latin-1") + body


def read_response(sock, timeout=30.0):
    """Read one Content-Length-framed response off a raw socket."""
    sock.settimeout(timeout)
    buf = b""
    while b"\r\n\r\n" not in buf:
        d = sock.recv(4096)
        if not d:
            raise AssertionError(f"closed mid-head: {buf!r}")
        buf += d
    head, _, rest = buf.partition(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split()[1])
    hdrs = {}
    for ln in lines[1:]:
        k, _, v = ln.partition(":")
        hdrs[k.strip().lower()] = v.strip()
    n = int(hdrs.get("content-length", 0))
    while len(rest) < n:
        d = sock.recv(4096)
        if not d:
            break
        rest += d
    return status, hdrs, rest[:n], rest[n:]


# ===========================================================================
# incremental parser — pure unit tests, no sockets
# ===========================================================================
class TestHttp1Parser:
    REQ = req_bytes("/v1/models/clf/predict", {"inputs": [[1.0, 2.0]]},
                    extra={"X-Tenant": "acme"})

    def test_byte_dribble_completes_only_on_last_byte(self):
        p = RequestParser()
        for b in self.REQ[:-1]:
            p.feed(bytes([b]))
            assert p.poll() is None
        p.feed(self.REQ[-1:])
        req = p.poll()
        assert req is not None
        assert (req.method, req.target) == ("POST",
                                            "/v1/models/clf/predict")
        assert req.get("x-tenant") == "acme"
        assert json.loads(req.body)["inputs"] == [[1.0, 2.0]]
        assert req.keep_alive  # HTTP/1.1 default

    def test_head_ready_before_body_for_preflight_checks(self):
        body_start = self.REQ.index(b"\r\n\r\n") + 4
        p = RequestParser()
        p.feed(self.REQ[:body_start])
        head = p.head()
        assert head is not None and head.get("content-length")
        assert p.poll() is None  # body still outstanding
        p.feed(self.REQ[body_start:])
        assert p.poll() is not None

    def test_pipelined_requests_never_misframed(self):
        a = req_bytes("/a", {"inputs": [[1.0]]})
        b = req_bytes("/b", {"inputs": [[2.0, 3.0]]})
        p = RequestParser()
        p.feed(a + b)  # one TCP segment, two requests
        ra, rb = p.poll(), p.poll()
        assert ra.target == "/a" and rb.target == "/b"
        assert json.loads(rb.body)["inputs"] == [[2.0, 3.0]]
        assert p.poll() is None and p.buffered() == 0

    def test_stray_crlf_between_keepalive_requests_tolerated(self):
        p = RequestParser()
        p.feed(self.REQ + b"\r\n" + self.REQ)
        assert p.poll() is not None and p.poll() is not None

    def test_malformed_request_line_400_and_poisoned(self):
        p = RequestParser()
        p.feed(b"NOT A VALID LINE AT ALL\r\n\r\n")
        with pytest.raises(ProtocolError) as ei:
            p.poll()
        assert ei.value.status == 400
        with pytest.raises(ProtocolError):  # poisoned: no resync guess
            p.head()

    def test_whitespace_before_colon_refused(self):
        p = RequestParser()
        p.feed(b"GET / HTTP/1.1\r\nHost : t\r\n\r\n")
        with pytest.raises(ProtocolError) as ei:
            p.poll()
        assert ei.value.status == 400

    def test_unsupported_version_505(self):
        p = RequestParser()
        p.feed(b"GET / HTTP/2.0\r\n\r\n")
        with pytest.raises(ProtocolError) as ei:
            p.poll()
        assert ei.value.status == 505

    def test_oversized_head_431_even_without_terminator(self):
        p = RequestParser(max_head=128)
        p.feed(b"GET /" + b"a" * 200)  # no CRLFCRLF ever arrives
        with pytest.raises(ProtocolError) as ei:
            p.head()
        assert ei.value.status == 431

    def test_keep_alive_version_defaults(self):
        def ka(first_line, conn=None):
            p = RequestParser()
            h = f"Connection: {conn}\r\n" if conn else ""
            p.feed(f"{first_line}\r\n{h}\r\n".encode())
            return p.poll().keep_alive
        assert ka("GET / HTTP/1.1") is True
        assert ka("GET / HTTP/1.1", "close") is False
        assert ka("GET / HTTP/1.0") is False
        assert ka("GET / HTTP/1.0", "keep-alive") is True

    def test_obs_fold_continuation_joined(self):
        p = RequestParser()
        p.feed(b"GET / HTTP/1.1\r\nX-Long: part one\r\n  part two\r\n\r\n")
        assert p.poll().get("x-long") == "part one part two"

    def test_bogus_content_length_frames_zero_body(self):
        # framing survives; the 400 taxonomy is the exchange layer's job
        p = RequestParser()
        p.feed(b"POST / HTTP/1.1\r\nContent-Length: nope\r\n\r\n")
        req = p.poll()
        assert req is not None and req.body == b""

    def test_render_head_single_framing_mode(self):
        h = render_head(200, {"A": "b"}, content_length=3)
        assert b"Content-Length: 3\r\n" in h
        assert b"Transfer-Encoding" not in h
        h = render_head(200, chunked=True, close=True)
        assert b"Transfer-Encoding: chunked\r\n" in h
        assert b"Content-Length" not in h
        assert b"Connection: close\r\n" in h

    def test_chunk_encoding_roundtrip(self):
        assert encode_chunk(b"") == b""  # empty must not terminate
        assert encode_chunk(b"abc") == b"3\r\nabc\r\n"
        assert CHUNK_TRAILER == b"0\r\n\r\n"


# ===========================================================================
# slow-loris / partial-parse robustness — both cores
# ===========================================================================
@pytest.fixture(scope="module")
def stack():
    model = make_model()
    reg = ModelRegistry()
    svc = reg.deploy("clf", model, input_spec=SPEC16, max_batch_size=8,
                     batch_timeout_ms=2.0, queue_capacity=256)
    yield reg, svc, model
    reg.stop_all()


@pytest.fixture(scope="module", params=["eventloop", "threaded"])
def wire(request, stack):
    reg, svc, model = stack
    fe = FrontendServer(reg, port=0, core=request.param)
    fe.start()
    yield fe, svc, model
    fe.stop()


class TestSlowLorisBothCores:
    def _sock(self, fe):
        return socket.create_connection(("127.0.0.1", fe.port),
                                        timeout=30)

    def test_dribbled_request_line_does_not_block_other_clients(
            self, wire):
        fe, svc, model = wire
        raw = req_bytes("/v1/models/clf/predict",
                        {"inputs": rows(np.random.default_rng(1),
                                        1).tolist()})
        s = self._sock(fe)
        try:
            # park a half-open request line on the server ...
            for b in raw[:10]:
                s.sendall(bytes([b]))
            time.sleep(0.05)
            # ... other clients must be completely unaffected
            x = rows(np.random.default_rng(2), 2)
            t0 = time.monotonic()
            status, _, body = post(
                fe.port, "/v1/models/clf/predict",
                json.dumps({"inputs": x.tolist()}).encode())
            assert status == 200 and time.monotonic() - t0 < 10
            ref, _ = model.apply(svc.params, svc.state, x, training=False)
            np.testing.assert_array_equal(
                np.asarray(json.loads(body)["outputs"], np.float32),
                np.asarray(ref))
            # the parked client eventually finishes its dribble and is
            # served normally — parked, not punished
            s.sendall(raw[10:])
            status, _, out, _ = read_response(s)
            assert status == 200 and b"outputs" in out
        finally:
            s.close()

    def test_split_headers_across_segments(self, wire):
        fe, _svc, _model = wire
        raw = req_bytes("/v1/models/clf/predict",
                        {"inputs": rows(np.random.default_rng(3),
                                        1).tolist()})
        cut1 = raw.index(b"Content-Length") + 9  # mid-header-NAME
        cut2 = raw.index(b"\r\n\r\n") + 2  # mid-terminator
        s = self._sock(fe)
        try:
            for part in (raw[:cut1], raw[cut1:cut2], raw[cut2:]):
                s.sendall(part)
                time.sleep(0.05)
            status, _, out, _ = read_response(s)
            assert status == 200 and b"outputs" in out
        finally:
            s.close()

    def test_truncated_body_disconnect_leaves_server_healthy(
            self, wire):
        fe, _svc, _model = wire
        head = (b"POST /v1/models/clf/predict HTTP/1.1\r\n"
                b"Host: t\r\nContent-Type: application/json\r\n"
                b"Content-Length: 500\r\n\r\n")
        before = fe.metrics.counter("frontend/responses_5xx").value
        s = self._sock(fe)
        s.sendall(head + b'{"inputs": [[')  # 487 bytes never arrive
        time.sleep(0.05)
        s.close()
        x = rows(np.random.default_rng(4), 1)
        status, _, _body = post(fe.port, "/v1/models/clf/predict",
                                json.dumps({"inputs": x.tolist()}).encode())
        assert status == 200
        assert fe.metrics.counter("frontend/responses_5xx").value == before

    def test_keep_alive_pipelined_requests_both_served_in_order(
            self, wire):
        fe, svc, model = wire
        xa = rows(np.random.default_rng(5), 1)
        xb = rows(np.random.default_rng(6), 2)
        raw = (req_bytes("/v1/models/clf/predict",
                         {"inputs": xa.tolist()})
               + req_bytes("/v1/models/clf/predict",
                           {"inputs": xb.tolist()}))
        s = self._sock(fe)
        try:
            s.sendall(raw)  # both requests in one write
            sa, _, outa, extra = read_response(s)
            # hand any read-ahead bytes back for the second response
            sb, _, outb, _ = read_response(_Rewound(s, extra))
            assert sa == 200 and sb == 200
            # the back-to-back pair may coalesce into one dispatch —
            # allclose, not bitwise (GEMM shape differs from batch-1)
            ref_a, _ = model.apply(svc.params, svc.state, xa,
                                   training=False)
            ref_b, _ = model.apply(svc.params, svc.state, xb,
                                   training=False)
            np.testing.assert_allclose(
                np.asarray(json.loads(outa)["outputs"], np.float32),
                np.asarray(ref_a), rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(
                np.asarray(json.loads(outb)["outputs"], np.float32),
                np.asarray(ref_b), rtol=1e-5, atol=1e-6)
        finally:
            s.close()


class _Rewound:
    """Socket wrapper replaying read-ahead bytes before real recvs."""

    def __init__(self, sock, leftover):
        self._sock = sock
        self._pending = leftover

    def settimeout(self, t):
        self._sock.settimeout(t)

    def recv(self, n):
        if self._pending:
            out, self._pending = self._pending[:n], self._pending[n:]
            return out
        return self._sock.recv(n)


def rows(rng, n, din=16):
    return rng.normal(0, 1, (n, din)).astype(np.float32)


# ===========================================================================
# idle reaper + hard connection cap — both cores
# ===========================================================================
@pytest.fixture(scope="class", params=["eventloop", "threaded"])
def capped(request, stack):
    reg, svc, model = stack
    fe = FrontendServer(reg, port=0, core=request.param,
                        max_connections=4, idle_timeout_s=0.4)
    fe.start()
    yield fe, svc, model
    fe.stop()


class TestReaperAndCap:
    def test_cap_refuses_cheaply_then_recovers(self, capped):
        fe, _svc, _model = capped
        idles = [socket.create_connection(("127.0.0.1", fe.port),
                                          timeout=30) for _ in range(4)]
        try:
            wait_until(lambda: fe.open_connections == 4,
                       what="4 idle conns admitted")
            refused_before = fe.metrics.counter(
                "frontend/conns_refused").value
            over = socket.create_connection(("127.0.0.1", fe.port),
                                            timeout=30)
            over.settimeout(10)
            try:
                # past the cap: closed before any handler/exchange work
                assert over.recv(1) == b""
            except (ConnectionResetError, ConnectionAbortedError):
                pass
            finally:
                over.close()
            wait_until(lambda: fe.metrics.counter(
                "frontend/conns_refused").value > refused_before,
                what="refusal counted")
            # freeing one slot re-opens the door for active work
            idles.pop().close()
            wait_until(lambda: fe.open_connections <= 3,
                       what="slot released")
            x = rows(np.random.default_rng(7), 1)
            status, _, _b = post(
                fe.port, "/v1/models/clf/predict",
                json.dumps({"inputs": x.tolist()}).encode())
            assert status == 200
        finally:
            for s in idles:
                s.close()

    def test_idle_sockets_reaped_and_do_not_starve_active(self, capped):
        fe, svc, model = capped
        wait_until(lambda: fe.open_connections == 0,
                   what="previous test's conns drained")
        idles = [socket.create_connection(("127.0.0.1", fe.port),
                                          timeout=30) for _ in range(3)]
        try:
            wait_until(lambda: fe.open_connections == 3,
                       what="3 idle conns admitted")
            # active traffic flows with the idle flood parked (the
            # 10k-scale version of this is bench.py --serving)
            x = rows(np.random.default_rng(8), 2)
            for _ in range(3):
                # the previous post's server-side conn releases
                # asynchronously after the client close — wait for the
                # free slot or the cap (3 idle + 1 draining) refuses us
                wait_until(lambda: fe.open_connections <= 3,
                           what="active slot free under the cap")
                status, _, body = post(
                    fe.port, "/v1/models/clf/predict",
                    json.dumps({"inputs": x.tolist()}).encode())
                assert status == 200
            ref, _ = model.apply(svc.params, svc.state, x, training=False)
            np.testing.assert_array_equal(
                np.asarray(json.loads(body)["outputs"], np.float32),
                np.asarray(ref))
            # past idle_timeout_s the parked sockets are closed on us
            wait_until(lambda: fe.open_connections == 0, timeout=15,
                       what="idle conns reaped")
            for s in idles:
                s.settimeout(10)
                try:
                    assert s.recv(1) == b""
                except (ConnectionResetError, ConnectionAbortedError,
                        socket.timeout):
                    pass
            if fe.core == "eventloop":  # threaded reaps via rfile timeout
                assert fe.metrics.counter(
                    "frontend/conns_reaped").value >= 3
        finally:
            for s in idles:
                s.close()


# ===========================================================================
# SO_REUSEPORT sharding
# ===========================================================================
_HAS_REUSEPORT = hasattr(socket, "SO_REUSEPORT")


class TestSharding:
    def _hammer(self, port, svc, model, n=8):
        x = rows(np.random.default_rng(9), 1)
        ref, _ = model.apply(svc.params, svc.state, x, training=False)
        errs = []

        def one():
            try:
                status, _, body = post(
                    port, "/v1/models/clf/predict",
                    json.dumps({"inputs": x.tolist()}).encode())
                assert status == 200
                # concurrent requests coalesce into shared batches, so
                # GEMM shapes (and rounding) differ from the batch-1
                # reference — fan-in correctness here, bitwise parity
                # is test_frontend.py's single-dispatch gate
                np.testing.assert_allclose(
                    np.asarray(json.loads(body)["outputs"], np.float32),
                    np.asarray(ref), rtol=1e-5, atol=1e-6)
            except Exception as e:  # pragma: no cover - surfaced below
                errs.append(e)

        ts = [threading.Thread(target=one) for _ in range(n)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not errs, errs

    def test_multi_loop_shards_serve_all(self, stack):
        reg, svc, model = stack
        fe = FrontendServer(reg, port=0, core="eventloop", shards=2)
        fe.start()
        try:
            names = {t.name for t in threading.enumerate()}
            assert "bigdl-tpu-frontend-loop0" in names
            assert "bigdl-tpu-frontend-loop1" in names
            self._hammer(fe.port, svc, model)
        finally:
            fe.stop()
        # both loops joined on stop — no leaked threads
        names = {t.name for t in threading.enumerate()}
        assert "bigdl-tpu-frontend-loop0" not in names
        assert "bigdl-tpu-frontend-loop1" not in names

    @pytest.mark.skipif(not _HAS_REUSEPORT,
                        reason="platform lacks SO_REUSEPORT")
    def test_two_servers_share_one_port(self, stack):
        reg, svc, model = stack
        fe1 = FrontendServer(reg, port=0, core="eventloop",
                             reuse_port=True)
        fe1.start()
        fe2 = None
        try:
            fe2 = FrontendServer(reg, port=fe1.port, core="eventloop",
                                 reuse_port=True)
            fe2.start()
            assert fe2.port == fe1.port
            self._hammer(fe1.port, svc, model)
            # one shard going away must not brown out the port
            fe2.stop()
            fe2 = None
            self._hammer(fe1.port, svc, model, n=4)
        finally:
            if fe2 is not None:
                fe2.stop()
            fe1.stop()


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
