"""Learning-quality gates (VERDICT r2 Next #7): beyond 1-epoch smoke,
the zoo must actually LEARN.

- fast gate (always on): LeNet-5 reaches >=0.99 val top-1 in 3 epochs
  on the deterministic synthetic MNIST (the reference publishes >99%
  for real MNIST, ``DL/models/lenet``; the synthetic stand-in is
  template-based and equally separable).
- real-data gates (opt-in): point ``BIGDL_MNIST_DIR`` /
  ``BIGDL_CIFAR_DIR`` at the datasets to run the published-accuracy
  checks (LeNet >=0.99; ResNet-20 CIFAR-10 >=0.85 within a bounded
  epoch budget — the reference recipe reaches ~0.91 at full length,
  ``DL/models/resnet/README.md``).
"""

import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=1500):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, script), "--cpu", *args],
        capture_output=True, text=True, timeout=timeout, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    return r.stdout


def _final(out, key):
    for line in out.splitlines():
        if line.startswith("final:") and f"{key}=" in line:
            return float(line.split(f"{key}=")[1].split()[0])
    raise AssertionError(f"no final {key} in:\n{out[-2000:]}")


def test_lenet_synthetic_accuracy_gate():
    out = _run("examples/lenet/train.py", "-e", "3",
               "--synthetic-n", "4096", "-b", "128")
    assert _final(out, "val_top1") >= 0.99, out.splitlines()[-1]


@pytest.mark.skipif("BIGDL_MNIST_DIR" not in os.environ,
                    reason="set BIGDL_MNIST_DIR to run the real-MNIST "
                           "accuracy gate")
def test_lenet_real_mnist_gate():
    out = _run("examples/lenet/train.py", "-e", "5", "-b", "128",
               "-f", os.environ["BIGDL_MNIST_DIR"])
    assert _final(out, "val_top1") >= 0.99, out.splitlines()[-1]


@pytest.mark.skipif("BIGDL_CIFAR_DIR" not in os.environ,
                    reason="set BIGDL_CIFAR_DIR to run the real-CIFAR "
                           "accuracy gate (slow: ~30 epochs)")
def test_resnet20_real_cifar_gate():
    out = _run("examples/resnet/train_cifar10.py", "-e", "30",
               "-b", "128", "-f", os.environ["BIGDL_CIFAR_DIR"],
               timeout=14000)
    assert _final(out, "val_top1") >= 0.85, out.splitlines()[-1]
