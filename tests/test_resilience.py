"""bigdl_tpu.resilience — fault injection, self-healing serving,
numeric-failure recovery.

The load-bearing gates (ISSUE 10 acceptance):

- **Bitwise inertness** (K ∈ {1, 4}): with ``fault_plan=None`` no
  injector object exists and with ``numeric_guard`` live over all-finite
  training the loss sequence, dispatch count and final params are
  bitwise-identical to the default run; serving through a ``ReplicaSet``
  with no injector is bitwise-equal to direct ``model.apply``.
- **Self-healing**: a replica whose batcher thread is killed
  mid-traffic (real subprocess) is quarantined, its accepted requests
  fail over with zero losses and zero wrong answers, and it re-admits
  after probation — all visible in the ``resilience/*`` counters.
- **Numeric recovery**: ``skip`` gates the poisoned update away on
  device and training continues; ``rollback`` restores the latest
  valid snapshot; ``abort`` raises at the exact iteration.

Event-driven where possible (staged ``start=False`` services, injected
clocks for health/breaker state machines); the only polls are the ones
the production code itself documents as unavoidable (dead threads
cannot notify).
"""

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.dataset.prefetch import (DeviceBlockStager,
                                        MTSampleToMiniBatch)
from bigdl_tpu.resilience import (CircuitBreaker, FaultInjector,
                                  HealthPolicy, NonFiniteStepError,
                                  ReplicaHealth, ReplicaSet,
                                  parse_fault_plan)
from bigdl_tpu.resilience.faults import (InjectedFault,
                                         ReplicaDeathFault)
from bigdl_tpu.resilience.health import (ADMIT, PROBE, REFUSE,
                                         DEGRADED, HEALTHY, QUARANTINED)
from bigdl_tpu.serving import (DeadlineExceeded, InferenceService,
                               ModelRegistry, ServiceOverloaded)
from bigdl_tpu.telemetry.registry import MetricRegistry
from bigdl_tpu.utils.config import configure, reset_config

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
CHILD = os.path.join(HERE, "resil_serve_child.py")


def make_model(din=16, dout=4):
    return nn.Sequential(nn.Linear(din, 32), nn.ReLU(),
                         nn.Linear(32, dout), nn.SoftMax()).initialize(0)


SPEC16 = ((16,), np.float32)


def rows(rng, n, din=16):
    return rng.normal(0, 1, (n, din)).astype(np.float32)


@pytest.fixture(autouse=True)
def _clean_config():
    reset_config()
    yield
    reset_config()


# ===========================================================================
class TestFaultPlanGrammar:
    def test_full_grammar_parses_and_describes(self):
        plan = ("dispatch_error@at=3,target=1;"
                "dispatch_delay@ms=5.0,every=2,where=driver;"
                "replica_death@after=10,count=1;"
                "corrupt_batch@at=7;nonfinite_grads@p=0.5,until=20")
        clauses = parse_fault_plan(plan)
        assert [c.kind for c in clauses] == [
            "dispatch_error", "dispatch_delay", "replica_death",
            "corrupt_batch", "nonfinite_grads"]
        assert clauses[0].at == 3 and clauses[0].target == 1
        assert clauses[1].ms == 5.0 and clauses[1].where == "driver"
        assert clauses[2].after == 10 and clauses[2].count == 1
        # batch kinds always live in the driver
        assert clauses[3].where == "driver"
        # describe() round-trips through the parser
        redesc = parse_fault_plan(
            "; ".join(c.describe() for c in clauses))
        assert [c.describe() for c in redesc] == \
            [c.describe() for c in clauses]

    def test_empty_and_whitespace_plans_are_no_clauses(self):
        assert parse_fault_plan("") == []
        assert parse_fault_plan("  ;  ; ") == []

    @pytest.mark.parametrize("plan", [
        "exploding_gradient_storm",          # unknown kind
        "dispatch_error@frequency=3",        # unknown key
        "dispatch_error@at",                 # missing =
        "dispatch_error@p=1.5",              # p out of range
        "dispatch_error@where=everywhere",   # bad where
        "dispatch_delay@every=0",            # every < 1
    ])
    def test_malformed_plans_fail_loudly(self, plan):
        with pytest.raises(ValueError):
            parse_fault_plan(plan)

    def test_from_config_returns_none_for_empty_plan(self):
        # the provably-inert state: no injector OBJECT exists, so every
        # call site's `injector is not None` guard keeps the disabled
        # path byte-identical
        assert FaultInjector.from_config() is None
        configure(fault_plan="dispatch_error@at=0")
        try:
            inj = FaultInjector.from_config()
            assert inj is not None and len(inj.clauses) == 1
        finally:
            reset_config()

    def test_windows_and_budget(self):
        inj = FaultInjector("dispatch_error@after=2,until=5,count=2,"
                            "where=driver")
        fired = []
        for i in range(8):
            try:
                inj.driver_dispatch(i)
            except InjectedFault:
                fired.append(i)
        # window [2, 5) admits 2,3,4; the count=2 budget stops at two
        assert fired == [2, 3]

    def test_target_scoping(self):
        inj = FaultInjector("dispatch_error@target=1")
        inj.serving_dispatch(0, replica=0)  # wrong replica: no fire
        with pytest.raises(InjectedFault):
            inj.serving_dispatch(0, replica=1)

    def test_probabilistic_clause_is_deterministic(self):
        plan = "dispatch_error@p=0.5,where=driver"

        def firing_set(seed):
            inj = FaultInjector(plan, seed=seed)
            out = set()
            for i in range(64):
                try:
                    inj.driver_dispatch(i)
                except InjectedFault:
                    out.add(i)
            return out

        a, b = firing_set(7), firing_set(7)
        assert a == b                       # replayable
        assert 8 < len(a) < 56              # actually probabilistic
        assert firing_set(8) != a           # seed matters

    def test_replica_death_is_base_exception(self):
        # must ESCAPE the dispatch error handler (Exception-scoped) so
        # it strands futures exactly like a real thread crash
        assert not issubclass(ReplicaDeathFault, Exception)
        inj = FaultInjector("replica_death@at=0")
        with pytest.raises(ReplicaDeathFault):
            inj.serving_dispatch(0, replica=None)

    def test_registry_counts_injected_faults(self):
        reg = MetricRegistry()
        inj = FaultInjector("dispatch_delay@ms=0.1,count=2",
                            registry=reg)
        for i in range(4):
            inj.serving_dispatch(i)
        assert reg.counter(
            "resilience/fault_dispatch_delay").value == 2


# ===========================================================================
class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class TestReplicaHealth:
    def test_degrade_and_recover(self):
        clock = _Clock()
        h = ReplicaHealth(0, HealthPolicy(), clock=clock)
        assert h.state == HEALTHY
        h.record_failure()
        assert h.state == DEGRADED
        h.record_success()
        assert h.state == HEALTHY

    def test_quarantine_probe_readmit_cycle(self):
        clock = _Clock()
        reg = MetricRegistry()
        h = ReplicaHealth(0, HealthPolicy(probe_backoff_s=1.0,
                                          probe_jitter=0.0),
                          registry=reg, clock=clock)
        for _ in range(3):
            h.record_failure()
        assert h.state == QUARANTINED
        assert h.admit() == REFUSE          # probation not yet due
        clock.t = 1.5
        assert h.admit() == PROBE           # exactly one probe
        assert h.admit() == REFUSE          # while the probe is in flight
        h.record_success(probe=True)
        assert h.state == HEALTHY
        assert h.admit() == ADMIT
        assert reg.counter("resilience/quarantines").value == 1
        assert reg.counter("resilience/probes").value == 1
        assert reg.counter("resilience/readmissions").value == 1

    def test_failed_probe_doubles_backoff(self):
        clock = _Clock()
        h = ReplicaHealth(0, HealthPolicy(probe_backoff_s=1.0,
                                          probe_jitter=0.0),
                          clock=clock)
        h.mark_dead()
        assert h.state == QUARANTINED
        first_wait = h.next_probe_in()
        assert first_wait == pytest.approx(1.0)
        clock.t = 1.0
        assert h.admit() == PROBE
        h.record_failure(probe=True)
        # the next window uses the doubled backoff
        assert h.next_probe_in() == pytest.approx(2.0)
        # a probe success resets the ladder
        clock.t = 3.0
        assert h.admit() == PROBE
        h.record_success(probe=True)
        h.mark_dead()
        assert h.next_probe_in() == pytest.approx(1.0)

    def test_jitter_is_deterministic_per_replica(self):
        mk = lambda ix: ReplicaHealth(  # noqa: E731
            ix, HealthPolicy(probe_backoff_s=1.0, probe_jitter=0.5,
                             seed=3), clock=_Clock())
        a, b, c = mk(0), mk(0), mk(1)
        for h in (a, b, c):
            h.mark_dead()
        assert a.next_probe_in() == b.next_probe_in()   # replayable
        assert a.next_probe_in() != c.next_probe_in()   # decorrelated

    def test_stale_nonprobe_success_does_not_readmit(self):
        clock = _Clock()
        h = ReplicaHealth(0, HealthPolicy(), clock=clock)
        h.mark_dead()
        h.record_success(probe=False)  # late completion from pre-death
        assert h.state == QUARANTINED

    def test_stale_nonprobe_failures_do_not_inflate_backoff(self):
        # regression: a wedge with N requests in flight drains N stale
        # failures into the quarantined replica; they must not
        # reschedule the probe window or double the backoff — one
        # incident is one piece of evidence
        clock = _Clock()
        h = ReplicaHealth(0, HealthPolicy(probe_backoff_s=0.5,
                                          probe_jitter=0.0),
                          clock=clock)
        h.mark_dead()
        first = h.next_probe_in()
        for _ in range(8):
            h.record_failure(probe=False)  # stranded-request drain
        assert h.next_probe_in() == pytest.approx(first)
        clock.t = first
        assert h.admit() == PROBE  # probation unchanged at 0.5s


class TestCircuitBreaker:
    def test_trip_halfopen_retrip_close(self):
        clock = _Clock()
        reg = MetricRegistry()
        brk = CircuitBreaker(trip_after=3, cooldown_s=10.0,
                             registry=reg, clock=clock)
        for _ in range(2):
            brk.record_failure()
        assert brk.allow()
        brk.record_failure()                 # third: trips
        assert not brk.allow()
        assert reg.counter("resilience/breaker_trips").value == 1
        clock.t = 10.0
        assert brk.allow()                   # half-open
        brk.record_failure()                 # failed trial: re-trip,
        assert not brk.allow()               # cooldown doubled
        clock.t = 25.0
        assert not brk.allow()               # 20s cooldown from t=10
        clock.t = 30.0
        assert brk.allow()
        brk.record_success()                 # closes + resets
        assert brk.allow()
        assert brk.snapshot()["cooldown_s"] == 10.0

    def test_overload_is_not_a_poison_signal(self):
        # contract: ModelRegistry must NOT record ServiceOverloaded /
        # ServiceClosed outcomes into the breaker
        reg = ModelRegistry(breaker_trip_after=1)
        svc_outcomes = reg.record_outcome
        brk = CircuitBreaker(trip_after=1)
        svc_outcomes(brk, ServiceOverloaded(5, 5, "m"))
        assert brk.allow()
        svc_outcomes(brk, RuntimeError("boom"))
        assert not brk.allow()


class TestRegistryBreakerFallback:
    def _registry_with_two_versions(self):
        metrics = MetricRegistry()
        reg = ModelRegistry(breaker_trip_after=2,
                            breaker_cooldown_s=3600.0, registry=metrics)
        model = make_model()
        reg.deploy("m", model, version=1, input_spec=SPEC16,
                   max_batch_size=4)
        reg.deploy("m", model, version=2, input_spec=SPEC16,
                   max_batch_size=4)
        return reg, metrics

    def test_poisoned_latest_falls_back_to_previous(self):
        reg, metrics = self._registry_with_two_versions()
        rng = np.random.default_rng(0)
        x = rows(rng, 2)
        v2 = reg.get("m", 2)
        expected = np.asarray(reg.get("m", 1).predict(x, timeout=60))
        # poison v2: every request dies at its future
        poisoned = lambda *a, **k: (_ for _ in ()).throw(  # noqa: E731
            RuntimeError("poisoned deploy"))
        v2.predict = poisoned
        for _ in range(2):
            with pytest.raises(RuntimeError):
                reg.predict("m", x, timeout=60)
        assert reg.breaker_state("m", 2)["open"]
        # latest-wins now routes around the tripped version
        out = reg.predict("m", x, timeout=60)
        np.testing.assert_array_equal(np.asarray(out), expected)
        assert metrics.counter(
            "resilience/breaker_fallbacks").value >= 1
        # pinned requests bypass the breaker: the caller asked for v2,
        # they get its errors
        with pytest.raises(RuntimeError):
            reg.predict("m", x, version=2, timeout=60)
        reg.stop_all()

    def test_cancelled_future_is_no_breaker_outcome(self):
        # regression: a cancelled submit() future used to record a
        # breaker SUCCESS, resetting a poisoned deploy's failure streak
        reg, _ = self._registry_with_two_versions()
        brk = reg._breakers[("m", 2)]
        brk.record_failure()
        fut = reg.submit("m", rows(np.random.default_rng(2), 1),
                         version=2)
        fut.cancel()  # may or may not win vs the batcher — both legal
        time.sleep(0.05)  # let the done-callback run
        if fut.cancelled():
            assert brk.snapshot()["consecutive_failures"] == 1
        reg.stop_all()

    def test_all_breakers_open_serves_newest_anyway(self):
        reg, _ = self._registry_with_two_versions()
        rng = np.random.default_rng(1)
        x = rows(rng, 1)
        for v in (1, 2):
            brk = reg._breakers[("m", v)]
            brk.record_failure()
            brk.record_failure()
            assert not brk.allow()
        # serving a maybe-poisoned model beats serving nothing
        out = reg.predict("m", x, timeout=60)
        assert np.asarray(out).shape == (1, 4)
        reg.stop_all()


# ===========================================================================
class TestDeadlines:
    def test_expired_before_submit_never_queues(self):
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=4, start=False)
        fut = svc.submit(rows(np.random.default_rng(0), 1),
                         deadline=time.monotonic() - 0.1)
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=5)
        assert svc.queue_depth() == 0
        svc.stop()

    def test_expired_in_queue_refused_before_device_call(self):
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=4, start=False)
        rng = np.random.default_rng(0)
        doomed = svc.submit(rows(rng, 1),
                            deadline=time.monotonic() + 0.05)
        alive = svc.submit(rows(rng, 1))
        time.sleep(0.1)  # the staged queue lets the deadline lapse
        svc.start()
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=10)
        assert np.asarray(alive.result(timeout=10)).shape == (1, 4)
        svc.stop()


class TestRetryAfterHint:
    def test_overloaded_carries_drain_estimate(self):
        svc = InferenceService(make_model(), input_spec=SPEC16,
                               max_batch_size=4, queue_capacity=2,
                               start=False)
        rng = np.random.default_rng(0)
        # no dispatch observed yet: the hint is honestly None
        svc.submit(rows(rng, 1))
        svc.submit(rows(rng, 1))
        with pytest.raises(ServiceOverloaded) as ei:
            svc.submit(rows(rng, 1))
        assert ei.value.retry_after_ms is None
        svc.start()
        svc.predict(rows(rng, 1), timeout=60)  # establishes a rate
        svc.stop()
        # the drain-rate EWMA now yields a bounded positive hint
        hint = svc._batcher.retry_after_ms(depth=4)
        assert hint is not None and 1.0 <= hint <= 10_000.0

    def test_prediction_service_shim_retries_once(self, monkeypatch):
        from bigdl_tpu.optim.predictor import PredictionService
        shim = PredictionService(make_model(), batch_size=4)
        x = np.ones((1, 16), np.float32)
        expected = shim.predict(x)
        calls = []
        real_predict = shim.service.predict

        def flaky(arr, timeout=None):
            calls.append(1)
            if len(calls) == 1:
                raise ServiceOverloaded(4, 4, "m", retry_after_ms=1.0)
            return real_predict(arr, timeout=timeout)

        monkeypatch.setattr(shim.service, "predict", flaky)
        out = shim.predict(x)  # transient overload absorbed
        np.testing.assert_array_equal(np.asarray(out),
                                      np.asarray(expected))
        assert len(calls) == 2

        def always_full(arr, timeout=None):
            raise ServiceOverloaded(4, 4, "m", retry_after_ms=1.0)

        monkeypatch.setattr(shim.service, "predict", always_full)
        with pytest.raises(ServiceOverloaded):
            shim.predict(x)  # sustained overload is still felt upstream
        shim.service.stop()


# ===========================================================================
class TestReplicaSet:
    def _set(self, **kw):
        kw.setdefault("n_replicas", 2)
        kw.setdefault("input_spec", SPEC16)
        kw.setdefault("max_batch_size", 4)
        kw.setdefault("name", "rs")
        return ReplicaSet(make_model(), **kw)

    def test_least_queue_depth_routing(self):
        rs = self._set(start=False)  # staged: queues grow, none drain
        rng = np.random.default_rng(0)
        futs = [rs.submit(rows(rng, 1)) for _ in range(4)]
        # 4 staged single-row submits alternate 0,1,0,1 (shallowest
        # queue, ties to the lowest index)
        assert [s.queue_depth() for s in rs._replicas] == [2, 2]
        rs.start()
        for f in futs:
            assert np.asarray(f.result(timeout=30)).shape == (1, 4)
        rs.stop()

    def test_failover_on_injected_dispatch_error(self):
        reg = MetricRegistry()
        rs = self._set(
            fault_injector=FaultInjector("dispatch_error@target=0"),
            registry=reg, max_retries=2)
        rng = np.random.default_rng(0)
        x = rows(rng, 1)
        direct, _ = rs._replicas[1].model.apply(
            rs._replicas[1].params, rs._replicas[1].state, x,
            training=False)
        # replica 0 fails EVERY dispatch; the router must land every
        # request on replica 1 (first attempts that picked 0 fail over)
        outs = [np.asarray(rs.predict(x, timeout=30)) for _ in range(6)]
        for out in outs:
            np.testing.assert_array_equal(out, np.asarray(direct))
        snap = reg.snapshot()["counters"]
        assert snap["resilience/failovers"] >= 1
        # replica 0's failures eventually quarantine it
        assert rs.health_states()[0] in (DEGRADED, QUARANTINED)
        rs.stop()

    def test_all_quarantined_sheds_with_probation_hint(self):
        rs = self._set(health=HealthPolicy(probe_backoff_s=30.0))
        for h in rs._health:
            h.mark_dead()
        with pytest.raises(ServiceOverloaded) as ei:
            rs.submit(rows(np.random.default_rng(0), 1))
        # the retry-after hint is the next probation window
        assert ei.value.retry_after_ms is not None
        assert ei.value.retry_after_ms > 1000.0
        assert rs.stats()["resilience"]["resilience/sheds"] == 1
        rs.stop()

    def test_deadline_default_resolves_through_engine_chain(self):
        # serving_deadline_ms rides the same explicit > env > tuned >
        # default chain as the other serving knobs
        configure(serving_deadline_ms=75.0)
        try:
            rs = self._set(start=False)
            assert rs.deadline_s == pytest.approx(0.075)
            rs.stop(drain=False)
            rs2 = self._set(start=False, deadline_ms=10.0)  # explicit wins
            assert rs2.deadline_s == pytest.approx(0.010)
            rs2.stop(drain=False)
        finally:
            reset_config()

    def test_supervisor_times_out_wedged_request(self):
        # staged replicas never dispatch — only the outside supervisor
        # can resolve the stuck request, via the propagated deadline
        rs = self._set(start=False, deadline_ms=50.0, max_retries=0)
        fut = rs.submit(np.ones((1, 16), np.float32))
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=10)
        snap = rs.stats()["resilience"]
        assert snap["resilience/deadline_timeouts"] >= 1
        # a parked batcher made NO progress since the deadline: that is
        # wedge evidence, so the replica's health must have recorded it
        assert rs._health[0].state != HEALTHY
        rs.stop(drain=False)


class TestReplicaSetReviewRegressions:
    """Post-review hardening gates (PR-10 code review)."""

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_routing_path_death_sweeps_stranded_running_requests(self):
        """Regression (found by the obs-plane PR's deadline-less load):
        a request mid-dispatch at replica death is marked RUNNING, so
        revive's backlog cancellation can't touch it — and if the
        ROUTING path revived the replica before the supervisor's next
        liveness poll, ``svc.alive`` read True again and the stranded
        request hung until its deadline (forever, with none).  The
        death handler now sweeps the dead replica's inflight entries
        itself.  Supervisor disabled here so only that sweep can
        rescue the victim."""
        rs = ReplicaSet(
            make_model(), n_replicas=2, input_spec=SPEC16,
            max_batch_size=4, batch_timeout_ms=0.0, deadline_ms=0,
            fault_injector=FaultInjector("replica_death@target=0,at=0",
                                         seed=0),
            name="stranded",
            health=HealthPolicy(probe_backoff_s=30.0))
        # no supervisor: the poll must not be what rescues the victim
        rs._ensure_supervisor_locked = lambda: None
        x = rows(np.random.default_rng(0), 1)
        victim = rs.submit(x)  # routed to r0, dies mid-dispatch
        deadline = time.monotonic() + 5.0
        while rs.replica(0).alive and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not rs.replica(0).alive, "death fault never fired"
        assert not victim.done()  # stranded: RUNNING on a dead batcher
        # the next routed request spots the dead batcher — the handler
        # must revive AND fail the victim over, not just revive
        other = rs.submit(x)
        np.testing.assert_allclose(np.asarray(other.result(10.0)),
                                   np.asarray(victim.result(10.0)))
        assert rs.stats()["resilience"]["resilience/failovers"] >= 1
        rs.stop()

    def test_both_quarantined_replicas_readmit(self):
        # regression: _pick used to consume EVERY due replica's one
        # probation-probe slot while dispatching only one, leaking
        # _probe_inflight on the rest — the leaked replicas refused
        # probes forever and could never re-admit
        rs = ReplicaSet(make_model(), n_replicas=2, input_spec=SPEC16,
                        max_batch_size=4, name="both-quar",
                        health=HealthPolicy(probe_backoff_s=0.05))
        for h in rs._health:
            h.mark_dead()
        x = rows(np.random.default_rng(0), 1)
        deadline = time.monotonic() + 20.0
        while rs.health_states() != [HEALTHY, HEALTHY]:
            assert time.monotonic() < deadline, (
                f"stuck at {rs.health_states()} — probe slot leaked")
            try:
                rs.predict(x, timeout=5.0)
            except ServiceOverloaded:
                time.sleep(0.02)  # before both probation windows open
        assert rs.stats()["resilience"]["resilience/readmissions"] == 2
        rs.stop()

    def test_congestion_deadline_is_not_a_health_failure(self):
        # regression: a batcher-refused queue expiry (pure congestion)
        # used to count against replica health, so a deadline storm
        # under overload could cascade-quarantine healthy replicas.
        # Only the supervisor's wedged-tagged timeout is evidence.
        rs = ReplicaSet(make_model(), n_replicas=1, input_spec=SPEC16,
                        max_batch_size=4, name="congest")
        from concurrent.futures import Future
        from bigdl_tpu.resilience.replica_set import _Route
        inner = Future()
        inner.set_exception(DeadlineExceeded("expired in queue"))
        r = _Route(None, Future(), None, 0)
        rs._inflight[1] = (r, 0, inner, False)
        rs._on_done(1)
        assert rs._health[0].state == HEALTHY  # congestion: no penalty
        wedged_exc = DeadlineExceeded("supervisor timeout")
        wedged_exc.wedged = True
        inner2 = Future()
        inner2.set_exception(wedged_exc)
        r2 = _Route(None, Future(), None, 0)
        rs._inflight[2] = (r2, 0, inner2, False)
        rs._on_done(2)
        assert rs._health[0].state == DEGRADED  # wedged: evidence
        assert rs.stats()["resilience"][
            "resilience/deadline_timeouts"] == 2
        rs.stop(drain=False)

    def test_exhausted_replicas_surface_real_error_not_shed(self):
        # regression: when every replica had been tried with retry
        # budget left, the request's REAL failure was replaced by a
        # fabricated ServiceOverloaded ("queue full") and counted as a
        # shed — a deterministic dispatch bug diagnosed as overload
        rs = ReplicaSet(make_model(), n_replicas=2, input_spec=SPEC16,
                        max_batch_size=4, name="exhaust",
                        max_retries=3,
                        fault_injector=FaultInjector("dispatch_error"))
        with pytest.raises(InjectedFault):  # the actual failure class
            rs.predict(rows(np.random.default_rng(0), 1), timeout=30)
        assert rs.stats()["resilience"]["resilience/sheds"] == 0
        rs.stop()

    def test_caller_bug_on_probe_does_not_extend_quarantine(self):
        # regression: a malformed request that happened to be a
        # quarantined replica's probation probe was recorded as a probe
        # FAILURE, doubling its backoff — the replica never saw it
        rs = ReplicaSet(make_model(), n_replicas=1, input_spec=SPEC16,
                        max_batch_size=4, name="callerbug",
                        health=HealthPolicy(probe_backoff_s=0.01))
        rs._health[0].mark_dead()
        time.sleep(0.05)  # probation window opens
        too_big = rows(np.random.default_rng(0), 9)  # > max_batch_size
        with pytest.raises(ValueError):
            rs.submit(too_big)
        # the probe slot was released without an outcome: the replica
        # is immediately probe-able again and a well-formed request
        # re-admits it
        out = rs.predict(rows(np.random.default_rng(1), 1), timeout=30)
        assert np.asarray(out).shape == (1, 4)
        assert rs.health_states() == [HEALTHY]
        rs.stop()

    def test_fault_plan_change_between_runs_is_honored(self):
        # regression: the FaultInjector was cached on the optimizer
        # forever, so clearing (or changing) Config.fault_plan between
        # optimize() calls on the same object was silently ignored
        configure(fault_plan="dispatch_delay@ms=0.1,count=1")
        try:
            losses, opt, _ = tiny_run(iters=4)
            assert opt._fault_injector is not None
            configure(fault_plan="")
            opt.set_end_when(optim.max_iteration(8)).optimize()
            assert opt._fault_injector is None  # honored: back to inert
        finally:
            reset_config()

    def test_predict_wait_timeout_normalized_to_deadline_exceeded(self):
        # regression: on py<3.11 the result-wait expiry raised
        # concurrent.futures.TimeoutError (NOT builtin TimeoutError),
        # slipping past callers' deadline handling
        rs = ReplicaSet(make_model(), n_replicas=1, input_spec=SPEC16,
                        max_batch_size=4, name="wait", start=False)
        with pytest.raises(DeadlineExceeded):
            rs.predict(rows(np.random.default_rng(0), 1), timeout=0.1)
        rs.stop(drain=False)


class TestReplicaDeathSubprocess:
    """The ISSUE-10 acceptance gate, in a REAL subprocess: kill one
    replica's batcher mid-traffic; zero lost, zero wrong, quarantine
    and readmission all present in the metrics."""

    def test_kill_quarantine_failover_readmit(self):
        env = dict(os.environ)
        env["PYTHONPATH"] = (REPO + os.pathsep + env.get("PYTHONPATH", "")
                             ).rstrip(os.pathsep)
        proc = subprocess.run(
            [sys.executable, CHILD], env=env, capture_output=True,
            text=True, timeout=300)
        assert proc.returncode == 0, proc.stderr[-2000:]
        report = json.loads(proc.stdout.strip().splitlines()[-1])
        counts = report["counts"]
        assert report["lost"] == 0
        assert counts["wrong"] == 0
        assert counts["ok"] > 100          # real traffic flowed
        assert report["saw_quarantine"]    # the death was visible
        res = report["resilience"]
        assert res["resilience/replica_deaths"] == 1
        assert res["resilience/quarantines"] == 1
        assert res["resilience/revivals"] == 1
        assert res["resilience/readmissions"] == 1  # probation worked
        assert res["resilience/failovers"] >= 1     # stranded work moved
        # the killed replica is back in rotation by the end
        assert report["final_health"] == ["healthy"] * 4


# ===========================================================================
class RecordingSummary:
    def __init__(self):
        self.losses = []

    def add_train_step(self, step, loss, lr, throughput):
        self.losses.append(loss)

    def add_scalar(self, *a):
        pass

    def trigger_for(self, name):
        return None


def tiny_run(iters=6, k=1, guard=None, plan=None, ckpt=None, seed=7):
    if plan is not None:
        configure(fault_plan=plan)
    try:
        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(0, 1, (16,)).astype(np.float32),
                          np.int32(rng.integers(0, 4)))
                   for _ in range(64)]
        model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(),
                              nn.Linear(16, 4), nn.LogSoftMax())
        rec = RecordingSummary()
        opt = (optim.LocalOptimizer(model,
                                    DataSet.array(samples)
                                    >> SampleToMiniBatch(16),
                                    nn.ClassNLLCriterion())
               .set_optim_method(optim.SGD(learning_rate=0.1))
               .set_seed(seed)
               .set_train_summary(rec)
               .set_steps_per_dispatch(k)
               .set_end_when(optim.max_iteration(iters)))
        if guard is not None:
            opt.set_numeric_guard(guard)
        if ckpt is not None:
            opt.set_checkpoint(ckpt, optim.several_iteration(1))
        opt.optimize()
        return np.asarray(rec.losses), opt, model
    finally:
        if plan is not None:
            reset_config()


class TestNumericGuard:
    def test_skip_gates_update_and_continues(self):
        losses, opt, model = tiny_run(guard="skip",
                                      plan="nonfinite_grads@at=2")
        assert len(losses) == 6
        assert not np.isfinite(losses[2])       # the poison was real
        assert np.isfinite(losses[3:]).all()    # training recovered
        snap = opt.metrics.registry.snapshot()["counters"]
        assert snap["resilience/steps_skipped"] == 1
        assert snap["resilience/nonfinite_steps"] == 1
        for leaf in jax_leaves(model._params):
            assert np.isfinite(np.asarray(leaf)).all()

    def test_skip_leaves_state_as_if_step_never_ran(self):
        # a poisoned FIRST step under skip must land exactly where a
        # run that never saw the poison landed after its first step:
        # losses from step 1 on are bitwise-identical because params
        # after the skipped step are bitwise the init params
        clean, _, _ = tiny_run(iters=5)
        poisoned, _, _ = tiny_run(iters=6, guard="skip",
                                  plan="corrupt_batch@at=0")
        # step j of the clean run sees the SAME params as step j+1 of
        # the poisoned run but a different batch, so compare the states
        # we can pin bitwise: the skipped step's loss is non-finite and
        # every later loss is finite
        assert not np.isfinite(poisoned[0])
        assert np.isfinite(poisoned[1:]).all()

    def test_abort_raises_at_exact_iteration(self):
        with pytest.raises(NonFiniteStepError) as ei:
            tiny_run(guard="abort", plan="corrupt_batch@at=3")
        assert ei.value.step == 3
        assert ei.value.policy == "abort"

    def test_abort_at_exact_iteration_fused_k4(self):
        # the poisoned step sits mid-block: the replay must still name
        # iteration 5, not the block boundary
        with pytest.raises(NonFiniteStepError) as ei:
            tiny_run(k=4, guard="abort", plan="nonfinite_grads@at=5",
                     iters=8)
        assert ei.value.step == 5

    def test_rollback_restores_latest_valid_and_completes(self):
        with tempfile.TemporaryDirectory() as d:
            losses, opt, _ = tiny_run(
                guard="rollback", plan="nonfinite_grads@at=4,count=1",
                ckpt=d)
        assert len(losses) == 6
        assert np.isfinite(losses).all()   # the re-run step was clean
        snap = opt.metrics.registry.snapshot()["counters"]
        assert snap["resilience/rollbacks"] == 1
        assert snap["resilience/nonfinite_steps"] == 1

    def test_rollback_without_checkpoint_refused_loudly(self):
        with pytest.raises(ValueError, match="rollback"):
            tiny_run(guard="rollback")

    def test_bad_policy_refused_loudly(self):
        model = nn.Sequential(nn.Linear(4, 2))
        opt = optim.LocalOptimizer(
            model, DataSet.array(
                [Sample(np.zeros(4, np.float32), np.int32(0))])
            >> SampleToMiniBatch(1), nn.ClassNLLCriterion())
        with pytest.raises(ValueError, match="numeric_guard"):
            opt.set_numeric_guard("explode")

    def test_env_policy_resolution_and_explicit_none_override(self):
        configure(numeric_guard="skip")
        try:
            model = nn.Sequential(nn.Linear(4, 2))
            opt = optim.LocalOptimizer(
                model, DataSet.array(
                    [Sample(np.zeros(4, np.float32), np.int32(0))])
                >> SampleToMiniBatch(1), nn.ClassNLLCriterion())
            assert opt._resolved_numeric_guard() == "skip"
            # explicit None IS the inert policy, not "unset"
            opt.set_numeric_guard(None)
            assert opt._resolved_numeric_guard() == "off"
        finally:
            reset_config()


def jax_leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)


def distri_run(iters=6, k=1, guard=None, plan=None):
    if plan is not None:
        configure(fault_plan=plan)
    try:
        rng = np.random.default_rng(0)
        samples = [Sample(rng.normal(0, 1, (16,)).astype(np.float32),
                          np.int32(rng.integers(0, 4)))
                   for _ in range(128)]
        model = nn.Sequential(nn.Linear(16, 16), nn.ReLU(),
                              nn.Linear(16, 4), nn.LogSoftMax())
        rec = RecordingSummary()
        opt = (optim.DistriOptimizer(model,
                                     DataSet.array(samples)
                                     >> SampleToMiniBatch(64),
                                     nn.ClassNLLCriterion())
               .set_optim_method(optim.SGD(learning_rate=0.1))
               .set_seed(7)
               .set_train_summary(rec)
               .set_steps_per_dispatch(k)
               .set_end_when(optim.max_iteration(iters)))
        if guard is not None:
            opt.set_numeric_guard(guard)
        opt.optimize()
        return np.asarray(rec.losses), opt
    finally:
        if plan is not None:
            reset_config()


class TestNumericGuardDistri:
    """The SPMD half of the guard: the finite verdict is a mesh-global
    ``pmin`` so every chip gates its owned ZeRO-1 slice identically."""

    def test_skip_all_finite_bitwise_inert_on_mesh(self):
        base, _ = distri_run()
        skip, _ = distri_run(guard="skip")
        np.testing.assert_array_equal(base, skip)

    def test_skip_poisoned_step_fused_k4(self):
        losses, opt = distri_run(k=4, guard="skip", iters=8,
                                 plan="nonfinite_grads@at=3")
        assert not np.isfinite(losses[3])
        assert np.isfinite(losses[4:]).all()
        snap = opt.metrics.registry.snapshot()["counters"]
        assert snap["resilience/steps_skipped"] == 1


# ===========================================================================
class TestInertness:
    """The ISSUE-10 acceptance gate: with ``fault_plan=None`` no
    injector exists and the numeric guard over all-finite training
    changes NOTHING — bitwise loss sequences, equal dispatch counts,
    bitwise final params, serving bitwise-equal to direct apply."""

    @pytest.mark.parametrize("k", [1, 4])
    def test_numeric_guard_all_finite_bitwise_inert(self, k):
        base_l, base_o, base_m = tiny_run(iters=8, k=k)
        skip_l, skip_o, skip_m = tiny_run(iters=8, k=k, guard="skip")
        np.testing.assert_array_equal(base_l, skip_l)
        assert base_o._dispatch_count == skip_o._dispatch_count
        for a, b in zip(jax_leaves(base_m._params),
                        jax_leaves(skip_m._params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.parametrize("k", [1, 4])
    def test_fault_plan_none_bitwise_inert(self, k):
        # fault_plan="" builds NO injector (structural inertness) and
        # two identical runs under that state are bitwise-equal — the
        # driver's fault sites are provably never entered
        assert FaultInjector.from_config() is None
        a_l, a_o, _ = tiny_run(iters=8, k=k)
        b_l, b_o, _ = tiny_run(iters=8, k=k)
        assert a_o._fault_injector is None
        np.testing.assert_array_equal(a_l, b_l)
        assert a_o._dispatch_count == b_o._dispatch_count

    def test_replica_set_serving_bitwise_equals_bare_engine(self):
        # the resilience front adds NOTHING to the serving numerics:
        # every ReplicaSet result is bitwise-equal to the bare
        # InferenceService of PR 5 (which tests/test_serving.py in turn
        # pins bitwise to direct ``model.apply`` per coalesced bucket)
        model = make_model()
        bare = InferenceService(model, input_spec=SPEC16,
                                max_batch_size=4, name="bare")
        rs = ReplicaSet(model, n_replicas=2, input_spec=SPEC16,
                        max_batch_size=4, name="inert")
        assert rs._faults is None  # no plan, no injector object
        rng = np.random.default_rng(5)
        for n in (1, 2, 4):
            x = rows(rng, n)
            out = np.asarray(rs.predict(x, timeout=60))
            ref = np.asarray(bare.predict(x, timeout=60))
            np.testing.assert_array_equal(out, ref)
        assert rs.stats()["resilience"]["resilience/sheds"] == 0
        bare.stop()
        rs.stop()


# ===========================================================================
class TestReplicaElasticity:
    """ISSUE 14 satellite: ``ReplicaSet.set_replica_count`` grow/shrink
    — unit-tested independently of the autoscaler that drives it."""

    def test_grow_warms_off_the_routing_path(self):
        rs = ReplicaSet(make_model(), n_replicas=1, input_spec=SPEC16,
                        max_batch_size=4, buckets="top", name="grow",
                        start=False)
        rep = rs.set_replica_count(3)
        assert rep == {"active": 3, "added": [1, 2], "retired": []}
        for ix in (1, 2):
            svc = rs.replica(ix)
            # fully AOT-warmed BEFORE admission: the grown replica
            # never serves a compile stall
            assert svc.warmed_up
            # same trace bill replica 0 paid at construction (warmup
            # probes + bucket executables)
            assert svc.compile_count == rs.replica(0).compile_count
        # staged routing spreads across all three (least-queue-depth)
        rng = np.random.default_rng(0)
        futs = [rs.submit(rows(rng, 1), timeout=30) for _ in range(3)]
        assert [rs.replica(i).queue_depth() for i in range(3)] \
            == [1, 1, 1]
        rs.start()
        for f in futs:
            f.result(timeout=30)
        rs.stop()

    def test_shrink_drains_queued_work_without_a_death(self):
        rs = ReplicaSet(make_model(), n_replicas=2, input_spec=SPEC16,
                        max_batch_size=4, buckets="top",
                        name="shrink", start=False)
        rng = np.random.default_rng(1)
        # stage work onto BOTH replicas, then retire one: its queued
        # futures must resolve (inline drain), not cancel or fail over
        futs = [rs.submit(rows(rng, 1), timeout=60) for _ in range(4)]
        assert rs.replica(1).queue_depth() == 2
        rep = rs.set_replica_count(1, timeout=30)
        assert rep["retired"] == [1]
        done = [f for f in futs if f.done()]
        assert len(done) == 2  # exactly r1's staged work drained
        for f in done:
            assert f.exception() is None
        snap = rs.registry.snapshot()["counters"]
        assert snap["resilience/replica_deaths"] == 0
        assert snap["resilience/replicas_retired"] == 1
        # retired slot: excluded from routing, executables released
        assert rs.n_replicas == 1 and rs.active_indices() == [0]
        assert rs.replica(1).params is None
        f5 = rs.submit(rows(rng, 1), timeout=30)
        assert rs.replica(0).queue_depth() == 3
        rs.start()
        for f in futs + [f5]:
            f.result(timeout=30)
        rs.stop()

    def test_shrink_under_live_load_resolves_everything(self):
        rs = ReplicaSet(make_model(), n_replicas=3, input_spec=SPEC16,
                        max_batch_size=4, buckets="top",
                        name="live-shrink")
        rng = np.random.default_rng(2)
        errs = []
        stop = threading.Event()

        def caller():
            while not stop.is_set():
                try:
                    rs.predict(rows(rng, 1), timeout=30)
                except Exception as e:
                    errs.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=caller) for _ in range(4)]
        for t in threads:
            t.start()
        rs.set_replica_count(1, timeout=30)
        rs.set_replica_count(2, timeout=30)
        stop.set()
        for t in threads:
            t.join()
        assert errs == []
        snap = rs.registry.snapshot()["counters"]
        assert snap["resilience/replica_deaths"] == 0
        rs.stop()

    def test_slot_reuse_and_health_reset(self):
        rs = ReplicaSet(make_model(), n_replicas=2, input_spec=SPEC16,
                        max_batch_size=4, buckets="top", name="reuse",
                        start=False)
        rs.set_replica_count(1)
        assert rs.health_snapshot()["retired_slots"] == [1]
        rep = rs.set_replica_count(2)
        assert rep["added"] == [1]  # the retired slot, reused
        assert rs.health_snapshot()["retired_slots"] == []
        assert rs.replica(1).warmed_up
        assert rs.health_states()[1] == HEALTHY  # fresh ledger
        assert rs.total_slots == 2
        rs.stop()

    def test_bounds_and_lifecycle_errors(self):
        rs = ReplicaSet(make_model(), n_replicas=1, input_spec=SPEC16,
                        max_batch_size=4, buckets="top",
                        name="bounds", start=False)
        with pytest.raises(ValueError):
            rs.set_replica_count(0)
        assert rs.set_replica_count(1) == {"active": 1, "added": [],
                                           "retired": []}
        rs.stop()
        from bigdl_tpu.serving import ServiceClosed
        with pytest.raises(ServiceClosed):
            rs.set_replica_count(2)

    def test_stats_and_health_exclude_retired(self):
        rs = ReplicaSet(make_model(), n_replicas=2, input_spec=SPEC16,
                        max_batch_size=4, buckets="top",
                        name="statsx", start=False)
        rs.set_replica_count(1)
        health = rs.health_snapshot()
        assert health["ok"] is True  # a retired slot is not an incident
        assert [r["ix"] for r in health["replicas"]] == [0]
        stats = rs.stats()
        assert [r["ix"] for r in stats["replicas"]] == [0]
        assert stats["retired_slots"] == [1]
        rs.stop()


# ===========================================================================
class TestStagerProducerFailure:
    """Satellite: an exception in the background batch-assembly thread
    must surface as the ORIGINAL error on the next ``take()`` instead
    of risking an indefinite block."""

    def _stager_over(self, source_iter, batch=4):
        import jax.numpy as jnp
        mt = MTSampleToMiniBatch(batch, workers=2)
        return DeviceBlockStager(
            mt(iter(source_iter)),
            lambda xs, ys: (jax_tree_map(jnp.asarray, xs),
                            None if ys is None
                            else jax_tree_map(jnp.asarray, ys)))

    def test_raising_source_surfaces_original_error(self):
        class Boom(RuntimeError):
            pass

        def source():
            rng = np.random.default_rng(0)
            for i in range(6):
                yield Sample(rng.normal(0, 1, (8,)).astype(np.float32),
                             np.int32(0))
            raise Boom("decoder exploded")

        stager = self._stager_over(source())
        xs, ys, sizes = stager.take(1, 10**9)  # first block is fine
        assert sizes == [4]
        t0 = time.monotonic()
        with pytest.raises(Boom, match="decoder exploded"):
            while True:  # the NEXT pull must raise, never wedge
                stager.take(1, 10**9)
        assert time.monotonic() - t0 < 30.0

    def test_dead_producer_without_delivery_surfaces(self, monkeypatch):
        # pathological case: the producer thread never runs at all (a
        # Thread.start that silently no-ops stands in for a thread the
        # OS killed before its first byte) — the consumer must raise,
        # not block forever on its queue
        from bigdl_tpu.dataset import prefetch as prefetch_mod

        class DeadThread:
            def __init__(self, *a, **kw):
                pass

            def start(self):
                pass

            def is_alive(self):
                return False

            def join(self, timeout=None):
                pass

        monkeypatch.setattr(prefetch_mod.threading, "Thread", DeadThread)
        mt = MTSampleToMiniBatch(2, workers=1)
        it = mt(iter([Sample(np.zeros(4, np.float32), np.int32(0))]))
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="producer thread died"):
            next(it)
        assert time.monotonic() - t0 < 30.0


def jax_tree_map(f, tree):
    import jax
    return jax.tree_util.tree_map(f, tree)


# ===========================================================================
class TestAsyncSnapshotWriterErrorContext:
    """Satellite: deferred-error reports name the snapshot path and
    step, so rollback policy can log exactly what it fell back from."""

    def test_deferred_error_names_path_and_step(self):
        from bigdl_tpu.checkpoint.snapshot import AsyncSnapshotWriter
        w = AsyncSnapshotWriter()

        def bad():
            raise IOError("disk full")

        w.submit(bad, context="step 42 → /ckpt/model.42")
        with pytest.raises(RuntimeError) as ei:
            w.drain()
        assert "step 42" in str(ei.value)
        assert "/ckpt/model.42" in str(ei.value)
        assert isinstance(ei.value.__cause__, IOError)
        w.close(raise_errors=False)

    def test_manager_save_threads_context_through(self, monkeypatch,
                                                  tmp_path):
        from bigdl_tpu.checkpoint import manager as manager_mod
        from bigdl_tpu.checkpoint.manager import CheckpointManager

        def failing_write(path, **kw):
            raise IOError(f"cannot write {path}")

        monkeypatch.setattr(manager_mod, "write_snapshot",
                            failing_write)
        mgr = CheckpointManager(str(tmp_path), async_save=True)
        params = {"w": np.zeros((2, 2), np.float32)}
        mgr.save(3, params)
        with pytest.raises(RuntimeError) as ei:
            mgr.wait()  # drain surfaces the deferred error
        msg = str(ei.value)
        assert "step 3" in msg and str(tmp_path) in msg
        mgr.close(raise_errors=False)


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
