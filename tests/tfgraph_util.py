"""Shared helper: hand-build binary GraphDef fixtures with protowire.

Used by test_interop.py and test_aux_subsystems.py (one copy; the wire
layout of NodeDef/TensorProto lives here only).
"""

import numpy as np

from bigdl_tpu.utils import protowire as pw


def node(name, op, inputs=(), **attrs):
    body = pw.enc_str(1, name) + pw.enc_str(2, op)
    for i in inputs:
        body += pw.enc_str(3, i)
    for k, v in attrs.items():
        body += pw.enc_bytes(5, pw.enc_str(1, k) + pw.enc_bytes(2, v))
    return pw.enc_bytes(1, body)


def attr_tensor(arr):
    """float32 TensorProto attr payload."""
    arr = np.asarray(arr, np.float32)
    t = pw.enc_varint(1, 1)  # DT_FLOAT
    shp = b"".join(pw.enc_bytes(2, pw.enc_varint(1, d)) for d in arr.shape)
    t += pw.enc_bytes(2, shp)
    t += pw.enc_bytes(4, arr.tobytes())
    return pw.enc_bytes(8, t)


def scalar_const(v):
    t = (pw.enc_varint(1, 1) + pw.enc_bytes(2, b"")
         + pw.enc_bytes(4, np.float32(v).tobytes()))
    return pw.enc_bytes(8, t)


def shape_const(dims):
    """int32 shape-vector TensorProto attr payload."""
    t = pw.enc_varint(1, 3)  # DT_INT32
    shp = pw.enc_bytes(2, pw.enc_varint(1, len(dims)))
    t += pw.enc_bytes(2, shp)
    t += pw.enc_bytes(4, np.asarray(dims, np.int32).tobytes())
    return pw.enc_bytes(8, t)


def string_const(strings):
    """DT_STRING vector TensorProto attr payload."""
    t = pw.enc_varint(1, 7)  # DT_STRING
    shp = pw.enc_bytes(2, pw.enc_varint(1, len(strings)))
    t += pw.enc_bytes(2, shp)
    for s in strings:
        t += pw.enc_bytes(8, s.encode() if isinstance(s, str) else s)
    return pw.enc_bytes(8, t)


def int_scalar_const(v):
    """int32 scalar TensorProto attr payload."""
    t = (pw.enc_varint(1, 3) + pw.enc_bytes(2, b"")
         + pw.enc_bytes(4, np.int32(v).tobytes()))
    return pw.enc_bytes(8, t)


def attr_int(v):
    """integer AttrValue payload (field 3 = i)."""
    return pw.enc_varint(3, int(v))


def attr_type(v):
    """type-enum AttrValue payload (field 6 = type)."""
    return pw.enc_varint(6, int(v))


def enter(name, inputs, frame):
    """Enter node with a frame_name attr (while-loop fixtures)."""
    body = pw.enc_str(1, name) + pw.enc_str(2, "Enter")
    for i in inputs:
        body += pw.enc_str(3, i)
    body += pw.enc_bytes(5, pw.enc_str(1, "frame_name")
                         + pw.enc_bytes(2, pw.enc_bytes(2, frame.encode())))
    return pw.enc_bytes(1, body)


def build_queue_graph(record_path, batch=8):
    """GraphDef with its WHOLE input pipeline in-graph:
    string_input_producer -> TFRecordReader -> DecodeRaw -> example
    queue -> QueueDequeueManyV2 -> linear regression -> in-graph MSE
    loss.  Shared by tests and examples/tensorflow (queue-fed demo)."""
    g = b""
    g += node("filenames", "Const", value=string_const([record_path]))
    g += node("fq", "FIFOQueueV2")
    g += node("fq_enq", "QueueEnqueueManyV2", ["fq", "filenames"])
    g += node("reader", "TFRecordReaderV2")
    g += node("read", "ReaderReadV2", ["reader", "fq"])
    g += node("decoded", "DecodeRaw", ["read:1"], out_type=attr_type(1))
    g += node("rec", "Reshape", ["decoded", "rec_shape"])
    g += node("rec_shape", "Const", value=shape_const([5]))
    g += node("eq", "FIFOQueueV2")
    g += node("eq_enq", "QueueEnqueueV2", ["eq", "rec"])
    g += node("batch_n", "Const", value=int_scalar_const(batch))
    g += node("dq", "QueueDequeueManyV2", ["eq", "batch_n"])
    g += node("xb", "Const", value=shape_const([0, 0]))
    g += node("xs", "Const", value=shape_const([-1, 4]))
    g += node("x", "Slice", ["dq", "xb", "xs"])
    g += node("yb", "Const", value=shape_const([0, 4]))
    g += node("ys", "Const", value=shape_const([-1, 1]))
    g += node("y", "Slice", ["dq", "yb", "ys"])
    g += node("w_init", "Const", value=attr_tensor(np.zeros((4, 1))))
    g += node("W", "VariableV2")
    g += node("W_assign", "Assign", ["W", "w_init"])
    g += node("pred", "MatMul", ["x", "W"])
    g += node("diff", "Sub", ["pred", "y"])
    g += node("sq", "Square", ["diff"])
    g += node("red", "Const", value=shape_const([0, 1]))
    g += node("loss", "Mean", ["sq", "red"])
    return g
