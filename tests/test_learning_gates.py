"""Always-on learning-quality gates (VERDICT r3 item 5).

Real-data accuracy gates stay env-gated (test_accuracy_gates.py needs
the datasets on disk); these two run in EVERY suite invocation on
structured synthetic data that already lives in-repo, and assert
non-trivial bars in minutes:

- char-LM perplexity (reference ``DL/models/rnn`` PTB recipe shape):
  a Markov corpus with known structure; the stacked-LSTM LM must push
  validation perplexity far below the uniform baseline.
- NCF hit-ratio (reference NCF/recommender workload of BASELINE.json):
  latent-factor synthetic ratings; HR@10 against 99 sampled negatives
  must clear random ranking by a wide margin.
"""
import numpy as np
import jax
import jax.numpy as jnp

from bigdl_tpu import nn, optim


def _char_corpus(n_chars=40000, seed=0):
    """Concatenation of a small word set in random order: within-word
    transitions are deterministic, word choice is the only entropy, so
    a competent LM lands well under ~4 ppl while uniform is 27."""
    rng = np.random.default_rng(seed)
    words = ["the ", "quick ", "brown ", "fox ", "jumps ", "over ",
             "lazy ", "dog ", "pack ", "my ", "box ", "with ", "five ",
             "dozen ", "jugs "]
    out = []
    total = 0
    while total < n_chars:
        w = words[rng.integers(0, len(words))]
        out.append(w)
        total += len(w)
    text = "".join(out)[:n_chars]
    chars = sorted(set(text))
    lut = {c: i for i, c in enumerate(chars)}
    return np.asarray([lut[c] for c in text], np.int32), len(chars)


class TestCharLMPerplexityGate:
    def test_perplexity_beats_structure_bar(self):
        data, vocab = _char_corpus()
        T, B = 32, 32
        n_seq = len(data) // (T + 1)
        seqs = data[:n_seq * (T + 1)].reshape(n_seq, T + 1)
        rng = np.random.default_rng(1)
        rng.shuffle(seqs)
        n_val = max(8, n_seq // 10)
        train, val = seqs[n_val:], seqs[:n_val]

        from bigdl_tpu.models.rnn import ptb_model
        model = ptb_model(vocab_size=vocab, embed_dim=32, hidden_size=64,
                          num_layers=1)
        p, st = model.init(jax.random.PRNGKey(0))
        method = optim.Adam(learning_rate=3e-3)
        os_ = method.init_state(p)
        crit = nn.ClassNLLCriterion()

        @jax.jit
        def step(p, os_, x, y, it):
            def loss_fn(p):
                out, _ = model.apply(p, st, x, training=True)
                return crit.apply(out.reshape(-1, vocab), y.reshape(-1))
            loss, g = jax.value_and_grad(loss_fn)(p)
            p, os_ = method.update(g, p, os_, 3e-3, it)
            return p, os_, loss

        @jax.jit
        def val_nll(p, x, y):
            out, _ = model.apply(p, st, x)
            return crit.apply(out.reshape(-1, vocab), y.reshape(-1))

        it = 0
        for epoch in range(3):
            for i in range(0, len(train) - B + 1, B):
                chunk = jnp.asarray(train[i:i + B])
                p, os_, loss = step(p, os_, chunk[:, :-1], chunk[:, 1:],
                                    it)
                it += 1
        v = jnp.asarray(val)
        ppl = float(jnp.exp(val_nll(p, v[:, :-1], v[:, 1:])))
        # uniform baseline = vocab (~27); word-structure source entropy
        # keeps a fitted LM well under 4
        assert ppl < 4.0, f"val perplexity {ppl:.2f} (uniform ~{vocab})"


class TestNCFHitRatioGate:
    def test_hit_ratio_beats_random_bar(self):
        from bigdl_tpu.dataset.movielens import synthetic_ratings
        from bigdl_tpu.models.recommender import NeuralCF
        n_users, n_items = 120, 50
        ratings = synthetic_ratings(n_users, n_items, 12000, seed=0)
        users = ratings[:, 0] - 1
        items = ratings[:, 1] - 1
        pos = ratings[:, 2] >= 4
        rng = np.random.default_rng(0)

        # leave-one-out: one held-out positive per user (when available)
        by_user = {}
        for u, i, is_pos in zip(users, items, pos):
            if is_pos:
                by_user.setdefault(int(u), []).append(int(i))
        test_pos = {u: its[0] for u, its in by_user.items() if len(its) > 1}
        held = set((u, i) for u, i in test_pos.items())

        tr_u, tr_i, tr_y = [], [], []
        seen = {}
        for u, i, is_pos in zip(users, items, pos):
            if (int(u), int(i)) in held:
                continue
            tr_u.append(u)
            tr_i.append(i)
            tr_y.append(1.0 if is_pos else 0.0)
            seen.setdefault(int(u), set()).add(int(i))
        # extra sampled negatives balance the implicit objective
        for u in list(test_pos):
            for _ in range(8):
                j = int(rng.integers(0, n_items))
                if j not in seen.get(u, set()) and j != test_pos[u]:
                    tr_u.append(u)
                    tr_i.append(j)
                    tr_y.append(0.0)
        tr_u = jnp.asarray(np.asarray(tr_u, np.int32))
        tr_i = jnp.asarray(np.asarray(tr_i, np.int32))
        tr_y = jnp.asarray(np.asarray(tr_y, np.float32))

        model = NeuralCF(n_users, n_items, embed_dim=16, mlp_dims=(32, 16))
        p, st = model.init(jax.random.PRNGKey(0))
        method = optim.Adam(learning_rate=5e-3)
        os_ = method.init_state(p)
        crit = nn.BCECriterion()

        @jax.jit
        def step(p, os_, u, i, y, it):
            def loss_fn(p):
                out, _ = model.apply(p, st, (u, i), training=True)
                return crit.apply(out.reshape(-1), y)
            loss, g = jax.value_and_grad(loss_fn)(p)
            p, os_ = method.update(g, p, os_, 5e-3, it)
            return p, os_, loss

        n = len(tr_y)
        B = 512
        it = 0
        for epoch in range(150):
            perm = rng.permutation(n)
            for s in range(0, n - B + 1, B):
                ix = jnp.asarray(perm[s:s + B])
                p, os_, loss = step(p, os_, tr_u[ix], tr_i[ix], tr_y[ix],
                                    it)
                it += 1

        # rank the held-out positive against 99 unseen negatives
        eval_users, eval_items = [], []
        for u, i_pos in test_pos.items():
            negs = []
            while len(negs) < 99:
                j = int(rng.integers(0, n_items))
                if j != i_pos and j not in seen.get(u, set()):
                    negs.append(j)
            eval_users.append([u] * 100)
            eval_items.append([i_pos] + negs)
        eu = jnp.asarray(np.asarray(eval_users, np.int32).reshape(-1))
        ei = jnp.asarray(np.asarray(eval_items, np.int32).reshape(-1))
        scores, _ = model.apply(p, st, (eu, ei))
        scores = scores.reshape(len(test_pos), 100)
        hr = optim.validation.HitRatio(10)
        hits, total = hr.batch_stats(scores)
        hr10 = float(hits) / float(total)
        # random ranking gives ~0.10
        assert hr10 >= 0.40, f"HR@10 {hr10:.3f} (random ~0.10)"
