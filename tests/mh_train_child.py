"""Child process for the 2-process multi-host DistriOptimizer test.

Usage: python mh_train_child.py <process_id> <coordinator_port>
Prints ``RESULT pid loss val`` on success.  Run by
``tests/test_multihost_failure.py`` — the analog of the reference's
local-mode-cluster distributed tests (SURVEY §4) for real multi-process
paths (``_make_global``, DistributedDataSet sharding, sharded eval,
process-0-only checkpointing).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

import jax

jax.config.update("jax_platforms", "cpu")

pid = int(sys.argv[1])
port = sys.argv[2]
ckpt_dir = sys.argv[3] if len(sys.argv) > 3 else None
jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                           num_processes=2, process_id=pid)

import numpy as np
from jax.sharding import Mesh

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import SampleToMiniBatch
from bigdl_tpu.dataset.dataset import DistributedDataSet
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.engine import Engine

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 8

# identical global dataset on every host; DistributedDataSet shards it
rng = np.random.RandomState(0)
centers = rng.randn(3, 8) * 4.0
y = rng.randint(0, 3, 256)
x = (centers[y] + rng.randn(256, 8)).astype(np.float32)
samples = [Sample(x[i], np.int32(y[i])) for i in range(256)]

train = DistributedDataSet(samples) >> SampleToMiniBatch(16)  # local 16
val = DistributedDataSet(samples) >> SampleToMiniBatch(16)

mesh = Mesh(np.array(jax.devices()), axis_names=("data",))
Engine.set_mesh(mesh)
model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 3),
                      nn.LogSoftMax())
opt = (optim.DistriOptimizer(model, train, nn.ClassNLLCriterion(),
                             mesh=mesh, parameter_sharding=True)
       .set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9,
                                   dampening=0.0))
       .set_end_when(optim.max_epoch(4))
       .set_validation(optim.every_epoch(), val, [optim.Top1Accuracy()]))
if ckpt_dir:
    opt.set_checkpoint(ckpt_dir, optim.every_epoch())
opt.optimize()
print(f"RESULT {pid} {opt.state['loss']:.6f} "
      f"{opt.state.get('score', float('nan')):.6f}", flush=True)
