"""Fused pallas kernel parity + activation-memory gates (round-10,
the HBM-floor PR).

Covers the ISSUE-8 acceptance surface:
- fused LSTM-cell and embedding-bag kernels gated bitwise-or-tolerance
  (forward AND gradient) against the XLA baseline, f32 and bf16, odd
  shapes (non-multiple-of-128 hidden/feature dims, empty bags,
  single-row batches), running the REAL kernel bodies under pallas
  interpret mode on CPU;
- the ``supported()`` fallback contract: unsupported shapes/dtypes
  silently take the XLA path with IDENTICAL (bitwise) results;
- ``Config.kernel_impl`` / ``BIGDL_TPU_KERNEL_IMPL`` resolution via
  ``Engine.kernel_impl()``;
- K∈{1,4} parity inside the fused-dispatch driver with the kernels
  engaged (the same discipline as tests/test_fused_step.py);
- ``Optimizer.set_activation_memory``: provably inert when off
  (bitwise loss sequence, equal dispatch count), exact-math for the
  remat policies, activation-dtype-only for bf16 (params stay f32).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn, optim
from bigdl_tpu.dataset import DataSet, Sample, SampleToMiniBatch
from bigdl_tpu.engine import Engine
from bigdl_tpu.nn.recurrent import LSTM, Recurrent
from bigdl_tpu.nn.sparse import (COOBatch, LookupTableSparse,
                                 SparseLinear, coo_spmm)
from bigdl_tpu.ops import pallas_embed, pallas_lstm, resolve_kernel_impl
from bigdl_tpu.optim.optimizer import LocalOptimizer


def xla_lstm_cell(zx, h, c, w_t, fb=0.0):
    """The reference chain ``LSTM.step_hoisted`` lowers to."""
    z = zx + h @ w_t
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i, f = jax.nn.sigmoid(i), jax.nn.sigmoid(f + fb)
    g, o = jnp.tanh(g), jax.nn.sigmoid(o)
    c_new = f * c + i * g
    return o * jnp.tanh(c_new), c_new


def xla_bag(rows, cols, vals, table, n):
    g = jnp.take(table, cols, axis=0) * vals[:, None]
    return jax.ops.segment_sum(g, rows, num_segments=n)


def _leaves_close(a, b, rtol, atol):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        np.testing.assert_allclose(np.asarray(x, np.float32),
                                   np.asarray(y, np.float32),
                                   rtol=rtol, atol=atol)


# ===========================================================================
# fused LSTM cell (ops/pallas_lstm.py)
# ===========================================================================
class TestLSTMCellParity:
    CASES = [
        # (N, H, dtype, fwd_tol, grad_tol) — odd (non-128-multiple)
        # hidden, single-row batch, the PTB shape, lane-aligned bf16
        (5, 130, jnp.float32, 1e-5, 1e-4),
        (1, 64, jnp.float32, 1e-5, 1e-4),
        (20, 650, jnp.float32, 1e-4, 1e-3),
        (8, 128, jnp.bfloat16, 3e-2, 2e-1),
    ]

    @pytest.mark.parametrize("N,H,dtype,ftol,gtol", CASES)
    def test_forward_and_grad_match_xla(self, N, H, dtype, ftol, gtol):
        assert pallas_lstm.supported(N, H, dtype)
        rng = np.random.default_rng(N * 1000 + H)
        mk = lambda *s: jnp.asarray(  # noqa: E731
            rng.normal(0, 0.5, s).astype(np.float32)).astype(dtype)
        zx, h, c = mk(N, 4 * H), mk(N, H), mk(N, H)
        w = mk(H, 4 * H)

        hp, cp = jax.jit(
            lambda *a: pallas_lstm.lstm_cell(*a, forget_bias=1.0))(
                zx, h, c, w)
        hx, cx = xla_lstm_cell(*(a.astype(jnp.float32)
                                 for a in (zx, h, c, w)), fb=1.0)
        _leaves_close((hp, cp), (hx, cx), rtol=ftol, atol=ftol)

        def loss_p(zx, h, c, w):
            a, b = pallas_lstm.lstm_cell(zx, h, c, w, forget_bias=1.0)
            return (a.astype(jnp.float32) ** 2).sum() \
                + (b.astype(jnp.float32) * 1.5).sum()

        def loss_x(zx, h, c, w):
            a, b = xla_lstm_cell(zx, h, c, w, 1.0)
            return (a ** 2).sum() + (b * 1.5).sum()

        gp = jax.jit(jax.grad(loss_p, argnums=(0, 1, 2, 3)))(zx, h, c, w)
        gx = jax.grad(loss_x, argnums=(0, 1, 2, 3))(
            *(a.astype(jnp.float32) for a in (zx, h, c, w)))
        _leaves_close(gp, gx, rtol=gtol, atol=gtol)

    def test_recurrent_scan_parity_with_grad(self):
        """End-to-end through Recurrent's lax.scan: the fused cell and
        the XLA cell produce the same sequence output and the same
        parameter gradients."""
        rng = np.random.default_rng(3)
        N, T, D, H = 4, 6, 10, 32
        x = jnp.asarray(rng.normal(0, 1, (N, T, D)).astype(np.float32))
        outs, grads = {}, {}
        for impl in ("xla", "pallas"):
            rec = Recurrent(LSTM(D, H, forget_bias=1.0, impl=impl))
            p, _ = rec.init(jax.random.PRNGKey(0))
            outs[impl], _ = jax.jit(
                lambda p, x: rec.apply(p, {}, x))(p, x)
            grads[impl] = jax.jit(jax.grad(
                lambda p, x: rec.apply(p, {}, x)[0].sum()))(p, x)
        _leaves_close(outs["pallas"], outs["xla"], 1e-5, 1e-5)
        _leaves_close(grads["pallas"], grads["xla"], 1e-4, 1e-4)


class TestLSTMSupportedGate:
    def test_dtype_and_budget_gates(self):
        assert pallas_lstm.supported(8, 128, jnp.float32)
        assert pallas_lstm.supported(8, 650, jnp.bfloat16)
        assert not pallas_lstm.supported(8, 128, jnp.int32)
        # H=1100 -> lane-padded weight panel over the element budget
        assert not pallas_lstm.supported(8, 1100, jnp.float32)
        assert not pallas_lstm.supported(0, 128, jnp.float32)

    def test_unsupported_shape_silently_takes_xla_path_bitwise(self):
        """impl="pallas" on a shape supported() rejects must produce
        BITWISE-identical results to impl="xla" — proof the fallback is
        the untouched baseline, not a second implementation."""
        rng = np.random.default_rng(7)
        N, T, D, H = 2, 3, 6, 1100  # over the weight-panel budget
        assert not pallas_lstm.supported(N, H, jnp.float32)
        x = jnp.asarray(rng.normal(0, 1, (N, T, D)).astype(np.float32))
        ys = {}
        for impl in ("xla", "pallas"):
            rec = Recurrent(LSTM(D, H, impl=impl))
            p, _ = rec.init(jax.random.PRNGKey(1))
            y, _ = jax.jit(lambda p, x: rec.apply(p, {}, x))(p, x)
            ys[impl] = np.asarray(y)
        assert np.array_equal(ys["pallas"], ys["xla"])


# ===========================================================================
# fused embedding-bag (ops/pallas_embed.py)
# ===========================================================================
class TestEmbeddingBagParity:
    CASES = [
        # (name, N, V, D, nnz, dtype, tol)
        ("aligned", 4, 64, 128, 9, jnp.float32, 1e-5),
        ("wide_d1", 8, 100, 1, 40, jnp.float32, 1e-5),
        ("odd_d", 5, 30, 10, 17, jnp.float32, 1e-5),
        ("single_row", 1, 20, 8, 5, jnp.float32, 1e-5),
        ("bf16", 6, 50, 16, 32, jnp.bfloat16, 5e-2),
    ]

    @pytest.mark.parametrize("name,N,V,D,nnz,dtype,tol", CASES)
    def test_forward_and_grad_match_xla(self, name, N, V, D, nnz, dtype,
                                        tol):
        assert pallas_embed.supported(nnz, N, (V, D), dtype)
        rng = np.random.default_rng(abs(hash(name)) % 2 ** 31)
        rows = jnp.asarray(rng.integers(0, N, nnz).astype(np.int32))
        cols = jnp.asarray(rng.integers(0, V, nnz).astype(np.int32))
        vals = jnp.asarray(rng.normal(0, 1, nnz).astype(np.float32))
        table = jnp.asarray(
            rng.normal(0, 1, (V, D)).astype(np.float32)).astype(dtype)

        got = jax.jit(lambda r, c, v, t: pallas_embed.embedding_bag_coo(
            r, c, v, t, N))(rows, cols, vals, table)
        want = xla_bag(rows, cols, vals, table, N)
        assert got.dtype == want.dtype
        _leaves_close(got, want, tol, tol)
        if dtype == jnp.bfloat16:
            # bf16 values too: the promoted output dtype must track the
            # ORIGINAL operand dtypes exactly like the XLA chain
            vb = vals.astype(jnp.bfloat16)
            got_b = pallas_embed.embedding_bag_coo(rows, cols, vb, table,
                                                   N)
            assert got_b.dtype == xla_bag(rows, cols, vb, table, N).dtype

        def loss_p(v, t):
            out = pallas_embed.embedding_bag_coo(rows, cols, v, t, N)
            return (out.astype(jnp.float32) ** 2).sum()

        def loss_x(v, t):
            return (xla_bag(rows, cols, v, t, N).astype(
                jnp.float32) ** 2).sum()

        gp = jax.jit(jax.grad(loss_p, argnums=(0, 1)))(vals, table)
        gx = jax.grad(loss_x, argnums=(0, 1))(vals, table)
        _leaves_close(gp, gx, tol * 10, tol * 10)

    def test_unsorted_rows_duplicates_and_padding(self):
        """The VMEM accumulator is order-independent: unsorted rows,
        duplicate (row, col) pairs and trailing (0, 0, 0.0) padding
        entries — exactly what batch_sparse_samples emits — all
        accumulate like the XLA segment-sum."""
        rows = jnp.asarray([3, 0, 3, 1, 0, 0, 0], jnp.int32)
        cols = jnp.asarray([2, 5, 2, 1, 0, 0, 0], jnp.int32)
        vals = jnp.asarray([1.0, 2.0, 0.5, -1.0, 3.0, 0.0, 0.0],
                           jnp.float32)
        table = jnp.asarray(
            np.random.default_rng(0).normal(0, 1, (8, 4)).astype(
                np.float32))
        got = pallas_embed.embedding_bag_coo(rows, cols, vals, table, 5)
        want = xla_bag(rows, cols, vals, table, 5)
        _leaves_close(got, want, 1e-5, 1e-5)
        # row 2 and 4 are empty segments -> exact zeros
        assert float(jnp.abs(got[2]).sum()) == 0.0
        assert float(jnp.abs(got[4]).sum()) == 0.0

    def test_sparse_layers_parity(self):
        rng = np.random.default_rng(11)
        coo = COOBatch(
            jnp.asarray(rng.integers(0, 5, 20).astype(np.int32)),
            jnp.asarray(rng.integers(0, 50, 20).astype(np.int32)),
            jnp.asarray(rng.normal(0, 1, 20).astype(np.float32)),
            (5, 50))
        for combiner in ("sum", "mean"):
            outs = {}
            for impl in ("xla", "pallas"):
                m = LookupTableSparse(50, 16, combiner, impl=impl)
                p, _ = m.init(jax.random.PRNGKey(2))
                outs[impl], _ = jax.jit(
                    lambda p, c: m.apply(p, {}, c))(p, coo)
            _leaves_close(outs["pallas"], outs["xla"], 1e-5, 1e-5)
        outs = {}
        for impl in ("xla", "pallas"):
            m = SparseLinear(50, 3, impl=impl)
            p, _ = m.init(jax.random.PRNGKey(3))
            outs[impl], _ = jax.jit(lambda p, c: m.apply(p, {}, c))(p, coo)
        _leaves_close(outs["pallas"], outs["xla"], 1e-5, 1e-5)


class TestEmbedSupportedGate:
    def test_gates(self):
        assert pallas_embed.supported(64, 8192, (100_000, 1),
                                      jnp.float32)  # the wide path
        assert not pallas_embed.supported(64, 8, (10, 4), jnp.int32)
        # D > 128 and not lane-aligned
        assert not pallas_embed.supported(64, 8, (10, 200), jnp.float32)
        # output accumulator over the VMEM element budget
        assert not pallas_embed.supported(64, 100_000, (10, 128),
                                          jnp.float32)
        assert not pallas_embed.supported(0, 8, (10, 4), jnp.float32)

    def test_unsupported_falls_back_bitwise(self):
        rng = np.random.default_rng(5)
        # D=200: not lane-aligned, >128 -> supported() rejects
        coo = COOBatch(
            jnp.asarray(rng.integers(0, 4, 12).astype(np.int32)),
            jnp.asarray(rng.integers(0, 9, 12).astype(np.int32)),
            jnp.asarray(rng.normal(0, 1, 12).astype(np.float32)),
            (4, 9))
        table = jnp.asarray(rng.normal(0, 1, (9, 200)).astype(np.float32))
        assert not pallas_embed.supported(12, 4, table.shape, table.dtype)
        a = np.asarray(coo_spmm(coo, table, impl="pallas"))
        b = np.asarray(coo_spmm(coo, table, impl="xla"))
        assert np.array_equal(a, b)


# ===========================================================================
# kernel_impl resolution (Config / env / Engine)
# ===========================================================================
@pytest.fixture
def _kernel_impl_guard():
    prev = Engine._state.kernel_impl
    yield
    Engine._state.kernel_impl = prev


class TestKernelImplResolution:
    def test_engine_default_flows_from_config(self, _kernel_impl_guard):
        from bigdl_tpu.utils.config import Config
        assert Config().kernel_impl == "auto"
        # auto on a CPU host resolves to xla (interpret kernels are
        # emulation, not a speedup)
        Engine.set_kernel_impl("auto")
        assert resolve_kernel_impl(None) == "xla"

    def test_engine_override_and_layer_override(self, _kernel_impl_guard):
        Engine.set_kernel_impl("pallas")
        assert resolve_kernel_impl(None) == "pallas"
        assert resolve_kernel_impl("xla") == "xla"  # layer arg wins
        Engine.set_kernel_impl("xla")
        assert resolve_kernel_impl(None) == "xla"
        assert resolve_kernel_impl("pallas") == "pallas"

    def test_invalid_values_rejected(self, _kernel_impl_guard):
        with pytest.raises(ValueError):
            Engine.set_kernel_impl("mosaic")
        with pytest.raises(ValueError):
            resolve_kernel_impl("cuda")

    def test_env_var_reaches_config(self, monkeypatch):
        from bigdl_tpu.utils.config import Config
        monkeypatch.setenv("BIGDL_TPU_KERNEL_IMPL", "pallas")
        assert Config.from_env().kernel_impl == "pallas"

    def test_engine_kernel_impl_engages_layers(self, _kernel_impl_guard):
        """No per-layer impl arg: the Engine-level knob alone flips the
        COO path onto the kernel (same numbers either way — this pins
        the RESOLUTION plumbing, parity is gated above)."""
        rng = np.random.default_rng(13)
        coo = COOBatch(
            jnp.asarray(rng.integers(0, 4, 10).astype(np.int32)),
            jnp.asarray(rng.integers(0, 20, 10).astype(np.int32)),
            jnp.asarray(rng.normal(0, 1, 10).astype(np.float32)),
            (4, 20))
        table = jnp.asarray(rng.normal(0, 1, (20, 8)).astype(np.float32))
        Engine.set_kernel_impl("xla")
        base = np.asarray(coo_spmm(coo, table))
        Engine.set_kernel_impl("pallas")
        fused = np.asarray(coo_spmm(coo, table))
        np.testing.assert_allclose(fused, base, rtol=1e-5, atol=1e-5)


# ===========================================================================
# K∈{1,4} parity inside the fused-dispatch driver (acceptance bar)
# ===========================================================================
class RecordingSummary:
    def __init__(self):
        self.rows = []

    def add_train_step(self, step, loss, lr, throughput):
        self.rows.append((step, loss, lr))

    def add_scalar(self, tag, value, step):
        pass

    def trigger_for(self, name):
        return None

    @property
    def losses(self):
        return np.array([l for _, l, _ in self.rows])


def _lm_samples(n=24, T=6, vocab=40, seed=0):
    rng = np.random.default_rng(seed)
    return [Sample(rng.integers(0, vocab, (T,)).astype(np.int32),
                   rng.integers(0, vocab, (T,)).astype(np.int32))
            for _ in range(n)]


def _run_lstm_driver(impl, k, iters=6):
    model = (nn.Sequential()
             .add(nn.LookupTable(40, 8))
             .add(Recurrent(LSTM(8, 32, impl=impl)))
             .add(nn.TimeDistributed(nn.Linear(32, 40)))
             .add(nn.LogSoftMax()))
    ds = DataSet.array(_lm_samples()) >> SampleToMiniBatch(8)
    rec = RecordingSummary()
    opt = (LocalOptimizer(
               model, ds,
               nn.TimeDistributedCriterion(nn.ClassNLLCriterion()))
           .set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9))
           .set_train_summary(rec)
           .set_steps_per_dispatch(k)
           .set_end_when(optim.max_iteration(iters)).set_seed(5))
    opt.optimize()
    return rec.losses, opt


def _sparse_samples(n=24, width=30, nnz=4, seed=0):
    from bigdl_tpu.dataset import SparseSample
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        idx = np.sort(rng.choice(width, nnz, replace=False))
        out.append(SparseSample(
            idx.astype(np.int32),
            rng.normal(0, 1, nnz).astype(np.float32), width,
            label=np.float32(rng.integers(0, 2))))
    return out


class _SparseToMiniBatch:
    """Minimal Transformer batching SparseSamples into COO minibatches
    (one fixed nnz bucket keeps every block signature identical)."""

    def __init__(self, batch_size, nnz_buckets):
        self.batch_size = batch_size
        self.nnz_buckets = nnz_buckets

    def __call__(self, it):
        from bigdl_tpu.dataset import batch_sparse_samples
        buf = []
        for s in it:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield batch_sparse_samples(buf, self.nnz_buckets)
                buf = []


def _run_sparse_driver(impl, k, iters=6):
    class _BCE:
        def __init__(self):
            self.bce = nn.BCECriterion()

        def apply(self, out, y):
            return self.bce.apply(jax.nn.sigmoid(out[:, 0]), y)

    model = SparseLinear(30, 1, impl=impl)
    ds = DataSet.array(_sparse_samples()) >> _SparseToMiniBatch(8, [64])
    rec = RecordingSummary()
    opt = (LocalOptimizer(model, ds, _BCE())
           .set_optim_method(optim.SGD(learning_rate=0.5))
           .set_train_summary(rec)
           .set_steps_per_dispatch(k)
           .set_end_when(optim.max_iteration(iters)).set_seed(5))
    opt.optimize()
    return rec.losses, opt


class TestFusedDispatchDriverParity:
    def test_lstm_pallas_matches_xla_for_k1_and_k4(self):
        ref = {}
        for k in (1, 4):
            lx, _ = _run_lstm_driver("xla", k)
            lp, _ = _run_lstm_driver("pallas", k)
            assert len(lp) == len(lx) == 6
            np.testing.assert_allclose(lp, lx, rtol=2e-4, atol=2e-5)
            ref[k] = lp
        # K-invariance with the kernel engaged (driver contract)
        np.testing.assert_allclose(ref[1], ref[4], rtol=1e-5, atol=1e-6)

    def test_sparse_pallas_matches_xla_for_k1_and_k4(self):
        ref = {}
        for k in (1, 4):
            lx, _ = _run_sparse_driver("xla", k)
            lp, _ = _run_sparse_driver("pallas", k)
            assert len(lp) == len(lx) == 6
            np.testing.assert_allclose(lp, lx, rtol=2e-4, atol=2e-5)
            ref[k] = lp
        np.testing.assert_allclose(ref[1], ref[4], rtol=1e-5, atol=1e-6)


# ===========================================================================
# Optimizer.set_activation_memory
# ===========================================================================
def _run_mlp(policy, call=True, iters=6):
    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(0, 1, (16,)).astype(np.float32),
                      np.int32(rng.integers(0, 4))) for _ in range(32)]
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                          nn.Linear(32, 4), nn.LogSoftMax())
    ds = DataSet.array(samples) >> SampleToMiniBatch(8)
    rec = RecordingSummary()
    opt = (LocalOptimizer(model, ds, nn.ClassNLLCriterion())
           .set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9))
           .set_train_summary(rec)
           .set_end_when(optim.max_iteration(iters)).set_seed(7))
    if call:
        opt.set_activation_memory(policy)
    opt.optimize()
    return rec.losses, opt


class TestActivationMemory:
    def test_off_is_provably_inert(self):
        """ISSUE-8 acceptance: bitwise loss sequence + equal dispatch
        count whether set_activation_memory was never called or called
        with "none"/None."""
        l_base, o_base = _run_mlp(None, call=False)
        for policy in (None, "none"):
            l_p, o_p = _run_mlp(policy)
            assert l_p.tolist() == l_base.tolist()  # bitwise
            assert o_p._dispatch_count == o_base._dispatch_count

    def test_remat_policies_are_exact_math(self):
        """Remat changes WHAT is stored, never what is computed: the
        loss trajectory and final params stay identical to float
        rounding (XLA may fuse the recomputed chain differently, so
        bitwise is graph-dependent — measured one-ulp-level deltas on
        some graphs; the math itself is exact)."""
        l_base, o_base = _run_mlp(None, call=False)
        for policy in ("dots", "full"):
            l_p, o_p = _run_mlp(policy)
            np.testing.assert_allclose(l_p, l_base, rtol=1e-6,
                                       atol=1e-7, err_msg=policy)
            for a, b in zip(
                    jax.tree_util.tree_leaves(o_base.model._params),
                    jax.tree_util.tree_leaves(o_p.model._params)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                           rtol=1e-5, atol=1e-7)

    def test_bf16_changes_activations_never_params_or_update(self):
        l_base, _ = _run_mlp(None, call=False)
        l_bf, o_bf = _run_mlp("bf16")
        assert l_bf.tolist() != l_base.tolist()  # numerics did change
        assert abs(l_bf[-1] - l_base[-1]) < 0.2  # ... but sanely
        for leaf in jax.tree_util.tree_leaves(o_bf.model._params):
            assert np.asarray(leaf).dtype == np.float32
        for leaf in jax.tree_util.tree_leaves(o_bf._final_opt_state):
            if hasattr(leaf, "dtype") and np.issubdtype(
                    np.asarray(leaf).dtype, np.floating):
                assert np.asarray(leaf).dtype == np.float32

    def test_combined_policies_and_validation(self):
        l_base, _ = _run_mlp(None, call=False)
        l_c, _ = _run_mlp("bf16+dots")
        assert abs(l_c[-1] - l_base[-1]) < 0.2
        with pytest.raises(ValueError):
            _run_mlp("fp8")

    def test_bf16_policy_conflicts_with_explicit_f32_compute(self):
        """An explicit non-bf16 compute dtype contradicts a bf16
        activation policy — refused loudly, never silently dropped."""
        rng = np.random.default_rng(1)
        samples = [Sample(rng.normal(0, 1, (8,)).astype(np.float32),
                          np.int32(0)) for _ in range(8)]
        model = nn.Sequential(nn.Linear(8, 2), nn.LogSoftMax())
        ds = DataSet.array(samples) >> SampleToMiniBatch(4)
        opt = (LocalOptimizer(model, ds, nn.ClassNLLCriterion())
               .set_compute_dtype(jnp.float32)
               .set_activation_memory("bf16")
               .set_end_when(optim.max_iteration(1)))
        with pytest.raises(ValueError, match="conflicts"):
            opt.optimize()


if __name__ == "__main__":
    import sys
    sys.exit(pytest.main([__file__, "-q"]))
