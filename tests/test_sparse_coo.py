"""Sparse end-to-end: COOBatch kernels, SparseSample/SparseMiniBatch
batching, and the Wide&Deep recipe training from sparse batches
(VERDICT r3 item 3; reference MiniBatch.scala:588, SparseTensorBLAS)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu import nn
from bigdl_tpu.dataset import (SparseSample, SparseMiniBatch,
                               batch_sparse_samples)
from bigdl_tpu.nn.sparse import COOBatch, coo_spmm


def rand_coo(rng, n, d, nnz):
    row = rng.integers(0, n, nnz).astype(np.int32)
    col = rng.integers(0, d, nnz).astype(np.int32)
    # avoid duplicate (row, col) pairs so dense comparison is exact
    seen = set()
    keep = []
    for k in range(nnz):
        if (row[k], col[k]) not in seen:
            seen.add((row[k], col[k]))
            keep.append(k)
    row, col = row[keep], col[keep]
    val = rng.normal(0, 1, len(keep)).astype(np.float32)
    return COOBatch(jnp.asarray(row), jnp.asarray(col), jnp.asarray(val),
                    (n, d))


class TestCOOKernels:
    def test_spmm_matches_dense(self):
        rng = np.random.default_rng(0)
        coo = rand_coo(rng, 6, 40, 30)
        W = jnp.asarray(rng.normal(0, 1, (40, 5)).astype(np.float32))
        got = coo_spmm(coo, W)
        want = coo.to_dense() @ W
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-5)

    def test_sparse_linear_coo_matches_dense(self):
        rng = np.random.default_rng(1)
        coo = rand_coo(rng, 4, 20, 15)
        m = nn.SparseLinear(20, 3)
        p, s = m.init(jax.random.PRNGKey(0))
        y, _ = m.apply(p, s, coo)
        want = coo.to_dense() @ p["weight"] + p["bias"]
        np.testing.assert_allclose(np.asarray(y), np.asarray(want),
                                   atol=1e-5)

    @pytest.mark.parametrize("combiner", ["sum", "mean", "sqrtn"])
    def test_lookup_combiners_match_bag_path(self, combiner):
        # same logical input via bags and via COO must agree
        ids = np.array([[0, 2, -1], [1, -1, -1]], np.int32)
        w = np.array([[1.0, 2.0, 0.0], [3.0, 0.0, 0.0]], np.float32)
        m = nn.LookupTableSparse(5, 4, combiner)
        p, s = m.init(jax.random.PRNGKey(0))
        y_bag, _ = m.apply(p, s, (jnp.asarray(ids), jnp.asarray(w)))
        coo = COOBatch(jnp.asarray([0, 0, 1], jnp.int32),
                       jnp.asarray([0, 2, 1], jnp.int32),
                       jnp.asarray([1.0, 2.0, 3.0], jnp.float32), (2, 5))
        y_coo, _ = m.apply(p, s, coo)
        np.testing.assert_allclose(np.asarray(y_bag), np.asarray(y_coo),
                                   atol=1e-5)

    def test_lookup_mean_negative_weights_raw_sum(self):
        # reference LookupTableSparse.scala:123-133: mean divides by the
        # RAW weight sum, so negative weights must not be abs()ed.
        # row0: 2*e0 + (-1)*e2 over denom (2 - 1) = 1
        ids = np.array([[0, 2]], np.int32)
        w = np.array([[2.0, -1.0]], np.float32)
        m = nn.LookupTableSparse(5, 4, "mean")
        p, s = m.init(jax.random.PRNGKey(0))
        want = 2.0 * p["weight"][0] - 1.0 * p["weight"][2]  # denom == 1
        y_bag, _ = m.apply(p, s, (jnp.asarray(ids), jnp.asarray(w)))
        np.testing.assert_allclose(np.asarray(y_bag[0]), np.asarray(want),
                                   atol=1e-5)
        coo = COOBatch(jnp.asarray([0, 0], jnp.int32),
                       jnp.asarray([0, 2], jnp.int32),
                       jnp.asarray([2.0, -1.0], jnp.float32), (1, 5))
        y_coo, _ = m.apply(p, s, coo)
        np.testing.assert_allclose(np.asarray(y_coo), np.asarray(y_bag),
                                   atol=1e-5)

    def test_join_table_coo(self):
        c1 = COOBatch(jnp.asarray([0, 1], jnp.int32),
                      jnp.asarray([1, 0], jnp.int32),
                      jnp.asarray([1.0, 2.0]), (2, 3))
        c2 = COOBatch(jnp.asarray([0], jnp.int32),
                      jnp.asarray([1], jnp.int32),
                      jnp.asarray([5.0]), (2, 4))
        j = nn.SparseJoinTable([3, 4])
        out, _ = j.apply({}, {}, [c1, c2])
        assert isinstance(out, COOBatch)
        assert out.dense_shape == (2, 7)
        dense = np.asarray(out.to_dense())
        want = np.zeros((2, 7), np.float32)
        want[0, 1], want[1, 0], want[0, 3 + 1] = 1.0, 2.0, 5.0
        np.testing.assert_array_equal(dense, want)

    def test_jit_reuse_across_batches_same_bucket(self):
        # COOBatch is a pytree with static dense_shape: two batches in
        # the same nnz bucket must hit the same compiled fn
        m = nn.SparseLinear(10, 2)
        p, s = m.init(jax.random.PRNGKey(0))
        traces = []

        @jax.jit
        def f(p, coo):
            traces.append(1)
            return m.apply(p, {}, coo)[0]

        rng = np.random.default_rng(2)
        for _ in range(3):
            samples = [SparseSample([1, 3], [1.0, -1.0], 10)
                       for _ in range(4)]
            mb = batch_sparse_samples(samples, nnz_buckets=[16, 64])
            f(p, mb.input)
        assert len(traces) == 1


class TestSparseBatching:
    def mk_samples(self, rng, n, d=50, with_dense=True):
        out = []
        for i in range(n):
            nnz = int(rng.integers(1, 6))
            idx = rng.choice(d, nnz, replace=False)
            vals = rng.normal(0, 1, nnz)
            dense = [rng.normal(0, 1, (3,)).astype(np.float32)] \
                if with_dense else None
            out.append(SparseSample(idx, vals, d, dense=dense,
                                    label=np.float32(i % 2)))
        return out

    def test_batch_roundtrip(self):
        rng = np.random.default_rng(0)
        samples = self.mk_samples(rng, 5)
        mb = batch_sparse_samples(samples)
        assert isinstance(mb, SparseMiniBatch)
        coo, dense0 = mb.input
        assert coo.dense_shape == (5, 50)
        assert dense0.shape == (5, 3)
        assert mb.target.shape == (5,)
        d = np.asarray(coo.to_dense())
        for i, s in enumerate(samples):
            want = np.zeros(50, np.float32)
            want[s.indices] = s.values
            np.testing.assert_allclose(d[i], want, atol=1e-6)

    def test_bucket_padding_static(self):
        rng = np.random.default_rng(1)
        samples = self.mk_samples(rng, 3, with_dense=False)
        mb = batch_sparse_samples(samples, nnz_buckets=[32, 128])
        assert mb.input.row.shape == (32,)

    def test_bucket_overflow_raises(self):
        rng = np.random.default_rng(2)
        samples = self.mk_samples(rng, 40, with_dense=False)
        with pytest.raises(ValueError):
            batch_sparse_samples(samples, nnz_buckets=[4])

    def test_slice_unsupported(self):
        rng = np.random.default_rng(3)
        mb = batch_sparse_samples(self.mk_samples(rng, 3, with_dense=False))
        with pytest.raises(TypeError):
            mb.slice(0, 1)


class TestWideDeepFromSparseMiniBatch:
    """The recipe test the verdict asked for: Wide&Deep trains directly
    from SparseMiniBatch COO wide features — no fixed-width bag
    preprocessing anywhere."""

    def test_trains_and_loss_drops(self):
        from bigdl_tpu import models, optim
        rng = np.random.default_rng(0)
        wide_dim, n_fields, dense_dim = 80, 2, 3
        model = models.WideAndDeep(wide_dim, [10, 8], dense_dim,
                                   embed_dim=4, hidden=(16,))
        p, st = model.init(jax.random.PRNGKey(0))
        method = optim.Adam(learning_rate=0.01)
        os_ = method.init_state(p)
        crit = nn.BCECriterion()

        # structured synthetic signal: label = [has wide feature < 10]
        def make_batch():
            samples = []
            for _ in range(32):
                nnz = int(rng.integers(1, 5))
                idx = rng.choice(wide_dim, nnz, replace=False)
                label = np.float32(1.0 if (idx < 10).any() else 0.0)
                deep = rng.integers(0, 8, (n_fields,)).astype(np.int32)
                dense = rng.normal(0, 1, (dense_dim,)).astype(np.float32)
                samples.append(SparseSample(
                    idx, np.ones(nnz, np.float32), wide_dim,
                    dense=[deep, dense], label=label))
            return batch_sparse_samples(samples, nnz_buckets=[256])

        @jax.jit
        def step(p, os_, coo, deep_ids, dense, y, it):
            def loss_fn(p):
                out, _ = model.apply(p, st, (coo, deep_ids, dense))
                return crit.apply(out[:, 0], y)
            loss, g = jax.value_and_grad(loss_fn)(p)
            p, os_ = method.update(g, p, os_, 0.01, it)
            return p, os_, loss

        losses = []
        for it in range(200):
            mb = make_batch()
            coo, deep_ids, dense = mb.input
            p, os_, loss = step(p, os_, coo, jnp.asarray(deep_ids),
                                jnp.asarray(dense), jnp.asarray(mb.target),
                                it)
            losses.append(float(loss))
        first = np.mean(losses[:5])
        last = np.mean(losses[-5:])
        assert last < first * 0.75, (first, last)
