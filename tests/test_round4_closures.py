"""Round-4 small closures (VERDICT r3 item 9): the last missing
forward TF ops, the debug_nans opt-in, and their wiring."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from bigdl_tpu.ops.registry import get_op


class TestRound4Ops:
    def test_approximate_equal(self):
        out = np.asarray(get_op("ApproximateEqual")(
            {"tolerance": 0.01}, jnp.asarray([1.0, 2.0]),
            jnp.asarray([1.005, 2.5])))
        assert out.tolist() == [True, False]

    def test_dilation2d_valid_matches_naive(self):
        rng = np.random.default_rng(0)
        x = rng.normal(0, 1, (1, 6, 7, 2)).astype(np.float32)
        f = rng.normal(0, 1, (3, 2, 2)).astype(np.float32)
        got = np.asarray(get_op("Dilation2D")(
            {"strides": [1, 1, 1, 1], "rates": [1, 1, 1, 1],
             "padding": b"VALID"}, jnp.asarray(x), jnp.asarray(f)))
        OH, OW = 4, 6
        want = np.zeros((1, OH, OW, 2), np.float32)
        for y in range(OH):
            for xx in range(OW):
                for c in range(2):
                    want[0, y, xx, c] = max(
                        x[0, y + dy, xx + dx, c] + f[dy, dx, c]
                        for dy in range(3) for dx in range(2))
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_dilation2d_same_stride2_shape(self):
        x = jnp.zeros((2, 9, 10, 3))
        f = jnp.zeros((3, 3, 3))
        got = get_op("Dilation2D")(
            {"strides": [1, 2, 2, 1], "rates": [1, 1, 1, 1],
             "padding": b"SAME"}, x, f)
        assert got.shape == (2, 5, 5, 3)

    def test_dilation2d_rates(self):
        # rate 2: effective kernel 3 with holes — max over offsets 0, 2
        x = jnp.asarray(np.arange(5, dtype=np.float32)
                        ).reshape(1, 5, 1, 1)
        f = jnp.zeros((2, 1, 1))
        got = np.asarray(get_op("Dilation2D")(
            {"strides": [1, 1, 1, 1], "rates": [1, 2, 1, 1],
             "padding": b"VALID"}, x, f))
        np.testing.assert_allclose(got.reshape(-1), [2, 3, 4])

    def test_random_shuffle_deterministic_permutation(self):
        v = jnp.arange(16)
        a = np.asarray(get_op("RandomShuffle")(
            {"seed": 3, "_node_name": "rs"}, v))
        b = np.asarray(get_op("RandomShuffle")(
            {"seed": 3, "_node_name": "rs"}, v))
        assert sorted(a.tolist()) == list(range(16))
        assert (a == b).all() and a.tolist() != list(range(16))

    def test_substr(self):
        out = get_op("Substr")(
            {}, np.asarray([b"hello", b"world"], object), 1, 3)
        assert out.tolist() == [b"ell", b"orl"]

    def test_assert_noop(self):
        get_op("Assert")({}, np.asarray(True), np.asarray([1]))
        with pytest.raises(AssertionError):
            get_op("Assert")({}, np.asarray(False), np.asarray([42]))
        assert get_op("NoOp")({}) == ()


class TestDebugNans:
    def test_opt_in_fires_on_nan(self):
        from bigdl_tpu.utils.config import (apply_debug_config, configure,
                                            reset_config)
        try:
            configure(debug_nans=True)
            apply_debug_config()
            with pytest.raises(FloatingPointError):
                jax.jit(lambda x: (x * 0.0) / (x * 0.0))(jnp.asarray(1.0))
        finally:
            configure(debug_nans=False)
            apply_debug_config()
            reset_config()

    def test_env_var_coerces(self, monkeypatch):
        from bigdl_tpu.utils.config import Config
        monkeypatch.setenv("BIGDL_TPU_DEBUG_NANS", "1")
        assert Config.from_env().debug_nans is True
