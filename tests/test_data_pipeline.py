"""Data-pipeline round-2 tests: TFRecord, CIFAR, vision-2.0 transforms,
text pipeline, MT prefetch assembler.

Reference test analogs: ``TEST/dataset/`` + ``TEST/transform/vision/``
specs + ``TFRecordIterator`` usage in the TF importer tests.
"""

import os
import time

import numpy as np
import pytest

from bigdl_tpu.dataset import (DataSet, MTSampleToMiniBatch,
                               SampleToMiniBatch, cifar, text, tfrecord)
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.transform import vision as V


class TestTFRecord:
    def test_roundtrip(self, tmp_path):
        p = str(tmp_path / "x.tfrecord")
        tfrecord.write_examples(p, [
            {"img": b"abc", "label": 3, "w": np.array([1.0, 2.0])},
            {"img": b"de", "label": np.array([-1, 5]), "w": [0.25]},
        ])
        exs = list(tfrecord.read_examples(p))
        assert exs[0]["img"] == [b"abc"]
        assert exs[0]["label"].tolist() == [3]
        np.testing.assert_allclose(exs[1]["w"], [0.25])
        assert exs[1]["label"].tolist() == [-1, 5]

    def test_crc_detects_corruption(self, tmp_path):
        p = str(tmp_path / "x.tfrecord")
        tfrecord.write_records(p, [b"payload-one"])
        raw = bytearray(open(p, "rb").read())
        raw[14] ^= 0xFF  # flip a payload byte
        open(p, "wb").write(bytes(raw))
        with pytest.raises(IOError):
            list(tfrecord.read_records(p))

    def test_reads_reference_tf_file_if_present(self):
        p = ("/root/reference/spark/dl/src/test/resources/tf/"
             "mnist_train.tfrecord")
        if not os.path.exists(p):
            pytest.skip("reference resources not available")
        exs = list(tfrecord.read_examples(p))
        assert len(exs) == 10
        assert exs[0]["image/encoded"][0][:4] == b"\x89PNG"
        assert 0 <= int(exs[0]["image/class/label"][0]) <= 9


class TestCifar:
    def test_synthetic_learnable_format(self):
        imgs, labels = cifar.synthetic_cifar(64)
        assert imgs.shape == (64, 32, 32, 3) and imgs.dtype == np.uint8
        assert labels.min() >= 0 and labels.max() <= 9

    def test_bin_format_loader(self, tmp_path):
        # fabricate one binary batch in the CIFAR-10 layout
        n = 10
        rng = np.random.RandomState(0)
        labels = rng.randint(0, 10, n).astype(np.uint8)
        imgs = rng.randint(0, 255, (n, 3, 32, 32)).astype(np.uint8)
        rec = np.concatenate([labels[:, None],
                              imgs.reshape(n, -1)], axis=1)
        d = tmp_path / "cifar-10-batches-bin"
        d.mkdir()
        for i in range(1, 6):
            rec.tofile(str(d / f"data_batch_{i}.bin"))
        rec.tofile(str(d / "test_batch.bin"))
        tr_i, tr_l = cifar.load_cifar10(str(tmp_path), train=True)
        te_i, te_l = cifar.load_cifar10(str(tmp_path), train=False)
        assert tr_i.shape == (50, 32, 32, 3)
        assert te_i.shape == (10, 32, 32, 3)
        np.testing.assert_array_equal(te_l, labels)
        # channel order: record is CHW planes -> loader returns HWC
        np.testing.assert_array_equal(te_i[0, :, :, 0], imgs[0, 0])


class TestVisionTransforms:
    def _feat(self, seed=0):
        rng = np.random.RandomState(seed)
        return V.ImageFeature(rng.randint(0, 255, (8, 6, 3)).astype(
            np.float32), label=1)

    def test_frame_pipeline_compose(self):
        frame = V.ImageFrame.array(
            [np.full((4, 4, 3), 100.0, np.float32)], [0])
        frame = (frame >> V.Brightness(10, 10)
                 >> V.ChannelNormalize((110, 110, 110), (1, 1, 1))
                 >> V.ImageFrameToSample())
        s = frame.features[0]["sample"]
        assert s.feature.shape == (3, 4, 4)
        np.testing.assert_allclose(s.feature, 0.0)

    def test_hsv_roundtrip(self):
        rng = np.random.RandomState(3)
        img = rng.randint(0, 255, (5, 5, 3)).astype(np.float32)
        back = V._hsv_to_rgb(V._rgb_to_hsv(img))
        np.testing.assert_allclose(back, img, atol=0.5)

    def test_saturation_grey_is_fixed_point(self):
        grey = np.full((4, 4, 3), 128.0, np.float32)
        f = V.Saturation(0.5, 0.5).transform(V.ImageFeature(grey))
        np.testing.assert_allclose(f.image, grey, atol=0.6)

    def test_resize_and_aspect_scale(self):
        f = self._feat()
        V.Resize(16, 12).transform(f)
        assert f.image.shape == (16, 12, 3)
        f2 = V.ImageFeature(np.zeros((100, 50, 3), np.float32))
        V.AspectScale(min_size=25).transform(f2)
        assert f2.image.shape == (50, 25, 3)

    def test_resize_bilinear_values(self):
        img = np.array([[0.0, 2.0], [4.0, 6.0]], np.float32)
        out = V._resize_bilinear(img, 4, 4)
        assert out.shape == (4, 4)
        # corners preserved-ish, monotone rows
        assert out[0, 0] == 0.0 and out[-1, -1] == 6.0
        assert (np.diff(out, axis=1) >= 0).all()

    def test_expand_and_random_alter_aspect(self):
        f = self._feat()
        V.Expand(max_expand_ratio=2.0, seed=1).transform(f)
        assert f.image.shape[0] >= 8 and f.image.shape[1] >= 6
        f2 = self._feat()
        V.RandomAlterAspect(target_size=7, seed=2).transform(f2)
        assert f2.image.shape == (7, 7, 3)

    def test_crops_and_flip(self):
        f = self._feat()
        V.CenterCrop(4, 4).transform(f)
        assert f.image.shape == (4, 4, 3)
        g = self._feat()
        img0 = g.image.copy()
        V.HFlip(threshold=1.1).transform(g)  # always flips
        np.testing.assert_allclose(g.image, img0[:, ::-1])

    def test_random_transformer_prob(self):
        always = V.RandomTransformer(V.Brightness(5, 5), prob=1.0)
        never = V.RandomTransformer(V.Brightness(5, 5), prob=0.0)
        base = np.zeros((2, 2, 3), np.float32)
        np.testing.assert_allclose(
            always.transform(V.ImageFeature(base.copy())).image, 5.0)
        np.testing.assert_allclose(
            never.transform(V.ImageFeature(base.copy())).image, 0.0)


class TestTextPipeline:
    def test_tokenizer_and_dictionary(self):
        sents = [text.sentence_tokenizer(s)
                 for s in ["The cat sat.", "The dog sat!"]]
        d = text.Dictionary(sents, vocab_size=4)
        assert d.vocab_size() == 5  # 4 words + <unk>
        assert d.index("the") != d.index("sat")
        assert d.index("zebra") == d.word2index[text.Dictionary.UNKNOWN]

    def test_dictionary_save_load(self, tmp_path):
        d = text.Dictionary([["a", "b", "a"]])
        p = str(tmp_path / "vocab.txt")
        d.save(p)
        d2 = text.Dictionary.load(p)
        assert d2.word2index == d.word2index

    def test_labeled_sentence_pipeline(self):
        corpus = text.synthetic_corpus(20)
        toks = [text.sentence_tokenizer(s) for s in corpus]
        d = text.Dictionary(toks)
        pipe = (text.TextToLabeledSentence(d)
                >> text.LabeledSentenceToSample(fixed_length=12))
        samples = list(pipe(iter(toks)))
        assert len(samples) == 20
        for s in samples:
            assert s.feature.shape == (12,) and s.label.shape == (12,)
        # shift property on an unpadded prefix
        raw = d.encode(toks[0])
        np.testing.assert_array_equal(samples[0].feature[:len(raw) - 1],
                                      raw[:-1])
        np.testing.assert_array_equal(samples[0].label[:len(raw) - 1],
                                      raw[1:])

    def test_ptb_batches(self):
        ids = np.arange(21)
        x, y = text.ptb_batches(ids, num_steps=5)
        assert x.shape == (4, 5)
        np.testing.assert_array_equal(y, x + 1)


class TestMTPrefetch:
    def test_batches_match_serial(self):
        samples = [Sample(np.full((3,), i, np.float32), np.int32(i % 2))
                   for i in range(37)]

        def tf(s):
            return Sample(s.feature * 2.0, s.label)

        mt = MTSampleToMiniBatch(8, tf, workers=4, prefetch=2)
        batches = list(mt(iter(samples)))
        assert len(batches) == 4  # 37 // 8, remainder dropped
        flat = np.concatenate([b.input for b in batches])
        np.testing.assert_allclose(flat[:, 0], np.arange(32) * 2.0)

    def test_keep_remainder(self):
        samples = [Sample(np.zeros(2, np.float32), np.int32(0))
                   for _ in range(10)]
        mt = MTSampleToMiniBatch(4, None, drop_remainder=False)
        sizes = [b.size() for b in mt(iter(samples))]
        assert sizes == [4, 4, 2]

    def test_worker_error_propagates(self):
        def bad(s):
            raise RuntimeError("boom")

        mt = MTSampleToMiniBatch(2, bad)
        with pytest.raises(RuntimeError):
            list(mt(iter([Sample(np.zeros(1), np.int32(0))] * 4)))

    def test_random_augmentation_is_schedule_independent(self):
        # VERDICT r2 weak#2 root cause: ThreadRng draws depended on which
        # worker thread got each sample.  Under the assembler the draws
        # must be a pure function of (seed, stream index): many-worker
        # and single-worker runs produce IDENTICAL batches.
        from bigdl_tpu.dataset import image
        samples = [Sample(np.random.RandomState(i).rand(3, 8, 8)
                          .astype(np.float32), np.int32(0))
                   for i in range(32)]

        def run(workers):
            crop = image.RandomCropper(4, 4, pad=2)
            flip = image.HFlip()

            def aug(s):
                s = next(iter(crop(iter([s]))))
                return next(iter(flip(iter([s]))))

            mt = MTSampleToMiniBatch(8, aug, workers=workers)
            return np.concatenate([b.input for b in mt(iter(samples))])

        a, b, c = run(8), run(8), run(1)
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, c)

    def test_augmentation_varies_across_passes(self):
        # ...but iterating the SAME transformer again (epoch 2 over a
        # fixed-order dataset) must draw FRESH augmentation, not replay
        # epoch 1 (code-review r3 finding)
        from bigdl_tpu.dataset import image
        samples = [Sample(np.random.RandomState(i).rand(3, 8, 8)
                          .astype(np.float32), np.int32(0))
                   for i in range(16)]
        crop = image.RandomCropper(4, 4, pad=2)

        def aug(s):
            return next(iter(crop(iter([s]))))

        mt = MTSampleToMiniBatch(8, aug, workers=4)
        e1 = np.concatenate([b.input for b in mt(iter(samples))])
        e2 = np.concatenate([b.input for b in mt(iter(samples))])
        assert not np.array_equal(e1, e2)

    def test_prefetch_overlaps(self):
        # producer keeps the queue full while the consumer is slow
        samples = [Sample(np.zeros(1, np.float32), np.int32(0))
                   for _ in range(24)]
        mt = MTSampleToMiniBatch(4, None, workers=2, prefetch=3)
        it = mt(iter(samples))
        first = next(it)
        time.sleep(0.05)  # let the producer run ahead
        rest = list(it)
        assert 1 + len(rest) == 6


class TestReviewFixes:
    """Regressions for round-2 review findings on the data pipeline."""

    def test_random_transforms_advance_between_samples(self):
        # one instance must give different draws per call (a fresh instance
        # per sample used to replay the identical 'random' crop forever)
        from bigdl_tpu.dataset import image
        rng_img = np.random.RandomState(0).rand(40, 40, 3).astype(np.float32)
        crop = image.RandomCropper(8, 8)
        outs = {bytes(next(iter(crop(iter([Sample(rng_img, 0)])))).feature)
                for _ in range(20)}
        assert len(outs) > 1, "RandomCropper draws never advance"
        flip = image.HFlip(threshold=0.5)
        decisions = {bool(np.allclose(
            next(iter(flip(iter([Sample(rng_img, 0)])))).feature, rng_img))
            for _ in range(50)}
        assert decisions == {True, False}, "HFlip never varies"

    def test_thread_rng_distinct_across_threads(self):
        from concurrent.futures import ThreadPoolExecutor
        from bigdl_tpu.utils.imgops import ThreadRng
        rng = ThreadRng(1)
        with ThreadPoolExecutor(max_workers=4) as pool:
            draws = list(pool.map(lambda _: rng.random(), range(8)))
        assert len(set(draws)) > 1

    def test_prefetch_consumer_early_exit_unblocks_producer(self):
        import threading
        before = threading.active_count()
        samples = [Sample(np.zeros(4, np.float32), np.int32(0))
                   for _ in range(512)]
        mt = MTSampleToMiniBatch(4, None, workers=2, prefetch=1)
        it = mt(iter(samples))
        next(it)
        it.close()  # early exit mid-epoch
        deadline = time.time() + 5.0
        while threading.active_count() > before and time.time() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= before + 1, \
            "producer thread leaked after early consumer exit"

    def test_chained_close_propagates_to_inner_assembler(self):
        """Early consumer exit on a CHAINED pipeline: closing the outer
        generator must shut the inner assembler's producer thread down
        deterministically (the outer producer closes its source in its
        finally), not leave it to GC."""
        import threading

        def sample_stream():
            i = 0
            while True:  # infinite: only shutdown propagation ends it
                yield Sample(np.full(3, i, np.float32), np.int32(0))
                i += 1

        before = threading.active_count()
        inner = MTSampleToMiniBatch(4, None, workers=2, prefetch=2)
        rebatch = MTSampleToMiniBatch(2, None, workers=2, prefetch=2)

        def batch_to_samples(batches):
            for b in batches:
                for i in range(b.size()):
                    yield Sample(b.input[i], b.target[i])

        outer = rebatch(batch_to_samples(inner(sample_stream())))
        next(outer)
        outer.close()  # must cascade: outer producer → inner generator
        deadline = time.time() + 5.0
        while threading.active_count() > before and time.time() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= before, \
            "chained early exit leaked a producer thread"

    def test_throw_mid_epoch_cleans_up_threads_and_queue(self):
        """Exception injected at the consumption point (generator.throw
        — what a crashing training loop does to its data iterator) must
        neither deadlock the bounded queue nor leak the producer."""
        import threading
        before = threading.active_count()
        samples = [Sample(np.zeros(4, np.float32), np.int32(0))
                   for _ in range(4096)]
        mt = MTSampleToMiniBatch(4, None, workers=2, prefetch=1)
        it = mt(iter(samples))
        next(it)
        with pytest.raises(RuntimeError, match="step exploded"):
            it.throw(RuntimeError("step exploded"))
        deadline = time.time() + 5.0
        while threading.active_count() > before and time.time() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= before, \
            "producer thread leaked after consumer exception"

    def test_shared_lighting_constants(self):
        from bigdl_tpu.dataset import image
        from bigdl_tpu.transform import vision as V
        from bigdl_tpu.utils import imgops
        # both stacks consume the same kernel (no drifting copies)
        f = V.Lighting(alphastd=0.0).transform(
            V.ImageFeature(np.zeros((2, 2, 3), np.float32)))
        np.testing.assert_allclose(f.image, 0.0)
        s = next(iter(image.Lighting(alphastd=0.0)(
            iter([Sample(np.zeros((2, 2, 3), np.float32), 0)]))))
        np.testing.assert_allclose(s.feature, 0.0)
        assert imgops.LIGHTING_EIGVAL.shape == (3,)


class TestSequenceFile:
    def test_roundtrip_and_sync_markers(self, tmp_path):
        from bigdl_tpu.dataset import seqfile as sq
        p = str(tmp_path / "part-0.seq")
        recs = [(f"img{i}\n{i % 7}".encode(), bytes([i % 251]) * (50 + i))
                for i in range(300)]
        sq.write_seqfile(p, recs, sync_interval=64)
        back = list(sq.read_seqfile(p))
        assert len(back) == 300
        assert back[0][0] == b"img0\n0"
        assert back[123][1] == recs[123][1]

    def test_imagenet_key_convention(self, tmp_path):
        from bigdl_tpu.dataset import seqfile as sq
        assert sq.parse_imagenet_key(b"n0123/img.jpg\n42") == \
            ("n0123/img.jpg", 42)
        assert sq.parse_imagenet_key(b"7") == (None, 7)
        p = str(tmp_path / "p.seq")
        sq.write_seqfile(p, [(b"a\n3", b"xyz"), (b"5", b"pq")])
        out = list(sq.seqfiles_to_byte_records([p]))
        assert out == [(3, b"xyz"), (5, b"pq")]

    def test_vint_edge_cases(self):
        from bigdl_tpu.dataset.seqfile import read_vint, write_vint
        for v in (0, 1, -1, 127, -112, 128, -113, 1 << 20, -(1 << 20),
                  (1 << 31) - 1):
            b = write_vint(v)
            got, pos = read_vint(b, 0)
            assert got == v and pos == len(b)

    def test_block_compressed_roundtrip(self, tmp_path):
        # r3: block compression is now READ/WRITTEN (MapReduce default
        # output format); full coverage in test_round3_closures.py
        from bigdl_tpu.dataset import seqfile as sq
        p = str(tmp_path / "c.seq")
        recs = [(f"k{i}".encode(), f"v{i}".encode() * 10)
                for i in range(10)]
        sq.write_seqfile(p, recs, sync_interval=4, block_compressed=True)
        assert list(sq.read_seqfile(p)) == recs


class TestBuiltinLoaders:
    def test_movielens_format_and_parse(self, tmp_path):
        from bigdl_tpu.dataset import movielens
        syn = movielens.synthetic_ratings(n_ratings=50)
        assert syn.shape == (50, 3)
        assert syn[:, 2].min() >= 1 and syn[:, 2].max() <= 5
        p = tmp_path / "ratings.dat"
        p.write_text("\n".join(f"{u}::{i}::{r}::0" for u, i, r in syn))
        back = movielens.load(str(tmp_path))
        np.testing.assert_array_equal(back, syn)
        samples = movielens.to_implicit_samples(syn)
        assert samples[0].feature.shape == (2,)

    def test_news20_tree_and_synthetic(self, tmp_path):
        from bigdl_tpu.dataset import news20
        for cat, docs in (("alt.atheism", ["hello world"]),
                          ("sci.space", ["rockets fly", "orbit high"])):
            d = tmp_path / cat
            d.mkdir()
            for i, t in enumerate(docs):
                (d / f"{i}").write_text(t)
        texts, labels, cats = news20.load(str(tmp_path))
        assert cats == ["alt.atheism", "sci.space"]
        assert list(labels) == [0, 1, 1]
        texts2, labels2, cats2 = news20.synthetic_news(50, 3)
        assert len(texts2) == 50 and set(labels2) <= {0, 1, 2}


def test_seqfile_truncation_detected(tmp_path):
    from bigdl_tpu.dataset import seqfile as sq
    p = str(tmp_path / "t.seq")
    sq.write_seqfile(p, [(b"k", b"v" * 100)])
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[:-20])  # cut mid-value
    with pytest.raises(IOError, match="truncated"):
        list(sq.read_seqfile(p))


def test_seqfile_record_compression_roundtrip(tmp_path):
    from bigdl_tpu.dataset import seqfile as sq
    p = str(tmp_path / "c.seq")
    recs = [(f"k{i}".encode(), (f"payload-{i}-" * 20).encode())
            for i in range(120)]
    sq.write_seqfile(p, recs, compressed=True, sync_interval=50)
    back = list(sq.read_seqfile(p))
    assert back == recs
    # compressed file is smaller than the raw payload total
    import os as _os
    assert _os.path.getsize(p) < sum(len(v) for _, v in recs)


def test_seqfile_unknown_codec_rejected(tmp_path):
    import struct
    from bigdl_tpu.dataset import seqfile as sq
    p = str(tmp_path / "x.seq")
    with open(p, "wb") as f:
        f.write(b"SEQ\x06")
        f.write(sq._hadoop_string(sq.TEXT))
        f.write(sq._hadoop_string(sq.TEXT))
        f.write(bytes([1, 0]))
        f.write(sq._hadoop_string("org.example.SnappyCodec"))
        f.write(struct.pack(">i", 0))
        f.write(b"\x00" * 16)
    with pytest.raises(NotImplementedError, match="codec"):
        list(sq.read_seqfile(p))
